"""Checkpoint integrity, retry, escalation, and crash-window recovery.

Backend-level tests (no training loop): the two-slot msgpack latest with
crc32 sidecars, the orbax pointer checksum + other-slot fallback, the
bounded retry-with-backoff policy, the consecutive-failure escalation,
and the satellite crash windows — a kill between ``_drain``'s two
renames (stale ``.old``) and between the ptr-tmp write and its
``os.replace``.
"""

import json
import os

import numpy as np
import pytest

from msrflute_tpu.engine.checkpoint import (LATEST, LATEST_PREV,
                                            CheckpointManager)
from msrflute_tpu.engine.round import ServerState
from msrflute_tpu.resilience.integrity import (CheckpointEscalationError,
                                               RetryPolicy, blob_checksum,
                                               run_with_retry, tree_checksum)


def _state(round_no: int, scale: float = 1.0) -> ServerState:
    return ServerState(
        params={"w": np.full((4, 3), scale, np.float32),
                "b": np.arange(3, dtype=np.float32) * scale},
        opt_state={"m": np.zeros((4, 3), np.float32)},
        strategy_state={}, round=round_no)


def _no_sleep_policy(**over):
    kw = dict(retries=3, backoff_base_s=0.0, backoff_max_s=0.0,
              jitter=0.0, escalation_threshold=3)
    kw.update(over)
    return RetryPolicy(**kw)


# ----------------------------------------------------------------------
# msgpack: sidecars + two-slot fallback
# ----------------------------------------------------------------------
def test_msgpack_latest_rotates_prev_and_writes_sidecars(tmp_path):
    cm = CheckpointManager(str(tmp_path), retry=_no_sleep_policy())
    cm.save_latest(_state(1, scale=1.0))
    cm.save_latest(_state(2, scale=2.0))
    for name in (LATEST, LATEST + ".sum", LATEST_PREV, LATEST_PREV + ".sum"):
        assert (tmp_path / name).exists(), name
    meta = json.load(open(tmp_path / (LATEST + ".sum")))
    blob = open(tmp_path / LATEST, "rb").read()
    assert meta["crc32"] == blob_checksum(blob)
    assert meta["size"] == len(blob)
    # latest holds round 2, prev holds round 1
    assert cm.load(_state(0)).round == 2
    os.remove(tmp_path / LATEST)
    restored = cm.load(_state(0))
    assert restored.round == 1
    assert any(e["event"] == "restored from backup slot"
               for e in cm.recovery_events)


def test_msgpack_flipped_byte_falls_back_with_recovery_event(tmp_path):
    cm = CheckpointManager(str(tmp_path), retry=_no_sleep_policy())
    cm.save_latest(_state(1, scale=1.0))
    cm.save_latest(_state(2, scale=2.0))
    path = tmp_path / LATEST
    blob = bytearray(path.read_bytes())
    blob[len(blob) // 3] ^= 0xFF
    path.write_bytes(bytes(blob))
    restored = cm.load(_state(0))
    assert restored.round == 1
    assert restored.params["w"][0, 0] == 1.0
    events = [e["event"] for e in cm.recovery_events]
    assert any("integrity check failed" in e for e in events)


def test_msgpack_torn_write_truncation_falls_back(tmp_path):
    """A torn write (truncated file, size mismatch vs sidecar) must fall
    back too — not just a clean bit flip."""
    cm = CheckpointManager(str(tmp_path), retry=_no_sleep_policy())
    cm.save_latest(_state(1))
    cm.save_latest(_state(2))
    path = tmp_path / LATEST
    path.write_bytes(path.read_bytes()[: path.stat().st_size // 2])
    assert cm.load(_state(0)).round == 1


def test_msgpack_checkpoint_without_sidecar_still_loads(tmp_path):
    """Pre-integrity checkpoints (no .sum sidecar) keep loading —
    verification is vacuous, not fatal."""
    cm = CheckpointManager(str(tmp_path), retry=_no_sleep_policy())
    cm.save_latest(_state(4))
    os.remove(tmp_path / (LATEST + ".sum"))
    assert cm.load(_state(0)).round == 4
    assert cm.recovery_events == []


# ----------------------------------------------------------------------
# retry + escalation
# ----------------------------------------------------------------------
def test_retry_recovers_from_transient_io_faults(tmp_path):
    fails = iter([True, True, False, False])
    cm = CheckpointManager(str(tmp_path), retry=_no_sleep_policy(),
                           io_fault=lambda: next(fails) and
                           (_ for _ in ()).throw(OSError("transient")))
    cm.save_latest(_state(3))
    assert cm.load(_state(0)).round == 3
    assert cm.escalator.consecutive == 0  # success reset the counter


def test_escalation_aborts_after_consecutive_failures(tmp_path):
    def always_fail():
        raise OSError("disk on fire")

    cm = CheckpointManager(str(tmp_path),
                           retry=_no_sleep_policy(escalation_threshold=2),
                           io_fault=always_fail)
    cm.save_latest(_state(1))  # failure 1: warn and continue
    with pytest.raises(CheckpointEscalationError):
        cm.save_latest(_state(2))  # failure 2: hits the threshold
    assert cm.escalator.consecutive == 2


def test_run_with_retry_propagates_fatal_signals():
    def interrupt():
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        run_with_retry(interrupt, _no_sleep_policy())


def test_retry_backoff_is_exponential_capped_and_jitter_free_when_zero():
    pol = RetryPolicy(retries=5, backoff_base_s=1.0, backoff_max_s=4.0,
                      jitter=0.0)
    assert [pol.delay(a) for a in range(4)] == [1.0, 2.0, 4.0, 4.0]
    jittered = RetryPolicy(backoff_base_s=1.0, jitter=0.5)
    assert all(0.5 <= jittered.delay(0) <= 1.5 for _ in range(16))


# ----------------------------------------------------------------------
# orbax: pointer checksum, slot fallback, crash windows, drain re-queue
# ----------------------------------------------------------------------
def _orbax_cm(tmp_path, **kw):
    kw.setdefault("retry", _no_sleep_policy())
    return CheckpointManager(str(tmp_path), backend="orbax", **kw)


def _commit_latest(cm, state):
    cm.save_latest(state)
    cm.wait()  # commits the pointer at the slot


def test_orbax_ptr_records_tree_checksum_and_verifies(tmp_path):
    cm = _orbax_cm(tmp_path)
    _commit_latest(cm, _state(1))
    ptr = json.load(open(tmp_path / cm._LATEST_PTR))
    slot_dir = cm._orbax_path(ptr["slot"])
    assert ptr["crc32"] == tree_checksum(slot_dir)
    assert cm.load(_state(0)).round == 1


def test_orbax_corrupted_slot_falls_back_to_other_slot(tmp_path):
    cm = _orbax_cm(tmp_path)
    _commit_latest(cm, _state(1, scale=1.0))
    _commit_latest(cm, _state(2, scale=2.0))  # lands in the OTHER slot
    ptr = json.load(open(tmp_path / cm._LATEST_PTR))
    slot_dir = cm._orbax_path(ptr["slot"])
    # flip a byte in some file of the committed slot
    for root, _dirs, files in os.walk(slot_dir):
        if files:
            victim = os.path.join(root, sorted(files)[0])
            break
    blob = bytearray(open(victim, "rb").read() or b"\0")
    blob[0] ^= 0xFF
    open(victim, "wb").write(bytes(blob))

    restored = cm.load(_state(0))
    assert restored.round == 1  # the surviving slot's generation
    events = [e["event"] for e in cm.recovery_events]
    assert any("checksum" in e for e in events)
    assert any("backup slot" in e for e in events)


def test_orbax_legacy_bare_slot_pointer_still_loads(tmp_path):
    cm = _orbax_cm(tmp_path)
    _commit_latest(cm, _state(3))
    slot = json.load(open(tmp_path / cm._LATEST_PTR))["slot"]
    (tmp_path / cm._LATEST_PTR).write_text(slot)  # pre-integrity format
    assert cm.load(_state(0)).round == 3


def test_crash_between_ptr_tmp_write_and_replace_keeps_old_anchor(tmp_path):
    """Satellite crash window: a kill after writing ``ptr.tmp`` but
    before ``os.replace`` must leave the committed pointer (and its
    round) authoritative."""
    cm = _orbax_cm(tmp_path)
    _commit_latest(cm, _state(1))
    # simulate the torn commit of round 2: slot saved, ptr.tmp written,
    # replace never happened
    other = cm._LATEST_SLOTS[1]
    cm._orbax_save(cm._orbax_path(other), _state(2))
    cm._drain()
    (tmp_path / (cm._LATEST_PTR + ".tmp")).write_text(
        json.dumps({"slot": other, "crc32": "dead"}))
    cm2 = _orbax_cm(tmp_path)
    assert cm2.load(_state(0)).round == 1


def test_crash_between_best_swap_renames_recovers_from_old(tmp_path):
    """Satellite crash window: killed between ``final -> final.old`` and
    ``tmp -> final`` leaves only ``.old`` + the tmp dir; ``load`` must
    restore the previous best from ``.old``."""
    cm = _orbax_cm(tmp_path)
    cm.save_best(_state(1), "loss")
    cm.wait()  # the swap committed: best_val_loss_model.orbax exists
    final = cm._orbax_path("best_val_loss_model.orbax")
    assert os.path.isdir(final)
    # round-2 best: save the .new dir, then simulate the kill mid-swap
    cm.save_best(_state(2), "loss")
    cm._orbax.wait_until_finished()
    os.rename(final, final + ".old")
    cm._pending_renames.clear()  # the process died; nothing pending

    cm2 = _orbax_cm(tmp_path)
    restored = cm2.load_best(_state(0), "loss")
    assert restored is not None and restored.round == 1


def test_drain_requeues_failed_renames(tmp_path, monkeypatch):
    """Satellite fix: one failed rename must be RE-QUEUED, not dropped —
    the next drain commits the stranded save."""
    cm = _orbax_cm(tmp_path)
    cm.save_best(_state(5), "acc")
    cm._orbax.wait_until_finished()  # orbax's own commit must land first

    real_rename = os.rename
    boom = {"left": 1}
    final_name = "best_val_acc_model.orbax"

    def flaky_rename(src, dst):
        # fail only OUR .new -> final swap, not orbax-internal renames
        if boom["left"] and str(dst).endswith(final_name):
            boom["left"] -= 1
            raise OSError("transient NFS blip")
        return real_rename(src, dst)

    monkeypatch.setattr(os, "rename", flaky_rename)
    cm._drain()  # rename fails once -> re-queued
    assert len(cm._pending_renames) == 1
    final = cm._orbax_path("best_val_acc_model.orbax")
    assert not os.path.isdir(final)
    cm._drain()  # next drain commits it
    assert cm._pending_renames == []
    assert os.path.isdir(final)
    assert cm.load_best(_state(0), "acc").round == 5


def test_drain_failure_counts_toward_escalation_but_keeps_renames(
        tmp_path, monkeypatch):
    cm = _orbax_cm(tmp_path)
    cm._pending_renames.append((str(tmp_path / "ghost.new"),
                                str(tmp_path / "ghost")))
    monkeypatch.setattr(cm._orbax, "wait_until_finished",
                        lambda: (_ for _ in ()).throw(OSError("io")))
    before = cm.escalator.consecutive
    cm._drain()
    assert cm.escalator.consecutive == before + 1
    # the queued rename survives (its tmp dir may belong to an EARLIER
    # successful save; the isdir guard skips truly-failed ones)
    assert len(cm._pending_renames) == 1
