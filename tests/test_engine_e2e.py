"""End-to-end round-loop smoke tests on the 8-device virtual mesh —
the analogue of reference ``testing/test_e2e_trainer.py`` (which shells out
to a 2-process torch.distributed run), plus correctness assertions the
reference never had: learning actually reduces loss, checkpoints resume.
"""

import numpy as np
import pytest

from msrflute_tpu.config import FLUTEConfig
from msrflute_tpu.engine import OptimizationServer
from msrflute_tpu.models import make_task


def _config(max_iteration=6, **server_over):
    raw = {
        "model_config": {"model_type": "LR", "num_classes": 4, "input_dim": 8},
        "strategy": "fedavg",
        "server_config": {
            "max_iteration": max_iteration,
            "num_clients_per_iteration": 4,
            "initial_lr_client": 0.5,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": 2,
            "rec_freq": 100,
            "initial_val": True,
            "best_model_criterion": "acc",
            "data_config": {"val": {"batch_size": 8}, "test": {"batch_size": 8}},
            **server_over,
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.5},
            "data_config": {"train": {"batch_size": 4}},
        },
    }
    return FLUTEConfig.from_dict(raw)


@pytest.fixture(scope="module")
def trained(tmp_path_factory, synth_dataset, mesh8):
    cfg = _config()
    task = make_task(cfg.model_config)
    server = OptimizationServer(
        task, cfg, synth_dataset, val_dataset=synth_dataset,
        model_dir=str(tmp_path_factory.mktemp("models")), mesh=mesh8, seed=1)
    initial = server._maybe_eval  # run explicit initial eval through train()
    state = server.train()
    return server, state


def test_training_improves_metrics(trained, synth_dataset):
    server, state = trained
    assert state.round == 6
    # linear separable toy data: accuracy should beat the 1/4 chance level
    assert server.best_val["acc"].value > 0.5
    assert "loss" in server.best_val


def test_checkpoint_resume(trained, synth_dataset, mesh8, tmp_path):
    server, state = trained
    # latest checkpoint exists and loads back with identical params
    restored = server.ckpt.load(server.engine.init_state(
        __import__("jax").random.PRNGKey(0)))
    assert restored is not None
    assert restored.round == 6
    import jax
    old = jax.device_get(state.params)
    new = jax.device_get(restored.params)
    for a, b in zip(jax.tree.leaves(old), jax.tree.leaves(new)):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_resume_continues_rounds(synth_dataset, mesh8, tmp_path):
    cfg = _config(max_iteration=2)
    task = make_task(cfg.model_config)
    d = str(tmp_path / "m")
    s1 = OptimizationServer(task, cfg, synth_dataset, val_dataset=synth_dataset,
                            model_dir=d, mesh=mesh8, seed=2)
    s1.train()
    cfg2 = _config(max_iteration=4, resume_from_checkpoint=True)
    s2 = OptimizationServer(task, cfg2, synth_dataset, val_dataset=synth_dataset,
                            model_dir=d, mesh=mesh8, seed=3)
    assert s2.state.round == 2
    final = s2.train()
    assert final.round == 4


def test_dga_strategy_runs(synth_dataset, mesh8, tmp_path):
    raw_over = {"aggregate_median": "softmax", "softmax_beta": 0.5,
                "weight_train_loss": "train_loss", "stale_prob": 0.3}
    cfg = _config(max_iteration=3, **raw_over)
    cfg.strategy = "dga"
    from msrflute_tpu.strategies import select_strategy, DGA
    assert select_strategy("dga") is DGA
    task = make_task(cfg.model_config)
    server = OptimizationServer(task, cfg, synth_dataset,
                                val_dataset=synth_dataset,
                                model_dir=str(tmp_path / "dga"), mesh=mesh8)
    state = server.train()
    assert state.round == 3
    # staleness buffer is threaded state
    assert "stale_grad_sum" in state.strategy_state



def test_async_latest_msgpack_checkpoint(synth_dataset, mesh8, tmp_path):
    """server_config.checkpoint_async: true — per-round latest saves run
    on the writer thread (overlapping the next round on a real chip) yet
    land bit-identical durable state; resume restores it exactly."""
    import jax
    cfg = _config(max_iteration=3, checkpoint_async=True)
    task = make_task(cfg.model_config)
    d = str(tmp_path / "async")
    s1 = OptimizationServer(task, cfg, synth_dataset,
                            val_dataset=synth_dataset,
                            model_dir=d, mesh=mesh8, seed=5)
    state = s1.train()  # train() waits on the writer before returning
    assert s1.ckpt.async_latest
    restored = s1.ckpt.load(s1.engine.init_state(jax.random.PRNGKey(0)))
    assert restored is not None and restored.round == 3
    for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(a, b)
    # resume through the ordinary ctor path sees the async-written file
    cfg2 = _config(max_iteration=5, checkpoint_async=True,
                   resume_from_checkpoint=True)
    s2 = OptimizationServer(task, cfg2, synth_dataset,
                            val_dataset=synth_dataset,
                            model_dir=d, mesh=mesh8, seed=6)
    assert s2.state.round == 3
    assert s2.train().round == 5


def test_orbax_async_checkpoint_backend(synth_dataset, mesh8, tmp_path):
    """server_config.checkpoint_backend: orbax — async saves land durable
    checkpoints and resume restores the exact state, like msgpack."""
    import os
    import jax

    cfg = _config(max_iteration=3)
    cfg.server_config["checkpoint_backend"] = "orbax"
    task = make_task(cfg.model_config)
    server = OptimizationServer(task, cfg, synth_dataset,
                                val_dataset=synth_dataset,
                                model_dir=str(tmp_path), mesh=mesh8, seed=0)
    state = server.train()
    # two-slot latest: pointer file names the committed slot directory
    # and (since the resilience PR) records its tree checksum
    import json as _json
    ptr = _json.loads((tmp_path / "latest_model.orbax.ptr").read_text())
    assert os.path.isdir(tmp_path / ptr["slot"])
    assert ptr["crc32"]
    assert any(n.startswith("best_val_") and n.endswith(".orbax")
               for n in os.listdir(tmp_path))

    # resume: fresh server restores round + params, and — crucially —
    # TRAINS on, which requires the optax namedtuple structure (not a
    # plain state-dict) to have been reconstructed
    cfg2 = _config(max_iteration=5)
    cfg2.server_config["checkpoint_backend"] = "orbax"
    cfg2.server_config["resume_from_checkpoint"] = True
    server2 = OptimizationServer(task, cfg2, synth_dataset,
                                 val_dataset=synth_dataset,
                                 model_dir=str(tmp_path), mesh=mesh8, seed=0)
    assert server2.state.round == 3
    for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(server2.state.params))):
        np.testing.assert_array_equal(a, b)
    assert server2.train().round == 5

    # warm-start from an orbax checkpoint directory (pretrained_model_path
    # accepts either backend's output)
    from msrflute_tpu.engine.checkpoint import load_pretrained_params
    best_dir = next(str(tmp_path / n) for n in os.listdir(tmp_path)
                    if n.startswith("best_val_") and n.endswith(".orbax"))
    warm = load_pretrained_params(best_dir, server2.state.params)
    assert jax.tree.structure(warm) == jax.tree.structure(
        jax.device_get(server2.state.params))
