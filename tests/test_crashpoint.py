"""Crash-point fuzzing (flutearmor leg 3), tier-1 slice.

``tools/crashpoint.py`` intercepts the atomic-commit syscalls
(``os.replace`` / ``os.rename`` / ``os.link``) under one model dir,
kills the run with a ``BaseException`` at a chosen commit index, then
relaunches with ``resume_from_checkpoint`` and asserts the finished
params are bit-identical to an uninterrupted run.  CI runs the FULL
kill matrix (every commit, serial and depth-3); this file keeps a
representative slice inside tier-1's budget: the first commit (death
before ANY durable state), a mid-sequence row spill, a point inside the
two-slot ``latest`` rotation, and the final ``status_log`` commit.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

from crashpoint import CrashPoint, KillSwitch, fuzz  # noqa: E402


def test_killswitch_census_sees_every_durable_sequence(tmp_path):
    """The interception layer itself: a census run counts commits only
    under the armed scope and logs the op census the fuzzer enumerates
    — row spills + marker, latest rotation, sidecars, status log."""
    rec = fuzz(depth=0, rounds=3, kill_points=[], verbose=False,
               workdir=str(tmp_path))
    assert rec["points_fuzzed"] == 0
    census = rec["census"]
    assert rec["durable_ops"] == len(census) > 10
    joined = "\n".join(census)
    for needle in ("fleet_carry/row_", "fleet_carry/fleet_round.npy",
                   "latest_model.msgpack", "latest_model.msgpack.sum",
                   "link:latest_model.msgpack.prev.lnk",
                   "status_log.json"):
        assert needle in joined, f"census missing {needle}:\n{joined}"


def test_crashpoint_is_uncatchable_by_retry_ladders():
    """CrashPoint must ride through ``except Exception`` — the whole
    point of modelling a kill, not an IO error."""
    assert issubclass(CrashPoint, BaseException)
    assert not issubclass(CrashPoint, Exception)

    from msrflute_tpu.resilience.integrity import (DurableIOLadder,
                                                   RetryPolicy)
    calls = {"n": 0}

    def die():
        calls["n"] += 1
        raise CrashPoint("kill")

    ladder = DurableIOLadder(
        policy=RetryPolicy(retries=3, backoff_base_s=0.0, jitter=0.0))
    with pytest.raises(CrashPoint):
        ladder.run(die, surface="store_write", what="crashpoint-probe")
    assert calls["n"] == 1  # no retry consumed the kill


def test_kill_matrix_slice_serial_resumes_bit_identical(tmp_path):
    """Serial loop: kill before the FIRST commit (no durable state at
    all — resume must cold-start), inside the latest rotation, and at
    the final status-log commit; every point resumes bit-identical."""
    rec = fuzz(depth=0, rounds=3, kill_points=[0, 12, 31],
               verbose=False, workdir=str(tmp_path))
    assert rec["points_fuzzed"] == 3  # fuzz() asserts parity per point


def test_kill_matrix_slice_pipelined_resumes_bit_identical(tmp_path):
    """Depth-3 ring: same contract with the pipelined loop's commit
    interleaving — one early spill, one mid-matrix point, post-phase
    kill (commit landed, process state lost) on the last commit."""
    rec = fuzz(depth=3, rounds=3, kill_points=[1, 15], verbose=False,
               workdir=str(tmp_path))
    assert rec["points_fuzzed"] == 2
    last = rec["durable_ops"] - 1
    rec_post = fuzz(depth=3, rounds=3, phase="post", kill_points=[last],
                    verbose=False, workdir=str(tmp_path / "post"))
    assert rec_post["points_fuzzed"] == 1
