"""Lazy hdf5-backed dataset — the "millions of clients" scale path.

A federated round only touches its sampled clients, so sample IO and
featurization must be on-demand (reference scale claim ``README.md:9``;
the reference itself caches the full dataset per worker,
``core/client.py:76-99`` — this is the TPU build doing better).  Checks:
array parity with the eager loader, bounded LRU, IO-free scrubbing, engine
round equivalence eager-vs-lazy, and the config wiring.
"""

import numpy as np
import pytest

from msrflute_tpu.data.dataset import ArraysDataset, LazyUserDataset, \
    scrub_empty_clients
from msrflute_tpu.data.user_blob import (LazyHDF5Users, UserBlob,
                                         load_user_blob,
                                         save_user_blob_hdf5)


def _write_blob(path, n_users=6, dim=8, empty=()):
    rng = np.random.default_rng(0)
    users, counts, data, labels = [], [], [], []
    for u in range(n_users):
        n = 0 if u in empty else int(rng.integers(3, 9))
        users.append(f"u{u}")
        counts.append(n)
        data.append(rng.normal(size=(n, dim)).astype(np.float64))
        labels.append(rng.integers(0, 4, size=(n,)).astype(np.int64))
    blob = UserBlob(user_list=users, num_samples=counts, user_data=data,
                    user_labels=labels)
    save_user_blob_hdf5(str(path), blob)
    return blob


def test_lazy_matches_eager(tmp_path):
    p = tmp_path / "blob.hdf5"
    _write_blob(p)
    eager = load_user_blob(str(p))
    lazy = LazyUserDataset(LazyHDF5Users(str(p)))
    assert lazy.user_list == eager.user_list
    assert lazy.num_samples == eager.num_samples
    for i in range(len(lazy)):
        arrays = lazy.user_arrays(i)
        np.testing.assert_allclose(
            arrays["x"], np.asarray(eager.user_data[i], np.float32),
            rtol=1e-6)
        np.testing.assert_array_equal(
            arrays["y"], np.asarray(eager.user_labels[i], np.int32))
        assert arrays["x"].dtype == np.float32
        assert arrays["y"].dtype == np.int32


def test_lru_bounded_and_cached(tmp_path):
    p = tmp_path / "blob.hdf5"
    _write_blob(p)
    users = LazyHDF5Users(str(p))
    reads = []
    orig = users.read
    users.read = lambda u: (reads.append(u) or orig(u))
    ds = LazyUserDataset(users, cache_users=2)
    for i in (0, 1, 2, 3):
        ds.user_arrays(i)
    assert len(ds._cache) == 2
    ds.user_arrays(3)                       # cached: no new read
    assert reads == ["u0", "u1", "u2", "u3"]
    ds.user_arrays(0)                       # evicted: re-read
    assert reads[-1] == "u0"


def test_scrub_is_io_free(tmp_path):
    p = tmp_path / "blob.hdf5"
    _write_blob(p, empty=(1, 4))
    users = LazyHDF5Users(str(p))
    reads = []
    orig = users.read
    users.read = lambda u: (reads.append(u) or orig(u))
    ds = scrub_empty_clients(LazyUserDataset(users))
    assert reads == []                      # subset view, no sample IO
    assert ds.user_list == ["u0", "u2", "u3", "u5"]
    assert all(n > 0 for n in ds.num_samples)
    assert ds.user_arrays(1)["x"].shape[0] == ds.num_samples[1]


def test_engine_round_equivalence(tmp_path, mesh8):
    """Two federated rounds on the lazy dataset == the same rounds on the
    eager ArraysDataset (bit-equal final params)."""
    from jax.flatten_util import ravel_pytree

    from msrflute_tpu.config import FLUTEConfig
    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task

    p = tmp_path / "blob.hdf5"
    _write_blob(p, n_users=8)
    lazy = LazyUserDataset(LazyHDF5Users(str(p)))
    eager = ArraysDataset(lazy.user_list,
                          [lazy.user_arrays(i) for i in range(len(lazy))],
                          lazy.num_samples)
    cfg_raw = {
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "fedavg",
        "server_config": {
            "max_iteration": 2, "num_clients_per_iteration": 4,
            "initial_lr_client": 0.5,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": 100, "initial_val": False,
            "data_config": {"val": {"batch_size": 8}},
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.5},
            "data_config": {"train": {"batch_size": 4}},
        },
    }

    def run(ds, tmp):
        cfg = FLUTEConfig.from_dict(cfg_raw)
        task = make_task(cfg.model_config)
        server = OptimizationServer(task, cfg, ds, model_dir=str(tmp),
                                    mesh=mesh8, seed=3)
        return ravel_pytree(server.train().params)[0]

    flat_lazy = run(lazy, tmp_path / "m1")
    flat_eager = run(eager, tmp_path / "m2")
    np.testing.assert_array_equal(np.asarray(flat_lazy),
                                  np.asarray(flat_eager))


def test_config_wiring(tmp_path):
    from msrflute_tpu.config import FLUTEConfig
    from msrflute_tpu.models import make_task
    from msrflute_tpu.tasks import build_task_datasets

    p = tmp_path / "blob.hdf5"
    _write_blob(p, empty=(2,))
    cfg = FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "fedavg",
        "server_config": {"max_iteration": 1,
                          "num_clients_per_iteration": 2,
                          "initial_lr_client": 0.1,
                          "optimizer_config": {"type": "sgd", "lr": 1.0},
                          "data_config": {"val": {"batch_size": 4}}},
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.1},
            "data_config": {"train": {
                "list_of_train_data": str(p), "batch_size": 4,
                "lazy": True, "lazy_cache_users": 4}}},
    })
    task = make_task(cfg.model_config)
    train, val, test = build_task_datasets(cfg, task)
    assert isinstance(train, LazyUserDataset)
    assert "u2" not in train.user_list      # scrubbed
    assert train._cache_users == 4
    # the CV per-user featurizer ran on access (image reshape + int32 y)
    arrays = train.user_arrays(0)
    assert arrays["x"].shape[1:] == (8,) and arrays["y"].dtype == np.int32

    # whole-blob-featurizer tasks without a per-user hook must reject lazy
    cfg.model_config["model_type"] = "GRU"
    cfg.model_config["vocab_size"] = 32
    gru_task = make_task(cfg.model_config)
    if getattr(gru_task, "make_dataset", None) is not None and \
            getattr(gru_task, "featurize_user", None) is None:
        with pytest.raises(ValueError, match="featurize"):
            build_task_datasets(cfg, gru_task)

    # lazy over a json blob is a config error
    cfg.model_config["model_type"] = "LR"
    cfg.client_config.data_config.train["list_of_train_data"] = "x.json"
    with pytest.raises(ValueError, match="hdf5"):
        build_task_datasets(cfg, make_task(cfg.model_config))
