"""msrflute_tpu — a TPU-native federated-learning simulation framework.

A brand-new, single-controller JAX/XLA framework with the capabilities of
microsoft/msrflute (FLUTE): large-scale federated-learning simulation with
per-client local SGD producing pseudo-gradients, weighted server-side
aggregation (FedAvg / FedProx / DGA / FedLabels), differential privacy with
RDP accounting, gradient quantization, personalization, checkpoint/resume and
a plugin model/dataset zoo.

Architecture (contrast with the reference, see SURVEY.md):

- FLUTE runs one Server process (rank 0) and N-1 Worker processes that
  exchange tensors through a hand-rolled opcode protocol over
  ``torch.distributed`` P2P (reference ``core/federated.py:20-145``).
  Here there is **no message protocol at all**: a round is a single jitted
  SPMD program over a ``jax.sharding.Mesh``.  The round's sampled clients
  are a leading array axis sharded over the mesh's ``clients`` axis; the
  per-client local-SGD loop is a ``lax.scan``; client parallelism is
  ``vmap`` inside ``shard_map``; aggregation is a weighted ``psum`` riding
  ICI/DCN instead of NCCL sends.
- The Python controller keeps only host-side orchestration: client
  sampling, data staging, checkpointing, logging, LR plateau decisions —
  exactly the data-dependent parts FLUTE also keeps out of its hot loop.

Package map:

- :mod:`msrflute_tpu.config`      — typed config tree + schema validation
  (parity with reference ``core/config.py`` / ``core/schema.py``).
- :mod:`msrflute_tpu.data`        — user-blob datasets (json/hdf5), padded
  fixed-shape batching (replaces torch DataLoaders + DynamicBatchSampler).
- :mod:`msrflute_tpu.models`      — flax model zoo + ``BaseTask`` contract
  (parity with ``core/model.py`` + ``experiments/*/model.py``).
- :mod:`msrflute_tpu.engine`      — client update fn, round engine, eval,
  checkpointing (parity with ``core/client.py``, ``core/server.py``,
  ``core/trainer.py``, ``core/evaluation.py``).
- :mod:`msrflute_tpu.strategies`  — FedAvg / DGA / FedLabels aggregators
  (parity with ``core/strategies/``).
- :mod:`msrflute_tpu.privacy`     — DP mechanisms, RDP accountant, attack
  metrics (parity with ``extensions/privacy``).
- :mod:`msrflute_tpu.ops`         — quantization & fused kernels (Pallas)
  (parity with ``extensions/quantization``).
- :mod:`msrflute_tpu.optim`       — optimizer / LR-scheduler factories
  (parity with ``utils/utils.py:27-224`` + ``utils/optimizers/``).
- :mod:`msrflute_tpu.parallel`    — mesh construction, sharding specs,
  collective helpers (replaces ``core/federated.py``).
- :mod:`msrflute_tpu.rl`          — RL meta-aggregator (parity with
  ``extensions/RL``).
"""

__version__ = "0.1.0"

# Tunnel-claim guardrail: in agent shells, importing the framework with the
# ambient axon env (instead of the sanctioned CPU env or a queue job) fails
# fast, BEFORE anything can dial the single-client TPU relay.  No-op for the
# round driver and human operators.  ``_guard`` is a leaf module (os-only)
# so no other package code — and no module-level jax import anywhere in the
# tree — can initialize a backend before this check runs.
from msrflute_tpu._guard import guard_tunnel_claim as _guard_tunnel_claim

_guard_tunnel_claim()
del _guard_tunnel_claim
