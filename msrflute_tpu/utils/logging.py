"""Logging & metric emission.

Parity target: reference ``utils/utils.py:299-332`` (``init_logging``,
timestamped ``print_rank``) and the AzureML ``run.log`` channel
(``core/server.py:43-44``).  The TPU build replaces AzureML with a JSONL
metric writer plus structured event records — both of which now live in
:mod:`msrflute_tpu.telemetry.metrics` (flutescope owns the run's
observability surface); this module keeps the historical import path
(``log_metric``/``flush_metrics``) as re-exports and the plain logger
setup.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

# canonical implementations live under telemetry/ — re-exported here so
# the dozens of existing call sites (and plugins) keep importing from
# utils.logging unchanged
from ..telemetry.metrics import (flush_metrics, log_event,  # noqa: F401
                                 log_metric)

_LOGGER = logging.getLogger("msrflute_tpu")


def init_logging(log_dir: Optional[str] = None, loglevel: int = logging.INFO) -> None:
    """File + stdout logging (reference ``utils/utils.py:299-307``), and a
    ``metrics.jsonl`` writer in place of AzureML ``run.log``."""
    from ..telemetry.metrics import open_metrics

    handlers: list = [logging.StreamHandler()]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        handlers.append(logging.FileHandler(os.path.join(log_dir, "log.out")))
        open_metrics(log_dir)
    logging.basicConfig(
        level=loglevel,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        handlers=handlers,
        force=True,
    )


def print_rank(msg: str, loglevel: int = logging.INFO) -> None:
    """Timestamped log line (reference ``utils/utils.py:311-322``; the rank
    prefix is moot in a single-controller design — we tag the process id of
    the controller instead when running multi-host)."""
    pid = os.environ.get("JAX_PROCESS_INDEX", "0")
    _LOGGER.log(loglevel, "p%s: %s", pid, msg)
