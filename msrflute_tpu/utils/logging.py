"""Logging & metric emission.

Parity target: reference ``utils/utils.py:299-332`` (``init_logging``,
timestamped ``print_rank``) and the AzureML ``run.log`` channel
(``core/server.py:43-44``).  The TPU build replaces AzureML with a JSONL
metric writer (one line per scalar) plus optional TensorBoard if available;
both are observable offline.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional

_LOGGER = logging.getLogger("msrflute_tpu")
_METRICS_FH = None


def init_logging(log_dir: Optional[str] = None, loglevel: int = logging.INFO) -> None:
    """File + stdout logging (reference ``utils/utils.py:299-307``), and a
    ``metrics.jsonl`` writer in place of AzureML ``run.log``."""
    global _METRICS_FH
    handlers: list = [logging.StreamHandler()]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        handlers.append(logging.FileHandler(os.path.join(log_dir, "log.out")))
        _METRICS_FH = open(os.path.join(log_dir, "metrics.jsonl"), "a")
    logging.basicConfig(
        level=loglevel,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        handlers=handlers,
        force=True,
    )


def print_rank(msg: str, loglevel: int = logging.INFO) -> None:
    """Timestamped log line (reference ``utils/utils.py:311-322``; the rank
    prefix is moot in a single-controller design — we tag the process id of
    the controller instead when running multi-host)."""
    pid = os.environ.get("JAX_PROCESS_INDEX", "0")
    _LOGGER.log(loglevel, "p%s: %s", pid, msg)


def log_metric(name: str, value: Any, step: Optional[int] = None,
               extra: Optional[Dict[str, Any]] = None) -> None:
    """Scalar metric emission (replaces AzureML ``run.log`` at reference
    ``core/server.py:261-264,523-525``)."""
    record = {"ts": time.time(), "name": name, "value": _to_py(value)}
    if step is not None:
        record["step"] = step
    if extra:
        record.update(extra)
    if _METRICS_FH is not None:
        _METRICS_FH.write(json.dumps(record) + "\n")
        _METRICS_FH.flush()
    _LOGGER.info("metric %s=%s%s", name, record["value"],
                 f" @ {step}" if step is not None else "")


def _to_py(value: Any) -> Any:
    try:
        import numpy as np
        if isinstance(value, (np.generic,)):
            return value.item()
        if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
            return value.item()
    except Exception:
        pass
    return value
