"""Logging & metric emission.

Parity target: reference ``utils/utils.py:299-332`` (``init_logging``,
timestamped ``print_rank``) and the AzureML ``run.log`` channel
(``core/server.py:43-44``).  The TPU build replaces AzureML with a JSONL
metric writer (one line per scalar) plus optional TensorBoard if available;
both are observable offline.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional

_LOGGER = logging.getLogger("msrflute_tpu")
_METRICS_FH = None
#: seconds between forced metrics-stream flushes; between them lines sit
#: in the file buffer (the server also flushes at every round-housekeeping
#: boundary and at train() exit, so round granularity is never lost)
_FLUSH_INTERVAL_SECS = 1.0
_LAST_FLUSH = 0.0


def init_logging(log_dir: Optional[str] = None, loglevel: int = logging.INFO) -> None:
    """File + stdout logging (reference ``utils/utils.py:299-307``), and a
    ``metrics.jsonl`` writer in place of AzureML ``run.log``."""
    global _METRICS_FH
    handlers: list = [logging.StreamHandler()]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        handlers.append(logging.FileHandler(os.path.join(log_dir, "log.out")))
        _METRICS_FH = open(os.path.join(log_dir, "metrics.jsonl"), "a")
        # buffered lines must still land if the process exits without a
        # final explicit flush (e.g. a CLI run killed between rounds)
        import atexit
        atexit.register(flush_metrics)
    logging.basicConfig(
        level=loglevel,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        handlers=handlers,
        force=True,
    )


def print_rank(msg: str, loglevel: int = logging.INFO) -> None:
    """Timestamped log line (reference ``utils/utils.py:311-322``; the rank
    prefix is moot in a single-controller design — we tag the process id of
    the controller instead when running multi-host)."""
    pid = os.environ.get("JAX_PROCESS_INDEX", "0")
    _LOGGER.log(loglevel, "p%s: %s", pid, msg)


def log_metric(name: str, value: Any, step: Optional[int] = None,
               extra: Optional[Dict[str, Any]] = None) -> None:
    """Scalar metric emission (replaces AzureML ``run.log`` at reference
    ``core/server.py:261-264,523-525``).

    Writes are BUFFERED: a flush-per-line put one syscall per scalar on
    the server's host tail (~6+ per round); lines now flush on a
    time-based cadence plus the explicit :func:`flush_metrics` points
    (round housekeeping, train exit, process exit).
    """
    global _LAST_FLUSH
    record = {"ts": time.time(), "name": name, "value": _to_py(value)}
    if step is not None:
        record["step"] = step
    if extra:
        record.update(extra)
    if _METRICS_FH is not None:
        _METRICS_FH.write(json.dumps(record) + "\n")
        if record["ts"] - _LAST_FLUSH >= _FLUSH_INTERVAL_SECS:
            _METRICS_FH.flush()
            _LAST_FLUSH = record["ts"]
    _LOGGER.info("metric %s=%s%s", name, record["value"],
                 f" @ {step}" if step is not None else "")


def flush_metrics() -> None:
    """Force buffered metric lines to disk (no-op without a writer)."""
    global _LAST_FLUSH
    if _METRICS_FH is not None:
        _METRICS_FH.flush()
        _LAST_FLUSH = time.time()


def _to_py(value: Any) -> Any:
    try:
        import numpy as np
        if isinstance(value, (np.generic,)):
            return value.item()
        if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
            return value.item()
    except Exception:
        pass
    return value
