from .logging import init_logging, print_rank, log_metric  # noqa: F401
from .metrics import Metric, MetricsDict, weighted_merge  # noqa: F401
from .io import try_except_save, update_json_log, write_yaml  # noqa: F401
from .strict import (strict_transfer_scope,  # noqa: F401
                     strict_transfers_enabled)
