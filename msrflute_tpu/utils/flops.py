"""Static per-op-type FLOP decomposition of a jitted function.

Chip-independent profiling support (SURVEY §5.1): XLA's
``compiled.cost_analysis()`` reports one aggregate FLOP number, which
says nothing about WHERE the FLOPs are.  This walks the function's
jaxpr — recursing through pjit/custom-vjp sub-jaxprs and multiplying
through ``scan`` trip counts — and buckets exact FLOP counts by op
class:

- ``dot``: ``dot_general`` (2·batch·M·N·K from the dimension numbers)
- ``conv``: ``conv_general_dilated``
  (2·|out|·in_ch_per_group·prod(kernel_spatial))
- ``elementwise``: unary/binary/ternary VPU ops, |out| each
- ``other``: everything else with an array output, |out| each
  (gather/scatter/reduce bookkeeping — not MXU work)

``cond`` branches are counted optimistically (max over branches) and
``while`` bodies cannot be counted statically (trip count unknown) —
both are surfaced in the result so a consumer knows when the counts are
approximate.  Used by ``tools/profile_round.py`` to show the headline
round is MXU-bound (conv+dot share) without needing the chip.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax
from jax.extend import core as jax_core

_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "pow", "max", "min", "rem",
    "neg", "abs", "sign", "floor", "ceil", "round",
    "exp", "log", "log1p", "expm1", "tanh", "logistic", "erf",
    "sqrt", "rsqrt", "cbrt", "sin", "cos", "tan",
    "integer_pow", "select_n", "clamp", "nextafter",
    "and", "or", "xor", "not",
    "eq", "ne", "lt", "le", "gt", "ge", "is_finite",
})

#: reduction primitives: roughly one op per INPUT element
_REDUCTIONS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin",
    "cumsum", "cumprod", "cummax", "cummin",
})


def _size(aval) -> float:
    shape = getattr(aval, "shape", ())
    return float(np.prod(shape)) if shape else 1.0


def _dot_flops(eqn) -> float:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    batch = float(np.prod([lhs.shape[i] for i in lb])) if lb else 1.0
    k = float(np.prod([lhs.shape[i] for i in lc])) if lc else 1.0
    m = float(np.prod([lhs.shape[i] for i in range(len(lhs.shape))
                       if i not in set(lc) | set(lb)]))
    n = float(np.prod([rhs.shape[i] for i in range(len(rhs.shape))
                       if i not in set(rc) | set(_rb)]))
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval  # kernel
    dn = eqn.params["dimension_numbers"]
    out_ch = float(rhs.shape[dn.rhs_spec[0]])
    kernel_elems = float(np.prod(rhs.shape))
    # per output element: one MAC per (in_ch/groups x kernel_spatial) tap
    return 2.0 * _size(out) * kernel_elems / max(out_ch, 1.0)


def _sub_jaxprs(value):
    if isinstance(value, jax_core.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, jax_core.Jaxpr):
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v)


def flops_by_op(fn, *args, **kwargs) -> Dict[str, Any]:
    """Trace ``fn(*args, **kwargs)`` and return FLOPs bucketed by op class
    plus ``total`` and share fractions.  Exact for dot/conv/elementwise
    under scans; ``approximate`` is True when cond/while made the count a
    bound rather than an identity."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    flags = {"approximate": False}

    def visit(jaxpr, mult: float, buckets) -> float:
        """Accumulate into ``buckets``; returns the subtree total (always
        equal to the sum of what this call added to ``buckets``, so
        shares stay consistent even through cond's max-branch rule)."""
        total = 0.0
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "scan":
                body = eqn.params["jaxpr"]
                total += visit(body.jaxpr,
                               mult * float(eqn.params["length"]), buckets)
                continue
            if prim == "cond":
                # count only the most expensive branch, in buckets AND in
                # total — each branch tallies into its own scratch dict
                # and only the max branch's is merged, or the shares'
                # denominator would drift from the bucket sum
                flags["approximate"] = True
                best_total, best_buckets = 0.0, None
                for b in eqn.params["branches"]:
                    scratch = {k: 0.0 for k in buckets}
                    t = visit(b.jaxpr, mult, scratch)
                    if best_buckets is None or t > best_total:
                        best_total, best_buckets = t, scratch
                for k, v in (best_buckets or {}).items():
                    buckets[k] += v
                total += best_total
                continue
            if prim == "while":
                flags["approximate"] = True  # trip count is dynamic
                for key in ("body_jaxpr", "cond_jaxpr"):
                    for sub in _sub_jaxprs(eqn.params.get(key)):
                        total += visit(sub, mult, buckets)
                continue
            sub_found = False
            for value in eqn.params.values():
                for sub in _sub_jaxprs(value):
                    total += visit(sub, mult, buckets)
                    sub_found = True
            if sub_found:
                continue  # pjit/remat/custom_vjp wrapper: body counted
            if prim == "dot_general":
                f = _dot_flops(eqn) * mult
                buckets["dot"] += f
            elif prim == "conv_general_dilated":
                f = _conv_flops(eqn) * mult
                buckets["conv"] += f
            elif prim in _ELEMENTWISE:
                f = _size(eqn.outvars[0].aval) * mult
                buckets["elementwise"] += f
            elif prim in _REDUCTIONS:
                f = _size(eqn.invars[0].aval) * mult
                buckets["other"] += f
            elif eqn.outvars and getattr(eqn.outvars[0].aval, "shape", None) \
                    is not None:
                # data movement (gather, transpose, pad, ...): count |out|
                # into "other" so the share denominators stay honest
                f = _size(eqn.outvars[0].aval) * mult
                buckets["other"] += f
            else:
                f = 0.0
            total += f
        return total

    buckets = {"dot": 0.0, "conv": 0.0, "elementwise": 0.0, "other": 0.0}
    total = visit(closed.jaxpr, 1.0, buckets)
    out: Dict[str, Any] = dict(buckets)
    out["total"] = total
    out["approximate"] = flags["approximate"]
    mxu = buckets["dot"] + buckets["conv"]
    out["mxu_share"] = round(mxu / total, 4) if total else 0.0
    for k in ("dot", "conv", "elementwise", "other"):
        out[f"{k}_share"] = round(buckets[k] / total, 4) if total else 0.0
    return out
