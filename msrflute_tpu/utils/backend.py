"""Backend insulation for a single-client TPU tunnel.

The chip here is reached through an exclusive-claim relay that can fail fast
OR hang on init, and a SIGKILLed claim wedges it for every later process —
so CPU-only codepaths (tests, dryruns, bench fallback) must keep jax from
ever initializing the TPU plugin.  This is the one shared implementation of
that discipline (used by ``tests/conftest.py``-style setups, ``bench.py``
and ``__graft_entry__.py``).
"""

from __future__ import annotations

import os
import re
from typing import Optional


def force_cpu_backend(n_devices: Optional[int] = None) -> None:
    """Configure this process for a (virtual) CPU mesh before first backend
    init: drop the TPU relay env, force ``jax_platforms=cpu`` (env var AND
    config — a sitecustomize may have imported jax already), and optionally
    request ``n_devices`` virtual host devices.

    Must run before anything triggers jax backend initialization; after
    that, XLA_FLAGS changes are ignored.
    """
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        # replace any pre-existing count unless it already suffices —
        # a smaller ambient value would bring up too few devices
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m and int(m.group(1)) >= n_devices:
            pass
        else:
            if m:
                flags = flags.replace(m.group(0), "")
            os.environ["XLA_FLAGS"] = (
                flags.strip() +
                f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


# Canonical home of the tunnel-claim guardrail is the leaf module
# ``msrflute_tpu._guard`` (so the root __init__ can run it before any other
# package code); re-exported here next to its sibling backend disciplines.
from msrflute_tpu._guard import guard_tunnel_claim  # noqa: F401


def enable_compilation_cache(cache_dir: str) -> bool:
    """Turn on jax's persistent XLA compilation cache (best-effort: an
    unwritable path must not abort a training run — it only forfeits the
    warm-start).  Returns whether it was enabled."""
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        return True
    except Exception:
        return False
