"""Dtype-grouped pytree flattening for dispatch-boundary packing.

On the remote-attached chip, per-dispatch overhead scales with the
argument/result BUFFER count (measured: the fuse=1 LR round dispatches in
~88 ms against a 0.14 ms trivial-op floor; `tools/dispatch_cost_probe.py`
pins the per-buffer cost).  A ResNet server state is ~100+ leaves; packed
it is one buffer per distinct dtype (usually 1-3).

Why not ``jax.flatten_util.ravel_pytree``: it promotes mixed dtypes to a
common dtype, which corrupts uint32 PRNG keys and large int32 counters
when the common type is floating.  Here leaves are grouped BY DTYPE and
concatenated raveled within each group — the round-trip is bit-exact for
every dtype, and inside jit the pack/unpack lowers to pure
reshape/slice/concat that XLA fuses away.

Usage::

    packer = build_packer(template_tree)
    vecs = packer.pack(tree)      # {dtype_str: 1-D array}, jit-safe
    tree2 = packer.unpack(vecs)   # original structure, bit-identical

The packer is built once from a template (shapes/dtypes must match later
trees — the jit retrace guard the engine already lives by).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class FlatPacker:
    """Pack/unpack a fixed-structure pytree into one 1-D array per dtype."""

    def __init__(self, template: Any):
        leaves, treedef = jax.tree.flatten(template)
        self.treedef = treedef
        #: per-leaf (dtype_str, offset, size, shape) in flatten order
        self._slots: List[Tuple[str, int, int, Tuple[int, ...]]] = []
        sizes: Dict[str, int] = {}
        for leaf in leaves:
            # jnp.asarray, not np: python scalars must get the same dtype
            # (int32/float32 under default jax config) that jnp.ravel will
            # produce at pack time, or the group keys/sizes are mislabeled
            arr = leaf if hasattr(leaf, "dtype") else jnp.asarray(leaf)
            dt = str(arr.dtype)
            size = int(np.prod(arr.shape)) if arr.shape else 1
            off = sizes.get(dt, 0)
            self._slots.append((dt, off, size, tuple(arr.shape)))
            sizes[dt] = off + size
        self.sizes = sizes  # {dtype_str: total elements}

    def pack(self, tree: Any) -> Dict[str, jnp.ndarray]:
        """One 1-D array per dtype, concatenated in flatten order."""
        leaves, treedef = jax.tree.flatten(tree)
        if len(leaves) != len(self._slots):
            raise ValueError(
                f"tree has {len(leaves)} leaves, packer built for "
                f"{len(self._slots)}")
        if treedef != self.treedef:
            raise ValueError(
                f"tree structure {treedef} != packer template "
                f"{self.treedef}")
        groups: Dict[str, list] = {}
        for leaf, (dt, _, _, shape) in zip(leaves, self._slots):
            leaf = leaf if hasattr(leaf, "dtype") else jnp.asarray(leaf)
            if tuple(leaf.shape) != shape:
                raise ValueError(
                    f"leaf shape {tuple(leaf.shape)} != packer "
                    f"template shape {shape}")
            if str(leaf.dtype) != dt:
                # a drifted dtype would silently promote its whole group
                # through jnp.concatenate — the exact corruption this
                # module exists to prevent
                raise ValueError(
                    f"leaf dtype {leaf.dtype} != packer template dtype {dt}")
            groups.setdefault(dt, []).append(jnp.ravel(leaf))
        return {dt: (jnp.concatenate(parts) if len(parts) > 1 else parts[0])
                for dt, parts in groups.items()}

    def unpack(self, vecs: Dict[str, jnp.ndarray]) -> Any:
        """Inverse of :meth:`pack` — bit-identical leaves, original tree."""
        leaves = []
        for dt, off, size, shape in self._slots:
            part = vecs[dt][off:off + size]  # static slice — XLA fuses it
            leaves.append(jnp.reshape(part, shape))
        return jax.tree.unflatten(self.treedef, leaves)

    def unpack_np(self, vecs: Dict[str, np.ndarray]) -> Any:
        """Host-side inverse of :meth:`pack` over already-fetched numpy
        buffers — pure views/reshapes, no device round-trip (the decode
        half of the one-transfer-per-round stats contract)."""
        leaves = []
        for dt, off, size, shape in self._slots:
            part = np.asarray(vecs[dt])[off:off + size]
            leaves.append(part.reshape(shape))
        return jax.tree.unflatten(self.treedef, leaves)

    def unpack_np_stacked(self, vecs: Dict[str, np.ndarray]) -> Any:
        """Like :meth:`unpack_np` but for buffers with a leading stack
        axis (``[R, n]``, e.g. a scanned multi-round program's per-round
        packed stats): each leaf comes back as ``[R, *slot_shape]``."""
        leaves = []
        for dt, off, size, shape in self._slots:
            arr = np.asarray(vecs[dt])
            leaves.append(arr[:, off:off + size].reshape(
                (arr.shape[0],) + shape))
        return jax.tree.unflatten(self.treedef, leaves)


def build_packer(template: Any) -> FlatPacker:
    return FlatPacker(template)
