"""Dtype-grouped pytree flattening for dispatch-boundary packing.

On the remote-attached chip, per-dispatch overhead scales with the
argument/result BUFFER count (measured: the fuse=1 LR round dispatches in
~88 ms against a 0.14 ms trivial-op floor; `tools/dispatch_cost_probe.py`
pins the per-buffer cost).  A ResNet server state is ~100+ leaves; packed
it is one buffer per distinct dtype (usually 1-3).

Why not ``jax.flatten_util.ravel_pytree``: it promotes mixed dtypes to a
common dtype, which corrupts uint32 PRNG keys and large int32 counters
when the common type is floating.  Here leaves are grouped BY DTYPE and
concatenated raveled within each group — the round-trip is bit-exact for
every dtype, and inside jit the pack/unpack lowers to pure
reshape/slice/concat that XLA fuses away.

Usage::

    packer = build_packer(template_tree)
    vecs = packer.pack(tree)      # {dtype_str: 1-D array}, jit-safe
    tree2 = packer.unpack(vecs)   # original structure, bit-identical

The packer is built once from a template (shapes/dtypes must match later
trees — the jit retrace guard the engine already lives by).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class FlatPacker:
    """Pack/unpack a fixed-structure pytree into one 1-D array per dtype."""

    def __init__(self, template: Any):
        leaves, treedef = jax.tree.flatten(template)
        self.treedef = treedef
        #: per-leaf (dtype_str, offset, size, shape) in flatten order
        self._slots: List[Tuple[str, int, int, Tuple[int, ...]]] = []
        sizes: Dict[str, int] = {}
        for leaf in leaves:
            # jnp.asarray, not np: python scalars must get the same dtype
            # (int32/float32 under default jax config) that jnp.ravel will
            # produce at pack time, or the group keys/sizes are mislabeled
            arr = leaf if hasattr(leaf, "dtype") else jnp.asarray(leaf)
            dt = str(arr.dtype)
            size = int(np.prod(arr.shape)) if arr.shape else 1
            off = sizes.get(dt, 0)
            self._slots.append((dt, off, size, tuple(arr.shape)))
            sizes[dt] = off + size
        self.sizes = sizes  # {dtype_str: total elements}

    def pack(self, tree: Any) -> Dict[str, jnp.ndarray]:
        """One 1-D array per dtype, concatenated in flatten order."""
        leaves, treedef = jax.tree.flatten(tree)
        if len(leaves) != len(self._slots):
            raise ValueError(
                f"tree has {len(leaves)} leaves, packer built for "
                f"{len(self._slots)}")
        if treedef != self.treedef:
            raise ValueError(
                f"tree structure {treedef} != packer template "
                f"{self.treedef}")
        groups: Dict[str, list] = {}
        for leaf, (dt, _, _, shape) in zip(leaves, self._slots):
            leaf = leaf if hasattr(leaf, "dtype") else jnp.asarray(leaf)
            if tuple(leaf.shape) != shape:
                raise ValueError(
                    f"leaf shape {tuple(leaf.shape)} != packer "
                    f"template shape {shape}")
            if str(leaf.dtype) != dt:
                # a drifted dtype would silently promote its whole group
                # through jnp.concatenate — the exact corruption this
                # module exists to prevent
                raise ValueError(
                    f"leaf dtype {leaf.dtype} != packer template dtype {dt}")
            groups.setdefault(dt, []).append(jnp.ravel(leaf))
        return {dt: (jnp.concatenate(parts) if len(parts) > 1 else parts[0])
                for dt, parts in groups.items()}

    def unpack(self, vecs: Dict[str, jnp.ndarray]) -> Any:
        """Inverse of :meth:`pack` — bit-identical leaves, original tree."""
        leaves = []
        for dt, off, size, shape in self._slots:
            part = vecs[dt][off:off + size]  # static slice — XLA fuses it
            leaves.append(jnp.reshape(part, shape))
        return jax.tree.unflatten(self.treedef, leaves)

    def unpack_np(self, vecs: Dict[str, np.ndarray]) -> Any:
        """Host-side inverse of :meth:`pack` over already-fetched numpy
        buffers — pure views/reshapes, no device round-trip (the decode
        half of the one-transfer-per-round stats contract)."""
        leaves = []
        for dt, off, size, shape in self._slots:
            part = np.asarray(vecs[dt])[off:off + size]
            leaves.append(part.reshape(shape))
        return jax.tree.unflatten(self.treedef, leaves)

    def unpack_np_stacked(self, vecs: Dict[str, np.ndarray]) -> Any:
        """Like :meth:`unpack_np` but for buffers with a leading stack
        axis (``[R, n]``, e.g. a scanned multi-round program's per-round
        packed stats): each leaf comes back as ``[R, *slot_shape]``."""
        leaves = []
        for dt, off, size, shape in self._slots:
            arr = np.asarray(vecs[dt])
            leaves.append(arr[:, off:off + size].reshape(
                (arr.shape[0],) + shape))
        return jax.tree.unflatten(self.treedef, leaves)


def build_packer(template: Any) -> FlatPacker:
    return FlatPacker(template)


# ----------------------------------------------------------------------
# host->device input staging (the flatpack idea mirrored onto the
# dispatch path): the faithful round used to device_put ~8-10 small host
# arrays per dispatch (masks, ids, lrs, chaos vectors, feature grids) —
# `tools/dispatch_cost_probe.py` measured the per-buffer RPC cost that
# makes that expensive on a remote-attached chip.  These packers collapse
# the staging to ONE host buffer (and one `jax.device_put`) per dtype
# group; the unpack runs INSIDE the jitted round program as static
# slices/reshapes that XLA fuses away, so the math is bit-identical.
# ----------------------------------------------------------------------

def canonical_np(x) -> np.ndarray:
    """Host-side dtype canonicalization matching what ``jax.device_put``
    does under the default x64-disabled config (int64 -> int32,
    float64 -> float32) — packing must group by the dtype the device
    array will actually have, or the slot table mislabels groups."""
    arr = np.asarray(x)
    if arr.dtype == np.int64:
        return arr.astype(np.int32)
    if arr.dtype == np.float64:
        return arr.astype(np.float32)
    if arr.dtype == np.uint64:
        return arr.astype(np.uint32)
    return arr


class AxisPacker:
    """Pack a fixed-structure tree of host arrays that SHARE their leading
    axes (e.g. every per-round operand is ``[K, ...]`` or ``[R, K, ...]``)
    into one ``[*lead, total]`` buffer per dtype.

    Keeping the shared axes intact (instead of raveling to 1-D like
    :class:`FlatPacker`) is what lets the staged buffer carry a clients-
    axis sharding: the round program's inputs stay sharded over the mesh
    while still crossing the host boundary as one transfer per dtype.
    """

    def __init__(self, template: Any, lead_ndim: int):
        self.lead_ndim = int(lead_ndim)
        leaves, treedef = jax.tree.flatten(template)
        self.treedef = treedef
        self.lead_shape = None
        #: per-leaf (dtype_str, offset, trailing_size, trailing_shape)
        self._slots: List[Tuple[str, int, int, Tuple[int, ...]]] = []
        sizes: Dict[str, int] = {}
        for leaf in leaves:
            arr = canonical_np(leaf)
            if arr.ndim < self.lead_ndim:
                raise ValueError(
                    f"AxisPacker leaf has {arr.ndim} dims, needs the "
                    f"{self.lead_ndim} shared leading axes")
            lead = tuple(arr.shape[:self.lead_ndim])
            if self.lead_shape is None:
                self.lead_shape = lead
            elif lead != self.lead_shape:
                raise ValueError(
                    f"AxisPacker leaves disagree on leading axes: "
                    f"{lead} != {self.lead_shape}")
            trailing = tuple(arr.shape[self.lead_ndim:])
            size = int(np.prod(trailing)) if trailing else 1
            dt = str(arr.dtype)
            off = sizes.get(dt, 0)
            self._slots.append((dt, off, size, trailing))
            sizes[dt] = off + size
        self.sizes = sizes

    @property
    def signature(self) -> Tuple:
        """Cache key for jitted unpackers: the full slot table."""
        return (self.lead_ndim, self.lead_shape, tuple(self._slots),
                self.treedef)

    def pack_np(self, tree: Any) -> Dict[str, np.ndarray]:
        """One ``[*lead, total]`` numpy buffer per dtype (host-side —
        the single memcpy that replaces N per-leaf transfers)."""
        leaves, treedef = jax.tree.flatten(tree)
        if treedef != self.treedef or len(leaves) != len(self._slots):
            raise ValueError(
                f"tree structure {treedef} != packer template "
                f"{self.treedef}")
        groups: Dict[str, list] = {}
        for leaf, (dt, _, size, trailing) in zip(leaves, self._slots):
            arr = canonical_np(leaf)
            if tuple(arr.shape[self.lead_ndim:]) != trailing or \
                    tuple(arr.shape[:self.lead_ndim]) != self.lead_shape:
                raise ValueError(
                    f"leaf shape {arr.shape} != packer template "
                    f"{self.lead_shape}+{trailing}")
            if str(arr.dtype) != dt:
                raise ValueError(
                    f"leaf dtype {arr.dtype} != packer template dtype {dt}")
            groups.setdefault(dt, []).append(
                arr.reshape(self.lead_shape + (size,)))
        return {dt: (np.concatenate(parts, axis=-1) if len(parts) > 1
                     else parts[0])
                for dt, parts in groups.items()}

    def unpack(self, vecs: Dict[str, jnp.ndarray]) -> Any:
        """Traced inverse of :meth:`pack_np` — static last-axis slices +
        reshapes, fused away by XLA inside the round program."""
        leaves = []
        for dt, off, size, trailing in self._slots:
            part = vecs[dt][..., off:off + size]
            leaves.append(jnp.reshape(part, self.lead_shape + trailing))
        return jax.tree.unflatten(self.treedef, leaves)


class ScalarStager:
    """FlatPacker + host-side pack for the replicated scalar operands
    (lrs, round indices, thresholds): one tiny 1-D buffer per dtype."""

    def __init__(self, template: Any):
        self.packer = FlatPacker(jax.tree.map(canonical_np, template))

    @property
    def signature(self) -> Tuple:
        return (tuple(self.packer._slots), self.packer.treedef)

    def pack_np(self, tree: Any) -> Dict[str, np.ndarray]:
        leaves, treedef = jax.tree.flatten(jax.tree.map(canonical_np, tree))
        if treedef != self.packer.treedef:
            raise ValueError(
                f"tree structure {treedef} != stager template "
                f"{self.packer.treedef}")
        groups: Dict[str, list] = {}
        for leaf, (dt, _, _, shape) in zip(leaves, self.packer._slots):
            arr = np.asarray(leaf)
            if str(arr.dtype) != dt or tuple(arr.shape) != shape:
                raise ValueError(
                    f"leaf {arr.dtype}{tuple(arr.shape)} != template "
                    f"{dt}{shape}")
            groups.setdefault(dt, []).append(arr.ravel())
        return {dt: (np.concatenate(parts) if len(parts) > 1 else parts[0])
                for dt, parts in groups.items()}

    def unpack(self, vecs: Dict[str, jnp.ndarray]) -> Any:
        return self.packer.unpack(vecs)
