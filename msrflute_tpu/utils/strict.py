"""Strict transfer mode — the runtime half of fluteguard.

``MSRFLUTE_STRICT_TRANSFERS=1`` wraps the server round loop in a
``jax.transfer_guard_device_to_host("disallow")`` scope: every IMPLICIT
device->host sync (``float()``/``int()`` on a device value, ``.item()``,
``np.asarray`` of a device array, stringification for logging) raises
at the offending line, while the sanctioned EXPLICIT fetches
(``jax.device_get`` — the flatpack packed-stats path, eval, the async
checkpoint writer) pass untouched.

This is what keeps the static model honest: fluteguard's host-sync
checker sees one module at a time, so a device value that crosses a
function boundary before being ``float()``ed is invisible to it — but
not to the guard.  Tier-1 runs the pipeline A/B equivalence under this
mode (``tests/test_bench_contract.py``), so "zero implicit syncs per
round" is a tested property, not a review convention.

Only the device->host direction is guarded: host->device staging of
round batches legitimately rides implicit transfers (``jnp.asarray`` on
scalars, jit argument staging), and the expensive direction on a
remote-attached chip is the blocking fetch anyway.

The scope is also thread-local by jax's design — the async checkpoint
writer's explicit fetches on its own thread are unaffected either way.
"""

from __future__ import annotations

import contextlib
import os

ENV_FLAG = "MSRFLUTE_STRICT_TRANSFERS"


def strict_transfers_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


@contextlib.contextmanager
def strict_transfer_scope():
    """Disallow implicit device->host transfers when the env flag is
    set; no-op (and jax-import-free) otherwise."""
    if not strict_transfers_enabled():
        yield
        return
    import jax

    with jax.transfer_guard_device_to_host("disallow"):
        yield
