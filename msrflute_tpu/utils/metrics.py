"""Metric data model.

Parity target: reference metric contract — every metric is
``{'value': float, 'higher_is_better': bool}`` produced by
``model.inference`` (``core/model.py:23-43``, ``core/metrics.py:35-56``),
merged across eval clients by sample-weighted averaging
(``core/evaluation.py:160-183``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Tuple


@dataclass
class Metric:
    value: float
    higher_is_better: bool = True

    def is_better_than(self, other: "Metric") -> bool:
        if self.higher_is_better:
            return self.value > other.value
        return self.value < other.value


MetricsDict = Dict[str, Metric]


def weighted_merge(parts: Iterable[Tuple[float, MetricsDict]]) -> MetricsDict:
    """Sample-weighted average of per-client metric dicts (reference
    ``core/evaluation.py:160-183``: metrics weighted by batch/sample counts)."""
    sums: Dict[str, float] = {}
    hib: Dict[str, bool] = {}
    total = 0.0
    for weight, metrics in parts:
        total += weight
        for name, metric in metrics.items():
            sums[name] = sums.get(name, 0.0) + weight * float(metric.value)
            hib[name] = metric.higher_is_better
    if total <= 0:
        return {}
    return {name: Metric(sums[name] / total, hib[name]) for name in sums}
