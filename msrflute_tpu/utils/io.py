"""Persistence helpers.

Parity target: reference ``utils/utils.py:335-359`` (``torch_save`` /
``try_except_save`` with 3 retries), ``write_yaml``, and
``update_json_log`` (``utils/utils.py:546-560``) used for
``status_log.json``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Dict

import yaml

_LOGGER = logging.getLogger("msrflute_tpu")


def try_except_save(save_fn: Callable[[], None], retries: int = 3,
                    delay_s: float = 1.0) -> bool:
    """Retry a save callable (reference ``utils/utils.py:348-359``).

    Fatal control-flow exceptions (``KeyboardInterrupt``/``SystemExit``)
    always propagate — a Ctrl-C mid-save must kill the process, not burn
    the retry budget.  The checkpoint manager uses the richer
    exponential-backoff policy in
    :mod:`msrflute_tpu.resilience.integrity` instead; this helper stays
    for simple best-effort persistence call sites.
    """
    for attempt in range(retries):
        try:
            save_fn()
            return True
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:  # noqa: BLE001 - deliberate: persist best-effort
            _LOGGER.warning("save attempt %d/%d failed: %s", attempt + 1, retries, exc)
            if attempt < retries - 1:
                time.sleep(delay_s)
    return False


def update_json_log(path: str, update: Dict[str, Any]) -> Dict[str, Any]:
    """Merge ``update`` into a JSON log file (reference
    ``utils/utils.py:546-560``), returning the merged dict.  A ``None``
    value DELETES the key (used to clear one-shot markers like the
    preemption flag once a resumed run completes)."""
    data: Dict[str, Any] = {}
    if os.path.exists(path):
        try:
            with open(path, "r") as fh:
                data = json.load(fh)
        except (json.JSONDecodeError, OSError):
            data = {}
    for key, value in update.items():
        if value is None:
            data.pop(key, None)
        else:
            data[key] = value
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(data, fh, indent=2)
    os.replace(tmp, path)
    return data


def write_yaml(path: str, payload: Dict[str, Any]) -> None:
    with open(path, "w") as fh:
        yaml.safe_dump(payload, fh)
