"""ASR n-best jsonl utilities.

Parity target: reference ``utils/utils.py:362-483`` — helpers used by the
(legacy) ASR tasks to dump n-best hypotheses as a jsonl manifest with
softmax-renormalized per-hypothesis loss weights, and the numerically-stable
``softmax`` helper (``utils/utils.py:78-114``).
"""

from __future__ import annotations

import copy
import json
import logging
from typing import Dict, List, Optional, Sequence

import numpy as np

from .logging import print_rank


def softmax(x: np.ndarray, axis: Optional[int] = None) -> np.ndarray:
    """Stable softmax (reference ``utils/utils.py:78-114``).  Like the
    reference, the default axis is the first NON-singleton one (a (1, n)
    row vector normalizes over n, not elementwise); 1-D inputs stay 1-D."""
    x = np.asarray(x, np.float64)
    if axis is None:
        axis = next((i for i, n in enumerate(x.shape) if n > 1), 0) \
            if x.ndim > 0 else 0
    shifted = x - np.max(x, axis=axis, keepdims=True)
    e = np.exp(shifted)
    return e / np.sum(e, axis=axis, keepdims=True)


def write_nbest_jsonl(uttid2jsonl: Dict[str, dict],
                      uttid2hypos: Dict[str, Sequence[Sequence[str]]],
                      uttid2scores: Dict[str, np.ndarray],
                      outputpath: str, nbest: int,
                      orgpath: str = "", newpath: str = "") -> bool:
    """Dump a jsonl manifest with n-best hypotheses (reference
    ``write_nbest_jsonl``): each utterance expands into ``nbest`` entries
    ``<uttid>-<n>`` whose ``loss_weight`` is the softmax of the n-best
    scores; missing hypotheses are back-filled from the 1-best; ``wav``
    paths are rewritten from ``orgpath`` to ``newpath``."""
    records: List[dict] = []
    for uttid, base in uttid2jsonl.items():
        if uttid not in uttid2hypos:
            print_rank(f"Missing utterance {uttid} in results",
                       loglevel=logging.WARNING)
            continue
        hypos = uttid2hypos[uttid]
        if len(hypos) == 0:
            print_rank(f"Empty hypotheses for {uttid}; skipping",
                       loglevel=logging.WARNING)
            continue
        if nbest > 1:
            scores = np.asarray(uttid2scores.get(uttid, []), np.float64)
            if scores.size:
                weights = scores
                while len(weights) < nbest:
                    print_rank(f"Missing {len(weights)}-th best result in "
                               f"{uttid}; appending 1-best score")
                    weights = np.append(weights, weights[0])
                weights = softmax(weights[:nbest]).reshape(-1)
            else:
                weights = np.ones(nbest) / nbest
            for n in range(nbest):
                hypo = hypos[n] if n < len(hypos) else hypos[0]
                rec = copy.deepcopy(base)
                rec["id"] = f"{uttid}-{n}"
                rec["text"] = " ".join(hypo)
                rec["loss_weight"] = float(weights[n])
                records.append(rec)
        else:
            rec = copy.deepcopy(base)
            rec["id"] = uttid
            rec["text"] = " ".join(hypos[0])
            records.append(rec)

    with open(outputpath, "w") as fh:
        for rec in records:
            if "wav" in rec and orgpath:
                rec["wav"] = rec["wav"].replace(orgpath, newpath)
            fh.write(json.dumps(rec) + "\n")
    return True
