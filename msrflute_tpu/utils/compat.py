"""jax version compatibility shims.

Leaf module (imports only jax): the package targets the current jax API
surface, but the container's baked-in toolchain may lag — ``jax.shard_map``
was promoted out of ``jax.experimental.shard_map`` (and its replication
check renamed ``check_rep`` -> ``check_vma``) after 0.4.x.  Every internal
module imports :func:`shard_map` from here so the call sites can stay
written against the modern signature.
"""

from __future__ import annotations

try:  # modern jax: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax <= 0.4.x: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
              check_vma=None, **kwargs):
    """``jax.shard_map`` with the modern signature on every jax we run on
    (``check_vma`` maps to ``check_rep`` on older releases)."""
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def profiler_start_trace(log_dir: str) -> bool:
    """Start a ``jax.profiler`` trace, tolerating old-jax/backend quirks
    (0.4.x raises from a second start or on backends without profiler
    support).  Returns success — telemetry's profiling window degrades
    to a logged warning instead of killing a run."""
    try:
        import jax
        jax.profiler.start_trace(log_dir)
        return True
    except Exception:
        return False


def profiler_stop_trace() -> bool:
    """Stop the active ``jax.profiler`` trace; False when no trace was
    running or the profiler is unavailable on this jax."""
    try:
        import jax
        jax.profiler.stop_trace()
        return True
    except Exception:
        return False
