"""jax version compatibility shims.

Leaf module (imports only jax): the package targets the current jax API
surface, but the container's baked-in toolchain may lag — ``jax.shard_map``
was promoted out of ``jax.experimental.shard_map`` (and its replication
check renamed ``check_rep`` -> ``check_vma``) after 0.4.x.  Every internal
module imports :func:`shard_map` from here so the call sites can stay
written against the modern signature.
"""

from __future__ import annotations

try:  # modern jax: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax <= 0.4.x: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
              check_vma=None, **kwargs):
    """``jax.shard_map`` with the modern signature on every jax we run on
    (``check_vma`` maps to ``check_rep`` on older releases)."""
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


# ----------------------------------------------------------------------
# chip peak-FLOPs table (flutescope device-truth: the MFU denominator)
# ----------------------------------------------------------------------
#: dense bf16 peak FLOP/s per TPU chip generation (vendor-published
#: per-chip numbers; keys are matched as substrings of
#: ``device.device_kind`` lowercased).  Longest key wins, so "v5e"
#: matches before "v5".
TPU_PEAK_FLOPS = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,   # v5e reports device_kind "TPU v5 lite"
    "v5p": 459e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
}

#: the bench harness's historical headline denominator (bench.py MFU
#: columns were published against this) — now sourced from the one table
V5E_BF16_PEAK_FLOPS = TPU_PEAK_FLOPS["v5e"]

#: documented NOMINAL peak for CPU (and unknown device kinds): a fixed
#: round number so CPU MFU values exist, are deterministic, and compare
#: across CPU runs — never against a real chip's.  ~a few-core host's
#: practical f32 throughput order of magnitude.
CPU_NOMINAL_PEAK_FLOPS = 1e11


def chip_peak_flops(device=None):
    """``(kind, peak_flops)`` for ``device`` (default: this process's
    first jax device).  TPU kinds resolve through :data:`TPU_PEAK_FLOPS`;
    CPU and unrecognized kinds fall back to
    :data:`CPU_NOMINAL_PEAK_FLOPS` so MFU stays computable everywhere
    (flutescope's CPU-fallback contract — the scorecard records the kind
    next to the number so a reader can tell which regime it is)."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = str(getattr(device, "device_kind", "cpu") or "cpu").lower()
    best = None
    for key, peak in TPU_PEAK_FLOPS.items():
        if key in kind and (best is None or len(key) > len(best[0])):
            best = (key, peak)
    if best is not None:
        return kind, best[1]
    return kind, CPU_NOMINAL_PEAK_FLOPS


#: HBM bandwidth (bytes/s) per TPU chip generation (vendor-published),
#: matched like :data:`TPU_PEAK_FLOPS`.  The roofline denominator of the
#: attention dispatch gate (ops/pallas_attention.py): estimated program
#: seconds = max(flops / peak, bytes / bandwidth).
TPU_HBM_BYTES_PER_SEC = {
    "v2": 700e9,
    "v3": 900e9,
    "v4": 1228e9,
    "v5e": 819e9,
    "v5 lite": 819e9,
    "v5p": 2765e9,
    "v6e": 1640e9,
    "v6 lite": 1640e9,
}

#: documented NOMINAL bandwidth for CPU / unknown kinds — the same
#: fixed-round-number contract as :data:`CPU_NOMINAL_PEAK_FLOPS`
CPU_NOMINAL_HBM_BYTES_PER_SEC = 5e10


def chip_hbm_bytes_per_sec(device=None):
    """``(kind, bytes_per_sec)`` for ``device`` (default: this process's
    first jax device) — the memory-side twin of :func:`chip_peak_flops`,
    with the identical longest-substring matching and CPU fallback."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = str(getattr(device, "device_kind", "cpu") or "cpu").lower()
    best = None
    for key, bw in TPU_HBM_BYTES_PER_SEC.items():
        if key in kind and (best is None or len(key) > len(best[0])):
            best = (key, bw)
    if best is not None:
        return kind, best[1]
    return kind, CPU_NOMINAL_HBM_BYTES_PER_SEC


def profiler_start_trace(log_dir: str) -> bool:
    """Start a ``jax.profiler`` trace, tolerating old-jax/backend quirks
    (0.4.x raises from a second start or on backends without profiler
    support).  Returns success — telemetry's profiling window degrades
    to a logged warning instead of killing a run."""
    try:
        import jax
        jax.profiler.start_trace(log_dir)
        return True
    except Exception:
        return False


def profiler_stop_trace() -> bool:
    """Stop the active ``jax.profiler`` trace; False when no trace was
    running or the profiler is unavailable on this jax."""
    try:
        import jax
        jax.profiler.stop_trace()
        return True
    except Exception:
        return False
