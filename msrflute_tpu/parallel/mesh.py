"""Device mesh construction & sharding helpers.

This module replaces the reference's entire process/rank plumbing
(``core/federated.py:45-55`` env-var ranks, ``e2e_trainer.py:95`` process
groups).  In the TPU-native design there are no worker processes: a
``jax.sharding.Mesh`` with a ``clients`` axis carries client parallelism
(what FLUTE does with one whole-model replica per GPU worker rank,
``doc/sphinx/overview.rst:6-27``), and an optional ``model`` axis carries
tensor sharding for big models (net-new vs the reference, which has none —
SURVEY.md §2.2).

Multi-host: call :func:`maybe_init_distributed` first; the same mesh code
then spans all hosts' devices and XLA routes collectives over ICI within a
slice and DCN across slices — the role NCCL/Gloo plays in the reference.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENTS_AXIS = "clients"
MODEL_AXIS = "model"


def maybe_init_distributed() -> None:
    """Initialize jax.distributed when launched multi-host (the analogue of
    ``torch.distributed.run`` rendezvous, reference ``README.md:80-87``)."""
    if os.environ.get("JAX_COORDINATOR_ADDRESS") and jax.process_count() == 1:
        jax.distributed.initialize()


def make_mesh(num_devices: Optional[int] = None,
              model_axis_size: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(clients, model)`` mesh over the available devices.

    ``model_axis_size=1`` (the default) gives pure client parallelism — the
    TPU equivalent of FLUTE's one-replica-per-worker pool.  Larger values
    carve each client group into a tensor-sharded subgroup (for mlm_bert
    style models).
    """
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        devs = devs[:num_devices]
    n = len(devs)
    if n % model_axis_size:
        raise ValueError(f"{n} devices not divisible by model_axis_size={model_axis_size}")
    grid = np.asarray(devs).reshape(n // model_axis_size, model_axis_size)
    return Mesh(grid, (CLIENTS_AXIS, MODEL_AXIS))


def client_axis_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays whose leading axis is the round's client axis."""
    return NamedSharding(mesh, P(CLIENTS_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def clients_axis_size(mesh: Mesh) -> int:
    """Number of shards the client axis splits into — the divisor of
    every per-device cost in the fleet transfer plane (page-pool HBM,
    page-in bytes, writeback bytes are all total / this)."""
    return int(mesh.shape[CLIENTS_AXIS])


def pad_to_mesh(k: int, mesh: Mesh) -> int:
    """Round client count up to a multiple of the clients-axis size."""
    n = mesh.shape[CLIENTS_AXIS]
    return ((k + n - 1) // n) * n
