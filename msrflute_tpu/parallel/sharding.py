"""Parameter sharding inference for the ``model`` mesh axis.

Net-new vs the reference (FLUTE has no tensor parallelism — SURVEY.md
§2.2): when the mesh carves a ``model`` axis, large parameters are sharded
across it and XLA's SPMD partitioner inserts the all-gathers/reduce-scatters
over ICI.  The heuristic shards each ≥2-D parameter along its largest
mesh-divisible dimension (embedding tables along vocab, dense kernels along
the wider of in/out), leaving small leaves replicated — the standard
Megatron-ish layout without hand-written per-layer rules, which is what the
generic model zoo needs.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import CLIENTS_AXIS, MODEL_AXIS


def slot_pool_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for slot-axis tables (the fleet carry page pool): the
    slot axis splits over ``clients`` exactly like the resident
    ``[N, ...]`` tables it replaced, so per-device pool HBM is
    ``slots / mesh_size`` rows.  A replicated spec here is the
    replicated-pool bug class flint's shard-ready rule pins: page-in
    bytes, writeback fetches, and pool HBM all multiply by mesh size
    instead of dividing."""
    return NamedSharding(mesh, P(CLIENTS_AXIS))


def quantize_pool_slots(slots: int, mesh: Mesh) -> int:
    """Quantize a page-pool slot count UP to a multiple of the clients
    mesh axis, so the slot axis splits into equal per-shard blocks.  The
    server applies this at construction AND at mesh-elastic resume: a
    fleet checkpoint saved on M shards resuming on M' re-derives its
    pool capacity for the NEW mesh here (the host row store is
    shard-agnostic, so only the slot geometry needs re-quantizing)."""
    shards = int(mesh.shape[CLIENTS_AXIS])
    slots = max(int(slots), 1)
    return ((slots + shards - 1) // shards) * shards


def infer_model_sharding(params: Any, mesh: Mesh,
                         min_elements: int = 16_384) -> Any:
    """Pytree of NamedShardings: big leaves sharded on ``model``, rest
    replicated."""
    axis_size = mesh.shape[MODEL_AXIS]

    def leaf_sharding(leaf):
        if axis_size == 1 or leaf.ndim < 2 or leaf.size < min_elements:
            return NamedSharding(mesh, P())
        # shard the largest divisible dim
        order = np.argsort(leaf.shape)[::-1]
        for dim in order:
            if leaf.shape[dim] % axis_size == 0:
                spec = [None] * leaf.ndim
                spec[int(dim)] = MODEL_AXIS
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(leaf_sharding, params)
