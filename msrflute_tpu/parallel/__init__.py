from .mesh import make_mesh, client_axis_sharding, replicated_sharding  # noqa: F401
