from .rl import RLAggregator  # noqa: F401
