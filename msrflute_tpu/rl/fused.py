"""Fused RL — the DQN aggregation-weight tuner as device-resident carry.

The host RL path (``rl/rl.py`` + ``engine/server.py::_run_rl_round``)
aggregates twice per round, validates both candidates, and rewards the
policy from the val-accuracy comparison — three host round trips that
force the serial loop.  This module is the overlap-capable variant
(``server_config.wantRL + fused_carry``): the whole tuner — Q-network
params, optimizer state, replay ring, epsilon schedule, and the delayed
experience — rides ``strategy_state`` as donated device buffers, and one
traced :meth:`combine` call per round

- finalizes LAST round's experience with its delayed reward (the
  round-over-round TRAIN-loss delta, discretized exactly like the host
  reward: +1 improved / 0.1 within 1e-3 / -1 regressed),
- pushes it into the on-device replay ring and takes one DQN step over a
  uniformly sampled minibatch,
- picks this round's action epsilon-greedily (annealed in-program) and
  re-weights the gathered client payload stack with ``exp(action)``
  (the reference ``weights_from_action`` map, NaN/Inf -> 0).

Documented tradeoffs vs the host path: the reward signal is the train
loss (one round delayed) instead of a val-accuracy A/B, the RL weights
are always applied (no keep-better arbitration — the policy must learn
to be no worse than the strategy weights), and ``wantLSTM``'s state
window stays host-only.  What it buys: zero host syncs, so RL runs fully
pipelined with bit-identical serial-vs-pipelined trajectories.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from ..optim import make_optimizer


class FusedRL:
    """In-program DQN weight tuner over a fixed ``K``-client cohort."""

    #: per-client feature count (weight, magnitude, mean, variance —
    #: the reference state layout, ``dga.py:305``)
    N_FEATS = 4

    def __init__(self, rl_config, cohort_k: int):
        self.cfg = rl_config
        self.k = int(cohort_k)
        self.in_dim = self.N_FEATS * self.k
        self.eps0 = float(rl_config.get("initial_epsilon", 0.5))
        self.final_eps = float(rl_config.get("final_epsilon", 1e-4))
        self.eps_gamma = float(rl_config.get("epsilon_gamma", 0.9))
        self.minibatch = int(rl_config.get("minibatch_size", 16))
        self.max_memory = int(rl_config.get("max_replay_memory_size", 1000))
        params_spec = rl_config.get("network_params") or \
            [self.in_dim, 128, 128, self.k]
        if isinstance(params_spec, str):
            params_spec = [int(x) for x in params_spec.split(",")]
        self.sizes = tuple(int(x) for x in params_spec[1:])
        if self.sizes[-1] != self.k:
            raise ValueError(
                f"fused RL network_params output size {self.sizes[-1]} != "
                f"padded cohort size {self.k}")
        import flax.linen as nn

        class _Net(nn.Module):
            sizes: tuple

            @nn.compact
            def __call__(self, x):
                for h in self.sizes[:-1]:
                    x = nn.relu(nn.Dense(h)(x))
                return nn.Dense(self.sizes[-1])(x)

        self.net = _Net(sizes=self.sizes)
        self.tx = make_optimizer(rl_config.optimizer_config)

    # ------------------------------------------------------------------
    def init_state(self, rng: jax.Array) -> Dict[str, Any]:
        params = self.net.init(jax.random.fold_in(rng, 0xF),
                               jnp.zeros((self.in_dim,)))["params"]
        m = self.max_memory
        return {
            "net": params,
            "opt": self.tx.init(params),
            "replay_s": jnp.zeros((m, self.in_dim), jnp.float32),
            "replay_a": jnp.zeros((m, self.k), jnp.float32),
            "replay_r": jnp.zeros((m,), jnp.float32),
            "count": jnp.zeros((), jnp.int32),
            "ptr": jnp.zeros((), jnp.int32),
            "eps": jnp.asarray(self.eps0, jnp.float32),
            # delayed experience: last round's (state, action, loss)
            "prev_s": jnp.zeros((self.in_dim,), jnp.float32),
            "prev_a": jnp.zeros((self.k,), jnp.float32),
            "prev_loss": jnp.zeros((), jnp.float32),
            "have_prev": jnp.zeros((), jnp.float32),
        }

    # ------------------------------------------------------------------
    def combine(self, state: Dict[str, Any], per_client: Dict[str, Any],
                stack_tree: Any, cur_loss: jnp.ndarray, rng: jax.Array
                ) -> Tuple[Any, Dict[str, Any], Dict[str, jnp.ndarray]]:
        """One traced RL round: delayed reward -> replay push -> DQN step
        -> epsilon-greedy action -> re-weighted aggregate.

        ``per_client``: ``{"w","mag","mean","var"}`` each ``[K]``;
        ``stack_tree``: the full per-client payload stack (each leaf
        ``[K, ...]``); ``cur_loss``: this round's mean train loss.
        Returns ``(aggregate, new_state, rl_stats)``.
        """
        w = per_client["w"]
        k_act = int(w.shape[0])
        if k_act > self.k:
            raise ValueError(
                f"fused RL cohort {k_act} exceeds the configured "
                f"num_clients_per_iteration grid ({self.k})")
        pad = self.k - k_act  # dataset smaller than ncpi: zero-pad feats
        state_vec = jnp.concatenate([
            jnp.pad(per_client[f], (0, pad))
            for f in ("w", "mag", "mean", "var")
        ]).astype(jnp.float32)
        state_vec = jnp.nan_to_num(state_vec, nan=0.0, posinf=0.0,
                                   neginf=0.0)

        # -- delayed reward for LAST round's action (discretized like the
        # host compute_reward, over train-loss improvement) --------------
        delta = state["prev_loss"] - cur_loss
        reward = jnp.where(jnp.abs(delta) < 1e-3, 0.1,
                           jnp.where(delta > 0, 1.0, -1.0))
        reward = reward * state["have_prev"]
        # push (prev_s, prev_a, reward) into the ring only when it exists;
        # a dropped write targets index max_memory (out of bounds -> drop)
        slot = jnp.where(state["have_prev"] > 0, state["ptr"],
                         self.max_memory)
        replay_s = state["replay_s"].at[slot].set(state["prev_s"],
                                                  mode="drop")
        replay_a = state["replay_a"].at[slot].set(state["prev_a"],
                                                  mode="drop")
        replay_r = state["replay_r"].at[slot].set(reward, mode="drop")
        pushed = (state["have_prev"] > 0).astype(jnp.int32)
        count = jnp.minimum(state["count"] + pushed, self.max_memory)
        ptr = (state["ptr"] + pushed) % self.max_memory

        # -- one DQN step over a uniform minibatch (no-op until the ring
        # holds at least one experience) ---------------------------------
        idx = jax.random.randint(jax.random.fold_in(rng, 1),
                                 (self.minibatch,), 0,
                                 jnp.maximum(count, 1))
        bs, ba, br = replay_s[idx], replay_a[idx], replay_r[idx]

        def loss_fn(p):
            q = jnp.sum(self.net.apply({"params": p}, bs) * ba, axis=-1)
            return jnp.mean((q - br) ** 2)

        qloss, grads = jax.value_and_grad(loss_fn)(state["net"])
        updates, new_opt = self.tx.update(grads, state["opt"], state["net"])
        stepped = optax.apply_updates(state["net"], updates)
        new_net = jax.tree.map(lambda new, old: jnp.where(count > 0,
                                                          new, old),
                               stepped, state["net"])
        new_opt = jax.tree.map(lambda new, old: jnp.where(count > 0,
                                                          new, old),
                               new_opt, state["opt"])
        qloss = qloss * (count > 0).astype(jnp.float32)

        # -- epsilon-greedy action for THIS round ------------------------
        explore = jax.random.uniform(jax.random.fold_in(rng, 2)) <= \
            state["eps"]
        rand_action = jax.random.uniform(jax.random.fold_in(rng, 3),
                                         (self.k,))
        net_action = self.net.apply({"params": new_net}, state_vec)
        action = jnp.where(explore, rand_action, net_action)
        action_k = action[:k_act]
        # reference weights_from_action: exp(action), NaN/Inf -> 0; gate
        # on the strategy weight so padding/dropped clients stay out
        rl_w = jnp.nan_to_num(jnp.exp(action_k), nan=0.0, posinf=0.0,
                              neginf=0.0) * (w > 0).astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(rl_w), 1e-12)
        agg = jax.tree.map(
            lambda g: jnp.tensordot(rl_w.astype(g.dtype), g,
                                    axes=[[0], [0]]) / denom.astype(g.dtype),
            stack_tree)

        new_eps = jnp.where(state["eps"] * self.eps_gamma > self.final_eps,
                            state["eps"] * self.eps_gamma, state["eps"])
        new_state = dict(
            state, net=new_net, opt=new_opt, replay_s=replay_s,
            replay_a=replay_a, replay_r=replay_r, count=count, ptr=ptr,
            eps=new_eps, prev_s=state_vec, prev_a=action,
            prev_loss=cur_loss, have_prev=jnp.ones((), jnp.float32))
        rl_stats = {"rl_reward": reward, "rl_qloss": qloss,
                    "rl_epsilon": state["eps"],
                    "rl_explored": explore.astype(jnp.float32)}
        return agg, new_state, rl_stats
