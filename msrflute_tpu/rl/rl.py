"""RL meta-aggregator — DQN-style re-weighting of client updates.

Parity target: reference ``extensions/RL/RL.py`` + the DGA hooks
(``core/strategies/dga.py:286-406``):

- state = concat(client weights, grad magnitudes, grad means, grad vars)
  (``dga.py:305``), length ``4 * clients_per_round``;
- action = MLP (optionally LSTM over a window of recent states) output,
  epsilon-greedy with annealed epsilon (``RL.py:183-201``);
- aggregation weights = ``exp(action)`` with NaN/Inf -> 0
  (``dga.py:306-315``);
- reward by comparing val accuracy of the RL-aggregated model vs the
  standard aggregation: +1 if better (keep RL model), 0.1 if within 1e-3
  (keep if ``marginal_update_RL``), -1 otherwise (``dga.py:366-390``);
- DQN update: replay memory, ``q = sum(model(state) * action)``, MSE to the
  reward, epsilon annealing (``RL.py:204-262``), checkpoint + stats file
  (``RL.py:314-340``).

TPU-native: the network is flax, its train step is one jitted function;
replay memory and epsilon schedule stay host-side (tiny, data-dependent).
"""

from __future__ import annotations

import json
import os
import random
from typing import List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import serialization

from ..config import RLConfig
from ..optim import make_optimizer
from ..utils.logging import print_rank


class _QNet(nn.Module):
    """MLP head (reference ``NeuralNetwork``, ``RL.py:79-144``); with
    ``want_lstm`` a bidirectional LSTM encodes the state window first."""

    sizes: Sequence[int]
    want_lstm: bool = False

    @nn.compact
    def __call__(self, x):
        if self.want_lstm:
            # x: [T, F] window of recent states, or [B, T, F]
            squeeze = x.ndim == 2
            if squeeze:
                x = x[None]
            fwd = nn.RNN(nn.OptimizedLSTMCell(self.sizes[0]))(x)
            bwd = nn.RNN(nn.OptimizedLSTMCell(self.sizes[0]),
                         reverse=True)(x)
            x = (fwd + bwd)[:, -1]  # last timestep only, like forward()
            if squeeze:
                x = x[0]
        for h in self.sizes[:-1]:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.sizes[-1])(x)


class RLAggregator:
    """Host-driven RL weight estimator with jitted forward/train."""

    def __init__(self, rl_config: RLConfig, num_clients_per_iteration: int,
                 model_dir: str, seed: int = 0):
        self.cfg = rl_config
        self.out_size = int(num_clients_per_iteration)
        self.want_lstm = bool(rl_config.get("wantLSTM", False))
        self.epsilon = float(rl_config.get("initial_epsilon", 0.5))
        self.final_epsilon = float(rl_config.get("final_epsilon", 1e-4))
        self.epsilon_gamma = float(rl_config.get("epsilon_gamma", 0.9))
        self.minibatch = int(rl_config.get("minibatch_size", 16))
        self.max_memory = int(rl_config.get("max_replay_memory_size", 1000))
        self.replay: List[Tuple[np.ndarray, np.ndarray, float]] = []
        self.state_window: List[np.ndarray] = []
        self.running_loss = 0.0
        self.step = 0
        self.rl_weights: Optional[np.ndarray] = None
        self.rl_losses = None
        self._pyrng = random.Random(seed)

        in_dim = 4 * self.out_size
        params_spec = rl_config.get("network_params") or [in_dim, 128, 128,
                                                          self.out_size]
        if isinstance(params_spec, str):
            params_spec = [int(x) for x in params_spec.split(",")]
        self.net = _QNet(sizes=tuple(int(x) for x in params_spec[1:]),
                         want_lstm=self.want_lstm)
        rng = jax.random.PRNGKey(seed)
        dummy = (jnp.zeros((self.minibatch, in_dim)) if self.want_lstm
                 else jnp.zeros((in_dim,)))
        self.params = self.net.init(rng, dummy)["params"]
        self.tx = make_optimizer(rl_config.optimizer_config)
        self.opt_state = self.tx.init(self.params)

        descriptor = rl_config.get("model_descriptor_RL", "Default")
        base = rl_config.get("RL_path") or model_dir
        os.makedirs(base, exist_ok=True)
        self.model_name = os.path.join(
            base, f"rl_{self.out_size}.{descriptor}.model")
        self.stats_name = os.path.join(
            base, f"rl_{self.out_size}.{descriptor}.stats")
        self._forward = jax.jit(
            lambda p, s: self.net.apply({"params": p}, s))
        self._train_step = jax.jit(self._make_train_step())
        self.load_saved_status()

    # ------------------------------------------------------------------
    def forward(self, state: np.ndarray) -> np.ndarray:
        """Epsilon-greedy action (reference ``RL.py:183-201``)."""
        state = np.asarray(state, np.float32).reshape(-1)
        if self.want_lstm:
            self.state_window.append(state)
            self.state_window = self.state_window[-self.minibatch:]
            window = np.zeros((self.minibatch, state.shape[0]), np.float32)
            if self.state_window:
                window[-len(self.state_window):] = np.stack(self.state_window)
            state_in = window
        else:
            state_in = state
        if self._pyrng.random() <= self.epsilon:
            print_rank("RL: performed random action")
            action = np.random.default_rng(
                self._pyrng.randrange(2**31)).random(self.out_size)
        else:
            action = np.asarray(self._forward(self.params,
                                              jnp.asarray(state_in)))
            if action.ndim > 1:
                action = action[-1]
        return action.astype(np.float32)

    def weights_from_action(self, action: np.ndarray) -> np.ndarray:
        w = np.exp(action.astype(np.float64))
        w[~np.isfinite(w)] = 0.0
        return w.astype(np.float32)

    # ------------------------------------------------------------------
    def _make_train_step(self):
        net = self.net
        tx = self.tx

        def train_step(params, opt_state, states, actions, rewards):
            def loss_fn(p):
                out = net.apply({"params": p}, states)
                q = jnp.sum(out * actions, axis=-1)
                return jnp.mean((q - rewards) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return train_step

    def train(self, state: np.ndarray, action: np.ndarray,
              reward: float) -> float:
        """One replay-buffer DQN step (reference ``RL.py:204-262``)."""
        self.replay.append((np.asarray(state, np.float32).reshape(-1),
                            np.asarray(action, np.float32), float(reward)))
        if len(self.replay) > self.max_memory:
            self.replay.pop(0)
        if self.epsilon * self.epsilon_gamma > self.final_epsilon:
            self.epsilon *= self.epsilon_gamma
        if self.want_lstm:
            batch = self.replay[-self.minibatch:]
        else:
            batch = self._pyrng.sample(
                self.replay, min(len(self.replay), self.minibatch))
        states = np.stack([b[0] for b in batch])
        actions = np.stack([b[1] for b in batch])
        rewards = np.asarray([b[2] for b in batch], np.float32)
        if self.want_lstm:
            # one padded window sequence; Q is read at the last timestep,
            # matching forward()
            pad = np.zeros((self.minibatch - len(batch), states.shape[1]),
                           np.float32)
            states = np.concatenate([pad, states])[None]  # [1, T, F]
            actions = actions[-1:]
            rewards = rewards[-1:]
        self.params, self.opt_state, loss = self._train_step(
            self.params, self.opt_state, jnp.asarray(states),
            jnp.asarray(actions), jnp.asarray(rewards))
        loss = float(loss)
        self.running_loss = loss if self.running_loss == 0 else \
            0.95 * self.running_loss + 0.05 * loss
        self.step += 1
        return loss

    # ------------------------------------------------------------------
    def compute_reward(self, baseline_acc: float, rl_acc: float,
                       marginal_update: bool) -> Tuple[float, bool]:
        """Reward + keep-RL-model decision (reference ``dga.py:366-390``)."""
        if abs(baseline_acc - rl_acc) < 0.001:
            return 0.1, bool(marginal_update)
        if rl_acc > baseline_acc:
            return 1.0, True
        return -1.0, False

    # ------------------------------------------------------------------
    def save(self) -> None:
        # tmp + os.replace on both files: the RL tuner checkpoint is a
        # resume anchor like any other — a crash mid-write must leave
        # the previous generation loadable, not a torn msgpack
        blob = serialization.msgpack_serialize(serialization.to_state_dict({
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
        }))
        tmp = self.model_name + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, self.model_name)
        stats_tmp = self.stats_name + ".tmp"
        with open(stats_tmp, "w") as fh:
            json.dump({"step": self.step, "epsilon": self.epsilon,
                       "running_loss": self.running_loss}, fh)
        os.replace(stats_tmp, self.stats_name)

    def load_saved_status(self) -> None:
        if os.path.exists(self.model_name):
            with open(self.model_name, "rb") as fh:
                raw = serialization.msgpack_restore(fh.read())
            target = serialization.to_state_dict({
                "params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)})
            merged = serialization.from_state_dict(target, raw)
            self.params = merged["params"]
            self.opt_state = merged["opt_state"]
            print_rank(f"RL: restored model from {self.model_name}")
        if os.path.exists(self.stats_name):
            with open(self.stats_name) as fh:
                stats = json.load(fh)
            self.step = int(stats.get("step", 0))
            self.epsilon = float(stats.get("epsilon", self.epsilon))
            self.running_loss = float(stats.get("running_loss", 0.0))
