"""The crash-safe metrics stream (``metrics.jsonl``) + structured events.

Moved here from ``utils/logging.py`` (which keeps its public
``log_metric``/``flush_metrics`` names as re-exports) so the run's whole
observability surface lives under ``telemetry/``:

- scalar metrics: one JSON line per value, buffered with a time-based
  flush cadence plus the explicit flush points (round housekeeping,
  train exit, process exit, and — new — the preemption drain path, so a
  SIGTERM'd run never loses the in-flight round's metrics);
- structured EVENT records (``{"event": kind, ...}`` lines in the same
  stream): preemption requests, chaos fault rounds, checkpoint
  fallback/recovery — previously only greppable log text, now records a
  reader (``tools/scope``) can tabulate;
- bounded growth (ISSUE 13): ``telemetry.max_log_mb`` size-caps the
  stream.  At a flush point past the cap the current file rotates to a
  numbered segment — hardlink the live inode to ``metrics.jsonl.N``,
  then atomically swap an empty inode into the primary name (tmp +
  ``os.replace``, the blessed idiom: no crash instant loses lines; the
  worst case is the link-then-swap window, where a crash leaves the
  newest lines under BOTH names and readers may double-count that one
  segment's tail) — and a ``log_rotated`` event opens the new segment.
  ``tools/scope`` readers walk rotated segments transparently.

No jax import, no telemetry-object dependency: this module is the
always-on half of flutescope (the span tracer is the opt-in half), so
event emission works identically whether ``server_config.telemetry`` is
configured or not.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

_LOGGER = logging.getLogger("msrflute_tpu")
_METRICS_FH = None
_METRICS_PATH = None
#: seconds between forced metrics-stream flushes; between them lines sit
#: in the file buffer (the server also flushes at every round-housekeeping
#: boundary, at train() exit, and from the preemption drain path, so
#: round granularity is never lost)
_FLUSH_INTERVAL_SECS = 1.0
_LAST_FLUSH = 0.0
#: size cap in bytes (0 = unbounded, the default); set from the
#: telemetry block's ``max_log_mb`` knob at scope construction
_MAX_LOG_BYTES = 0
_BYTES_WRITTEN = 0
#: guards the file handle against the rotation swap: writers land on
#: other threads too (the async checkpoint writer's events), and a
#: write racing a close would turn log rotation into spurious stream
#: errors.  Held only around buffered writes/flushes and the handle
#: exchange — never around a file open (the lock-discipline contract).
_FH_LOCK = threading.Lock()


def open_metrics(log_dir: str) -> None:
    """Open (append) ``<log_dir>/metrics.jsonl`` as the process's metric
    stream and register the at-exit flush."""
    global _METRICS_FH, _METRICS_PATH, _BYTES_WRITTEN
    os.makedirs(log_dir, exist_ok=True)
    _METRICS_PATH = os.path.join(log_dir, "metrics.jsonl")
    try:
        _BYTES_WRITTEN = os.path.getsize(_METRICS_PATH)
    except OSError:
        _BYTES_WRITTEN = 0
    _METRICS_FH = open(_METRICS_PATH, "a")
    # buffered lines must still land if the process exits without a
    # final explicit flush (e.g. a CLI run killed between rounds)
    import atexit
    atexit.register(flush_metrics)


def set_max_log_mb(mb: float) -> None:
    """Arm size-capped rotation for the metrics stream (``telemetry.
    max_log_mb``; 0 disables).  Rotation happens only at flush points —
    never mid-line — so a reader's torn-tail tolerance is the only
    crash concession."""
    global _MAX_LOG_BYTES
    _MAX_LOG_BYTES = int(float(mb) * 2 ** 20) if mb else 0


def rotate_jsonl(path: str, fh):
    """Rotate one append-mode jsonl stream to its next numbered segment
    and hand back ``(new_fh, segment_index)`` — WITHOUT closing ``fh``
    (the caller exchanges handles under its own lock, then closes the
    old one; a concurrent writer still holding it writes the OLD inode,
    which is exactly the segment file, so no line is ever lost to a
    closed handle).

    The blessed crash-ordering: (1) flush + hardlink the live inode to
    ``<path>.N`` — both names now reference every line ever written;
    (2) atomically swap a fresh empty inode into the primary name via
    tmp + ``os.replace``; (3) open the new primary for append.  The
    only crash artifact is the link-to-swap window where segment N and
    the primary briefly alias the same inode (readers may double-count
    that tail once)."""
    fh.flush()
    seg = 1
    while os.path.exists(f"{path}.{seg}"):
        seg += 1
    os.link(path, f"{path}.{seg}")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8"):
        pass
    os.replace(tmp, path)
    return open(path, "a", encoding="utf-8"), seg


def jsonl_segment_paths(path: str) -> list:
    """Rotated segments of one jsonl stream, oldest first, primary
    last — the reader-side mirror of :func:`rotate_jsonl` (tools/scope
    carries its own pure-stdlib copy of this walk; the two are pinned
    together by tests/test_endurance.py)."""
    out = []
    seg = 1
    while os.path.exists(f"{path}.{seg}"):
        out.append(f"{path}.{seg}")
        seg += 1
    if os.path.exists(path):
        out.append(path)
    return out


def _maybe_rotate() -> None:
    """Flush-point rotation check (never per line).  The new handle
    opens OUTSIDE the lock, the exchange happens under it, and only
    then does the old handle close — a writer that raced the swap was
    either holding the lock (so it finished first) or lands on the new
    handle.  Emits the ``log_rotated`` event as the NEW segment's
    first record so the rotation is observable in the stream it
    rotated."""
    global _METRICS_FH, _BYTES_WRITTEN
    if not _MAX_LOG_BYTES or _METRICS_FH is None or \
            _METRICS_PATH is None or _BYTES_WRITTEN < _MAX_LOG_BYTES:
        return
    try:
        new_fh, seg = rotate_jsonl(_METRICS_PATH, _METRICS_FH)
    except OSError:
        return  # rotation is an optimization; never kill the stream
    with _FH_LOCK:
        old, _METRICS_FH = _METRICS_FH, new_fh
        rotated_bytes = _BYTES_WRITTEN
        _BYTES_WRITTEN = 0
    try:
        old.close()
    except OSError:
        pass
    log_event("log_rotated", file="metrics.jsonl", segment=seg,
              rotated_bytes=rotated_bytes)


def metrics_open() -> bool:
    return _METRICS_FH is not None


def _write_line(record: Dict[str, Any]) -> None:
    global _LAST_FLUSH, _BYTES_WRITTEN
    if _METRICS_FH is not None:
        line = json.dumps(record) + "\n"
        with _FH_LOCK:
            fh = _METRICS_FH
            if fh is None or fh.closed:
                return
            fh.write(line)
            _BYTES_WRITTEN += len(line)
            if record["ts"] - _LAST_FLUSH >= _FLUSH_INTERVAL_SECS:
                fh.flush()
                _LAST_FLUSH = record["ts"]


def log_metric(name: str, value: Any, step: Optional[int] = None,
               extra: Optional[Dict[str, Any]] = None) -> None:
    """Scalar metric emission (replaces AzureML ``run.log`` at reference
    ``core/server.py:261-264,523-525``).

    Writes are BUFFERED: a flush-per-line put one syscall per scalar on
    the server's host tail (~6+ per round); lines flush on a time-based
    cadence plus the explicit :func:`flush_metrics` points.
    """
    record = {"ts": time.time(), "name": name, "value": _to_py(value)}
    if step is not None:
        record["step"] = step
    if extra:
        record.update(extra)
    _write_line(record)
    _LOGGER.info("metric %s=%s%s", name, record["value"],
                 f" @ {step}" if step is not None else "")


def log_event(kind: str, **fields: Any) -> None:
    """One structured event record in the metrics stream (preemption,
    chaos faults, checkpoint recovery, watchdog findings).  Replaces the
    grep-a-log-line observability those paths had before flutescope."""
    record = {"ts": time.time(), "event": kind}
    record.update({k: _to_py(v) for k, v in fields.items()})
    # attribute off-main-thread emissions (the async checkpoint writer,
    # future fleet-mode workers) to their named thread; every spawned
    # thread carries a name (flint's thread-escape spawn-hygiene check)
    emitter = threading.current_thread()
    if emitter is not threading.main_thread():
        record.setdefault("thread", emitter.name)
    _write_line(record)
    _LOGGER.info("event %s %s", kind,
                 {k: v for k, v in record.items()
                  if k not in ("ts", "event")})


def flush_metrics() -> None:
    """Force buffered metric/event lines to disk (no-op without a
    writer).  The preemption drain path calls this so a SIGTERM'd run's
    in-flight round records are durable before the process exits."""
    global _LAST_FLUSH
    if _METRICS_FH is not None:
        with _FH_LOCK:
            fh = _METRICS_FH
            if fh is not None and not fh.closed:
                fh.flush()
            _LAST_FLUSH = time.time()
        _maybe_rotate()


def _to_py(value: Any) -> Any:
    """JSON-serializable python scalar from an already-HOST value (the
    metric contract: callers ``device_get`` first — the host-sync lint
    polices the call sites; these ``.item()``s only ever see numpy)."""
    try:
        import numpy as np
        if isinstance(value, (np.generic,)):
            # flint: disable=host-sync np.generic is a host scalar; .item() is a pure python-type conversion
            return value.item()
        if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
            # flint: disable=host-sync 0-d numpy array handed in by callers that already fetched; json needs the python scalar
            return value.item()
    except Exception:
        pass
    return value
