"""The crash-safe metrics stream (``metrics.jsonl``) + structured events.

Moved here from ``utils/logging.py`` (which keeps its public
``log_metric``/``flush_metrics`` names as re-exports) so the run's whole
observability surface lives under ``telemetry/``:

- scalar metrics: one JSON line per value, buffered with a time-based
  flush cadence plus the explicit flush points (round housekeeping,
  train exit, process exit, and — new — the preemption drain path, so a
  SIGTERM'd run never loses the in-flight round's metrics);
- structured EVENT records (``{"event": kind, ...}`` lines in the same
  stream): preemption requests, chaos fault rounds, checkpoint
  fallback/recovery — previously only greppable log text, now records a
  reader (``tools/scope``) can tabulate.

No jax import, no telemetry-object dependency: this module is the
always-on half of flutescope (the span tracer is the opt-in half), so
event emission works identically whether ``server_config.telemetry`` is
configured or not.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

_LOGGER = logging.getLogger("msrflute_tpu")
_METRICS_FH = None
#: seconds between forced metrics-stream flushes; between them lines sit
#: in the file buffer (the server also flushes at every round-housekeeping
#: boundary, at train() exit, and from the preemption drain path, so
#: round granularity is never lost)
_FLUSH_INTERVAL_SECS = 1.0
_LAST_FLUSH = 0.0


def open_metrics(log_dir: str) -> None:
    """Open (append) ``<log_dir>/metrics.jsonl`` as the process's metric
    stream and register the at-exit flush."""
    global _METRICS_FH
    os.makedirs(log_dir, exist_ok=True)
    _METRICS_FH = open(os.path.join(log_dir, "metrics.jsonl"), "a")
    # buffered lines must still land if the process exits without a
    # final explicit flush (e.g. a CLI run killed between rounds)
    import atexit
    atexit.register(flush_metrics)


def metrics_open() -> bool:
    return _METRICS_FH is not None


def _write_line(record: Dict[str, Any]) -> None:
    global _LAST_FLUSH
    if _METRICS_FH is not None:
        _METRICS_FH.write(json.dumps(record) + "\n")
        if record["ts"] - _LAST_FLUSH >= _FLUSH_INTERVAL_SECS:
            _METRICS_FH.flush()
            _LAST_FLUSH = record["ts"]


def log_metric(name: str, value: Any, step: Optional[int] = None,
               extra: Optional[Dict[str, Any]] = None) -> None:
    """Scalar metric emission (replaces AzureML ``run.log`` at reference
    ``core/server.py:261-264,523-525``).

    Writes are BUFFERED: a flush-per-line put one syscall per scalar on
    the server's host tail (~6+ per round); lines flush on a time-based
    cadence plus the explicit :func:`flush_metrics` points.
    """
    record = {"ts": time.time(), "name": name, "value": _to_py(value)}
    if step is not None:
        record["step"] = step
    if extra:
        record.update(extra)
    _write_line(record)
    _LOGGER.info("metric %s=%s%s", name, record["value"],
                 f" @ {step}" if step is not None else "")


def log_event(kind: str, **fields: Any) -> None:
    """One structured event record in the metrics stream (preemption,
    chaos faults, checkpoint recovery, watchdog findings).  Replaces the
    grep-a-log-line observability those paths had before flutescope."""
    record = {"ts": time.time(), "event": kind}
    record.update({k: _to_py(v) for k, v in fields.items()})
    # attribute off-main-thread emissions (the async checkpoint writer,
    # future fleet-mode workers) to their named thread; every spawned
    # thread carries a name (flint's thread-escape spawn-hygiene check)
    emitter = threading.current_thread()
    if emitter is not threading.main_thread():
        record.setdefault("thread", emitter.name)
    _write_line(record)
    _LOGGER.info("event %s %s", kind,
                 {k: v for k, v in record.items()
                  if k not in ("ts", "event")})


def flush_metrics() -> None:
    """Force buffered metric/event lines to disk (no-op without a
    writer).  The preemption drain path calls this so a SIGTERM'd run's
    in-flight round records are durable before the process exits."""
    global _LAST_FLUSH
    if _METRICS_FH is not None:
        _METRICS_FH.flush()
        _LAST_FLUSH = time.time()


def _to_py(value: Any) -> Any:
    """JSON-serializable python scalar from an already-HOST value (the
    metric contract: callers ``device_get`` first — the host-sync lint
    polices the call sites; these ``.item()``s only ever see numpy)."""
    try:
        import numpy as np
        if isinstance(value, (np.generic,)):
            # flint: disable=host-sync np.generic is a host scalar; .item() is a pure python-type conversion
            return value.item()
        if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
            # flint: disable=host-sync 0-d numpy array handed in by callers that already fetched; json needs the python scalar
            return value.item()
    except Exception:
        pass
    return value
