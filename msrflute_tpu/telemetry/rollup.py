"""flutescope endurance — streaming rollups + the flight recorder.

The longitudinal half of flutescope (ISSUE 13).  Everything the tracer
and metrics stream record is per-event: fine for a 50-round CPU run,
useless for a 3-day fleet run whose limiting signals are TRENDS —
throughput drift, straggler accumulation, host-memory creep — and whose
forensic record must survive the process dying.  Two pieces:

- :class:`RollupEngine` — incremental windowed rollups over values the
  host tail ALREADY holds (span durations, per-round wall clocks, the
  fetched client counts, live MFU, host RSS, the device-truth layer's
  cumulative counters).  Every ``rollup_window`` rounds one JSON line is
  appended to ``<telemetry>/rollups.jsonl`` (complete-line append +
  flush — the crash-safe jsonl idiom) and the window state resets, so
  host memory stays O(window), never O(run length).  Per-phase p50/p95
  inside a window are EXACT (the window's samples are retained — the
  window bound is the memory bound); run-cumulative quantiles come from
  a :class:`P2Quantile` streaming sketch (O(1) memory per phase).
- :class:`FlightRecorder` — a bounded ring of the last-N structured
  events plus the live (unflushed) rollup window and the scorecard,
  persisted atomically as ``<telemetry>/flight.json`` on watchdog
  abort, preemption, or any BaseException exit from the round loop.
  The record of a dead days-long run is always on disk, written by the
  path that killed it — not dependent on a clean shutdown.

Zero-cost contract (tests/test_telemetry_contract.py): nothing here is
constructed when telemetry is off, and nothing here ever touches a
device value — every input is a host float the round loop already
fetched or measured.  No jax import (the telemetry package contract).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "FlightRecorder", "P2Quantile", "RollupEngine", "host_rss_bytes",
]

ROLLUPS_FILENAME = "rollups.jsonl"
FLIGHT_FILENAME = "flight.json"


# ----------------------------------------------------------------------
# host RSS (pure stdlib; the rss_leak watchdog's input)
# ----------------------------------------------------------------------
def host_rss_bytes() -> Optional[int]:
    """This process's CURRENT resident set size in bytes, or None when
    the platform offers no cheap reading.  Linux reads one line of
    ``/proc/self/statm`` (pages); the fallback uses ``getrusage``
    ``ru_maxrss`` — a PEAK, not a current value, so the leak detector's
    slope still rises with a leak but can never fall (documented in
    docs/observability.md)."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as fh:
            rss_pages = int(fh.read().split()[1])
        return rss_pages * (os.sysconf("SC_PAGE_SIZE")
                            if hasattr(os, "sysconf") else 4096)
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports KiB, macOS bytes; by this branch we are not on
        # a /proc system, so assume the BSD convention
        return int(ru)
    except Exception:
        return None


# ----------------------------------------------------------------------
# streaming quantiles
# ----------------------------------------------------------------------
class P2Quantile:
    """The P² streaming quantile estimator (Jain & Chlamtac 1985):
    one quantile, five markers, O(1) memory and O(1) per observation.

    EXACT for the first five observations; beyond that the markers
    interpolate parabolically — the classic accuracy is well within a
    few percent on smooth distributions, which is what a trend gate
    needs (the per-window quantiles in the rollup records stay exact;
    this sketch backs only the run-CUMULATIVE columns, where retaining
    every sample would be the O(run length) memory this module exists
    to remove).  Deterministic for a fixed observation order."""

    __slots__ = ("p", "n", "_heights", "_positions", "_desired", "_incr")

    def __init__(self, p: float):
        if not 0.0 < p < 1.0:
            raise ValueError(f"quantile p must be in (0, 1), got {p}")
        self.p = float(p)
        self.n = 0
        self._heights: List[float] = []
        self._positions = [1.0, 2.0, 3.0, 4.0, 5.0]
        self._desired = [1.0, 1.0 + 2.0 * p, 1.0 + 4.0 * p,
                         3.0 + 2.0 * p, 5.0]
        self._incr = [0.0, p / 2.0, p, (1.0 + p) / 2.0, 1.0]

    def observe(self, value: float) -> None:
        value = float(value)
        self.n += 1
        if len(self._heights) < 5:
            self._heights.append(value)
            self._heights.sort()
            return
        q = self._heights
        if value < q[0]:
            q[0] = value
            k = 0
        elif value >= q[4]:
            q[4] = value
            k = 3
        else:
            k = 0
            while k < 3 and value >= q[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            self._positions[i] += 1.0
        for i in range(5):
            self._desired[i] += self._incr[i]
        # adjust the three interior markers toward their desired
        # positions, parabolic when the neighbor gap allows, linear else
        for i in (1, 2, 3):
            d = self._desired[i] - self._positions[i]
            np_, nm = self._positions[i + 1], self._positions[i - 1]
            if (d >= 1.0 and np_ - self._positions[i] > 1.0) or \
                    (d <= -1.0 and nm - self._positions[i] < -1.0):
                d = 1.0 if d >= 1.0 else -1.0
                qi = self._parabolic(i, d)
                if not q[i - 1] < qi < q[i + 1]:
                    qi = q[i] + d * (q[i + int(d)] - q[i]) / (
                        self._positions[i + int(d)] - self._positions[i])
                q[i] = qi
                self._positions[i] += d
        return

    def _parabolic(self, i: int, d: float) -> float:
        q, pos = self._heights, self._positions
        return q[i] + d / (pos[i + 1] - pos[i - 1]) * (
            (pos[i] - pos[i - 1] + d) * (q[i + 1] - q[i]) /
            (pos[i + 1] - pos[i]) +
            (pos[i + 1] - pos[i] - d) * (q[i] - q[i - 1]) /
            (pos[i] - pos[i - 1]))

    @property
    def value(self) -> Optional[float]:
        if not self._heights:
            return None
        if len(self._heights) < 5 or self.n <= 5:
            # exact small-sample quantile (nearest-rank, matching the
            # repo's _p50 convention of sorted[int(n*p)])
            ordered = sorted(self._heights)
            idx = min(int(len(ordered) * self.p), len(ordered) - 1)
            return ordered[idx]
        return self._heights[2]


def _exact_quantile(values: List[float], p: float) -> Optional[float]:
    """Nearest-rank quantile of a retained sample list (the per-window
    EXACT numbers — same convention as scope_cli's ``_p50``)."""
    if not values:
        return None
    ordered = sorted(values)
    return ordered[min(int(len(ordered) * p), len(ordered) - 1)]


# ----------------------------------------------------------------------
# the rollup engine
# ----------------------------------------------------------------------
class RollupEngine:
    """Windowed longitudinal rollups appended to ``rollups.jsonl``.

    The server's host tail feeds it per-round observations
    (:meth:`observe_round`), the telemetry scope feeds it per-phase span
    durations (:meth:`observe_phase`) and event kinds
    (:meth:`observe_event`); :meth:`maybe_flush` runs on the round
    housekeeping cadence and appends ONE record per completed window.
    All state is bounded: window samples reset at flush, cumulative
    quantiles are P² sketches, counters are dicts over the (small)
    event-kind vocabulary.

    Thread-aware, like the Tracer: ``observe_phase`` arrives from the
    async-checkpoint writer thread (its ``ckpt_async_write`` span) and
    ``observe_event`` from the stall-monitor thread, while the main
    thread flushes — ONE lock guards all window/cumulative mutation
    and record building snapshots under it; the jsonl append happens
    OUTSIDE the lock (the lock-discipline contract: no file opens in a
    held region).
    """

    #: default rounds per rollup window
    DEFAULT_WINDOW = 16

    def __init__(self, out_dir: str, window: int = DEFAULT_WINDOW,
                 ladder=None):
        self.out_dir = out_dir
        self.path = os.path.join(out_dir, ROLLUPS_FILENAME)
        self.window = max(int(window), 1)
        self.windows_flushed = 0
        #: rollup degradation ledger: a window whose append exhausts its
        #: retries is DROPPED and counted — telemetry loss must never
        #: become a host-tail exception in a training run
        self.windows_dropped = 0
        #: optional resilience.DurableIOLadder governing the jsonl
        #: append (surface "writer": retry, then drop); None appends raw
        #: but still drops-and-counts on failure
        self.ladder = ladder
        #: optional ``on_drop(rec)`` callback the server wires to emit
        #: the ``rollup_windows_dropped`` instant event
        self.on_drop = None
        self._fh = None  # opened lazily at first flush
        self._lock = threading.Lock()
        # ---- window state (reset at every flush) ----
        self._w_round_lo: Optional[int] = None
        self._w_round_hi: Optional[int] = None
        self._w_secs: List[float] = []
        self._w_clients = 0.0
        self._w_mfu: List[float] = []
        self._w_phase: Dict[str, List[float]] = {}
        self._w_events: Dict[str, int] = {}
        self._w_t0 = time.time()
        # ---- cumulative state (bounded: sketches + counters) ----
        self._c_secs_p50 = P2Quantile(0.5)
        self._c_secs_p95 = P2Quantile(0.95)
        self._c_phase: Dict[str, Dict[str, P2Quantile]] = {}
        self._c_events: Dict[str, int] = {}
        self._c_rounds = 0
        self._c_clients = 0.0
        # last-known cumulative gauges (device-truth counters, tracer
        # drops) — handed in by the scope at observe/flush time, never
        # read from a device
        self.gauges: Dict[str, Any] = {}

    # -- feeds ----------------------------------------------------------
    def observe_round(self, round_no: int, secs: float, clients: float,
                      mfu: Optional[float] = None,
                      rss_bytes: Optional[int] = None) -> None:
        with self._lock:
            if self._w_round_lo is None:
                self._w_round_lo = int(round_no)
            self._w_round_hi = int(round_no)
            self._w_secs.append(float(secs))
            self._w_clients += float(clients)
            if mfu is not None:
                self._w_mfu.append(float(mfu))
            if rss_bytes is not None:
                self.gauges["host_rss_bytes"] = int(rss_bytes)
            self._c_secs_p50.observe(secs)
            self._c_secs_p95.observe(secs)
            self._c_rounds += 1
            self._c_clients += float(clients)

    def observe_phase(self, name: str, secs: float) -> None:
        with self._lock:
            self._w_phase.setdefault(name, []).append(float(secs))
            sketches = self._c_phase.get(name)
            if sketches is None:
                sketches = {"p50": P2Quantile(0.5),
                            "p95": P2Quantile(0.95)}
                self._c_phase[name] = sketches
            sketches["p50"].observe(secs)
            sketches["p95"].observe(secs)

    def observe_event(self, kind: str) -> None:
        with self._lock:
            self._w_events[kind] = self._w_events.get(kind, 0) + 1
            self._c_events[kind] = self._c_events.get(kind, 0) + 1

    def update_gauges(self, values: Dict[str, Any]) -> None:
        with self._lock:
            self.gauges.update(values)

    # -- records --------------------------------------------------------
    def _rounds_in_window(self) -> int:
        return len(self._w_secs)

    def window_record(self, partial: bool = False) -> Dict[str, Any]:
        """The CURRENT window as a record (flushed form, or the live
        snapshot the flight recorder embeds)."""
        with self._lock:
            return self._window_record_locked(partial=partial)

    def _window_record_locked(self, partial: bool = False
                              ) -> Dict[str, Any]:
        # caller holds self._lock
        wall = time.time() - self._w_t0
        # flint: disable=event-schema rollups.jsonl record-type tag, not a telemetry event name
        rec: Dict[str, Any] = {
            "kind": "rollup",
            "window": self.windows_flushed,
            "ts": round(time.time(), 3),
            "round_lo": self._w_round_lo,
            "round_hi": self._w_round_hi,
            "rounds": self._rounds_in_window(),
            "wall_secs": round(wall, 3),
            "secs_per_round_p50": _exact_quantile(self._w_secs, 0.5),
            "secs_per_round_p95": _exact_quantile(self._w_secs, 0.95),
            "clients": round(self._w_clients, 1),
            "clients_per_sec": (round(self._w_clients / wall, 3)
                                if wall > 0 else None),
            "mfu_p50": _exact_quantile(self._w_mfu, 0.5),
            "phase_secs": {
                name: {"count": len(vals),
                       "total": round(sum(vals), 6),
                       "p50": round(_exact_quantile(vals, 0.5), 6),
                       "p95": round(_exact_quantile(vals, 0.95), 6)}
                for name, vals in sorted(self._w_phase.items())},
            "events": dict(sorted(self._w_events.items())),
            # run-cumulative columns (sketch-backed, O(1) memory)
            "cum": {
                "rounds": self._c_rounds,
                "clients": round(self._c_clients, 1),
                "secs_per_round_p50": self._c_secs_p50.value,
                "secs_per_round_p95": self._c_secs_p95.value,
                "events": dict(sorted(self._c_events.items())),
            },
        }
        if partial:
            rec["partial"] = True
        rec.update({k: v for k, v in sorted(self.gauges.items())})
        return rec

    def _reset_window(self) -> None:
        self._w_round_lo = None
        self._w_round_hi = None
        self._w_secs = []
        self._w_clients = 0.0
        self._w_mfu = []
        self._w_phase = {}
        self._w_events = {}
        self._w_t0 = time.time()

    def _append(self, rec: Dict[str, Any]) -> None:
        def _do() -> None:
            if self._fh is None:
                os.makedirs(self.out_dir, exist_ok=True)
                self._fh = open(self.path, "a", encoding="utf-8")
            # one complete line + flush: the crash-safe jsonl idiom — a
            # reader (scope watch / health) never sees a torn record
            # older than the last flush, and a kill loses at most the
            # line being written (readers tolerate a torn tail)
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        if self.ladder is not None:
            ok = self.ladder.run(_do, surface="writer",
                                 what="rollup window append")
        else:
            try:
                _do()
                ok = True
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:  # noqa: BLE001 - telemetry must not abort
                ok = False
        if not ok:
            self._drop_window(rec)

    def _drop_window(self, rec: Dict[str, Any]) -> None:
        """Writer exhaustion: the window record is lost, the loss is
        counted, the handle resets (a broken fh must not poison every
        later flush), and the server's callback turns it into the
        ``rollup_windows_dropped`` instant event — the degradation table
        in action, never an exception up the host tail."""
        self.windows_dropped += 1
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
        cb = self.on_drop
        if cb is not None:
            cb(rec)

    def maybe_flush(self) -> Optional[Dict[str, Any]]:
        """Housekeeping-cadence flush point: append the window record
        when the window is complete; returns the record iff flushed."""
        with self._lock:
            if self._rounds_in_window() < self.window:
                return None
        return self.flush_window()

    def flush_window(self, partial: bool = False
                     ) -> Optional[Dict[str, Any]]:
        """Force-flush the current window (train-exit / close path
        passes ``partial=True`` for an incomplete window).  Record
        building + window reset are atomic under the lock; the file
        append happens outside it."""
        with self._lock:
            if self._rounds_in_window() == 0:
                return None
            rec = self._window_record_locked(partial=partial)
            self.windows_flushed += 1
            self._reset_window()
        self._append(rec)
        return rec

    def close(self) -> None:
        self.flush_window(partial=True)
        if self._fh is not None and not self._fh.closed:
            self._fh.close()


# ----------------------------------------------------------------------
# the flight recorder
# ----------------------------------------------------------------------
class FlightRecorder:
    """Bounded ring of the last-N structured events + the live rollup
    window + the scorecard, persisted atomically as ``flight.json``.

    Fed from the telemetry scope's event path (every structured event
    passes through, whatever its stream destinations); persisted by the
    paths that end a run abnormally — watchdog abort, preemption,
    any BaseException out of the round loop.  ``persist`` is tmp +
    ``os.replace`` (the blessed atomic-write idiom) and re-entrant:
    each call overwrites with the full reason history, so a stall
    abort followed by the exception unwind leaves ONE coherent record
    carrying both."""

    DEFAULT_EVENTS = 256

    def __init__(self, out_dir: str, max_events: int = DEFAULT_EVENTS):
        self.out_dir = out_dir
        self.path = os.path.join(out_dir, FLIGHT_FILENAME)
        self.ring: deque = deque(maxlen=max(int(max_events), 8))
        self.reasons: List[Dict[str, Any]] = []
        #: best-effort scorecard builder (the server wires its
        #: ``build_scorecard``); called at persist time, never earlier
        self.card_fn: Optional[Callable[[], Dict[str, Any]]] = None
        #: the live rollup engine (None when rollups are disabled)
        self.rollup: Optional[RollupEngine] = None

    def record_event(self, kind: str, fields: Dict[str, Any]) -> None:
        self.ring.append({"ts": round(time.time(), 3), "kind": kind,
                          **fields})

    def persist(self, reason: str,
                detail: Optional[str] = None) -> Optional[str]:
        """Write ``flight.json`` atomically; returns the path (None on
        a write failure — the caller is already on an abort path and
        must never die on forensics IO)."""
        self.reasons.append({"ts": round(time.time(), 3),
                             "reason": str(reason),
                             **({"detail": str(detail)[:2000]}
                                if detail else {})})
        record: Dict[str, Any] = {
            "reasons": list(self.reasons),
            "written_ts": round(time.time(), 3),
            "host_rss_bytes": host_rss_bytes(),
            "events": list(self.ring),
        }
        if self.rollup is not None:
            try:
                record["live_window"] = self.rollup.window_record(
                    partial=True)
                record["rollup_windows_flushed"] = \
                    self.rollup.windows_flushed
            except Exception:
                pass
        if self.card_fn is not None:
            try:
                record["scorecard"] = self.card_fn()
            except Exception:
                record["scorecard"] = None
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(record, fh, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
            return self.path
        except OSError:
            return None
