"""flutescope spans — zero-dependency, thread-aware round tracing.

The observability counterpart of the PR-1 pipeline and the PR-2 transfer
contract: every round phase (pack -> dispatch -> device execute ->
packed-stats decode -> housekeeping -> checkpoint submit/drain) becomes a
span, emitted in TWO forms simultaneously:

- ``trace.json`` — Chrome-trace/Perfetto ``traceEvents`` JSON.  Load it
  at https://ui.perfetto.dev to SEE the pipeline overlap: round k's
  host-tail span on the main-thread track running while round k+1's
  device span is open on the "rounds in flight" track, the async
  checkpoint writer on its own thread track, chaos/checkpoint/preemption
  instant events pinned at their timestamps.
- ``events.jsonl`` — one JSON line per completed span/event, appended
  incrementally (crash-safe: a SIGKILL loses at most the buffered tail;
  the preemption drain path flushes it explicitly).

Two span APIs, because the pipelined loop needs both:

- ``with tracer.span("pack", rounds=R):`` — context manager for phases
  that nest normally on the calling thread's track.
- ``token = tracer.begin("round", round0=k)`` / ``tracer.end(token)`` —
  explicit begin/end for spans that OUTLIVE the code block that opened
  them (round k's device window stays open across the host's dispatch of
  k+1).  These land on virtual "in flight" tracks so overlapping spans
  never nest wrongly in a viewer.

Hard constraints (the zero-cost / zero-transfer contract, pinned by
``tests/test_telemetry_contract.py``):

- no jax import anywhere in this module — span args must already be host
  values; handing a device array to a span is devbus misuse (the
  host-sync lint covers the ``.item()``/``float()`` spellings);
- when telemetry is off nothing here is ever constructed; the module's
  only off-path surface is the shared :data:`NULL_SPAN` no-op context.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: the telemetry-off fast path: one shared, stateless no-op context
#: manager (contextlib.nullcontext is re-enterable) — call sites pay a
#: None-check and nothing else
NULL_SPAN = contextlib.nullcontext()

#: virtual-track base tid for begin/end spans (real thread tracks use
#: the OS thread ident; anything >= this is an "in flight" slot)
_FLIGHT_TID_BASE = 1_000_000


class SpanToken:
    """Handle for an explicit begin/end span (see :meth:`Tracer.begin`)."""

    __slots__ = ("name", "args", "t0_us", "tid", "done")

    def __init__(self, name: str, args: Dict[str, Any], t0_us: float,
                 tid: int):
        self.name = name
        self.args = args
        self.t0_us = t0_us
        self.tid = tid
        self.done = False


class Tracer:
    """Collects spans/events; writes ``trace.json`` + ``events.jsonl``.

    Thread-aware: spans record the emitting thread's ident as the trace
    ``tid`` and register a ``thread_name`` metadata row on first use, so
    the async checkpoint writer's serialize/write spans appear on their
    own track.  All mutation is under one lock — span emission is a dict
    append, never IO (IO happens at :meth:`flush`/:meth:`close`, plus
    buffered JSONL appends).
    """

    #: in-memory event cap: past this, new TRACE events are dropped
    #: (counted, and flagged in the flushed trace) while the incremental
    #: JSONL stream keeps recording — bounds a 100k-round run's RAM
    MAX_EVENTS = 1_000_000
    #: minimum seconds between flush_throttled() rewrites of trace.json
    #: (each flush rewrites the whole file; the throttle bounds the
    #: O(events) cost while keeping the on-disk trace reasonably fresh)
    FLUSH_INTERVAL_SECS = 30.0

    def __init__(self, out_dir: str):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.trace_path = os.path.join(out_dir, "trace.json")
        self.events_path = os.path.join(out_dir, "events.jsonl")
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._dropped = 0
        self._t0 = time.perf_counter()
        self._epoch0 = time.time()
        self._pid = os.getpid()
        self._named_threads: set = set()
        self._free_slots: List[int] = []
        self._next_slot = 0
        self._jsonl_fh = open(self.events_path, "a", encoding="utf-8")
        try:
            self._jsonl_bytes = os.path.getsize(self.events_path)
        except OSError:
            self._jsonl_bytes = 0
        #: events.jsonl size cap in bytes (0 = unbounded); armed from
        #: the telemetry block's ``max_log_mb`` knob
        self.max_log_bytes = 0
        self._last_flush = 0.0
        self._closed = False

    @property
    def dropped(self) -> int:
        """Trace events dropped past :data:`MAX_EVENTS` so far — the
        counter ISSUE 13 surfaces into the rollup stream and scorecard
        (the in-trace flag alone was invisible to gates)."""
        return self._dropped

    # -- clock ----------------------------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _epoch_of(self, ts_us: float) -> float:
        return self._epoch0 + ts_us / 1e6

    # -- track bookkeeping ----------------------------------------------
    def _thread_tid(self) -> int:
        ident = threading.get_ident()
        if ident not in self._named_threads:
            self._named_threads.add(ident)
            self._events.append({
                "name": "thread_name", "ph": "M", "pid": self._pid,
                "tid": ident,
                "args": {"name": threading.current_thread().name}})
        return ident

    def _alloc_flight_tid(self) -> int:
        if self._free_slots:
            return _FLIGHT_TID_BASE + self._free_slots.pop()
        slot = self._next_slot
        self._next_slot += 1
        tid = _FLIGHT_TID_BASE + slot
        self._events.append({
            "name": "thread_name", "ph": "M", "pid": self._pid,
            "tid": tid, "args": {"name": f"rounds in flight (slot {slot})"}})
        return tid

    # -- emission -------------------------------------------------------
    def _jsonl(self, record: Dict[str, Any]) -> None:
        # caller holds the lock; buffered append (flush() forces it out)
        if not self._jsonl_fh.closed:
            line = json.dumps(record) + "\n"
            self._jsonl_fh.write(line)
            self._jsonl_bytes += len(line)

    def _append_trace(self, event: Dict[str, Any]) -> None:
        # caller holds the lock.  Past the cap, trace events drop
        # (counted — flush() flags it) but the JSONL stream still
        # records, so nothing is silently lost, only un-visualized.
        if len(self._events) >= self.MAX_EVENTS:
            self._dropped += 1
            return
        self._events.append(event)

    def _emit_complete(self, name: str, t0_us: float, dur_us: float,
                       args: Dict[str, Any], tid: int) -> None:
        with self._lock:
            self._append_trace({
                "name": name, "ph": "X", "ts": round(t0_us, 1),
                "dur": round(max(dur_us, 0.0), 1),
                "pid": self._pid, "tid": tid, "args": args})
            # flint: disable=event-schema events.jsonl record-type tag, not a telemetry event name
            self._jsonl({"kind": "span", "name": name,
                         "ts": round(self._epoch_of(t0_us), 6),
                         "dur_s": round(dur_us / 1e6, 6), **args})

    # -- public span API ------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **args: Any):
        """Context-managed span on the calling thread's track."""
        with self._lock:
            tid = self._thread_tid()
        t0 = self._now_us()
        try:
            yield
        finally:
            self._emit_complete(name, t0, self._now_us() - t0, args, tid)

    def begin(self, name: str, **args: Any) -> SpanToken:
        """Open a span that another code path will :meth:`end` — the
        pipelined-overlap case, placed on a virtual in-flight track."""
        with self._lock:
            tid = self._alloc_flight_tid()
        return SpanToken(name, args, self._now_us(), tid)

    def end(self, token: Optional[SpanToken]) -> None:
        if token is None or token.done:
            return
        token.done = True
        self._emit_complete(token.name, token.t0_us,
                            self._now_us() - token.t0_us, token.args,
                            token.tid)
        with self._lock:
            self._free_slots.append(token.tid - _FLIGHT_TID_BASE)

    def instant(self, name: str, **args: Any) -> None:
        """One structured instant event (chaos fault, checkpoint
        fallback, preemption, watchdog finding) in both streams."""
        ts = self._now_us()
        with self._lock:
            tid = self._thread_tid()
            self._append_trace({
                "name": name, "ph": "i", "s": "p", "ts": round(ts, 1),
                "pid": self._pid, "tid": tid, "args": args})
            # flint: disable=event-schema events.jsonl record-type tag, not a telemetry event name
            self._jsonl({"kind": "event", "name": name,
                         "ts": round(self._epoch_of(ts), 6), **args})

    def counter(self, name: str, value: float, **args: Any) -> None:
        """A Perfetto counter-track sample (devbus scalars plot as time
        series)."""
        ts = self._now_us()
        with self._lock:
            self._append_trace({
                "name": name, "ph": "C", "ts": round(ts, 1),
                "pid": self._pid, "tid": 0,
                "args": {"value": float(value)}})
            # flint: disable=event-schema events.jsonl record-type tag, not a telemetry event name
            self._jsonl({"kind": "counter", "name": name,
                         "ts": round(self._epoch_of(ts), 6),
                         "value": float(value), **args})

    # -- persistence ----------------------------------------------------
    def flush(self) -> None:
        """Rewrite ``trace.json`` (complete, valid JSON every time — a
        trace captured mid-run still loads in Perfetto) and force the
        JSONL buffer out.  The server calls :meth:`flush_throttled` at
        round-housekeeping cadence and this directly at train exit and
        from the preemption flush path."""
        with self._lock:
            snapshot = list(self._events)
            dropped = self._dropped
            if not self._jsonl_fh.closed:
                self._jsonl_fh.flush()
        if dropped:
            # no silent caps: a capped trace says so, in the trace
            snapshot.append({
                "name": "tracer_events_capped", "ph": "i", "s": "p",
                "ts": round(self._now_us(), 1), "pid": self._pid,
                "tid": 0, "args": {"dropped": dropped,
                                   "cap": self.MAX_EVENTS}})
        tmp = self.trace_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"traceEvents": snapshot,
                       "displayTimeUnit": "ms"}, fh)
        os.replace(tmp, self.trace_path)
        self._last_flush = time.perf_counter()
        self._maybe_rotate_jsonl()

    def _maybe_rotate_jsonl(self) -> None:
        """Size-capped events.jsonl rotation (``telemetry.max_log_mb``),
        run at flush cadence.  Inode-swap ordering so no writer is ever
        blocked and no line is ever lost: (1) hardlink the live inode to
        ``events.jsonl.N``; (2) swap a fresh empty inode into the
        primary name (tmp + ``os.replace``); (3) open the new inode;
        (4) under the lock, exchange the handle and close the old one.
        A concurrent span emitted between (2) and (4) still writes the
        OLD inode — which is exactly the segment file now — so ordering
        is preserved; all file opens happen OUTSIDE the tracer lock
        (the lock-discipline contract)."""
        with self._lock:
            need = (self.max_log_bytes and not self._jsonl_fh.closed and
                    self._jsonl_bytes >= self.max_log_bytes)
            rotated_bytes = self._jsonl_bytes
        if not need:
            return
        seg = 1
        while os.path.exists(f"{self.events_path}.{seg}"):
            seg += 1
        try:
            os.link(self.events_path, f"{self.events_path}.{seg}")
            tmp = self.events_path + ".tmp"
            with open(tmp, "w", encoding="utf-8"):
                pass
            os.replace(tmp, self.events_path)
            new_fh = open(self.events_path, "a", encoding="utf-8")
        except OSError:
            return  # rotation is best-effort; the stream must survive
        with self._lock:
            old = self._jsonl_fh
            self._jsonl_fh = new_fh
            self._jsonl_bytes = 0
        if not old.closed:
            old.flush()
            old.close()
        self.instant("log_rotated", file="events.jsonl", segment=seg,
                     rotated_bytes=rotated_bytes)

    def flush_throttled(self) -> None:
        """Round-cadence flush point: rewrites at most once per
        :data:`FLUSH_INTERVAL_SECS` (a full rewrite is O(events)), so a
        long run keeps a reasonably fresh on-disk trace without paying
        the rewrite every round.  The JSONL stream needs no throttle —
        it is incremental."""
        if time.perf_counter() - self._last_flush >= \
                self.FLUSH_INTERVAL_SECS:
            self.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.flush()
        with self._lock:
            if not self._jsonl_fh.closed:
                self._jsonl_fh.close()
