"""Device-metric bus — per-round device scalars riding the packed stats.

The one sanctioned way for engine/strategy code to get a device scalar
into the host-side telemetry stream.  The contract that makes it safe:

- **publish at trace time, inside the round program.**  A publisher
  calls ``bus.publish("dp_clip_frac", b)`` while ``round_step`` is being
  traced; the engine drains the pending values into ``round_stats`` just
  before the flatpack pack, so every published scalar leaves the device
  through the SAME single per-dtype-group transfer as the built-in
  stats.  Zero new ``device_get``s, clean under
  ``MSRFLUTE_STRICT_TRANSFERS=1`` and ``tools/flint`` by construction.
- **never** publish via ``.item()`` / ``float(device_value)`` /
  ``np.asarray(device_value)`` — that is a per-scalar host sync, exactly
  what the bus exists to avoid.  The host-sync lint flags those
  spellings in engine/ops/strategies/telemetry modules
  (``tests/test_analysis.py`` corpus).
- host-side values that were ALREADY fetched through a bundled
  ``device_get`` (the scaffold/EF round tails' ``c_norm``, the stashed
  ``dp_clip``) go through :meth:`publish_host` — a pure bookkeeping call
  that emits the metric/counter without touching the device.

No jax import: published values are opaque to the bus (jnp arrays at
trace time, python floats host-side); the engine owns staging them.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

#: round-stats key prefix for bus-published scalars — the host consumer
#: recognizes (and strips) it after the packed fetch
PREFIX = "devbus_"


class DeviceMetricBus:
    """Trace-time registry of per-round device scalars.

    One instance per :class:`~msrflute_tpu.engine.round.RoundEngine`;
    ``enabled`` is decided once at engine build from
    ``server_config.telemetry`` (off => every publish is a no-op and the
    compiled round program is byte-identical to a telemetry-free build).
    """

    def __init__(self, enabled: bool = False):
        self.enabled = bool(enabled)
        self._pending: Dict[str, Any] = {}

    def publish(self, name: str, value: Any) -> None:
        """Register one per-round scalar (trace time, device value).
        Later publishes under the same name in the same round replace
        earlier ones."""
        if not self.enabled:
            return
        self._pending[str(name)] = value

    def drain(self) -> Dict[str, Any]:
        """The engine's hook, called once per ``round_step`` trace just
        before the flatpack pack: pending values keyed for the stats
        tree."""
        if not self._pending:
            return {}
        out = {PREFIX + k: v for k, v in self._pending.items()}
        self._pending.clear()
        return out

    @staticmethod
    def split_fetched(stats: Dict[str, Any]) -> List[Tuple[str, Any]]:
        """Host side: the bus-published entries of one FETCHED stats dict
        (numpy, post flatpack decode), with the prefix stripped —
        ``[(name, per-round array), ...]``."""
        return [(k[len(PREFIX):], v) for k, v in sorted(stats.items())
                if k.startswith(PREFIX)]
