"""flutescope device-truth layer — compiled-program cost capture and the
recompile sentinel.

Everything flutescope reported before this module was *host-side* time:
spans, wall clocks, fetched scalars.  The compiled XLA executable knows
the other half — how many FLOPs and HBM bytes a round program actually
costs, and when a "steady-state" loop silently recompiled (forfeiting
the whole overlap win).  This module is the ONE place that knowledge is
extracted:

- :class:`XlaIntrospector` — the per-run registry.  The engine wraps
  each fused-round entry point (``round_step``, ``multi_round_r{R}``,
  ``staged_r{R}``, the payload/custom-agg programs, the eval step) in an
  :class:`_InstrumentedFn` that owns the signature->executable cache via
  the AOT path (``jitted.lower(*args).compile()``), so every compile is
  OBSERVED at the moment it happens, with ``cost_analysis()`` FLOPs +
  bytes-accessed and ``memory_analysis()`` temp/argument/output bytes
  recorded per entry point.  The AOT cache replaces jax's internal jit
  dispatch cache for the wrapped callable — same lowering, same
  executable, bit-identical outputs (pinned by the telemetry on/off
  equivalence tests) — which is exactly what makes the capture total:
  a compile cannot happen behind the sentinel's back.
- **recompile sentinel** — each call computes a cheap hashable
  structural key (C++ flatten + per-leaf shape/dtype/weak-type tuples;
  static config is baked into the entry-point name); the descriptive
  signature + per-leaf path map are built only when the key is NEW,
  i.e. at compile time.  A SECOND distinct signature for the same
  entry point is a ``recompile`` event carrying the leaf-level diff
  vs. the previous compile; the ``recompile_storm`` watchdog detector
  (telemetry/watchdog.py) counts these after warmup.
- MFU / HBM helpers — :func:`mfu` is the ONE place the
  ``flops / (secs x chip_peak_flops)`` math lives (bench.py,
  tools/profile_round.py and the server's live per-round MFU all call
  it, so the three can never drift); :func:`aot_cost` is the shared
  "compile this and tell me what it costs" used by the ad-hoc
  call sites the tools had grown.

Import discipline: NO jax import at module import time (the telemetry
package contract — bench.py must pick a backend first); jax is touched
lazily inside calls.  No device values are ever materialized here: cost
and memory analyses are host metadata of the executable, and the
wrapper returns the program's output arrays untouched.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "XlaIntrospector", "aot_cost", "cost_analysis", "memory_analysis",
    "mfu", "operand_signature", "program_size_bytes", "signature_diff",
]


# ----------------------------------------------------------------------
# operand signatures (the recompile sentinel's identity)
# ----------------------------------------------------------------------
def _leaf_desc(leaf: Any) -> List[Any]:
    """``[shape, dtype, weak_type]`` of one operand leaf — exactly the
    structural facts jax's jit cache keys on for array arguments."""
    shape = getattr(leaf, "shape", None)
    if shape is None:
        # non-array leaf (python scalar riding the tree): its type is
        # its signature — a changed python type retraces too
        return [[], type(leaf).__name__, False]
    dtype = str(getattr(leaf, "dtype", ""))
    weak = bool(getattr(getattr(leaf, "aval", None), "weak_type", False))
    return [list(shape), dtype, weak]


def _leaf_key(leaf: Any) -> Any:
    """Hashable structural identity of one leaf — the dispatch-time
    cache key's element.  MUST distinguish exactly what
    :func:`_leaf_desc` does: the two are the fast and the descriptive
    spelling of the same identity."""
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return type(leaf).__name__
    return (tuple(shape), str(getattr(leaf, "dtype", "")),
            bool(getattr(getattr(leaf, "aval", None), "weak_type", False)))


def structural_key(args: Any) -> Tuple[Any, ...]:
    """Hashable ``(treedef, per-leaf keys)`` of an operand tree — the
    per-dispatch cache key.  Built from the C++ flatten plus one tuple
    per leaf (no path strings, no json, no sha1), so the hot dispatch
    path stays cheap even for thousand-leaf param trees; the expensive
    descriptive :func:`operand_signature` runs only when this key is
    NEW (i.e. at compile time, when the diff payload is needed)."""
    from jax.tree_util import tree_flatten

    leaves, treedef = tree_flatten(args)
    return (treedef, tuple(_leaf_key(leaf) for leaf in leaves))


def operand_signature(args: Any) -> Tuple[str, Dict[str, List[Any]]]:
    """``(hash, desc)`` of an operand tree.

    ``desc`` maps each leaf's tree path to ``[shape, dtype, weak_type]``;
    ``hash`` additionally covers the treedef (a changed pytree structure
    — new dict key, dropped operand — is a retrace even when every
    surviving leaf matches).
    """
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, treedef = tree_flatten_with_path(args)
    desc = {keystr(path): _leaf_desc(leaf) for path, leaf in leaves}
    blob = json.dumps([str(treedef), desc], sort_keys=True)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16], desc


def signature_diff(old: Dict[str, List[Any]],
                   new: Dict[str, List[Any]]) -> Dict[str, Any]:
    """Leaf-level difference between two operand signatures — the
    payload of a ``recompile`` event: WHICH operand changed shape/dtype,
    from what, to what."""
    changed = {path: {"was": old[path], "now": new[path]}
               for path in sorted(set(old) & set(new))
               if old[path] != new[path]}
    added = {path: new[path] for path in sorted(set(new) - set(old))}
    removed = {path: old[path] for path in sorted(set(old) - set(new))}
    out: Dict[str, Any] = {}
    if changed:
        out["changed"] = changed
    if added:
        out["added"] = added
    if removed:
        out["removed"] = removed
    return out


# ----------------------------------------------------------------------
# executable analyses (None-safe across jax versions/backends)
# ----------------------------------------------------------------------
def cost_analysis(compiled: Any) -> Dict[str, float]:
    """``{"flops", "bytes_accessed"}`` of a compiled executable, or ``{}``
    when the backend/jax version cannot provide it (multihost partial
    executables, very old runtimes).  The normalization — 0.4.x returns
    a one-dict-per-device list — lives HERE so bench/profiler/telemetry
    can never disagree about it."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        cost = dict(cost)
    except Exception:
        return {}
    out = {}
    if "flops" in cost:
        out["flops"] = float(cost["flops"])
    if "bytes accessed" in cost:
        out["bytes_accessed"] = float(cost["bytes accessed"])
    return out


def memory_analysis(compiled: Any) -> Dict[str, int]:
    """Temp/argument/output byte sizes of a compiled executable —
    ``temp`` is XLA's scratch high-watermark, and ``temp + argument +
    output`` is the program's resident HBM footprint (``hbm_bytes``).
    ``{}`` when unavailable."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    out: Dict[str, int] = {}
    for field, attr in (("temp_bytes", "temp_size_in_bytes"),
                        ("argument_bytes", "argument_size_in_bytes"),
                        ("output_bytes", "output_size_in_bytes"),
                        ("generated_code_bytes",
                         "generated_code_size_in_bytes")):
        value = getattr(mem, attr, None)
        if value is not None:
            out[field] = int(value)
    if {"temp_bytes", "argument_bytes", "output_bytes"} <= set(out):
        out["hbm_bytes"] = (out["temp_bytes"] + out["argument_bytes"]
                            + out["output_bytes"])
    return out


def aot_cost(fn: Callable, *args: Any) -> Optional[Dict[str, Any]]:
    """Compile ``jit(fn)`` (or an already-jitted callable) for ``args``
    via the AOT path and return its merged cost + memory analysis, or
    None when analysis is unavailable.  The one helper behind the
    bench's ``grad_step_cost``, the profiler's cost section and the
    static reports — the ad-hoc ``.lower().compile().cost_analysis()``
    call sites they each used to carry."""
    import jax

    import time

    try:
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        tic = time.perf_counter()
        compiled = jitted.lower(*args).compile()
        secs = time.perf_counter() - tic
    except Exception:
        return None
    out: Dict[str, Any] = {}
    out.update(cost_analysis(compiled))
    out.update(memory_analysis(compiled))
    if out:
        # lower+compile wall seconds of THIS aot call (0.0 when the
        # persistent compilation cache already held the executable) —
        # the bench's per-protocol compile-cost observable
        out["compile_seconds"] = round(secs, 4)
    return out or None


def program_size_bytes(fn: Callable, *args: Any) -> Optional[int]:
    """Compiled-program SIZE proxy for one entry point at one signature:
    the executable's ``generated_code_bytes`` when the backend reports
    it (TPU), else the lowered StableHLO module's text size (CPU reports
    0 generated bytes).  Both scale with traced program TEXT — cloned
    scan bodies, unrolled epochs — not with executed FLOPs, which is
    exactly what the epoch-bloat regression guard must pin
    (tests/test_megakernel.py): a fused-epoch program at num_epochs=4
    sits in the same size class as num_epochs=1, the legacy unrolled
    trace does not."""
    import jax

    try:
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        lowered = jitted.lower(*args)
    except Exception:
        return None
    try:
        gen = memory_analysis(lowered.compile()).get("generated_code_bytes")
        if gen:
            return int(gen)
    except Exception:
        pass
    try:
        return len(lowered.as_text())
    except Exception:
        return None


def mfu(flops: float, secs: float,
        peak_flops: Optional[float] = None) -> Optional[float]:
    """Model FLOPs utilization: ``flops / (secs x peak)``.

    THE shared MFU formula (bench.py / tools/profile_round.py / the
    server's live per-round value).  ``peak_flops`` defaults to this
    process's chip via :func:`~msrflute_tpu.utils.compat.chip_peak_flops`
    — on CPU that is a documented NOMINAL peak, so CPU MFU values are
    comparable across CPU runs but never against a TPU's.  Returns None
    when any input is missing/non-positive.
    """
    if not flops or not secs or secs <= 0:
        return None
    if peak_flops is None:
        from ..utils.compat import chip_peak_flops
        _, peak_flops = chip_peak_flops()
    if not peak_flops or peak_flops <= 0:
        return None
    return float(flops) / float(secs) / float(peak_flops)


# ----------------------------------------------------------------------
# the instrumented entry point + per-run registry
# ----------------------------------------------------------------------
class _InstrumentedFn:
    """AOT-cached wrapper around one jitted entry point.

    Owns the signature -> compiled-executable mapping (so the registry
    sees every compile), dispatches through the cached executable, and
    passes outputs through untouched.  Donation, shardings and
    bit-identical math all ride the identical lowering the plain jit
    call would have used.
    """

    __slots__ = ("_registry", "name", "_jitted", "_cache", "_sig_by_key",
                 "rounds")

    def __init__(self, registry: "XlaIntrospector", name: str,
                 jitted: Callable, rounds: int = 1):
        self._registry = registry
        self.name = name
        self._jitted = jitted
        self._cache: Dict[Any, Any] = {}
        #: structural key -> the descriptive signature hash recorded at
        #: compile time (note_dispatch attributes cost to THIS variant)
        self._sig_by_key: Dict[Any, str] = {}
        #: rounds one call of this entry point executes (R for fused
        #: chunks) — the registry divides FLOPs by it for per-round MFU
        self.rounds = int(rounds)

    def __call__(self, *args: Any) -> Any:
        key = structural_key(args)
        compiled = self._cache.get(key)
        if compiled is None:
            # compile time (the cold path): the descriptive signature +
            # per-leaf desc are built HERE only — steady-state dispatch
            # pays just the tuple key above.  lower+compile wall seconds
            # ride the compile record (ISSUE 12: compile cost is a real
            # per-entry-point budget, surfaced in bench device_truth).
            import time

            sig, desc = operand_signature(args)
            tic = time.perf_counter()
            compiled = self._jitted.lower(*args).compile()
            secs = time.perf_counter() - tic
            self._cache[key] = compiled
            self._sig_by_key[key] = sig
            self._registry.record_compile(self.name, sig, desc, compiled,
                                          rounds=self.rounds,
                                          compile_seconds=secs)
        self._registry.note_dispatch(self.name, self._sig_by_key[key])
        return compiled(*args)

    @property
    def cache_len(self) -> int:
        return len(self._cache)


class XlaIntrospector:
    """One run's compiled-entry-point registry (constructed ONLY when
    ``server_config.telemetry.xla`` enables the layer — the zero-cost
    contract pins that a telemetry-off run never builds one).

    Events are buffered in :attr:`pending_events` and drained by the
    server's host tail into the structured-event streams — compile
    observation itself performs no file IO and no device access.
    """

    def __init__(self) -> None:
        #: entry name -> record (signature, analyses, compile count)
        self.entries: Dict[str, Dict[str, Any]] = {}
        #: structured events awaiting the host tail's drain
        self.pending_events: List[Dict[str, Any]] = []
        #: all compiles / compiles beyond the first per entry point
        self.compiles = 0
        self.recompiles = 0
        #: ``{"entry", "flops", "hbm_bytes", "rounds"}`` of the most
        #: recent round-program dispatch (the server snapshots this per
        #: chunk for the live MFU computation)
        self.last_dispatch: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------
    def wrap(self, name: str, jitted: Callable,
             rounds: int = 1) -> _InstrumentedFn:
        """Wrap one jitted entry point for observed AOT dispatch."""
        return _InstrumentedFn(self, name, jitted, rounds=rounds)

    # ------------------------------------------------------------------
    def record_compile(self, name: str, sig: str,
                       desc: Dict[str, List[Any]], compiled: Any,
                       rounds: int = 1,
                       compile_seconds: Optional[float] = None
                       ) -> Dict[str, Any]:
        """Register one observed compile; returns the entry record.
        First compile of an entry point is an ``xla_compile`` event
        (expected warmup); any later one is a ``recompile`` event
        carrying the operand diff — the sentinel's finding.
        ``compile_seconds`` (lower+compile wall time, when the caller
        measured it) accumulates per entry point across variants."""
        analysis: Dict[str, Any] = {}
        analysis.update(cost_analysis(compiled))
        analysis.update(memory_analysis(compiled))
        entry = self.entries.get(name)
        is_recompile = entry is not None
        event: Dict[str, Any] = {
            "kind": "recompile" if is_recompile else "xla_compile",
            "entry": name, "signature": sig, "rounds": int(rounds),
        }
        event.update(analysis)
        if compile_seconds is not None:
            event["compile_seconds"] = round(float(compile_seconds), 4)
        if is_recompile:
            self.recompiles += 1
            event["compile_index"] = entry["compiles"]
            event["diff"] = signature_diff(entry["desc"], desc)
            entry["compiles"] += 1
            entry["signature"] = sig
            entry["desc"] = desc
            entry.update(analysis)
            if compile_seconds is not None:
                entry["compile_seconds"] = round(
                    entry.get("compile_seconds", 0.0)
                    + float(compile_seconds), 4)
        else:
            entry = {"compiles": 1, "signature": sig, "desc": desc,
                     "rounds": int(rounds), "variants": {}}
            entry.update(analysis)
            if compile_seconds is not None:
                entry["compile_seconds"] = round(float(compile_seconds), 4)
            self.entries[name] = entry
        # per-variant analysis: when several compiled variants of one
        # entry point coexist (bucket churn — the case the sentinel
        # observes), dispatch attribution must come from the variant
        # actually dispatched, not whichever compiled last
        entry.setdefault("variants", {})[sig] = analysis
        self.compiles += 1
        self.pending_events.append(event)
        return entry

    def note_dispatch(self, name: str, sig: Optional[str] = None) -> None:
        """Mark ``name`` as the most recently dispatched entry point
        (round-program entries feed the live MFU; others are ignored by
        the server's snapshot).  ``sig`` selects the compiled VARIANT
        whose analysis is attributed — with several coexisting variants
        (bucket churn) the live MFU/HBM must describe the program that
        actually ran this chunk."""
        entry = self.entries.get(name)
        if entry is None:
            return
        analysis = entry.get("variants", {}).get(sig, entry)
        self.last_dispatch = {
            "entry": name,
            "rounds": int(entry.get("rounds", 1)),
            "flops": analysis.get("flops"),
            "bytes_accessed": analysis.get("bytes_accessed"),
            "hbm_bytes": analysis.get("hbm_bytes"),
        }

    # ------------------------------------------------------------------
    def drain_events(self) -> List[Dict[str, Any]]:
        """Hand the buffered compile/recompile events to the caller
        (the server's host tail, which owns emitting them)."""
        out, self.pending_events = self.pending_events, []
        return out

    def summary(self) -> Dict[str, Any]:
        """Per-entry-point table for the scorecard: FLOPs, bytes, HBM
        footprint, compile count — signatures/descs elided (they live
        in the event stream)."""
        out: Dict[str, Any] = {}
        for name, entry in sorted(self.entries.items()):
            out[name] = {k: entry[k] for k in
                         ("compiles", "rounds", "flops", "bytes_accessed",
                          "temp_bytes", "argument_bytes", "output_bytes",
                          "hbm_bytes", "compile_seconds") if k in entry}
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The cumulative device-truth gauges one rollup window carries
        (ISSUE 13): compile/recompile counters + the HBM high-watermark.
        Host metadata only — reading it never touches a device."""
        return {"compiles": int(self.compiles),
                "recompiles": int(self.recompiles),
                "hbm_peak_bytes": self.hbm_peak_bytes()}

    def hbm_peak_bytes(self) -> Optional[int]:
        """High-watermark resident HBM footprint across every compiled
        entry point (the biggest single program the run dispatched)."""
        peaks = [entry["hbm_bytes"] for entry in self.entries.values()
                 if "hbm_bytes" in entry]
        return max(peaks) if peaks else None
