"""Host-side watchdogs over the per-round telemetry stream.

Detectors (all pure host math over values the round loop ALREADY holds —
fetched train losses, wall-clock round times, the checkpoint
escalator's consecutive-failure count; never a device read):

- **nan_loss** — NaN/inf per-round training loss;
- **round_time** — a round slower than ``round_time_factor`` x the
  trailing-window median (the "where did my throughput go" tripwire);
- **ckpt_failures** — a consecutive checkpoint-save failure streak
  reaching ``ckpt_failure_streak`` (reads the
  :class:`~msrflute_tpu.resilience.integrity.FailureEscalator` counter —
  this fires WARNINGS well before the escalator's own abort threshold
  would kill the run);
- **quarantine_rate** — the fluteshield-quarantined fraction of a
  round's live cohort exceeds ``quarantine_rate_threshold``.  A few
  quarantined clients is the defense working; most of the cohort
  quarantined means the GLOBAL model is what's diverging (every honest
  client returns garbage) — the distinction between "screen and carry
  on" and "stop the run".  Fed only when ``server_config.robust``
  screening is on (the fraction rides the packed round stats).

Each detector has a configurable action (``server_config.telemetry.
watchdog``): ``off`` | ``log`` (event only) | ``mark`` (event + durable
``status_log.json`` marker via the server's mark callback) | ``abort``
(raise :class:`WatchdogAbort` out of the round loop).  Every firing is
emitted as a structured event whatever the action.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Callable, Dict, Optional

ACTIONS = ("off", "log", "mark", "abort")

_DEFAULTS = {
    "nan_loss": "abort",
    "round_time_action": "log",
    "round_time_factor": 3.0,
    "round_time_window": 16,
    "ckpt_failure_action": "mark",
    "ckpt_failure_streak": 3,
    "quarantine_rate_action": "mark",
    "quarantine_rate_threshold": 0.5,
}


class WatchdogAbort(RuntimeError):
    """A watchdog with action ``abort`` fired — the run stops with the
    finding in the message instead of training on garbage."""


class Watchdog:
    """Per-run detector state.  ``on_event(kind, **fields)`` receives
    every finding (trace instant + metrics-stream event); ``on_mark``
    persists a finding to the status log for ``mark``/``abort``."""

    def __init__(self, raw: Optional[Dict[str, Any]] = None,
                 on_event: Optional[Callable[..., None]] = None,
                 on_mark: Optional[Callable[[str, Dict[str, Any]], None]]
                 = None):
        raw = dict(raw or {})
        cfg = dict(_DEFAULTS)
        cfg.update({k: raw[k] for k in _DEFAULTS if k in raw})
        for key in ("nan_loss", "round_time_action", "ckpt_failure_action",
                    "quarantine_rate_action"):
            if cfg[key] not in ACTIONS:
                raise ValueError(
                    f"telemetry.watchdog.{key}: {cfg[key]!r} not in "
                    f"{ACTIONS}")
        self.cfg = cfg
        self.on_event = on_event or (lambda kind, **f: None)
        self.on_mark = on_mark or (lambda kind, fields: None)
        window = max(int(cfg["round_time_window"]), 4)
        self._times: deque = deque(maxlen=window)
        self._last_ckpt_streak = 0
        #: findings fired this run (observability + tests)
        self.findings: list = []

    # ------------------------------------------------------------------
    def observe_round(self, round_no: int,
                      train_loss: Optional[float] = None,
                      round_secs: Optional[float] = None,
                      ckpt_failures: int = 0,
                      quarantine_frac: Optional[float] = None) -> None:
        """Feed one completed round's host-side observations; applies
        every enabled detector and its configured action."""
        if train_loss is not None and self.cfg["nan_loss"] != "off" and \
                not math.isfinite(float(train_loss)):
            self._fire("nan_loss", self.cfg["nan_loss"],
                       round=round_no, train_loss=float(train_loss))
        if quarantine_frac is not None and \
                self.cfg["quarantine_rate_action"] != "off":
            thresh = float(self.cfg["quarantine_rate_threshold"])
            if float(quarantine_frac) > thresh:
                self._fire("quarantine_rate",
                           self.cfg["quarantine_rate_action"],
                           round=round_no,
                           quarantined_frac=round(float(quarantine_frac),
                                                  4),
                           threshold=thresh)
        if round_secs is not None and \
                self.cfg["round_time_action"] != "off":
            factor = float(self.cfg["round_time_factor"])
            if len(self._times) >= self._times.maxlen // 2:
                med = sorted(self._times)[len(self._times) // 2]
                if med > 0 and round_secs > factor * med:
                    self._fire("round_time_regression",
                               self.cfg["round_time_action"],
                               round=round_no,
                               round_secs=round(float(round_secs), 4),
                               trailing_median_secs=round(float(med), 4),
                               factor=factor)
            self._times.append(float(round_secs))
        streak = int(self.cfg["ckpt_failure_streak"])
        if self.cfg["ckpt_failure_action"] != "off" and streak > 0 and \
                ckpt_failures >= streak and \
                ckpt_failures > self._last_ckpt_streak:
            # fire once per new failure in the streak, not once per round
            # forever after; a success resets the escalator counter and
            # therefore re-arms this detector
            self._fire("ckpt_failure_streak",
                       self.cfg["ckpt_failure_action"],
                       round=round_no, consecutive_failures=ckpt_failures)
        self._last_ckpt_streak = int(ckpt_failures)

    # ------------------------------------------------------------------
    def _fire(self, kind: str, action: str, **fields: Any) -> None:
        self.findings.append({"kind": kind, "action": action, **fields})
        self.on_event(f"watchdog_{kind}", action=action, **fields)
        if action in ("mark", "abort"):
            self.on_mark(kind, fields)
        if action == "abort":
            raise WatchdogAbort(
                f"watchdog {kind} fired ({fields}); configured action is "
                "abort — set server_config.telemetry.watchdog to 'mark' "
                "or 'log' to continue through this condition")
