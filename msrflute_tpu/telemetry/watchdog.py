"""Host-side watchdogs over the per-round telemetry stream.

Detectors (all pure host math over values the round loop ALREADY holds —
fetched train losses, wall-clock round times, the checkpoint
escalator's consecutive-failure count; never a device read):

- **nan_loss** — NaN/inf per-round training loss;
- **round_time** — a round slower than ``round_time_factor`` x the
  trailing-window median (the "where did my throughput go" tripwire);
- **ckpt_failures** — a consecutive checkpoint-save failure streak
  reaching ``ckpt_failure_streak`` (reads the
  :class:`~msrflute_tpu.resilience.integrity.FailureEscalator` counter —
  this fires WARNINGS well before the escalator's own abort threshold
  would kill the run);
- **quarantine_rate** — the fluteshield-quarantined fraction of a
  round's live cohort exceeds ``quarantine_rate_threshold``.  A few
  quarantined clients is the defense working; most of the cohort
  quarantined means the GLOBAL model is what's diverging (every honest
  client returns garbage) — the distinction between "screen and carry
  on" and "stop the run".  Fed only when ``server_config.robust``
  screening is on (the fraction rides the packed round stats);
- **recompile_storm** — the device-truth layer's sentinel counter
  (telemetry/xla.py ``recompile`` events: a SECOND compile of an entry
  point that was already warm) reaches
  ``recompile_storm_threshold`` after
  ``recompile_storm_warmup_rounds``.  A steady-state round loop
  compiles each entry point exactly once; every recompile stalls the
  pipeline for a full XLA compile and silently forfeits the overlap
  win, so a storm of them is a "your shapes are churning" finding, not
  noise.  Recompiles that land during the warmup rounds (legitimate
  geometry discovery: step/length buckets, eval-boundary chunk sizes)
  set the baseline and never count toward the storm.

Each detector has a configurable action (``server_config.telemetry.
watchdog``): ``off`` | ``log`` (event only) | ``mark`` (event + durable
``status_log.json`` marker via the server's mark callback) | ``abort``
(raise :class:`WatchdogAbort` out of the round loop).  Every firing is
emitted as a structured event whatever the action.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Callable, Dict, Optional

ACTIONS = ("off", "log", "mark", "abort")

_DEFAULTS = {
    "nan_loss": "abort",
    "round_time_action": "log",
    "round_time_factor": 3.0,
    "round_time_window": 16,
    "ckpt_failure_action": "mark",
    "ckpt_failure_streak": 3,
    "quarantine_rate_action": "mark",
    "quarantine_rate_threshold": 0.5,
    "recompile_storm_action": "log",
    "recompile_storm_threshold": 3,
    "recompile_storm_warmup_rounds": 2,
}


class WatchdogAbort(RuntimeError):
    """A watchdog with action ``abort`` fired — the run stops with the
    finding in the message instead of training on garbage."""


class Watchdog:
    """Per-run detector state.  ``on_event(kind, **fields)`` receives
    every finding (trace instant + metrics-stream event); ``on_mark``
    persists a finding to the status log for ``mark``/``abort``."""

    def __init__(self, raw: Optional[Dict[str, Any]] = None,
                 on_event: Optional[Callable[..., None]] = None,
                 on_mark: Optional[Callable[[str, Dict[str, Any]], None]]
                 = None):
        raw = dict(raw or {})
        cfg = dict(_DEFAULTS)
        cfg.update({k: raw[k] for k in _DEFAULTS if k in raw})
        for key in ("nan_loss", "round_time_action", "ckpt_failure_action",
                    "quarantine_rate_action", "recompile_storm_action"):
            if cfg[key] not in ACTIONS:
                raise ValueError(
                    f"telemetry.watchdog.{key}: {cfg[key]!r} not in "
                    f"{ACTIONS}")
        self.cfg = cfg
        self.on_event = on_event or (lambda kind, **f: None)
        self.on_mark = on_mark or (lambda kind, fields: None)
        window = max(int(cfg["round_time_window"]), 4)
        self._times: deque = deque(maxlen=window)
        self._last_ckpt_streak = 0
        # recompile sentinel state: recompiles observed during the
        # warmup rounds set the baseline; only growth past it counts
        self._recompile_baseline: Optional[int] = None
        self._last_storm_count = 0
        #: findings fired this run (observability + tests)
        self.findings: list = []

    # ------------------------------------------------------------------
    def observe_round(self, round_no: int,
                      train_loss: Optional[float] = None,
                      round_secs: Optional[float] = None,
                      ckpt_failures: int = 0,
                      quarantine_frac: Optional[float] = None,
                      recompiles: Optional[int] = None) -> None:
        """Feed one completed round's host-side observations; applies
        every enabled detector and its configured action.

        ``recompiles`` is the CUMULATIVE recompile-event count from the
        device-truth layer (``RoundEngine.recompile_count`` /
        ``XlaIntrospector.recompiles``) — already "compiles beyond the
        first per entry point", so warm-up first compiles never feed the
        storm detector."""
        if train_loss is not None and self.cfg["nan_loss"] != "off" and \
                not math.isfinite(float(train_loss)):
            self._fire("nan_loss", self.cfg["nan_loss"],
                       round=round_no, train_loss=float(train_loss))
        if quarantine_frac is not None and \
                self.cfg["quarantine_rate_action"] != "off":
            thresh = float(self.cfg["quarantine_rate_threshold"])
            if float(quarantine_frac) > thresh:
                self._fire("quarantine_rate",
                           self.cfg["quarantine_rate_action"],
                           round=round_no,
                           quarantined_frac=round(float(quarantine_frac),
                                                  4),
                           threshold=thresh)
        if round_secs is not None and \
                self.cfg["round_time_action"] != "off":
            factor = float(self.cfg["round_time_factor"])
            if len(self._times) >= self._times.maxlen // 2:
                med = sorted(self._times)[len(self._times) // 2]
                if med > 0 and round_secs > factor * med:
                    self._fire("round_time_regression",
                               self.cfg["round_time_action"],
                               round=round_no,
                               round_secs=round(float(round_secs), 4),
                               trailing_median_secs=round(float(med), 4),
                               factor=factor)
            self._times.append(float(round_secs))
        if recompiles is not None and \
                self.cfg["recompile_storm_action"] != "off":
            warmup = int(self.cfg["recompile_storm_warmup_rounds"])
            if round_no < warmup or self._recompile_baseline is None:
                # warmup rounds (and the first post-warmup observation)
                # anchor the baseline: geometry discovery retraces are
                # expected and must not arm the storm
                self._recompile_baseline = int(recompiles)
            storm = int(recompiles) - self._recompile_baseline
            threshold = int(self.cfg["recompile_storm_threshold"])
            if round_no >= warmup and storm >= threshold and \
                    storm > self._last_storm_count:
                # fire on each NEW recompile past the threshold (the
                # ckpt-streak pattern), not once per round forever
                self._fire("recompile_storm",
                           self.cfg["recompile_storm_action"],
                           round=round_no, recompiles_after_warmup=storm,
                           threshold=threshold)
            self._last_storm_count = storm
        streak = int(self.cfg["ckpt_failure_streak"])
        if self.cfg["ckpt_failure_action"] != "off" and streak > 0 and \
                ckpt_failures >= streak and \
                ckpt_failures > self._last_ckpt_streak:
            # fire once per new failure in the streak, not once per round
            # forever after; a success resets the escalator counter and
            # therefore re-arms this detector
            self._fire("ckpt_failure_streak",
                       self.cfg["ckpt_failure_action"],
                       round=round_no, consecutive_failures=ckpt_failures)
        self._last_ckpt_streak = int(ckpt_failures)

    # ------------------------------------------------------------------
    def _fire(self, kind: str, action: str, **fields: Any) -> None:
        self.findings.append({"kind": kind, "action": action, **fields})
        # name the observing thread in the event and the abort message:
        # a ckpt_failure_streak seen from the named writer thread and
        # one seen from the round loop are different debugging stories
        thread = threading.current_thread().name
        self.on_event(f"watchdog_{kind}", action=action, thread=thread,
                      **fields)
        if action in ("mark", "abort"):
            self.on_mark(kind, fields)
        if action == "abort":
            raise WatchdogAbort(
                f"watchdog {kind} fired on thread {thread} ({fields}); "
                "configured action is abort — set server_config."
                "telemetry.watchdog to 'mark' or 'log' to continue "
                "through this condition")
