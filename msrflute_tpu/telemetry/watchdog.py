"""Host-side watchdogs over the per-round telemetry stream.

Detectors (all pure host math over values the round loop ALREADY holds —
fetched train losses, wall-clock round times, the checkpoint
escalator's consecutive-failure count; never a device read):

- **nan_loss** — NaN/inf per-round training loss;
- **round_time** — a round slower than ``round_time_factor`` x the
  trailing-window median (the "where did my throughput go" tripwire);
- **ckpt_failures** — a consecutive checkpoint-save failure streak
  reaching ``ckpt_failure_streak`` (reads the
  :class:`~msrflute_tpu.resilience.integrity.FailureEscalator` counter —
  this fires WARNINGS well before the escalator's own abort threshold
  would kill the run);
- **quarantine_rate** — the fluteshield-quarantined fraction of a
  round's live cohort exceeds ``quarantine_rate_threshold``.  A few
  quarantined clients is the defense working; most of the cohort
  quarantined means the GLOBAL model is what's diverging (every honest
  client returns garbage) — the distinction between "screen and carry
  on" and "stop the run".  Fed only when ``server_config.robust``
  screening is on (the fraction rides the packed round stats);
- **recompile_storm** — the device-truth layer's sentinel counter
  (telemetry/xla.py ``recompile`` events: a SECOND compile of an entry
  point that was already warm) reaches
  ``recompile_storm_threshold`` after
  ``recompile_storm_warmup_rounds``.  A steady-state round loop
  compiles each entry point exactly once; every recompile stalls the
  pipeline for a full XLA compile and silently forfeits the overlap
  win, so a storm of them is a "your shapes are churning" finding, not
  noise.  Recompiles that land during the warmup rounds (legitimate
  geometry discovery: step/length buckets, eval-boundary chunk sizes)
  set the baseline and never count toward the storm.

Longitudinal detectors (ISSUE 13 — the days-long-run tier; the three
above see one round at a time, these see the TREND):

- **stall** — no round-completion heartbeat within
  ``max(stall_factor x trailing-median round time, stall_grace_secs)``.
  The round_time detector structurally cannot see this: it only runs
  when a round COMPLETES, and a hung device dispatch never completes.
  Detection therefore lives on a named monitor thread
  (``flutescope-stall-monitor``, spawned only when the action is not
  ``off``) polling a heartbeat the drain path updates.  ``abort`` from
  the monitor persists the flight record FIRST (the forensics must be
  durable before any unwind), then interrupts the main thread —
  best-effort by construction: a hang inside a C extension call only
  observes the interrupt when Python bytecode resumes, which is exactly
  why the flight record is written before it;
- **rss_leak** — the least-squares slope of host RSS over a trailing
  ``rss_leak_window``-round window exceeds ``rss_leak_mb_per_round``.
  A slow host-memory leak (an unbounded cache, a list that should have
  been a ring) is invisible per-round and fatal at day two; the window
  re-anchors after each firing so a sustained leak fires once per
  window, not once per round;
- **throughput_drift** — the trailing-window median secs-per-round
  exceeds ``throughput_drift_factor`` x the ANCHOR window's median (the
  first full window observed — compile warmup inflates the anchor, so
  the detector is conservative by construction).  Catches the slow
  degradations round_time's 3x-median spike rule never trips on:
  fragmentation, straggler accumulation, a datacenter neighbor.

Each detector has a configurable action (``server_config.telemetry.
watchdog``): ``off`` | ``log`` (event only) | ``mark`` (event + durable
``status_log.json`` marker via the server's mark callback) | ``abort``
(raise :class:`WatchdogAbort` out of the round loop).  Every firing is
emitted as a structured event whatever the action.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

ACTIONS = ("off", "log", "mark", "abort")

_DEFAULTS = {
    "nan_loss": "abort",
    "round_time_action": "log",
    "round_time_factor": 3.0,
    "round_time_window": 16,
    "ckpt_failure_action": "mark",
    "ckpt_failure_streak": 3,
    "quarantine_rate_action": "mark",
    "quarantine_rate_threshold": 0.5,
    "recompile_storm_action": "log",
    "recompile_storm_threshold": 3,
    "recompile_storm_warmup_rounds": 2,
    # longitudinal detectors (ISSUE 13).  stall defaults OFF because it
    # is the one detector that spawns a monitor thread — endurance
    # configs opt in; the trend detectors are pure observe_round math
    # and default to log like round_time.
    "stall_action": "off",
    "stall_factor": 10.0,
    "stall_poll_secs": 5.0,
    "stall_grace_secs": 30.0,
    "rss_leak_action": "log",
    "rss_leak_window": 32,
    "rss_leak_mb_per_round": 1.0,
    "throughput_drift_action": "log",
    "throughput_drift_window": 16,
    "throughput_drift_factor": 1.5,
}

#: detector keys holding an action value (shared with schema.py's
#: enum validation — a key added here without a schema row is exactly
#: what the flint schema_drift rule exists to catch)
ACTION_KEYS = (
    "nan_loss", "round_time_action", "ckpt_failure_action",
    "quarantine_rate_action", "recompile_storm_action", "stall_action",
    "rss_leak_action", "throughput_drift_action",
)


class WatchdogAbort(RuntimeError):
    """A watchdog with action ``abort`` fired — the run stops with the
    finding in the message instead of training on garbage."""


class Watchdog:
    """Per-run detector state.  ``on_event(kind, **fields)`` receives
    every finding (trace instant + metrics-stream event); ``on_mark``
    persists a finding to the status log for ``mark``/``abort``."""

    def __init__(self, raw: Optional[Dict[str, Any]] = None,
                 on_event: Optional[Callable[..., None]] = None,
                 on_mark: Optional[Callable[[str, Dict[str, Any]], None]]
                 = None):
        raw = dict(raw or {})
        cfg = dict(_DEFAULTS)
        cfg.update({k: raw[k] for k in _DEFAULTS if k in raw})
        for key in ACTION_KEYS:
            if cfg[key] not in ACTIONS:
                raise ValueError(
                    f"telemetry.watchdog.{key}: {cfg[key]!r} not in "
                    f"{ACTIONS}")
        self.cfg = cfg
        self.on_event = on_event or (lambda kind, **f: None)
        self.on_mark = on_mark or (lambda kind, fields: None)
        #: flight-record persist callback (the server wires the
        #: telemetry scope's recorder): the stall monitor calls it
        #: BEFORE interrupting the main thread on abort, so the
        #: forensic record is durable whatever happens to the unwind
        self.on_flight: Optional[Callable[[str], None]] = None
        window = max(int(cfg["round_time_window"]), 4)
        self._times: deque = deque(maxlen=window)
        self._last_ckpt_streak = 0
        # recompile sentinel state: recompiles observed during the
        # warmup rounds set the baseline; only growth past it counts
        self._recompile_baseline: Optional[int] = None
        self._last_storm_count = 0
        # rss_leak trailing window of (round_no, rss_bytes) samples
        self._rss: deque = deque(
            maxlen=max(int(cfg["rss_leak_window"]), 4))
        # throughput_drift: anchor window (the first full window) +
        # trailing window + a fired latch so a sustained drift is one
        # finding per excursion, not one per round
        drift_w = max(int(cfg["throughput_drift_window"]), 4)
        self._drift_anchor: list = []
        self._drift_trail: deque = deque(maxlen=drift_w)
        self._drift_active = False
        # stall heartbeat: a 3-slot list holder mutated by SLICE
        # assignment (atomic under the GIL; a fresh-list rebind would be
        # a cross-thread handoff of live state — the thread-escape
        # class).  [beat_monotonic, trailing_median_secs, round_no]
        self._beat: list = [None, 0.0, -1]
        self._stall_stop = threading.Event()
        self._stall_thread: Optional[threading.Thread] = None
        #: findings fired this run (observability + tests)
        self.findings: list = []

    # ------------------------------------------------------------------
    def observe_round(self, round_no: int,
                      train_loss: Optional[float] = None,
                      round_secs: Optional[float] = None,
                      ckpt_failures: int = 0,
                      quarantine_frac: Optional[float] = None,
                      recompiles: Optional[int] = None,
                      host_rss_bytes: Optional[int] = None) -> None:
        """Feed one completed round's host-side observations; applies
        every enabled detector and its configured action.

        ``recompiles`` is the CUMULATIVE recompile-event count from the
        device-truth layer (``RoundEngine.recompile_count`` /
        ``XlaIntrospector.recompiles``) — already "compiles beyond the
        first per entry point", so warm-up first compiles never feed the
        storm detector."""
        if train_loss is not None and self.cfg["nan_loss"] != "off" and \
                not math.isfinite(float(train_loss)):
            self._fire("nan_loss", self.cfg["nan_loss"],
                       round=round_no, train_loss=float(train_loss))
        if quarantine_frac is not None and \
                self.cfg["quarantine_rate_action"] != "off":
            thresh = float(self.cfg["quarantine_rate_threshold"])
            if float(quarantine_frac) > thresh:
                self._fire("quarantine_rate",
                           self.cfg["quarantine_rate_action"],
                           round=round_no,
                           quarantined_frac=round(float(quarantine_frac),
                                                  4),
                           threshold=thresh)
        if round_secs is not None and \
                self.cfg["round_time_action"] != "off":
            factor = float(self.cfg["round_time_factor"])
            if len(self._times) >= self._times.maxlen // 2:
                med = sorted(self._times)[len(self._times) // 2]
                if med > 0 and round_secs > factor * med:
                    self._fire("round_time_regression",
                               self.cfg["round_time_action"],
                               round=round_no,
                               round_secs=round(float(round_secs), 4),
                               trailing_median_secs=round(float(med), 4),
                               factor=factor)
            self._times.append(float(round_secs))
        if recompiles is not None and \
                self.cfg["recompile_storm_action"] != "off":
            warmup = int(self.cfg["recompile_storm_warmup_rounds"])
            if round_no < warmup or self._recompile_baseline is None:
                # warmup rounds (and the first post-warmup observation)
                # anchor the baseline: geometry discovery retraces are
                # expected and must not arm the storm
                self._recompile_baseline = int(recompiles)
            storm = int(recompiles) - self._recompile_baseline
            threshold = int(self.cfg["recompile_storm_threshold"])
            if round_no >= warmup and storm >= threshold and \
                    storm > self._last_storm_count:
                # fire on each NEW recompile past the threshold (the
                # ckpt-streak pattern), not once per round forever
                self._fire("recompile_storm",
                           self.cfg["recompile_storm_action"],
                           round=round_no, recompiles_after_warmup=storm,
                           threshold=threshold)
            self._last_storm_count = storm
        streak = int(self.cfg["ckpt_failure_streak"])
        if self.cfg["ckpt_failure_action"] != "off" and streak > 0 and \
                ckpt_failures >= streak and \
                ckpt_failures > self._last_ckpt_streak:
            # fire once per new failure in the streak, not once per round
            # forever after; a success resets the escalator counter and
            # therefore re-arms this detector
            self._fire("ckpt_failure_streak",
                       self.cfg["ckpt_failure_action"],
                       round=round_no, consecutive_failures=ckpt_failures)
        self._last_ckpt_streak = int(ckpt_failures)
        if host_rss_bytes is not None and \
                self.cfg["rss_leak_action"] != "off":
            self._observe_rss(round_no, int(host_rss_bytes))
        if round_secs is not None and \
                self.cfg["throughput_drift_action"] != "off":
            self._observe_drift(round_no, float(round_secs))
        # heartbeat for the stall monitor: one slice assignment of
        # (monotonic now, trailing median, round) — the monitor thread
        # only ever READS the holder, so there is no lock to contend on
        # and no live object handed across the thread boundary
        med = 0.0
        if self._times:
            med = sorted(self._times)[len(self._times) // 2]
        self._beat[0:3] = [time.monotonic(), float(med), int(round_no)]

    # ------------------------------------------------------------------
    # longitudinal detectors (ISSUE 13)
    # ------------------------------------------------------------------
    def _observe_rss(self, round_no: int, rss: int) -> None:
        """Trailing-window least-squares slope of host RSS vs round.
        Pure python (n = rss_leak_window, tiny); fires when the slope
        exceeds ``rss_leak_mb_per_round`` over a FULL window, then
        re-anchors (clears the window) so a sustained leak is one
        finding per window."""
        self._rss.append((int(round_no), float(rss)))
        if len(self._rss) < self._rss.maxlen:
            return
        xs = [float(r) for r, _ in self._rss]
        ys = [v for _, v in self._rss]
        n = len(xs)
        mx = sum(xs) / n
        my = sum(ys) / n
        var = sum((x - mx) ** 2 for x in xs)
        if var <= 0:
            return
        slope = sum((x - mx) * (y - my)
                    for x, y in zip(xs, ys)) / var  # bytes per round
        thresh = float(self.cfg["rss_leak_mb_per_round"]) * 2 ** 20
        if thresh > 0 and slope > thresh:
            self._rss.clear()
            self._fire("rss_leak", self.cfg["rss_leak_action"],
                       round=round_no,
                       slope_mb_per_round=round(slope / 2 ** 20, 3),
                       threshold_mb_per_round=round(thresh / 2 ** 20, 3),
                       window_rounds=n,
                       rss_mb=round(ys[-1] / 2 ** 20, 1))

    def _observe_drift(self, round_no: int, secs: float) -> None:
        """Trailing-median secs-per-round vs the anchor window (the
        first full window observed).  A latch keeps a sustained drift
        to one finding per excursion; recovery below the factor
        re-arms."""
        if len(self._drift_anchor) < self._drift_trail.maxlen:
            self._drift_anchor.append(float(secs))
            return
        self._drift_trail.append(float(secs))
        if len(self._drift_trail) < self._drift_trail.maxlen:
            return
        anchor = sorted(self._drift_anchor)[len(self._drift_anchor) // 2]
        trail = sorted(self._drift_trail)[len(self._drift_trail) // 2]
        factor = float(self.cfg["throughput_drift_factor"])
        if anchor > 0 and trail > factor * anchor:
            if not self._drift_active:
                self._drift_active = True
                self._fire("throughput_drift",
                           self.cfg["throughput_drift_action"],
                           round=round_no,
                           trailing_median_secs=round(trail, 4),
                           anchor_median_secs=round(anchor, 4),
                           factor=factor)
        else:
            self._drift_active = False

    # ------------------------------------------------------------------
    # the stall monitor (named thread; spawned only when configured)
    # ------------------------------------------------------------------
    def start_stall_monitor(self) -> bool:
        """Spawn the monitor thread iff ``stall_action`` is not ``off``
        and none is running; returns whether a monitor is active.  The
        server calls this at train() entry and :meth:`stop_stall_monitor`
        on every exit path."""
        if self.cfg["stall_action"] == "off":
            return False
        if self._stall_thread is not None and \
                self._stall_thread.is_alive():
            return True
        self._stall_stop.clear()
        # the monitor ARMS at the first round-completion heartbeat: the
        # window between train() entry and round 0's drain is compile
        # warmup (tens of seconds on a cold cache — longer than any
        # sane grace), not a stall.  A hang BEFORE the first completed
        # round is therefore invisible to this detector by design;
        # the flight recorder + external job timeout own that window.
        self._beat[0:3] = [None, 0.0, -1]
        self._stall_thread = threading.Thread(
            target=self._stall_loop, name="flutescope-stall-monitor",
            daemon=True)
        self._stall_thread.start()
        return True

    def stop_stall_monitor(self) -> None:
        self._stall_stop.set()
        thread = self._stall_thread
        if thread is not None and thread.is_alive() and \
                thread is not threading.current_thread():
            thread.join(timeout=2.0)
        self._stall_thread = None

    def _stall_loop(self) -> None:
        poll = max(float(self.cfg["stall_poll_secs"]), 0.01)
        factor = float(self.cfg["stall_factor"])
        grace = float(self.cfg["stall_grace_secs"])
        action = self.cfg["stall_action"]
        fired_for: Optional[float] = None  # beat we already fired on
        while not self._stall_stop.wait(poll):
            beat, med, rnd = self._beat[0], self._beat[1], self._beat[2]
            if beat is None or beat == fired_for:
                continue
            limit = max(factor * float(med), grace)
            if limit <= 0:
                continue
            since = time.monotonic() - beat
            if since <= limit:
                continue
            fired_for = beat
            try:
                self._fire("stall", action, round=int(rnd) + 1,
                           secs_since_heartbeat=round(since, 3),
                           limit_secs=round(limit, 3),
                           trailing_median_secs=round(float(med), 4))
            except WatchdogAbort as exc:
                # the abort cannot unwind the MAIN thread from here.
                # Persist the flight record first (the durable forensic
                # copy is the whole point), then interrupt the main
                # thread.  With the server's graceful-preemption handler
                # installed (the normal train window) the interrupt
                # lands as a SIGINT preemption request — drain, durable
                # checkpoint, resumable exit, flight carrying the stall;
                # without it, KeyboardInterrupt unwinds through the
                # server's BaseException net.  A hang inside a C
                # extension call defers the interrupt until Python
                # resumes; the flight record is on disk regardless.
                if self.on_flight is not None:
                    try:
                        self.on_flight(f"watchdog_stall: {exc}")
                    except Exception:
                        pass
                import _thread
                _thread.interrupt_main()
                return

    # ------------------------------------------------------------------
    def _fire(self, kind: str, action: str, **fields: Any) -> None:
        self.findings.append({"kind": kind, "action": action, **fields})
        # name the observing thread in the event and the abort message:
        # a ckpt_failure_streak seen from the named writer thread and
        # one seen from the round loop are different debugging stories
        thread = threading.current_thread().name
        self.on_event(f"watchdog_{kind}", action=action, thread=thread,
                      **fields)
        if action in ("mark", "abort"):
            self.on_mark(kind, fields)
        if action == "abort":
            raise WatchdogAbort(
                f"watchdog {kind} fired on thread {thread} ({fields}); "
                "configured action is abort — set server_config."
                "telemetry.watchdog to 'mark' or 'log' to continue "
                "through this condition")
