"""flutescope — round-structured telemetry for the TPU round loop.

Four parts, one config block (``server_config.telemetry``, default OFF
with a measured-zero-overhead fast path — see docs/observability.md):

- :mod:`.spans` — thread-aware span tracer emitting Perfetto-loadable
  ``trace.json`` + a crash-safe ``events.jsonl`` stream;
- :mod:`.devbus` — the device-metric bus: per-round device scalars that
  ride the EXISTING flatpack packed-stats single transfer (zero new
  ``device_get``s);
- :mod:`.profiling` — opt-in ``jax.profiler`` capture for a configured
  round window, compat-guarded for old jax;
- :mod:`.watchdog` — NaN-loss / round-time-regression /
  checkpoint-failure-streak detectors with log/mark/abort actions.

Plus :mod:`.metrics` (the always-on ``metrics.jsonl`` writer + structured
event records, re-exported by ``utils.logging``) and :mod:`.timing` (the
bench/tools stopwatch primitives).

This package imports no jax at import time (``bench.py`` must pick a
backend before jax loads); :mod:`.profiling` touches jax only through
``utils.compat`` when a capture actually starts.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from . import metrics
from .devbus import DeviceMetricBus
from .spans import NULL_SPAN, SpanToken, Tracer
from .timing import Stopwatch, scalar_time
from .watchdog import Watchdog, WatchdogAbort

__all__ = [
    "DeviceMetricBus", "NULL_SPAN", "SpanToken",
    "Stopwatch", "Telemetry", "Tracer", "Watchdog", "WatchdogAbort",
    "devbus_config_enabled", "emit_event", "make_telemetry",
    "scalar_time", "telemetry_config_enabled", "xla_config_enabled",
]

#: subdirectory of the model dir holding trace.json/events.jsonl/profiles
TELEMETRY_DIRNAME = "telemetry"

#: the compact per-run regression surface (tools/scope diff reads it)
SCORECARD_FILENAME = "scorecard.json"


def telemetry_config_enabled(raw: Optional[Dict[str, Any]]) -> bool:
    """Whether a raw ``server_config.telemetry`` block turns the
    subsystem on (absent or ``enable: false`` => off)."""
    return bool(raw) and bool(dict(raw).get("enable", True))


def devbus_config_enabled(raw: Optional[Dict[str, Any]]) -> bool:
    """Whether the device-metric bus is on for this config — the engine
    reads this at build time (a disabled bus leaves the compiled round
    program byte-identical to a telemetry-free build)."""
    return telemetry_config_enabled(raw) and \
        bool(dict(raw).get("devbus", True))


def xla_config_enabled(raw: Optional[Dict[str, Any]]) -> bool:
    """Whether the device-truth layer (``telemetry/xla.py``: compiled
    cost/memory capture + recompile sentinel) is on — the engine reads
    this at build time and constructs an :class:`~.xla.XlaIntrospector`
    only then (telemetry off => zero xla-introspection objects, the
    zero-cost contract)."""
    return telemetry_config_enabled(raw) and \
        bool(dict(raw).get("xla", True))


class Telemetry:
    """One run's telemetry scope: tracer + watchdog + profiler handles.

    Constructed only when ``server_config.telemetry`` enables the
    subsystem — the round loop holds ``None`` otherwise and pays a single
    is-None check per instrumentation point (the zero-cost contract,
    ``tests/test_telemetry_contract.py``).
    """

    def __init__(self, raw: Dict[str, Any], model_dir: str):
        self.raw = dict(raw)
        self.out_dir = os.path.join(model_dir, TELEMETRY_DIRNAME)
        self.tracer: Optional[Tracer] = (
            Tracer(self.out_dir) if self.raw.get("trace", True) else None)
        self.watchdog = Watchdog(self.raw.get("watchdog"),
                                 on_event=self.event)
        self._nonscalar_warned: set = set()
        # lazy import: profiling reaches for jax (via utils.compat) only
        # when a capture window is configured and actually starts
        from .profiling import RoundProfiler
        self.profiler = RoundProfiler(self.raw.get("profile_rounds"),
                                      self.out_dir)

    # -- spans ----------------------------------------------------------
    def span(self, name: str, **args: Any):
        return self.tracer.span(name, **args) if self.tracer is not None \
            else NULL_SPAN

    def begin(self, name: str, **args: Any) -> Optional[SpanToken]:
        return self.tracer.begin(name, **args) if self.tracer is not None \
            else None

    def end(self, token: Optional[SpanToken]) -> None:
        if self.tracer is not None:
            self.tracer.end(token)

    # -- events / devbus ------------------------------------------------
    def event(self, kind: str, **fields: Any) -> None:
        """Structured record in BOTH streams: the always-on metrics
        stream and (when tracing) the trace's instant-event track."""
        metrics.log_event(kind, **fields)
        if self.tracer is not None:
            self.tracer.instant(kind, **fields)

    def devbus_host(self, name: str, value: float,
                    step: Optional[int] = None) -> None:
        """Host-side bus publish for values ALREADY fetched through a
        bundled ``device_get`` (scaffold ``c_norm``, the stashed
        ``dp_clip``): metric line + counter sample, no device access."""
        metrics.log_metric(f"devbus/{name}", float(value), step=step)
        if self.tracer is not None:
            self.tracer.counter(f"devbus/{name}", float(value))

    def consume_devbus(self, stats: Dict[str, Any], round0: int,
                       rounds: int) -> None:
        """Decode bus-published entries of one FETCHED stats dict (numpy,
        ``[R]``-leading) into per-round metric lines + counter samples.

        Non-scalar publishes (e.g. an un-reduced per-client vector from
        inside ``vmap``) are skipped with a one-time warning instead of
        crashing the host tail — the bus contract is per-round SCALARS;
        reduce (psum/mean) before publishing."""
        import numpy as np
        for name, arr in DeviceMetricBus.split_fetched(stats):
            for j in range(rounds):
                value = np.asarray(arr[j] if getattr(arr, "ndim", 0)
                                   else arr)
                if value.size != 1:
                    if name not in self._nonscalar_warned:
                        self._nonscalar_warned.add(name)
                        self.event("devbus_nonscalar_skipped",
                                   metric=name, shape=list(value.shape))
                    break
                value = float(value.reshape(()))
                metrics.log_metric(f"devbus/{name}", value, step=round0 + j)
                if self.tracer is not None:
                    self.tracer.counter(f"devbus/{name}", value)

    # -- scorecard ------------------------------------------------------
    def write_scorecard(self, card: Dict[str, Any]) -> Optional[str]:
        """Persist the run's compact regression surface
        (``telemetry/scorecard.json``) — the machine-readable summary
        ``tools/scope diff`` gates on.  Atomic (tmp + replace) so a
        concurrent reader never sees a torn card; returns the path, or
        None when the block disables it (``scorecard: false``)."""
        if not self.raw.get("scorecard", True):
            return None
        import json
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, SCORECARD_FILENAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(card, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    # -- lifecycle ------------------------------------------------------
    def flush(self) -> None:
        if self.tracer is not None:
            self.tracer.flush()
        metrics.flush_metrics()

    def flush_throttled(self) -> None:
        """Round-housekeeping flush point: keeps the on-disk trace
        reasonably fresh (Tracer.FLUSH_INTERVAL_SECS throttle) without
        paying the full-rewrite cost every round.  Metrics flush
        separately at their own cadence."""
        if self.tracer is not None:
            self.tracer.flush_throttled()

    def close(self) -> None:
        self.profiler.finish()
        if self.tracer is not None:
            self.tracer.close()
        metrics.flush_metrics()


def make_telemetry(raw: Optional[Dict[str, Any]],
                   model_dir: str) -> Optional[Telemetry]:
    """Build the run's :class:`Telemetry` scope, or None when the config
    block is absent/disabled (the default — and the fast path: the round
    loop then contains no telemetry state at all)."""
    if not telemetry_config_enabled(raw):
        return None
    return Telemetry(dict(raw), model_dir)


def emit_event(scope: Optional[Telemetry], kind: str, **fields: Any) -> None:
    """Structured event that works with or without a telemetry scope:
    always a metrics-stream record; additionally a trace instant when
    tracing is on.  The chaos/checkpoint/preemption paths emit through
    here so their events are never log-lines-only again."""
    if scope is not None:
        scope.event(kind, **fields)
    else:
        metrics.log_event(kind, **fields)
