"""flutescope — round-structured telemetry for the TPU round loop.

Four parts, one config block (``server_config.telemetry``, default OFF
with a measured-zero-overhead fast path — see docs/observability.md):

- :mod:`.spans` — thread-aware span tracer emitting Perfetto-loadable
  ``trace.json`` + a crash-safe ``events.jsonl`` stream;
- :mod:`.devbus` — the device-metric bus: per-round device scalars that
  ride the EXISTING flatpack packed-stats single transfer (zero new
  ``device_get``s);
- :mod:`.profiling` — opt-in ``jax.profiler`` capture for a configured
  round window, compat-guarded for old jax;
- :mod:`.watchdog` — NaN-loss / round-time-regression /
  checkpoint-failure-streak detectors with log/mark/abort actions, plus
  the longitudinal tier (stall / rss_leak / throughput_drift);
- :mod:`.rollup` — ISSUE 13's endurance layer: incremental windowed
  rollups (``rollups.jsonl``, O(window) host memory) and the flight
  recorder (``flight.json`` persisted on abort/preemption/exception).

Plus :mod:`.metrics` (the always-on ``metrics.jsonl`` writer + structured
event records, re-exported by ``utils.logging``) and :mod:`.timing` (the
bench/tools stopwatch primitives).

This package imports no jax at import time (``bench.py`` must pick a
backend before jax loads); :mod:`.profiling` touches jax only through
``utils.compat`` when a capture actually starts.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, Optional

from . import metrics, rollup
from .devbus import DeviceMetricBus
from .spans import NULL_SPAN, SpanToken, Tracer
from .timing import Stopwatch, scalar_time
from .watchdog import Watchdog, WatchdogAbort

__all__ = [
    "DeviceMetricBus", "NULL_SPAN", "SpanToken",
    "Stopwatch", "Telemetry", "Tracer", "Watchdog", "WatchdogAbort",
    "devbus_config_enabled", "emit_event", "make_telemetry",
    "scalar_time", "telemetry_config_enabled", "xla_config_enabled",
]

#: subdirectory of the model dir holding trace.json/events.jsonl/profiles
TELEMETRY_DIRNAME = "telemetry"

#: the compact per-run regression surface (tools/scope diff reads it)
SCORECARD_FILENAME = "scorecard.json"


def telemetry_config_enabled(raw: Optional[Dict[str, Any]]) -> bool:
    """Whether a raw ``server_config.telemetry`` block turns the
    subsystem on (absent or ``enable: false`` => off)."""
    return bool(raw) and bool(dict(raw).get("enable", True))


def devbus_config_enabled(raw: Optional[Dict[str, Any]]) -> bool:
    """Whether the device-metric bus is on for this config — the engine
    reads this at build time (a disabled bus leaves the compiled round
    program byte-identical to a telemetry-free build)."""
    return telemetry_config_enabled(raw) and \
        bool(dict(raw).get("devbus", True))


def xla_config_enabled(raw: Optional[Dict[str, Any]]) -> bool:
    """Whether the device-truth layer (``telemetry/xla.py``: compiled
    cost/memory capture + recompile sentinel) is on — the engine reads
    this at build time and constructs an :class:`~.xla.XlaIntrospector`
    only then (telemetry off => zero xla-introspection objects, the
    zero-cost contract)."""
    return telemetry_config_enabled(raw) and \
        bool(dict(raw).get("xla", True))


class Telemetry:
    """One run's telemetry scope: tracer + watchdog + profiler handles.

    Constructed only when ``server_config.telemetry`` enables the
    subsystem — the round loop holds ``None`` otherwise and pays a single
    is-None check per instrumentation point (the zero-cost contract,
    ``tests/test_telemetry_contract.py``).
    """

    def __init__(self, raw: Dict[str, Any], model_dir: str):
        self.raw = dict(raw)
        self.out_dir = os.path.join(model_dir, TELEMETRY_DIRNAME)
        self.tracer: Optional[Tracer] = (
            Tracer(self.out_dir) if self.raw.get("trace", True) else None)
        self.watchdog = Watchdog(self.raw.get("watchdog"),
                                 on_event=self.event)
        self._nonscalar_warned: set = set()
        # endurance layer (ISSUE 13): windowed rollups + flight recorder
        # — both default ON with telemetry (they are the days-long-run
        # observability; telemetry-off still constructs neither)
        self.rollup: Optional[rollup.RollupEngine] = None
        if self.raw.get("rollup", True):
            self.rollup = rollup.RollupEngine(
                self.out_dir,
                window=int(self.raw.get(
                    "rollup_window", rollup.RollupEngine.DEFAULT_WINDOW)))
        self.flight: Optional[rollup.FlightRecorder] = None
        if self.raw.get("flight", True):
            self.flight = rollup.FlightRecorder(
                self.out_dir,
                max_events=int(self.raw.get(
                    "flight_events", rollup.FlightRecorder.DEFAULT_EVENTS)))
            self.flight.rollup = self.rollup
        # the stall monitor persists the flight record BEFORE it
        # interrupts a hung main thread (watchdog.py) — wire it here so
        # the pairing exists whether or not the server adds context
        self.watchdog.on_flight = self.record_flight
        # bounded log growth (telemetry.max_log_mb): arms size-capped
        # rotation for metrics.jsonl AND events.jsonl at flush cadence.
        # Set UNCONDITIONALLY — the metrics cap is a process global, and
        # a later server constructed without the knob must get the
        # documented unbounded default back, not the previous run's cap
        max_log_mb = float(self.raw.get("max_log_mb", 0) or 0)
        metrics.set_max_log_mb(max_log_mb)
        if self.tracer is not None and max_log_mb > 0:
            self.tracer.max_log_bytes = int(max_log_mb * 2 ** 20)
        # lazy import: profiling reaches for jax (via utils.compat) only
        # when a capture window is configured and actually starts
        from .profiling import RoundProfiler
        self.profiler = RoundProfiler(self.raw.get("profile_rounds"),
                                      self.out_dir)

    # -- spans ----------------------------------------------------------
    def span(self, name: str, **args: Any):
        inner = (self.tracer.span(name, **args)
                 if self.tracer is not None else NULL_SPAN)
        if self.rollup is None:
            return inner
        # rollup-fed spans: ONE extra perf_counter pair per phase — the
        # windowed per-phase quantiles come from here, so they exist
        # even when the trace itself is disabled (trace: false)
        return self._rollup_span(name, inner)

    @contextlib.contextmanager
    def _rollup_span(self, name: str, inner):
        t0 = time.perf_counter()
        try:
            with inner:
                yield
        finally:
            self.rollup.observe_phase(name, time.perf_counter() - t0)

    def begin(self, name: str, **args: Any) -> Optional[SpanToken]:
        if self.tracer is not None:
            return self.tracer.begin(name, **args)
        if self.rollup is not None:
            # trace:false still feeds the rollup's per-phase quantiles
            # (the documented contract): a plain timing token on the
            # same µs convention, no tracer track behind it (tid -1)
            return SpanToken(name, args, time.perf_counter() * 1e6, -1)
        return None

    def end(self, token: Optional[SpanToken]) -> None:
        if token is None or token.done:
            return
        if self.tracer is not None:
            if self.rollup is not None:
                self.rollup.observe_phase(
                    token.name,
                    (self.tracer._now_us() - token.t0_us) / 1e6)
            self.tracer.end(token)
            return
        token.done = True
        if self.rollup is not None:
            self.rollup.observe_phase(
                token.name,
                (time.perf_counter() * 1e6 - token.t0_us) / 1e6)

    # -- events / devbus ------------------------------------------------
    def event(self, kind: str, **fields: Any) -> None:
        """Structured record in BOTH streams: the always-on metrics
        stream and (when tracing) the trace's instant-event track —
        plus the rollup window's event counters and the flight ring."""
        metrics.log_event(kind, **fields)
        if self.tracer is not None:
            self.tracer.instant(kind, **fields)
        if self.rollup is not None:
            self.rollup.observe_event(kind)
        if self.flight is not None:
            self.flight.record_event(kind, fields)

    def devbus_host(self, name: str, value: float,
                    step: Optional[int] = None) -> None:
        """Host-side bus publish for values ALREADY fetched through a
        bundled ``device_get`` (scaffold ``c_norm``, the stashed
        ``dp_clip``): metric line + counter sample, no device access."""
        metrics.log_metric(f"devbus/{name}", float(value), step=step)
        if self.tracer is not None:
            self.tracer.counter(f"devbus/{name}", float(value))

    def consume_devbus(self, stats: Dict[str, Any], round0: int,
                       rounds: int) -> None:
        """Decode bus-published entries of one FETCHED stats dict (numpy,
        ``[R]``-leading) into per-round metric lines + counter samples.

        Non-scalar publishes (e.g. an un-reduced per-client vector from
        inside ``vmap``) are skipped with a one-time warning instead of
        crashing the host tail — the bus contract is per-round SCALARS;
        reduce (psum/mean) before publishing."""
        import numpy as np
        for name, arr in DeviceMetricBus.split_fetched(stats):
            for j in range(rounds):
                value = np.asarray(arr[j] if getattr(arr, "ndim", 0)
                                   else arr)
                if value.size != 1:
                    if name not in self._nonscalar_warned:
                        self._nonscalar_warned.add(name)
                        self.event("devbus_nonscalar_skipped",
                                   metric=name, shape=list(value.shape))
                    break
                value = float(value.reshape(()))
                metrics.log_metric(f"devbus/{name}", value, step=round0 + j)
                if self.tracer is not None:
                    self.tracer.counter(f"devbus/{name}", value)

    # -- scorecard ------------------------------------------------------
    def write_scorecard(self, card: Dict[str, Any]) -> Optional[str]:
        """Persist the run's compact regression surface
        (``telemetry/scorecard.json``) — the machine-readable summary
        ``tools/scope diff`` gates on.  Atomic (tmp + replace) so a
        concurrent reader never sees a torn card; returns the path, or
        None when the block disables it (``scorecard: false``)."""
        if not self.raw.get("scorecard", True):
            return None
        import json
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, SCORECARD_FILENAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(card, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    # -- endurance rollups + flight recorder (ISSUE 13) -----------------
    def rollup_observe(self, round_no: int, secs: float, clients: float,
                       mfu: Optional[float] = None,
                       rss_bytes: Optional[int] = None,
                       xla_snapshot: Optional[Dict[str, Any]] = None
                       ) -> None:
        """One completed round's longitudinal observations (all values
        the host tail already holds — the zero-transfer contract)."""
        if self.rollup is None:
            return
        gauges = dict(xla_snapshot or {})
        if self.tracer is not None:
            gauges["trace_events_dropped"] = self.tracer.dropped
        if gauges:
            self.rollup.update_gauges(gauges)
        self.rollup.observe_round(round_no, secs, clients, mfu=mfu,
                                  rss_bytes=rss_bytes)

    def rollup_housekeeping(self) -> None:
        """Round-housekeeping flush point: append the rollup record
        when the window completed (bounded work, no throttle needed —
        at most one record per ``rollup_window`` rounds)."""
        if self.rollup is not None:
            self.rollup.maybe_flush()

    def record_flight(self, reason: str,
                      detail: Optional[str] = None) -> Optional[str]:
        """Persist ``flight.json`` (no-op when the recorder is off) —
        the abort/preemption/exception paths' forensic snapshot."""
        if self.flight is None:
            return None
        return self.flight.persist(reason, detail=detail)

    def set_flight_context(self, card_fn) -> None:
        """Wire the server's scorecard builder into the flight record
        (called best-effort at persist time, never earlier)."""
        if self.flight is not None:
            self.flight.card_fn = card_fn

    # -- lifecycle ------------------------------------------------------
    def flush(self) -> None:
        if self.tracer is not None:
            self.tracer.flush()
        metrics.flush_metrics()

    def flush_throttled(self) -> None:
        """Round-housekeeping flush point: keeps the on-disk trace
        reasonably fresh (Tracer.FLUSH_INTERVAL_SECS throttle) without
        paying the full-rewrite cost every round.  Metrics flush
        separately at their own cadence."""
        if self.tracer is not None:
            self.tracer.flush_throttled()

    def close(self) -> None:
        self.profiler.finish()
        self.watchdog.stop_stall_monitor()
        if self.rollup is not None:
            self.rollup.close()
        if self.tracer is not None:
            self.tracer.close()
        metrics.flush_metrics()


def make_telemetry(raw: Optional[Dict[str, Any]],
                   model_dir: str) -> Optional[Telemetry]:
    """Build the run's :class:`Telemetry` scope, or None when the config
    block is absent/disabled (the default — and the fast path: the round
    loop then contains no telemetry state at all)."""
    if not telemetry_config_enabled(raw):
        return None
    return Telemetry(dict(raw), model_dir)


def emit_event(scope: Optional[Telemetry], kind: str, **fields: Any) -> None:
    """Structured event that works with or without a telemetry scope:
    always a metrics-stream record; additionally a trace instant when
    tracing is on.  The chaos/checkpoint/preemption paths emit through
    here so their events are never log-lines-only again."""
    if scope is not None:
        scope.event(kind, **fields)
    else:
        metrics.log_event(kind, **fields)
