"""``tools/scope`` — summarize, diff, trend, watch and health-gate
flutescope output.

Five commands (the bare form stays ``tools/scope <run_dir>``):

- ``tools/scope <run_dir>`` / ``tools/scope summarize <run_dir>`` —
  ONE JSON object summarizing a run's telemetry (below);
- ``tools/scope diff A B [--gate] [--pct N]`` — compare two runs'
  ``scorecard.json`` regression surfaces (A = baseline, B = candidate)
  with per-metric thresholds; ``--gate`` exits **3** when B regresses,
  naming the offending metric — the CI / endurance-harness tripwire;
- ``tools/scope trend BENCH_*.json... [--gate] [--pct N]`` — walk a
  series of committed bench artifacts and flag a headline / per-protocol
  round-time regression between the last two measured entries (same
  exit-code contract);
- ``tools/scope watch <run_dir> [--interval S] [--once]`` — live tail
  of the endurance rollup stream (``rollups.jsonl``), one compact line
  per flushed window: the babysitting view of a days-long run;
- ``tools/scope health <run_dir> [--gate]`` — the endurance health
  ORACLE: one verdict over the rollup stream, watchdog firings, the
  flight record and the scorecard.  ``--gate`` exits **3** when the
  run is unhealthy (naming every finding), **2** when the inputs are
  unreadable — the exit code the endurance harness and CI smoke gate
  on (ISSUE 13).

All readers walk size-capped rotation segments
(``metrics.jsonl.1``, ``events.jsonl.2``, ...) transparently, oldest
first, and tolerate a torn trailing line from a crash mid-write.

Summarize input: a model dir (or its ``telemetry/`` subdir) holding any
of ``telemetry/trace.json``, ``telemetry/events.jsonl``,
``metrics.jsonl``.  Output: ONE JSON object answering the questions a
round trace exists for —

- **phase-time breakdown**: total/count/p50 per span name (pack,
  dispatch, stats_fetch, host_tail, housekeeping, ckpt_submit,
  ckpt_async_write, eval, round_device, ...) — where the round time
  went;
- **overlap efficiency**: the fraction of host-tail time that ran while
  a device round was in flight (pipeline health: ~100% means the host
  tail is fully hidden; ~0% means the pipeline isn't pipelining), plus
  a per-depth breakdown (``by_depth``): how much of that overlapped
  tail ran while exactly 1, 2, ... N device rounds were in flight —
  the depth-N ring's (``server_config.pipeline_depth``) evidence that
  extra depth is (or is not) buying additional overlap;
- **fault/event table**: chaos faults, checkpoint recovery/IO faults,
  preemption, watchdog findings — counts per kind;
- **round span + counters/metrics inventory** so a reader knows what
  else the run recorded.

Pure stdlib (the flint discipline: safe from any shell, never touches
jax); deterministic for a fixed input, which the golden-output test
(``tests/test_scope_cli.py``) pins against a recorded fixture run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

#: span names that constitute the host tail for the overlap metric
_HOST_TAIL_SPANS = ("host_tail",)
#: span name of the device in-flight window
_DEVICE_SPAN = "round_device"


def _load_trace(path: str) -> List[Dict[str, Any]]:
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    try:
        parsed = json.loads(text)
    except json.JSONDecodeError:
        # a run killed mid-write can leave a truncated JSON array;
        # salvage the complete prefix rather than refusing the file
        cut = text.rfind("}")
        if cut < 0:
            return []
        salvage = text[: cut + 1] + "]}" if text.lstrip().startswith("{") \
            else text[: cut + 1] + "]"
        try:
            parsed = json.loads(salvage)
        except json.JSONDecodeError:
            return []
    if isinstance(parsed, dict):
        return list(parsed.get("traceEvents", []))
    return list(parsed) if isinstance(parsed, list) else []


def _segment_paths(path: str) -> List[str]:
    """Rotated segments of one jsonl stream, oldest first, primary
    last — the reader-side mirror of the writer's size-capped rotation
    (``telemetry.max_log_mb``; telemetry/metrics.py ``rotate_jsonl``).
    Duplicated here as pure stdlib on purpose: tools/scope must never
    import the package (the flint discipline); the two walks are
    pinned together by tests/test_endurance.py."""
    out = []
    seg = 1
    while os.path.exists(f"{path}.{seg}"):
        out.append(f"{path}.{seg}")
        seg += 1
    if os.path.exists(path):
        out.append(path)
    return out


def _jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for seg in _segment_paths(path) or ([path] if os.path.exists(path)
                                        else []):
        with open(seg, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail line of a killed run
    return out


def _p50(values: List[float]) -> float:
    return sorted(values)[len(values) // 2] if values else 0.0


def _interval_overlap(a: List[Tuple[float, float]],
                      b: List[Tuple[float, float]]) -> float:
    """Total length of ``a`` intervals covered by the union of ``b``."""
    events = sorted(b)
    merged: List[Tuple[float, float]] = []
    for lo, hi in events:
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    covered = 0.0
    for lo, hi in a:
        for mlo, mhi in merged:
            if mhi <= lo:
                continue
            if mlo >= hi:
                break
            covered += min(hi, mhi) - max(lo, mlo)
    return covered


def _depth_segments(ivs: List[Tuple[float, float]]
                    ) -> Dict[int, List[Tuple[float, float]]]:
    """Timeline regions keyed by how many ``ivs`` cover them (>= 1) —
    the rounds-in-flight depth profile of the device windows."""
    events: List[Tuple[float, int]] = []
    for lo, hi in ivs:
        events.append((lo, 1))
        events.append((hi, -1))
    events.sort()
    segs: Dict[int, List[Tuple[float, float]]] = {}
    depth = 0
    prev: Optional[float] = None
    for t, d in events:
        if prev is not None and depth > 0 and t > prev:
            segs.setdefault(depth, []).append((prev, t))
        depth += d
        prev = t
    return segs


def summarize(run_dir: str) -> Dict[str, Any]:
    """The scope summary for one run directory (see module docstring)."""
    tdir = run_dir
    if os.path.isdir(os.path.join(run_dir, "telemetry")):
        tdir = os.path.join(run_dir, "telemetry")
    trace_path = os.path.join(tdir, "trace.json")
    events_path = os.path.join(tdir, "events.jsonl")
    # metrics.jsonl lives at the run root (init_logging), but tolerate a
    # copy next to the trace (fixtures, relocated runs)
    metrics_path = os.path.join(run_dir, "metrics.jsonl")
    if not os.path.exists(metrics_path):
        metrics_path = os.path.join(tdir, "metrics.jsonl")

    out: Dict[str, Any] = {"run_dir": os.path.basename(
        os.path.abspath(run_dir))}

    # ---- spans + overlap from the trace --------------------------------
    spans: Dict[str, List[float]] = {}
    host_tail_iv: List[Tuple[float, float]] = []
    device_iv: List[Tuple[float, float]] = []
    rounds: List[int] = []
    counters: Dict[str, Dict[str, Any]] = {}
    trace_events: Dict[str, int] = {}
    if os.path.exists(trace_path):
        for ev in _load_trace(trace_path):
            ph = ev.get("ph")
            name = str(ev.get("name", ""))
            if ph == "X":
                dur_s = float(ev.get("dur", 0.0)) / 1e6
                spans.setdefault(name, []).append(dur_s)
                iv = (float(ev.get("ts", 0.0)),
                      float(ev.get("ts", 0.0)) + float(ev.get("dur", 0.0)))
                if name in _HOST_TAIL_SPANS:
                    host_tail_iv.append(iv)
                elif name == _DEVICE_SPAN:
                    device_iv.append(iv)
                    args = ev.get("args") or {}
                    if "round0" in args:
                        r0 = int(args["round0"])
                        rounds.extend(
                            range(r0, r0 + int(args.get("rounds", 1))))
            elif ph == "i":
                trace_events[name] = trace_events.get(name, 0) + 1
            elif ph == "C":
                args = ev.get("args") or {}
                c = counters.setdefault(name, {"samples": 0, "last": None})
                c["samples"] += 1
                c["last"] = args.get("value")
        out["phase_secs"] = {
            name: {"count": len(vals),
                   "total_s": round(sum(vals), 6),
                   "p50_s": round(_p50(vals), 6)}
            for name, vals in sorted(spans.items())}
        if rounds:
            out["rounds"] = {"count": len(set(rounds)),
                             "first": min(rounds), "last": max(rounds)}
        tail_total = sum(hi - lo for lo, hi in host_tail_iv) / 1e6
        overlapped = _interval_overlap(host_tail_iv, device_iv) / 1e6
        out["overlap"] = {
            "host_tail_s": round(tail_total, 6),
            "overlapped_s": round(overlapped, 6),
            "efficiency_pct": round(100.0 * overlapped / tail_total, 1)
            if tail_total > 0 else 0.0,
        }
        segs = _depth_segments(device_iv)
        if segs:
            # host-tail seconds that ran while exactly d device rounds
            # were in flight: the depth-N pipeline ring's per-depth
            # evidence (a depth-3 config whose by_depth has no "2"/"3"
            # mass is not actually going deeper than 1)
            out["overlap"]["by_depth"] = {
                str(d): round(_interval_overlap(host_tail_iv, iv) / 1e6, 6)
                for d, iv in sorted(segs.items())}
            out["overlap"]["max_rounds_in_flight"] = max(segs)
        if counters:
            out["counters"] = {k: dict(v) for k, v in sorted(
                counters.items())}
    else:
        out["trace"] = "absent"

    # ---- event table: one structured event is emitted to up to three
    # streams (trace instant, events.jsonl, metrics.jsonl record) — take
    # the per-name MAX across sources so nothing is double-counted and a
    # stream a killed run lost does not under-count ------------------
    sources: List[Dict[str, int]] = [trace_events]
    if os.path.exists(events_path):
        counts: Dict[str, int] = {}
        for rec in _jsonl(events_path):
            if rec.get("kind") == "event":
                name = str(rec.get("name", "?"))
                counts[name] = counts.get(name, 0) + 1
        sources.append(counts)
    if os.path.exists(metrics_path):
        counts = {}
        metric_names: Dict[str, int] = {}
        for rec in _jsonl(metrics_path):
            if "event" in rec:
                name = str(rec["event"])
                counts[name] = counts.get(name, 0) + 1
            elif "name" in rec:
                metric_names[str(rec["name"])] = \
                    metric_names.get(str(rec["name"]), 0) + 1
        sources.append(counts)
        if metric_names:
            out["metrics"] = {"lines": sum(metric_names.values()),
                              "names": sorted(metric_names)}
    events: Dict[str, int] = {}
    for counts in sources:
        for name, count in counts.items():
            events[name] = max(events.get(name, 0), count)
    if events:
        out["events"] = dict(sorted(events.items()))

    # ---- device-truth scorecard (telemetry/scorecard.json): surfaced
    # verbatim so one `tools/scope <dir>` answers the MFU/HBM/recompile
    # questions without a second command ------------------------------
    card_path = os.path.join(tdir, "scorecard.json")
    if os.path.exists(card_path):
        try:
            with open(card_path, "r", encoding="utf-8") as fh:
                out["scorecard"] = json.load(fh)
        except (OSError, json.JSONDecodeError):
            out["scorecard"] = "unreadable"
    return out


# ======================================================================
# scorecard diff — the cross-run regression gate
# ======================================================================
#: per-metric regression rules: (direction, default threshold).
#: ``higher_frac``: B worse when > A x (1 + frac); ``lower_frac``: B
#: worse when < A x (1 - frac); ``higher_abs`` / ``lower_abs``:
#: absolute-delta rules (counts, percentage points).  Thresholds scale
#: with ``--pct`` except the count rules (any increase in recompiles /
#: puts-per-dispatch is a real finding — those counters are flat in a
#: healthy steady state by construction).
DIFF_RULES: Dict[str, Tuple[str, float]] = {
    "round_secs_p50": ("higher_frac", 0.15),
    "host_tail_secs_p50": ("higher_frac", 0.30),
    "staged_bytes_per_round_p50": ("higher_frac", 0.10),
    "hbm_peak_bytes": ("higher_frac", 0.10),
    "mfu_p50": ("lower_frac", 0.15),
    # real samples / padded grid slots (cohort shape-bucketing's win):
    # a DROP means the grids grew back toward the monolithic worst case
    # — e.g. a bucket-boundary change silently re-padding small clients
    "padding_efficiency": ("lower_frac", 0.10),
    # tape-slot occupancy of the cross-client megabatch lanes: a DROP
    # means the lane planner stopped packing small clients densely
    # (lane geometry drift) or the dispatch gate fell back to the
    # per-client vmap arm on buckets it used to fuse
    "megabatch_utilization": ("lower_frac", 0.10),
    "overlap_efficiency_pct": ("lower_abs", 10.0),
    "recompiles": ("higher_abs", 0.0),
    "puts_per_dispatch": ("higher_abs", 0.0),
    # fleet transfer plane (mesh-sharded page pool): per-device paging
    # bytes are total/mesh_size by construction — a replicated pool
    # snaps them back to the total (xmesh_size), far past this margin
    "fleet_page_in_bytes_per_device": ("higher_frac", 0.5),
    "fleet_writeback_bytes_per_device": ("higher_frac", 0.5),
    # prefetch coverage collapsing means the page-in host IO moved back
    # onto the critical path
    "fleet_prefetch_hit_rate": ("lower_abs", 0.25),
}

#: metrics whose thresholds scale with --pct (the wall-clock-ish ones)
_PCT_SCALED = {"round_secs_p50", "host_tail_secs_p50",
               "staged_bytes_per_round_p50", "hbm_peak_bytes", "mfu_p50",
               "padding_efficiency", "megabatch_utilization"}


def load_scorecard(path: str) -> Dict[str, Any]:
    """A scorecard from a file path, a run dir, or its telemetry dir."""
    if os.path.isdir(path):
        for cand in (os.path.join(path, "telemetry", "scorecard.json"),
                     os.path.join(path, "scorecard.json")):
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(
                f"no scorecard.json under {path!r} — was the run's "
                "telemetry block enabled (server_config.telemetry)?")
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def diff_scorecards(a: Dict[str, Any], b: Dict[str, Any],
                    pct: Optional[float] = None) -> Dict[str, Any]:
    """Compare baseline ``a`` against candidate ``b``: per-metric deltas
    plus the thresholded ``regressions`` list (each naming the metric,
    both values and the limit it broke).  ``pct`` overrides the
    wall-clock-class thresholds (as a percentage, e.g. 15)."""
    metrics: Dict[str, Any] = {}
    regressions: List[Dict[str, Any]] = []
    for name, (direction, default_thresh) in DIFF_RULES.items():
        va, vb = a.get(name), b.get(name)
        row: Dict[str, Any] = {"a": va, "b": vb}
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            row["delta"] = round(float(vb) - float(va), 6)
            if va:
                row["delta_pct"] = round(100.0 * (vb - va) / abs(va), 2)
            thresh = default_thresh
            if pct is not None and name in _PCT_SCALED:
                thresh = float(pct) / 100.0
            regressed, limit = False, None
            if direction == "higher_frac" and va > 0:
                limit = va * (1.0 + thresh)
                regressed = vb > limit
            elif direction == "lower_frac" and va > 0:
                limit = va * (1.0 - thresh)
                regressed = vb < limit
            elif direction == "higher_abs":
                limit = va + thresh
                regressed = vb > limit
            elif direction == "lower_abs":
                limit = va - thresh
                regressed = vb < limit
            if regressed:
                regressions.append({
                    "metric": name, "a": va, "b": vb,
                    "limit": round(float(limit), 6),
                    "rule": direction, "threshold": thresh})
        metrics[name] = row
    return {"metrics": metrics, "regressions": regressions,
            "ok": not regressions}


def _diff_main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="scope diff",
        description="compare two runs' scorecard.json regression "
                    "surfaces (A = baseline, B = candidate)")
    ap.add_argument("a", help="baseline: scorecard.json or run dir")
    ap.add_argument("b", help="candidate: scorecard.json or run dir")
    ap.add_argument("--gate", action="store_true",
                    help="exit 3 when the candidate regresses")
    ap.add_argument("--pct", type=float, default=None,
                    help="override the wall-clock-class thresholds (%%)")
    ap.add_argument("--indent", type=int, default=None)
    args = ap.parse_args(argv)
    try:
        card_a, card_b = load_scorecard(args.a), load_scorecard(args.b)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"scope diff: {exc}", file=sys.stderr)
        return 2
    out = diff_scorecards(card_a, card_b, pct=args.pct)
    out["a"], out["b"] = args.a, args.b
    print(json.dumps(out, indent=args.indent, sort_keys=True))
    if out["regressions"]:
        names = ", ".join(r["metric"] for r in out["regressions"])
        print(f"scope diff: REGRESSION in {names}", file=sys.stderr)
        if args.gate:
            return 3
    return 0


# ======================================================================
# bench-artifact trend — the committed-trajectory gate
# ======================================================================
def _bench_entry(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if "parsed" in data and "metric" not in data:
        # driver-round record (BENCH_rNN.json): bench.py's line sits
        # under "parsed" — null when the driver truncated the capture,
        # which trend treats as an unmeasured entry and skips over
        data = data.get("parsed") or {}
    protocols = {}
    for name, block in (data.get("extras") or {}).items():
        if isinstance(block, dict) and "secs_per_round" in block:
            row = {"secs_per_round": block.get("secs_per_round")}
            for key in ("mfu_vs_bf16_peak", "device_truth",
                        "padding_efficiency", "megabatch_utilization",
                        "rounds_to_target_accuracy", "traffic"):
                if key in block:
                    row[key] = block[key]
            protocols[name] = row
    return {"file": os.path.basename(path),
            "metric": data.get("metric"), "value": data.get("value"),
            "backend": (data.get("extras") or {}).get("backend"),
            "protocols": protocols}


def trend_bench(paths: List[str],
                pct: Optional[float] = None) -> Dict[str, Any]:
    """Series view over committed bench artifacts (given order — pass
    them sorted; BENCH_* stamps sort chronologically) + regressions
    between the last two entries that actually measured: the headline
    ``value``, each shared protocol's ``secs_per_round``, and — when a
    convergence target is configured — its
    ``rounds_to_target_accuracy``, all gated at ``pct`` (default 15%)
    worse-than-previous; efficiency ratios gate in the drop
    direction."""
    thresh = (float(pct) if pct is not None else 15.0) / 100.0
    series = [_bench_entry(p) for p in paths]
    measured = [e for e in series if isinstance(e.get("value"),
                                                (int, float))]
    regressions: List[Dict[str, Any]] = []
    if len(measured) >= 2:
        prev, last = measured[-2], measured[-1]
        if last["value"] > prev["value"] * (1.0 + thresh):
            regressions.append({
                "metric": "value", "a": prev["value"], "b": last["value"],
                "a_file": prev["file"], "b_file": last["file"],
                "limit": round(prev["value"] * (1.0 + thresh), 6),
                "threshold": thresh})
        for name in sorted(set(prev["protocols"]) & set(last["protocols"])):
            sa = prev["protocols"][name].get("secs_per_round")
            sb = last["protocols"][name].get("secs_per_round")
            if isinstance(sa, (int, float)) and \
                    isinstance(sb, (int, float)) and sa > 0 and \
                    sb > sa * (1.0 + thresh):
                regressions.append({
                    "metric": f"{name}.secs_per_round", "a": sa, "b": sb,
                    "a_file": prev["file"], "b_file": last["file"],
                    "limit": round(sa * (1.0 + thresh), 6),
                    "threshold": thresh})
            # efficiency ratios are gated in the OTHER direction: a
            # padding_efficiency drop means the round grids grew back
            # toward the monolithic pad-to-slowest worst case (cohort-
            # bucketing regression); a megabatch_utilization drop means
            # the lane planner stopped fusing small clients densely (or
            # the gate fell back to per-client vmap)
            for eff in ("padding_efficiency", "megabatch_utilization"):
                pa = prev["protocols"][name].get(eff)
                pb = last["protocols"][name].get(eff)
                if isinstance(pa, (int, float)) and \
                        isinstance(pb, (int, float)) and pa > 0 and \
                        pb < pa * (1.0 - thresh):
                    regressions.append({
                        "metric": f"{name}.{eff}",
                        "a": pa, "b": pb,
                        "a_file": prev["file"], "b_file": last["file"],
                        "limit": round(pa * (1.0 - thresh), 6),
                        "threshold": thresh})
            # convergence tier (flutetraffic): MORE rounds to the same
            # target accuracy is a regression, and so is LOSING a
            # previously-reached target (a measured count decaying to
            # null while the newer artifact still configures a target —
            # null without a configured target just means "not a
            # convergence run" and never gates)
            ra = prev["protocols"][name].get("rounds_to_target_accuracy")
            rb = last["protocols"][name].get("rounds_to_target_accuracy")
            if isinstance(ra, (int, float)) and ra > 0:
                tr_last = last["protocols"][name].get("traffic") or {}
                lost = (rb is None and
                        tr_last.get("target_accuracy") is not None)
                if lost or (isinstance(rb, (int, float)) and
                            rb > ra * (1.0 + thresh)):
                    regressions.append({
                        "metric": f"{name}.rounds_to_target_accuracy",
                        "a": ra, "b": rb,
                        "a_file": prev["file"], "b_file": last["file"],
                        "limit": round(ra * (1.0 + thresh), 6),
                        "threshold": thresh})
    return {"series": series, "regressions": regressions,
            "ok": not regressions}


def _trend_main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="scope trend",
        description="trend committed bench artifacts; gate on a "
                    "round-time regression between the last two")
    ap.add_argument("files", nargs="+", help="BENCH_*.json, oldest first")
    ap.add_argument("--gate", action="store_true",
                    help="exit 3 when the newest artifact regresses")
    ap.add_argument("--pct", type=float, default=None,
                    help="slower-than-previous threshold (%%, default 15)")
    ap.add_argument("--indent", type=int, default=None)
    args = ap.parse_args(argv)
    try:
        out = trend_bench(args.files, pct=args.pct)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"scope trend: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(out, indent=args.indent, sort_keys=True))
    if out["regressions"]:
        names = ", ".join(r["metric"] for r in out["regressions"])
        print(f"scope trend: REGRESSION in {names}", file=sys.stderr)
        if args.gate:
            return 3
    return 0


# ======================================================================
# endurance: rollup watch + the health oracle (ISSUE 13)
# ======================================================================
def _telemetry_dir(run_dir: str) -> str:
    if os.path.isdir(os.path.join(run_dir, "telemetry")):
        return os.path.join(run_dir, "telemetry")
    return run_dir


def _format_rollup(rec: Dict[str, Any]) -> str:
    """One compact human line per rollup window (the ``watch`` view)."""
    def num(key: str, fmt: str = "{:.3g}") -> str:
        value = rec.get(key)
        return fmt.format(value) if isinstance(value, (int, float)) \
            else "-"

    events = rec.get("events") or {}
    ev = " ".join(f"{k}:{v}" for k, v in sorted(events.items())) or "-"
    rss = rec.get("host_rss_bytes")
    rss_mb = f"{rss / 2**20:.0f}MB" if isinstance(rss, (int, float)) \
        else "-"
    return (f"w{rec.get('window', '?'):>3} "
            f"r[{rec.get('round_lo', '?')},{rec.get('round_hi', '?')}] "
            f"{num('secs_per_round_p50')}s/r "
            f"p95 {num('secs_per_round_p95')} "
            f"cl/s {num('clients_per_sec')} "
            f"mfu {num('mfu_p50', '{:.4f}')} "
            f"rss {rss_mb} "
            f"drop {rec.get('trace_events_dropped', 0)} "
            f"rc {rec.get('recompiles', 0)}"
            + (" PARTIAL" if rec.get("partial") else "")
            + f" | {ev}")


def _watch_main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="scope watch",
        description="live tail of a run's endurance rollup stream "
                    "(rollups.jsonl) — one line per flushed window")
    ap.add_argument("run_dir", help="model dir (or its telemetry/ subdir)")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="poll seconds between reads (default 5)")
    ap.add_argument("--once", action="store_true",
                    help="print what exists and exit (no follow)")
    args = ap.parse_args(argv)
    path = os.path.join(_telemetry_dir(args.run_dir), "rollups.jsonl")
    offset = 0
    printed_header = False
    import time as _time
    while True:
        if os.path.exists(path):
            size = os.path.getsize(path)
            if size < offset:
                offset = 0  # stream truncated/replaced: start over
            if size > offset:
                # binary read + byte offsets: text-mode seek is only
                # defined for cookies from tell(), and the tail we skip
                # may be a torn multi-byte write
                with open(path, "rb") as fh:
                    fh.seek(offset)
                    chunk = fh.read()
                # only consume complete lines; a torn tail stays
                # buffered for the next poll
                consumed = chunk.rfind(b"\n") + 1
                offset += consumed
                for raw in chunk[:consumed].splitlines():
                    line = raw.decode("utf-8", "replace").strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if not printed_header:
                        printed_header = True
                        print("# scope watch:", path, flush=True)
                    print(_format_rollup(rec), flush=True)
        if args.once:
            return 0
        try:
            _time.sleep(max(args.interval, 0.1))
        except KeyboardInterrupt:
            return 0


#: watchdog kinds whose firing makes a run UNHEALTHY for the gate.
#: round_time (a spiky chunk under chaos straggler inflation) and
#: quarantine_rate (the defense working) are informational; these six
#: mean the run is dying, leaking, drifting, or churning shapes.
CRITICAL_WATCHDOGS = ("stall", "nan_loss", "rss_leak",
                      "throughput_drift", "recompile_storm",
                      "ckpt_failure_streak")

#: last-vs-first rollup-window slowdown the static check tolerates
#: before calling the run unhealthy even without a watchdog firing
HEALTH_DRIFT_PCT = 75.0


def health(run_dir: str,
           pct: Optional[float] = None) -> Dict[str, Any]:
    """The endurance health verdict for one run directory.

    Sources (every one torn-line/rotation tolerant): the structured
    event streams (``metrics.jsonl`` + ``events.jsonl``), the rollup
    stream (``rollups.jsonl``), the flight record (``flight.json``) and
    the scorecard.  ``findings`` gate (exit 3); ``warnings`` inform.
    """
    tdir = _telemetry_dir(run_dir)
    findings: List[Dict[str, Any]] = []
    warnings: List[Dict[str, Any]] = []
    out: Dict[str, Any] = {"run_dir": os.path.basename(
        os.path.abspath(run_dir))}

    # ---- watchdog firings from the event streams + scorecard ---------
    fires: Dict[str, int] = {}
    metrics_path = os.path.join(run_dir, "metrics.jsonl")
    if not _segment_paths(metrics_path):
        metrics_path = os.path.join(tdir, "metrics.jsonl")
    # one firing reaches up to three streams; per-kind MAX across
    # sources (the summarize() convention) so nothing double-counts and
    # a stream a killed run lost does not under-count
    from_metrics: Dict[str, int] = {}
    for rec in _jsonl(metrics_path):
        if "event" in rec and str(rec["event"]).startswith("watchdog_"):
            kind = str(rec["event"])[len("watchdog_"):]
            from_metrics[kind] = from_metrics.get(kind, 0) + 1
    from_events: Dict[str, int] = {}
    for rec in _jsonl(os.path.join(tdir, "events.jsonl")):
        if rec.get("kind") == "event" and \
                str(rec.get("name", "")).startswith("watchdog_"):
            kind = str(rec["name"])[len("watchdog_"):]
            from_events[kind] = from_events.get(kind, 0) + 1
    for counts in (from_metrics, from_events):
        for kind, n in counts.items():
            fires[kind] = max(fires.get(kind, 0), n)
    card: Dict[str, Any] = {}
    card_path = os.path.join(tdir, "scorecard.json")
    if os.path.exists(card_path):
        try:
            with open(card_path, "r", encoding="utf-8") as fh:
                card = json.load(fh)
        except (OSError, json.JSONDecodeError):
            warnings.append({"check": "scorecard_unreadable"})
        for kind, n in (card.get("watchdog_fires") or {}).items():
            fires[kind] = max(fires.get(kind, 0), int(n))
    out["watchdog_fires"] = dict(sorted(fires.items()))
    for kind in CRITICAL_WATCHDOGS:
        if fires.get(kind):
            findings.append({"check": f"watchdog_{kind}",
                             "count": fires[kind]})

    # ---- flight record: a preemption flight is a drill/scheduler
    # artifact (the run resumed); any other reason means the run died
    # abnormally and the gate must say so --------------------------------
    flight_path = os.path.join(tdir, "flight.json")
    if os.path.exists(flight_path):
        try:
            with open(flight_path, "r", encoding="utf-8") as fh:
                flight = json.load(fh)
            reasons = [str(r.get("reason", "")) for r in
                       (flight.get("reasons") or [])]
            out["flight_reasons"] = reasons
            abnormal = [r for r in reasons
                        if not r.startswith("preemption")]
            if abnormal:
                findings.append({"check": "flight_abnormal",
                                 "reasons": abnormal})
            else:
                warnings.append({"check": "flight_preemption",
                                 "reasons": reasons})
        except (OSError, json.JSONDecodeError):
            warnings.append({"check": "flight_unreadable"})

    # ---- the rollup stream: presence + longitudinal statics ----------
    rollups = [r for r in _jsonl(os.path.join(tdir, "rollups.jsonl"))
               if r.get("kind") == "rollup" and r.get("rounds")]
    out["rollup_windows"] = len(rollups)
    if tdir != run_dir and not rollups:
        # a telemetry/ subdir exists, so telemetry RAN — a missing
        # rollup stream there means the endurance layer was disabled or
        # broken, which an endurance gate must refuse.  A run with no
        # telemetry dir at all simply has nothing to judge here
        # (telemetry-off runs are not unhealthy, just unobserved).
        findings.append({"check": "no_rollups",
                         "detail": "telemetry ran but no rollup window "
                                   "was ever flushed"})
    if len(rollups) >= 2:
        first, last = rollups[0], rollups[-1]
        a = first.get("secs_per_round_p50")
        b = last.get("secs_per_round_p50")
        thresh = (float(pct) if pct is not None else HEALTH_DRIFT_PCT) \
            / 100.0
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                and a > 0 and b > a * (1.0 + thresh):
            findings.append({
                "check": "rollup_throughput_drift",
                "first_p50": a, "last_p50": b,
                "limit": round(a * (1.0 + thresh), 6)})
        out["secs_per_round_p50"] = {"first": a, "last": b}
    if rollups:
        dropped = rollups[-1].get("trace_events_dropped")
        if dropped:
            warnings.append({"check": "trace_events_dropped",
                             "count": int(dropped)})
        out["last_window"] = {
            k: rollups[-1].get(k)
            for k in ("window", "round_hi", "secs_per_round_p50",
                      "clients_per_sec", "mfu_p50", "host_rss_bytes",
                      "recompiles")}

    if card:
        out["recompiles"] = card.get("recompiles")
        if card.get("trace_events_dropped"):
            warnings.append({"check": "scorecard_trace_events_dropped",
                             "count": card["trace_events_dropped"]})
    out["findings"] = findings
    out["warnings"] = warnings
    out["ok"] = not findings
    return out


def _health_main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="scope health",
        description="endurance health oracle over rollups + watchdog "
                    "firings + flight record + scorecard")
    ap.add_argument("run_dir", help="model dir (or its telemetry/ subdir)")
    ap.add_argument("--gate", action="store_true",
                    help="exit 3 when the run is unhealthy")
    ap.add_argument("--pct", type=float, default=None,
                    help="last-vs-first rollup slowdown tolerance "
                         "(%%, default 75)")
    ap.add_argument("--indent", type=int, default=None)
    args = ap.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"scope health: {args.run_dir!r} is not a directory",
              file=sys.stderr)
        return 2
    try:
        out = health(args.run_dir, pct=args.pct)
    except OSError as exc:
        print(f"scope health: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(out, indent=args.indent, sort_keys=True))
    if out["findings"]:
        names = ", ".join(f["check"] for f in out["findings"])
        print(f"scope health: UNHEALTHY ({names})", file=sys.stderr)
        if args.gate:
            return 3
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "diff":
        return _diff_main(argv[1:])
    if argv and argv[0] == "trend":
        return _trend_main(argv[1:])
    if argv and argv[0] == "watch":
        return _watch_main(argv[1:])
    if argv and argv[0] == "health":
        return _health_main(argv[1:])
    if argv and argv[0] == "summarize":
        argv = argv[1:]
    ap = argparse.ArgumentParser(
        description="summarize a run directory's flutescope telemetry")
    ap.add_argument("run_dir", help="model dir (or its telemetry/ subdir)")
    ap.add_argument("--indent", type=int, default=None,
                    help="pretty-print with this JSON indent")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"scope: {args.run_dir!r} is not a directory",
              file=sys.stderr)
        return 2
    print(json.dumps(summarize(args.run_dir), indent=args.indent,
                     sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
