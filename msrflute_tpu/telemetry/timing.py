"""The one timing source of truth for bench/tools phase timers.

Before flutescope, wall-clock timing lived in three ad-hoc probes:
``bench.py``'s inline ``tic = time.time()`` pairs,
``tools/profile_round.py``'s copies of them, and
``tools/timing_probe.py``'s scalar-fetch fence.  They now all sit on the
primitives here, so the methodology (perf_counter clock; scalar-fetch
sync fence on remote backends) cannot drift between the harnesses that
compare numbers.  Bench JSON field names are unchanged — only the
stopwatch behind them moved.

No jax at module import time (bench.py must select a backend before
anything imports jax); :func:`grad_wall` imports it lazily.
"""

from __future__ import annotations

import time
from typing import Any


class Stopwatch:
    """``with Stopwatch() as sw: ... ; sw.secs`` — one timed region on
    the perf_counter clock (the same clock the span tracer runs on).
    In-process server phases that belong in trace.json go through the
    tracer's own ``span()`` API; this is the bare harness-side timer."""

    def __init__(self):
        self.secs = 0.0
        self._t0 = 0.0

    def __enter__(self) -> "Stopwatch":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.secs = time.perf_counter() - self._t0


def scalar_time(fn, *args: Any, iters: int = 20) -> float:
    """Mean wall seconds per call of ``fn`` (which must return a SCALAR),
    fetching the value to host each iteration as the sync fence.

    ``jax.block_until_ready`` is NOT a trustworthy fence on the remote
    axon backend (the first committed ``flash_crossover.json`` read a
    flat ~0.045 ms at every length — the call returned before the device
    finished); a host ``float()`` of a scalar result cannot lie: the
    4-byte transfer completes only after the producing program does.
    Cost: one dispatch floor (~0.14 ms) per iteration, paid identically
    on both sides of any comparison built on this."""
    float(fn(*args))  # compile + first run
    tic = time.perf_counter()
    for _ in range(iters):
        float(fn(*args))
    return (time.perf_counter() - tic) / iters


def grad_wall(attn_fn, q, k, v, iters: int = 20) -> float:
    """Fwd+bwd wall time of ``sum(attn_fn(q,k,v)**2)`` w.r.t. all three
    inputs.  The jitted probe returns full-reduction sums of every grad —
    a scalar for :func:`scalar_time`'s fence that also keeps XLA from
    dead-code-eliminating any part of the backward pass."""
    import jax
    import jax.numpy as jnp

    def loss(q, k, v):
        return jnp.sum(attn_fn(q, k, v) ** 2)

    def probe(q, k, v):
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return (jnp.sum(dq.astype(jnp.float32)) +
                jnp.sum(dk.astype(jnp.float32)) +
                jnp.sum(dv.astype(jnp.float32)))

    return scalar_time(jax.jit(probe), q, k, v, iters=iters)
