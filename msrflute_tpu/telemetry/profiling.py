"""Opt-in ``jax.profiler`` capture for a configured round window.

``server_config.telemetry.profile_rounds`` names the window — an int
(``5``: profile the chunk containing round 5), a ``"lo:hi"`` string, or
a two-element list — and the server calls :meth:`RoundProfiler.observe`
at every chunk boundary.  The capture starts at the first chunk whose
round range reaches ``lo`` and stops at the first boundary at or past
``hi``, so a fused chunk spanning the window edge profiles whole (the
profiler cannot cut a compiled program in half).

Degrades gracefully on old jax (the container's 0.4.37) through the
:mod:`msrflute_tpu.utils.compat` wrappers: a failed start/stop logs one
warning and disables further attempts instead of killing the run.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional, Tuple

_LOGGER = logging.getLogger("msrflute_tpu")


def parse_profile_rounds(spec: Any) -> Optional[Tuple[int, int]]:
    """``None`` | int | ``"lo:hi"`` | [lo, hi] -> half-open round window
    ``(lo, hi)`` or None.  Raises ValueError on garbage (the schema calls
    this too, so a bad spec fails at config load, not round ``lo``)."""
    if spec is None:
        return None
    if isinstance(spec, bool):
        raise ValueError("telemetry.profile_rounds: must be an int, "
                         "'lo:hi', or [lo, hi] — got a boolean")
    if isinstance(spec, int):
        return (spec, spec + 1)
    if isinstance(spec, str):
        if ":" not in spec:
            raise ValueError(
                f"telemetry.profile_rounds: {spec!r} is not 'lo:hi'")
        lo_s, hi_s = spec.split(":", 1)
        lo, hi = int(lo_s), int(hi_s)
    elif isinstance(spec, (list, tuple)) and len(spec) == 2:
        lo, hi = int(spec[0]), int(spec[1])
    else:
        raise ValueError(
            f"telemetry.profile_rounds: {spec!r} must be an int, "
            "'lo:hi', or [lo, hi]")
    if lo < 0 or hi <= lo:
        raise ValueError(
            f"telemetry.profile_rounds: window [{lo}, {hi}) is empty or "
            "negative")
    return (lo, hi)


class RoundProfiler:
    """Drives one ``jax.profiler`` trace over the configured window."""

    def __init__(self, spec: Any, out_dir: str):
        self.window = parse_profile_rounds(spec)
        self.out_dir = os.path.join(out_dir, "xla_profile")
        self.active = False
        self.captured = False
        self.failed = False

    def observe(self, round_no: int, rounds: int = 1) -> None:
        """Chunk-boundary hook: the chunk about to dispatch covers
        ``[round_no, round_no + rounds)``.  The capture starts when that
        range INTERSECTS the window — not only when it starts exactly at
        ``lo`` — so a window falling inside a fused chunk still fires
        (the chunk profiles whole; a compiled program cannot be cut)."""
        if self.window is None or self.failed or self.captured:
            if self.active:
                self._stop()
            return
        lo, hi = self.window
        if self.active and round_no >= hi:
            self._stop()
        elif not self.active and round_no < hi and round_no + max(
                int(rounds), 1) > lo:
            self._start()

    def finish(self) -> None:
        """Train-exit hook: a window still open (run ended inside it)
        stops here so the capture is flushed."""
        if self.active:
            self._stop()

    # ------------------------------------------------------------------
    def _start(self) -> None:
        from ..utils.compat import profiler_start_trace
        if profiler_start_trace(self.out_dir):
            self.active = True
            _LOGGER.info("flutescope: jax.profiler capture started -> %s",
                         self.out_dir)
        else:
            self.failed = True
            _LOGGER.warning(
                "flutescope: jax.profiler trace unavailable on this jax "
                "version/backend; telemetry.profile_rounds disabled for "
                "this run")

    def _stop(self) -> None:
        from ..utils.compat import profiler_stop_trace
        self.active = False
        if profiler_stop_trace():
            self.captured = True
            _LOGGER.info("flutescope: jax.profiler capture written to %s",
                         self.out_dir)
        else:
            self.failed = True
