"""Tunnel-claim guardrail (docs/RUNBOOK.md failure mode 4).

Leaf module: imports nothing but ``os`` so the check runs before ANY other
package code (in particular before ``msrflute_tpu.utils``'s module-level
imports) — the root ``__init__`` calls it first, and a future module-level
``import jax`` elsewhere can never beat it to backend initialization.
Re-exported as ``utils.backend.guard_tunnel_claim``.
"""

import os


def guard_tunnel_claim() -> None:
    """Refuse to run toward the single-client TPU tunnel from an agent shell.

    Round 4 lost a six-hour chip window because an interactively launched
    ``python`` (ambient axon env) was killed mid-claim and the stale claim
    wedged the relay (docs/RUNBOOK.md failure mode 4).  The queue runner
    (``tools/tpu_runner.sh``) is the only sanctioned path to the chip from
    an agent shell; it marks its jobs with ``MSRFLUTE_CHIP_JOB=1``.

    Fires only in agent shells (``CLAUDECODE`` / ``AI_AGENT`` env markers):
    the round driver and human operators run without those and are never
    blocked.  The unsafe shape is a non-empty ``PALLAS_AXON_POOL_IPS`` —
    sitecustomize registers the axon plugin from that alone — unless
    ``JAX_PLATFORMS`` explicitly names an axon-free platform (an UNSET
    ``JAX_PLATFORMS`` lets jax auto-select the registered plugin).
    """
    if os.environ.get("MSRFLUTE_CHIP_JOB") == "1":
        return
    if not (os.environ.get("CLAUDECODE") or os.environ.get("AI_AGENT")):
        return
    platforms = os.environ.get("JAX_PLATFORMS", "").strip()
    if os.environ.get("PALLAS_AXON_POOL_IPS") and \
            (not platforms or "axon" in platforms):
        raise RuntimeError(
            "refusing to initialize the axon TPU backend from an agent "
            "shell: the tunnel is single-client and a killed claimant "
            "wedges it (docs/RUNBOOK.md failure mode 4).  For local work "
            "use the CPU env -- `tools/py <script>` or `env "
            "PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 python ...`.  Chip "
            "work goes through the queue: append a job to "
            "tools/tpu_jobs.d/ and let tools/tpu_runner.sh run it."
        )
