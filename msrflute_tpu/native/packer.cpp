// Native round-batch packer.
//
// Parity target: the reference feeds its trainers through torch
// DataLoaders whose collation runs in native worker code; here the
// analogous host hot loop is pack_round_batches' per-client gather into
// the static [K, S, B, ...] grid (msrflute_tpu/data/batching.py).  numpy's
// fancy-indexing gather is C-speed but single-threaded; at K=hundreds of
// clients x MBs each it serializes on one core.  This packer memcpy's all
// clients' selected rows in parallel.
//
// Built on demand by __init__.py::_build (g++ -O3 -shared -fPIC -std=c++17
// -pthread, no dependencies).  ABI: one flat C function so ctypes can call
// it with plain pointers.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Gather rows into a padded destination grid, parallel over clients.
//
//   srcs[j]        base pointer of client j's source array
//                  ([n_j, row_bytes] row-major)
//   dst            base of the destination grid
//                  ([K, slots, row_bytes] row-major, pre-zeroed)
//   takes          concatenated row indices; client j's indices are
//                  takes[offsets[j]] .. takes[offsets[j] + counts[j])
//   counts[j]      number of rows to copy for client j (<= slots)
//   offsets[j]     start of client j's indices within `takes`
//   K              number of clients
//   slots          destination capacity per client (S * B)
//   row_bytes      bytes per sample row (product of feature dims * itemsize)
//   n_threads      worker threads (<=0 -> hardware_concurrency)
void pack_gather_rows(const char** srcs, char* dst, const int64_t* takes,
                      const int64_t* counts, const int64_t* offsets,
                      int64_t K, int64_t slots, int64_t row_bytes,
                      int64_t n_threads) {
  if (K <= 0 || row_bytes <= 0) return;
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int workers = n_threads > 0 ? static_cast<int>(n_threads)
                              : (hw > 0 ? hw : 4);
  if (workers > K) workers = static_cast<int>(K);

  auto run = [&](int64_t j0, int64_t j1) {
    for (int64_t j = j0; j < j1; ++j) {
      const char* src = srcs[j];
      char* out = dst + j * slots * row_bytes;
      const int64_t* take = takes + offsets[j];
      const int64_t t = counts[j];
      for (int64_t r = 0; r < t; ++r) {
        std::memcpy(out + r * row_bytes, src + take[r] * row_bytes,
                    static_cast<size_t>(row_bytes));
      }
    }
  };

  if (workers <= 1) {
    run(0, K);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(workers);
  int64_t chunk = (K + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    int64_t j0 = w * chunk;
    int64_t j1 = j0 + chunk < K ? j0 + chunk : K;
    if (j0 >= j1) break;
    pool.emplace_back(run, j0, j1);
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
