"""Native runtime components (C++ via ctypes; no build-time deps).

The compute path is jax/XLA/Pallas; what stays native here is the host
runtime around it — currently the parallel round-batch packer
(:mod:`packer.cpp`), the analogue of the reference's native DataLoader
collation workers.  Everything degrades gracefully to the numpy
implementation when the shared library is absent (zero-install default)
or ``MSRFLUTE_NATIVE=0``.

The library is built on demand with the toolchain's ``g++`` (inline in
:func:`_build`: ``g++ -O3 -shared -fPIC -std=c++17 -pthread``) and the
``_packer.so`` is cached next to this file, rebuilt when the source is
newer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import List

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_packer.so")
_SRC_PATH = os.path.join(_HERE, "packer.cpp")

_lib = None
_lib_failed = False


def _build() -> bool:
    """Compile packer.cpp -> _packer.so with g++ (cached)."""
    try:
        if os.path.exists(_SO_PATH) and \
                os.path.getmtime(_SO_PATH) >= os.path.getmtime(_SRC_PATH):
            return True
    except OSError:
        # cached .so without its source: still usable
        return os.path.exists(_SO_PATH)
    tmp = None
    try:
        # unique tmp per process: concurrent builders must not share an
        # output inode, or one g++ keeps writing into the installed file
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
        os.close(fd)
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC_PATH, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO_PATH)
        return True
    except Exception:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    if os.environ.get("MSRFLUTE_NATIVE", "1") == "0" or not _build():
        _lib_failed = True
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
        lib.pack_gather_rows.restype = None
        # addresses travel as void*; c_char_p would copy the buffer CONTENT
        # when assigned, not the pointer
        lib.pack_gather_rows.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64]
        _lib = lib
    except Exception:
        _lib_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


def gather_rows(dst: np.ndarray, srcs: List[np.ndarray],
                takes: List[np.ndarray], n_threads: int = 0) -> bool:
    """Copy ``srcs[j][takes[j]]`` into ``dst[j, :len(takes[j])]`` for all
    clients in parallel.  ``dst`` is ``[K, slots, *feat]`` and must be
    C-contiguous and pre-zeroed; each ``srcs[j]`` is ``[n_j, *feat]``.

    Returns False (caller should fall back to numpy) when the native lib
    is unavailable or the arrays don't meet the layout contract.
    """
    lib = _load()
    if lib is None or dst.ndim < 2 or not dst.flags.c_contiguous:
        return False
    K = len(srcs)
    if K == 0 or K > dst.shape[0] or len(takes) != K:
        return False
    row_bytes = int(np.prod(dst.shape[2:], dtype=np.int64)) * dst.itemsize
    if row_bytes <= 0:
        return False
    src_ptrs = (ctypes.c_void_p * K)()
    counts = np.empty((K,), np.int64)
    offsets = np.empty((K,), np.int64)
    flat_takes: List[np.ndarray] = []
    keep_alive: List[np.ndarray] = []  # pins contiguous copies for the call
    pos = 0
    for j, (src, take) in enumerate(zip(srcs, takes)):
        src = np.ascontiguousarray(src)
        keep_alive.append(src)
        if src.dtype != dst.dtype or \
                src.shape[1:] != dst.shape[2:] or len(take) > dst.shape[1]:
            return False
        take = np.asarray(take, np.int64)
        if take.size and (take.min() < 0 or take.max() >= len(src)):
            return False
        src_ptrs[j] = src.ctypes.data
        counts[j] = take.size
        offsets[j] = pos
        flat_takes.append(take)
        pos += take.size
    all_takes = (np.concatenate(flat_takes) if pos
                 else np.empty((0,), np.int64))
    lib.pack_gather_rows(
        src_ptrs, ctypes.c_void_p(dst.ctypes.data),
        all_takes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        K, dst.shape[1], row_bytes, n_threads)
    return True
