"""The screening half of fluteshield: traced quarantine math.

Everything in this module that touches arrays is PURE TRACED code that
runs inside the fused round program (``engine/round.py``): the finite
checks, the masked median, and the quarantine mask are ordinary XLA ops
over values that never visit the host.  The host-side surface is the
config parse (:func:`make_shield`) and the run-level counters the
server accumulates from the packed round stats.

Numerical contract (pinned by ``tests/test_robust.py``):

- quarantined clients contribute EXACTLY zero to every aggregate —
  payload leaves, train-loss sum, sample counts, stat sums — via
  ``jnp.where`` on the keep mask (never a ``0 *`` multiply, which NaN
  survives);
- the median-of-norms vote counts only live, finite clients (padding
  slots and non-finite payloads cannot drag the threshold down);
- a degenerate all-zero-norm cohort disables the norm screen for that
  round instead of quarantining everyone (``median == 0`` guard);
- screening decisions are a pure function of this round's payloads, so
  serial and pipelined loops quarantine identically.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

#: robust aggregator vocabulary (schema ALLOWED_ROBUST_AGGREGATORS
#: mirrors this — schema_drift keeps them from desyncing via the docs)
AGGREGATORS = ("mean", "trimmed_mean", "median")


def masked_median(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Median of ``values[mask > 0]`` with static shapes (traced).

    Masked-out and non-finite entries sort to the top as ``+inf`` and
    are excluded by rank; even counts interpolate the two middle ranks.
    Returns 0.0 for an empty vote (the caller treats that as "no
    threshold this round").
    """
    finite = jnp.isfinite(values) & (mask > 0)
    srt = jnp.sort(jnp.where(finite, values, jnp.inf))
    n = jnp.sum(finite.astype(jnp.int32))
    i_lo = jnp.maximum((n - 1) // 2, 0)
    i_hi = jnp.maximum(n // 2, 0)
    ranks = jnp.arange(srt.shape[0])
    ind = 0.5 * ((ranks == i_lo).astype(srt.dtype)
                 + (ranks == i_hi).astype(srt.dtype))
    med = jnp.sum(jnp.where(jnp.isfinite(srt), srt, 0.0) * ind)
    return jnp.where(n > 0, med, 0.0)


class Shield:
    """One run's screening policy + counters.

    Traced entry point is :meth:`screen`; the object itself is static
    engine-build state (like the chaos flags): a config without a
    ``robust`` block never constructs one, and the engine compiles the
    exact pre-fluteshield program.
    """

    def __init__(self, screen_nonfinite: bool = True,
                 norm_multiplier: Optional[float] = 5.0,
                 aggregator: str = "mean",
                 trim_fraction: float = 0.1):
        if aggregator not in AGGREGATORS:
            raise ValueError(
                f"robust.aggregator must be one of {AGGREGATORS}, "
                f"got {aggregator!r}")
        if norm_multiplier is not None and float(norm_multiplier) < 1.0 \
                and float(norm_multiplier) != 0.0:
            raise ValueError(
                "robust.norm_multiplier must be >= 1 (it scales the "
                "median payload norm) or 0/absent to disable")
        if not 0.0 <= float(trim_fraction) < 0.5:
            raise ValueError(
                "robust.trim_fraction must be in [0, 0.5) — trimming "
                "half or more from each side leaves nothing to average")
        self.screen_nonfinite = bool(screen_nonfinite)
        self.norm_multiplier = (float(norm_multiplier)
                                if norm_multiplier else 0.0)
        self.aggregator = str(aggregator)
        self.trim_fraction = float(trim_fraction)
        #: run-level quarantine observability, accumulated by the server
        #: from the packed round stats (the same discipline as
        #: ``ChaosSchedule.counters``)
        self.counters: Dict[str, float] = {
            "quarantined_nonfinite": 0.0,
            "quarantined_norm_outlier": 0.0,
        }

    # ------------------------------------------------------------------
    @property
    def wants_stack(self) -> bool:
        """Whether the aggregator needs the per-client payload stack
        materialized (trimmed mean / median cannot ride psum'd sums)."""
        return self.aggregator in ("trimmed_mean", "median")

    # ------------------------------------------------------------------
    def screen(self, payload: Any, train_loss: jnp.ndarray,
               weight: jnp.ndarray, client_mask: jnp.ndarray,
               gather: Callable[[jnp.ndarray], jnp.ndarray]
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """TRACED: per-client quarantine decision for one round batch.

        ``payload``: the ``[K, ...]``-leading per-client pseudo-gradient
        tree (post strategy transform — what would actually aggregate);
        ``train_loss``/``weight``: per-client ``[K]``; ``client_mask``:
        live mask ``[K]`` (mesh padding + chaos dropout already folded).
        ``gather``: assembles a shard-local ``[K_local]`` vector into the
        full replicated ``[K]`` cohort (``all_gather`` over the clients
        axis in shard_map mode, identity under GSPMD/jit).

        Returns ``(keep [K] f32 in {0,1}, q_nonfinite [K],
        q_norm_outlier [K])`` — the q vectors are disjoint per-cause
        counts gated on ``client_mask`` (padding never counts).
        """
        k = client_mask.shape[0]
        ones = jnp.ones((k,), bool)
        finite = ones
        if self.screen_nonfinite:
            flags = [jnp.all(jnp.isfinite(
                        leaf.reshape(leaf.shape[0], -1)), axis=1)
                     for leaf in jax.tree.leaves(payload)
                     if jnp.issubdtype(leaf.dtype, jnp.floating)]
            flags.append(jnp.isfinite(train_loss))
            flags.append(jnp.isfinite(weight))
            for f in flags:
                finite = finite & f
        norm_ok = ones
        if self.norm_multiplier > 0.0:
            sq = sum(jnp.sum(leaf.reshape(leaf.shape[0], -1) ** 2, axis=1)
                     for leaf in jax.tree.leaves(payload)
                     if jnp.issubdtype(leaf.dtype, jnp.floating))
            norms = jnp.sqrt(sq)
            # only live, finite clients vote for the median — a NaN norm
            # or a padding slot must not drag the threshold around
            vote = client_mask * finite.astype(client_mask.dtype)
            med = masked_median(gather(norms), gather(vote))
            # degenerate all-zero cohort (round 0 freeze, empty round):
            # no threshold rather than quarantining every non-zero norm
            norm_ok = jnp.where(med > 0.0,
                                norms <= self.norm_multiplier * med, True)
        keep = finite & norm_ok
        finite_f = finite.astype(client_mask.dtype)
        q_nonfinite = client_mask * (1.0 - finite_f)
        q_norm = client_mask * finite_f * \
            (1.0 - norm_ok.astype(client_mask.dtype))
        return keep.astype(client_mask.dtype), q_nonfinite, q_norm

    # ------------------------------------------------------------------
    def screen_masked(self, norms: jnp.ndarray, train_loss: jnp.ndarray,
                      weight: jnp.ndarray, client_mask: jnp.ndarray,
                      gather: Callable[[jnp.ndarray], jnp.ndarray]
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """TRACED: :meth:`screen` for secure-aggregation rounds, voting
        on SUBMITTED norms instead of payload leaves.

        Under secure_agg the per-client payload is a masked int32 group
        element — uniformly distributed bits that carry no norm or
        finiteness signal by construction.  What the server CAN see in a
        verified-aggregation deployment is each client's proven norm
        bound, which the simulation models as ``norms``: the true L2
        norm of the post-corruption, pre-mask float payload, computed
        client-side by ``SecureAgg.mask_parts`` and submitted in the
        clear ([K] f32).  The screening policy (finite check, median
        vote, multiplier threshold) and the quarantine semantics are
        identical to :meth:`screen` — quarantine then feeds the mask
        cancellation path as one more dropout cause.
        """
        finite = jnp.ones(client_mask.shape, bool)
        if self.screen_nonfinite:
            # a NaN/Inf float payload yields a NaN/Inf norm (sqrt of a
            # sum of squares propagates), so the norm carries the
            # finiteness signal too
            finite = (jnp.isfinite(norms) & jnp.isfinite(train_loss)
                      & jnp.isfinite(weight))
        norm_ok = jnp.ones(client_mask.shape, bool)
        if self.norm_multiplier > 0.0:
            vote = client_mask * finite.astype(client_mask.dtype)
            med = masked_median(gather(norms), gather(vote))
            norm_ok = jnp.where(med > 0.0,
                                norms <= self.norm_multiplier * med, True)
        keep = finite & norm_ok
        finite_f = finite.astype(client_mask.dtype)
        q_nonfinite = client_mask * (1.0 - finite_f)
        q_norm = client_mask * finite_f * \
            (1.0 - norm_ok.astype(client_mask.dtype))
        return keep.astype(client_mask.dtype), q_nonfinite, q_norm

    # ------------------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        """The bench-contract record: a shielded run can never be
        silently compared against an undefended baseline."""
        return {
            "enabled": True,
            "screen_nonfinite": self.screen_nonfinite,
            "norm_multiplier": self.norm_multiplier,
            "aggregator": self.aggregator,
            "trim_fraction": self.trim_fraction,
        }


def make_shield(server_config) -> Optional[Shield]:
    """Build the run's :class:`Shield` from ``server_config.robust``
    (None when absent or ``enable: false`` — the firewall path)."""
    raw = server_config.get("robust") if server_config is not None else None
    if not raw:
        return None
    raw = dict(raw)
    if not raw.pop("enable", True):
        return None
    return Shield(
        screen_nonfinite=raw.get("screen_nonfinite", True),
        norm_multiplier=raw.get("norm_multiplier", 5.0),
        aggregator=raw.get("aggregator", "mean"),
        trim_fraction=raw.get("trim_fraction", 0.1),
    )
