"""fluteshield — screened aggregation for poisoned / broken cohorts.

FLUTE's premise is simulation over millions of UNRELIABLE clients, but
the aggregation path historically trusted every pseudo-gradient that
came back: one client emitting a NaN/Inf leaf (a diverged local run, a
corrupted transfer, an adversary) poisons the weighted sum, the global
model, and — through the logged train loss — trips the NaN watchdog's
whole-run abort.  fluteshield puts the defense INSIDE the fused round
program, mirroring the chaos-mask mechanics (``resilience/chaos.py``):

- **per-client screening** (:meth:`Shield.screen`): any-NaN/Inf finite
  checks over the post-transform payload tree + train loss + weight,
  and median-of-norms outlier screening (``norm_multiplier`` x the
  cohort's masked median payload norm).  The resulting quarantine mask
  folds into ``client_mask`` as data INSIDE the program — aggregation
  weights renormalize on device exactly like mesh padding, quarantined
  payloads are zeroed with ``jnp.where`` (a ``0 * NaN`` multiply would
  re-poison the sum), and per-cause counters ride the packed-stats
  single transfer (zero new ``device_get``s, clean under
  ``MSRFLUTE_STRICT_TRANSFERS=1``).
- **robust aggregators** (``strategies/robust.py``): coordinate-wise
  trimmed mean and coordinate-wise median over the screened per-client
  payload stack, for adversaries screening cannot catch (sign-flips at
  benign norm).
- **adversarial chaos streams** (``resilience/chaos.py``): seeded
  NaN-injection / gradient-scale / sign-flip corruption keyed per
  ``(seed, stream, round)``, so the defense is testable end-to-end
  (``tests/test_robust.py``, ``tools/chaos_smoke.py``).

Config (``server_config.robust``, schema ``ROBUST_KEYS``)::

    robust:
      screen_nonfinite: true     # quarantine any-NaN/Inf payloads
      norm_multiplier: 5.0       # quarantine norm > mult x median (0/None: off)
      aggregator: mean           # mean | trimmed_mean | median
      trim_fraction: 0.1         # per-side trim for trimmed_mean

The firewall contract: no ``robust`` block (or ``enable: false``)
compiles the exact round program this repo always had — bit-identical
params, serial and pipelined (``tests/test_robust.py``).
"""

from __future__ import annotations

from .shield import Shield, make_shield, masked_median  # noqa: F401

__all__ = ["Shield", "make_shield", "masked_median"]
