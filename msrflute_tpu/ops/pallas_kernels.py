"""Pallas TPU kernels for the DP/quantization/optimizer hot ops.

SURVEY.md §2.9: the reference has no native components — its NCCL/Gloo layer
maps to XLA collectives here, and the "custom kernel" obligation lands on
the fused elementwise passes over flattened updates.  Three kernels:

- :func:`fused_gaussian_noise` — ``out = x * scale + sigma * N(0,1)`` with
  the Gaussian generated **on-core** (pltpu PRNG + Box-Muller).  The jnp
  path materializes a full noise array in HBM
  (``jax.random.normal`` -> add), i.e. 3 HBM streams; the kernel reads x
  and writes out only — the noise never touches HBM.  Used by the
  server-side global-DP step (``privacy.apply_global_dp``).
- :func:`quant_bin_sparsify` — histogram binning to ``n_bins`` levels +
  magnitude sparsification in one pass (the elementwise core of
  ``ops.quantization``; min/max/quantile stay in XLA where sort belongs).
- :func:`fused_sgd_apply` — the momentum-SGD parameter update over the
  FLATTENED param vector in one pass: ``m' = g + mu*m``, ``p' = p -
  lr*m'``, with the all-padding-step no-op gate folded in.  The opt-in
  megakernel tail for small-model protocols whose per-leaf optimizer
  ops are too tiny to feed the MXU (``server_config.megakernel.
  pallas_apply``); XLA spells the same math as a dozen sub-lane-sized
  ops per leaf, this kernel as three aligned HBM streams.

All degrade gracefully: on non-TPU backends they run in Pallas interpret
mode (tests) or fall back to jnp.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_SUBLANES = 8
_BLOCK_ROWS = 256  # rows of 128 lanes per grid step (128 KiB f32 blocks)


def _pad_to_grid(flat: jnp.ndarray):
    n = flat.shape[0]
    per_block = _BLOCK_ROWS * _LANES
    padded = int(np.ceil(max(n, 1) / per_block)) * per_block
    x = jnp.zeros((padded,), flat.dtype).at[:n].set(flat)
    return x.reshape(padded // _LANES, _LANES), n


def _interpret_params():
    """TPU-interpreter params when this jax has them (they implement the
    pltpu PRNG primitives, unlike generic interpret mode); plain
    ``interpret=True`` on older releases that predate InterpretParams."""
    ip = getattr(pltpu, "InterpretParams", None)
    return ip() if ip is not None else True


def _interpret_default():
    """Off-TPU, run kernels under the TPU interpreter."""
    if jax.default_backend() == "tpu":
        return False
    return _interpret_params()


def _resolve_interpret(interpret):
    if interpret is None:
        return _interpret_default()
    if interpret is True:
        return _interpret_params()
    return interpret


# ----------------------------------------------------------------------
def bits_to_normal(b1: jnp.ndarray, b2: jnp.ndarray) -> jnp.ndarray:
    """Box-Muller: two uint32 random-bit draws -> standard normal.

    This is the DP-critical math of the noise kernel (a wrong sigma here
    silently under-noises every global-DP update), factored out so its
    statistics are testable with ANY uint32 source: the tests feed
    ``jax.random.bits`` on CPU (``tests/test_pallas_kernels.py``), the
    kernel feeds the on-core pltpu PRNG — the transform is identical.
    Top 24 bits -> uniform with 2^-24 resolution (f32-exact); the +1e-12
    floor guards ``log(0)`` and caps |z| at ~7.43.

    The float conversion routes through int32: after ``>> 8`` the value
    fits in 24 bits so the reinterpretation is exact, and mosaic lowers
    uint32->int32->f32 while rejecting the direct uint32->f32 cast
    (observed on silicon, ``tpu_pallas_tests.log`` round 4).
    """
    u1 = (b1 >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24)) + 1e-12
    u2 = (b2 >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))
    return jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(2.0 * np.pi * u2)


def _noise_kernel(seed_ref, params_ref, x_ref, o_ref):
    # distinct stream per grid block
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    scale = params_ref[0]
    sigma = params_ref[1]
    shape = x_ref.shape
    b1 = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    b2 = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    o_ref[:] = x_ref[:] * scale + sigma * bits_to_normal(b1, b2)


def fused_gaussian_noise(flat: jnp.ndarray, scale: jnp.ndarray,
                         sigma: jnp.ndarray, seed: jnp.ndarray,
                         interpret: Optional[bool] = None) -> jnp.ndarray:
    """``flat * scale + sigma * N(0,1)`` with on-core noise generation."""
    interpret = _resolve_interpret(interpret)
    if interpret is True:
        # old-jax off-TPU path: generic interpret mode cannot lower the
        # pltpu PRNG primitives, so run the SAME Box-Muller math on
        # jax.random bits (different stream than the on-core PRNG, same
        # distribution — the DP-critical transform is shared)
        k1, k2 = jax.random.split(jax.random.PRNGKey(jnp.asarray(seed)))
        b1 = jax.random.bits(k1, flat.shape, jnp.uint32)
        b2 = jax.random.bits(k2, flat.shape, jnp.uint32)
        x = flat.astype(jnp.float32)
        return (x * scale + sigma * bits_to_normal(b1, b2)).astype(flat.dtype)
    x2d, n = _pad_to_grid(flat.astype(jnp.float32))
    rows = x2d.shape[0]
    grid = rows // _BLOCK_ROWS
    out = pl.pallas_call(
        _noise_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(grid,),
            in_specs=[pl.BlockSpec((_BLOCK_ROWS, _LANES),
                                   lambda i, *_: (i, 0))],
            out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANES),
                                   lambda i, *_: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.float32),
        interpret=interpret,
    )(jnp.asarray([seed], jnp.int32),
      jnp.asarray([scale, sigma], jnp.float32), x2d)
    return out.reshape(-1)[:n].astype(flat.dtype)


# ----------------------------------------------------------------------
def _quant_kernel(params_ref, x_ref, o_ref, *, n_bins):
    lo = params_ref[0]
    hi = params_ref[1]
    thresh = params_ref[2]
    x = x_ref[:]
    width = jnp.maximum((hi - lo) / max(n_bins - 1, 1), 1e-30)
    idx = jnp.clip(jnp.round((x - lo) / width), 0, n_bins - 1)
    binned = lo + idx * width
    o_ref[:] = jnp.where(jnp.abs(x) > thresh, binned, 0.0)


def quant_bin_sparsify(flat: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray,
                       thresh: jnp.ndarray, n_bins: int,
                       interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused histogram binning + sub-threshold zeroing over a flat vector."""
    interpret = _resolve_interpret(interpret)
    x2d, n = _pad_to_grid(flat.astype(jnp.float32))
    rows = x2d.shape[0]
    grid = rows // _BLOCK_ROWS
    out = pl.pallas_call(
        functools.partial(_quant_kernel, n_bins=n_bins),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            in_specs=[pl.BlockSpec((_BLOCK_ROWS, _LANES),
                                   lambda i, *_: (i, 0))],
            out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANES),
                                   lambda i, *_: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.float32),
        interpret=interpret,
    )(jnp.asarray([lo, hi, thresh], jnp.float32), x2d)
    return out.reshape(-1)[:n].astype(flat.dtype)


# ----------------------------------------------------------------------
def _sgd_kernel(hyper_ref, p_ref, g_ref, m_ref, op_ref, om_ref):
    lr = hyper_ref[0]
    mu = hyper_ref[1]
    gate = hyper_ref[2]
    m_new = g_ref[:] + mu * m_ref[:]
    p_new = p_ref[:] - lr * m_new
    live = gate > 0
    op_ref[:] = jnp.where(live, p_new, p_ref[:])
    om_ref[:] = jnp.where(live, m_new, m_ref[:])


def fused_sgd_apply(p_flat: jnp.ndarray, g_flat: jnp.ndarray,
                    m_flat: jnp.ndarray, lr: jnp.ndarray,
                    momentum: jnp.ndarray, gate: jnp.ndarray,
                    interpret: Optional[bool] = None):
    """One-pass momentum-SGD apply over flat f32 vectors.

    ``(p', m') = (p - lr * m', g + mu * m)`` with ``gate <= 0`` pinning
    both outputs to their inputs (the all-padding-step no-op of
    ``engine/client_update.py``).  Matches ``optax.sgd(momentum=mu)``
    exactly: the optax trace is ``t' = g + mu*t`` and the applied update
    ``p + (-lr)*t'``, which is bitwise ``p - lr*t'`` in IEEE arithmetic
    (tests/test_pallas_kernels.py pins the equivalence).
    """
    interpret = _resolve_interpret(interpret)
    x2d, n = _pad_to_grid(p_flat.astype(jnp.float32))
    g2d, _ = _pad_to_grid(g_flat.astype(jnp.float32))
    m2d, _ = _pad_to_grid(m_flat.astype(jnp.float32))
    rows = x2d.shape[0]
    grid = rows // _BLOCK_ROWS
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i, *_: (i, 0))
    new_p, new_m = pl.pallas_call(
        _sgd_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            in_specs=[spec, spec, spec],
            out_specs=[spec, spec],
        ),
        out_shape=[jax.ShapeDtypeStruct(x2d.shape, jnp.float32),
                   jax.ShapeDtypeStruct(x2d.shape, jnp.float32)],
        interpret=interpret,
    )(jnp.stack([jnp.asarray(lr, jnp.float32),
                 jnp.asarray(momentum, jnp.float32),
                 jnp.asarray(gate, jnp.float32)]), x2d, g2d, m2d)
    return (new_p.reshape(-1)[:n].astype(p_flat.dtype),
            new_m.reshape(-1)[:n].astype(m_flat.dtype))
