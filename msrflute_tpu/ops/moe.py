"""Expert parallelism — switch-style top-1 MoE with all-to-all dispatch.

Net-new vs the reference (FLUTE has no model partitioning); completes the
parallelism toolbox (dp / tp / sp / pp / **ep**) on the same
``jax.sharding.Mesh`` machinery — see ``docs/architecture.md``.

Design: one expert per device on an ``expert`` mesh axis.  Tokens are
data-sharded over the SAME axis; each device routes its local tokens
(top-1, softmax gate), packs them into fixed-capacity per-expert buffers
(static shapes — overflow beyond capacity is dropped, the standard switch
behavior), exchanges buffers with ``lax.all_to_all`` so every device holds
exactly its own expert's tokens, applies the expert, and a second
``all_to_all`` returns results to their owners where gates scale them.
Everything is SPMD and differentiable; XLA rides the all-to-alls on ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

EXPERT_AXIS = "expert"


def _dispatch_indices(expert_id: jnp.ndarray, n_experts: int,
                      capacity: int):
    """Per-token slot in its expert's send buffer (or capacity = dropped).

    ``position_in_expert[i]`` = how many earlier local tokens chose the
    same expert; tokens beyond ``capacity`` are overflow.
    """
    onehot = jax.nn.one_hot(expert_id, n_experts, dtype=jnp.int32)  # [n, E]
    position_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot
    pos = jnp.sum(position_in_expert, axis=1)                       # [n]
    keep = pos < capacity
    return pos, keep


def moe_apply(router_w: jnp.ndarray, expert_params: Any,
              expert_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
              x: jnp.ndarray, mesh: Mesh, axis: str = EXPERT_AXIS,
              capacity_factor: float = 2.0) -> jnp.ndarray:
    """Top-1 MoE layer over globally ``[T, D]`` tokens.

    ``router_w``: ``[D, E]`` (replicated); ``expert_params``: pytree with
    leading axis E == mesh.shape[axis] (sharded over ``axis``);
    ``expert_fn(params_e, tokens) -> tokens`` shape-preserving.  ``x`` is
    sharded on T over ``axis`` (data-parallel tokens).  Returns the same
    sharding as ``x``; dropped (over-capacity) tokens pass through on the
    residual path (output 0 from the layer, the switch convention).
    """
    E = mesh.shape[axis]
    T, D = x.shape
    if T % E:
        raise ValueError(f"token count {T} not divisible by {axis}={E}")
    leaves = jax.tree.leaves(expert_params)
    if leaves and leaves[0].shape[0] != E:
        raise ValueError(
            f"expert_params leading axis {leaves[0].shape[0]} != {axis}={E}")
    local_t = T // E
    # per-(device, expert) buffer size; every local token fits iff one
    # expert hoards fewer than `capacity` of a device's tokens
    capacity = max(1, int(capacity_factor * local_t / E))

    def body(rw, ep, x_l):
        params_local = jax.tree.map(lambda a: a[0], ep)
        n = x_l.shape[0]
        logits = x_l @ rw                                # [n, E]
        expert_id = jnp.argmax(logits, axis=-1)
        gate = jax.nn.softmax(logits.astype(jnp.float32),
                              axis=-1)[jnp.arange(n), expert_id]
        pos, keep = _dispatch_indices(expert_id, E, capacity)

        # scatter local tokens into [E, capacity, D] send buffers
        send = jnp.zeros((E, capacity, D), x_l.dtype)
        send = send.at[expert_id, jnp.where(keep, pos, 0)].add(
            jnp.where(keep[:, None], x_l, 0.0))
        # exchange: device d's send[j] goes to device j; afterwards device
        # j holds [E_senders, capacity, D] — all tokens for ITS expert
        recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                              tiled=False)
        y = expert_fn(params_local, recv.reshape(E * capacity, D))
        y = y.reshape(E, capacity, D)
        # return: device j sends results back to each owner d
        back = lax.all_to_all(y, axis, split_axis=0, concat_axis=0,
                              tiled=False)                # [E, capacity, D]
        # gather each local token's result from its expert's buffer
        out = back[expert_id, pos] * keep[:, None].astype(x_l.dtype)
        return out * gate[:, None].astype(x_l.dtype)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), jax.tree.map(lambda _: P(axis), expert_params),
                  P(axis)),
        out_specs=P(axis), check_vma=False)
    return fn(router_w, expert_params, x)


class MoEFFN(nn.Module):
    """Switch top-1 MoE feed-forward as a drop-in flax module.

    Two execution modes over the SAME parameters:

    - **local** (``ep_mesh=None``): every device evaluates all experts and
      selects per token — exact routing, no capacity drops.  The federated
      path uses this (experts are tiny, clients ride the clients axis).
    - **expert-parallel** (``ep_mesh`` set): :func:`moe_apply` all-to-all
      dispatch with one expert per device of ``expert_axis``; requires
      ``num_experts == mesh.shape[expert_axis]``.  With capacity ample
      enough that nothing drops, both modes are numerically identical
      (tested).

    Input/output: ``[..., D]`` tokens (leading axes flattened internally).
    """

    num_experts: int
    hidden: int
    dtype: Any = jnp.float32
    ep_mesh: Optional[Mesh] = None
    expert_axis: str = EXPERT_AXIS
    capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, x):
        D = x.shape[-1]
        E = self.num_experts
        router = self.param("router", nn.initializers.lecun_normal(),
                            (D, E)).astype(self.dtype)
        w_in = self.param("w_in", nn.initializers.lecun_normal(),
                          (E, D, self.hidden)).astype(self.dtype)
        w_out = self.param("w_out", nn.initializers.lecun_normal(),
                           (E, self.hidden, D)).astype(self.dtype)
        lead = x.shape[:-1]
        t = x.reshape(-1, D).astype(self.dtype)

        if self.ep_mesh is not None:
            if self.ep_mesh.shape[self.expert_axis] != E:
                raise ValueError(
                    f"num_experts={E} != {self.expert_axis}="
                    f"{self.ep_mesh.shape[self.expert_axis]}")

            def expert_fn(p, tok):
                return nn.gelu(tok @ p["w_in"]) @ p["w_out"]

            y = moe_apply(router, {"w_in": w_in, "w_out": w_out}, expert_fn,
                          t, self.ep_mesh, axis=self.expert_axis,
                          capacity_factor=self.capacity_factor)
            return y.reshape(*lead, D)

        # local mode: evaluate all experts, select per token
        logits = (t @ router).astype(jnp.float32)          # [T, E]
        eid = jnp.argmax(logits, axis=-1)
        gate = jax.nn.softmax(logits, axis=-1)[
            jnp.arange(t.shape[0]), eid].astype(t.dtype)
        h = nn.gelu(jnp.einsum("td,edh->teh", t, w_in))
        y_all = jnp.einsum("teh,ehd->ted", h, w_out)       # [T, E, D]
        y = jnp.take_along_axis(y_all, eid[:, None, None], axis=1)[:, 0]
        return (y * gate[:, None]).reshape(*lead, D)
