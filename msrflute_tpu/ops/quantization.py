"""Gradient quantization — histogram binning + quantile sparsification.

Parity target: reference ``extensions/quantization/quant.py:9-100``:
per-layer (or global) min/max histogram binning of the gradient into
``2**quant_bits`` levels, with components whose magnitude falls below the
``quant_threshold`` quantile set to zero.  Semantics preserved:

- bin labels = ``linspace(min, max, n_bins)``; each value maps to the
  nearest label (the reference shifts by half a bin width before
  ``bucketize`` to turn ceil into round — here we use rounding directly);
- threshold = quantile of ``|grad|`` at ``quant_threshold``; strictly
  greater survives (``quant.py:50-51``).

TPU-native: pure jnp, runs inside the jitted round under vmap over clients.
This is the designated Pallas-fusion candidate (SURVEY.md §7): a fused
clip->noise->bin pass over the flat update; see
:mod:`msrflute_tpu.ops.pallas_kernels`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp


def approx_quantile_abs(x: jnp.ndarray, q, n_bins: int = 2048) -> jnp.ndarray:
    """Histogram-CDF approximation of ``quantile(|x|, q)``.

    ``jnp.quantile`` sorts — O(n log n) *per leaf per client* under the
    round's vmap, which profiling flagged as the dominant cost of a
    DGA+quant round.  A fixed-width histogram of ``|x|`` is one O(n)
    scatter-add; the threshold is linearly interpolated inside the bin
    where the CDF crosses ``q``.  Max error is one bin width
    (``max|x| / n_bins``) — far below the annealed-threshold granularity
    the reference runs with (``extensions/quantization/quant.py:50-51``).
    """
    a = jnp.abs(x.reshape(-1).astype(jnp.float32))
    hi = jnp.maximum(jnp.max(a), 1e-30)
    idx = jnp.clip((a / hi * n_bins).astype(jnp.int32), 0, n_bins - 1)
    # integer accumulators: float32 counts saturate at 2^24 (x+1 == x),
    # silently breaking the one-bin-width error bound for >16M-element leaves
    counts = jnp.zeros((n_bins,), jnp.int32).at[idx].add(1)
    cdf = jnp.cumsum(counts).astype(jnp.float32) / a.size
    # first bin whose cdf >= q, then interpolate within it
    bin_i = jnp.argmax(cdf >= q)
    prev = jnp.where(bin_i > 0, cdf[jnp.maximum(bin_i - 1, 0)], 0.0)
    frac = (q - prev) / jnp.maximum(cdf[bin_i] - prev, 1e-12)
    return (bin_i + jnp.clip(frac, 0.0, 1.0)) * hi / n_bins


def quantize_array(grad: jnp.ndarray, n_bins: int,
                   quant_threshold: float,
                   min_grad: Optional[jnp.ndarray] = None,
                   max_grad: Optional[jnp.ndarray] = None,
                   approx: bool = False) -> jnp.ndarray:
    """Quantize one tensor to ``n_bins`` levels, zeroing sub-threshold
    components (reference ``quant_bins`` + thresholding).

    Stats (min/max/quantile) run in XLA; on TPU the elementwise
    bin+sparsify pass runs as the fused Pallas kernel."""
    g = grad.astype(jnp.float32)
    lo = jnp.min(g) if min_grad is None else min_grad
    hi = jnp.max(g) if max_grad is None else max_grad
    thresh = (approx_quantile_abs(g, quant_threshold) if approx
              else jnp.quantile(jnp.abs(g), quant_threshold))
    if jax.default_backend() == "tpu":
        from .pallas_kernels import quant_bin_sparsify
        out = quant_bin_sparsify(g.reshape(-1), lo, hi, thresh, n_bins)
        return out.reshape(grad.shape).astype(grad.dtype)
    width = (hi - lo) / jnp.maximum(n_bins - 1, 1)
    # nearest-label rounding (== reference's half-bin-shifted bucketize)
    idx = jnp.clip(jnp.round((g - lo) / jnp.maximum(width, 1e-30)), 0, n_bins - 1)
    binned = lo + idx * width
    return jnp.where(jnp.abs(g) > thresh, binned, 0.0).astype(grad.dtype)


def quantize_pytree(tree: Any, quant_threshold: Optional[float],
                    quant_bits: int = 8, global_stats: bool = False,
                    approx: bool = False) -> Any:
    """Quantize every leaf (reference ``quant_model``).  ``global_stats``
    computes one min/max/threshold across all leaves (``quant.py:36-39``).
    ``approx`` swaps the exact sort-based quantile for the O(n)
    histogram-CDF estimate (config ``client_config.quant_approx``)."""
    if quant_threshold is None:
        return tree
    n_bins = 2 ** int(quant_bits)
    if not global_stats:
        return jax.tree.map(
            lambda g: quantize_array(g, n_bins, quant_threshold,
                                     approx=approx), tree)
    from jax.flatten_util import ravel_pytree
    flat, unravel = ravel_pytree(tree)
    lo, hi = jnp.min(flat), jnp.max(flat)
    thresh = (approx_quantile_abs(flat, quant_threshold) if approx
              else jnp.quantile(jnp.abs(flat), quant_threshold))
    width = (hi - lo) / jnp.maximum(n_bins - 1, 1)
    idx = jnp.clip(jnp.round((flat - lo) / jnp.maximum(width, 1e-30)), 0, n_bins - 1)
    binned = lo + idx * width
    return unravel(jnp.where(jnp.abs(flat) > thresh, binned, 0.0))
