"""Pallas flash attention — the long-context hot op, tiled for the MXU.

Net-new vs the reference (FLUTE has no attention models beyond HF BERT and
no long-context machinery, SURVEY.md §5.7).  This is the TPU-native
answer for the RingLM family: exact attention computed blockwise in VMEM
with an online softmax, O(L) memory instead of the O(L^2) score
materialization of the jnp path (``models/ringlm.py`` local mode).  Both
passes are Pallas kernels (FlashAttention-2 style tiling):

- forward: grid ``(B, H, Lq/block_q, Lk/block_k)`` with the key/value
  block index INNERMOST and ``arbitrary`` semantics — mosaic pipelines
  the next K/V block's HBM→VMEM fetch under the current block's MXU
  work, and the ``(m, l, acc)`` online-softmax carry lives in VMEM
  scratch across the inner sweep.  VMEM residency is O(block), never
  O(L): the round-4 kernels loaded the WHOLE key sequence per program
  (the kv BlockSpec spanned padded Lk), which both capped L at VMEM
  size and serialized HBM fetches behind compute — the measured reason
  dense beat flash at every length.
- backward: ``dq`` on the same grid shape; ``dk``/``dv`` on
  ``(B, H, Lk/block_k, Lq/block_q)`` (query blocks innermost), both
  accumulating into VMEM scratch and recomputing probabilities from the
  saved ``lse`` (no O(L^2) residuals).

Causal masking is GLOBAL-position based: dynamic ``q_offset``/``k_offset``
scalars (SMEM scalar-prefetch) shift the row/column ids, which is what
lets :func:`msrflute_tpu.ops.ring_attention.ring_self_attention` run these
same kernels on rotating chunk pairs whose positions differ per step.
:func:`flash_attention_lse` additionally returns the per-row logsumexp —
with a VJP that honors the lse cotangent — so rotation outputs can be
merged exactly outside the kernel.

Length/feature padding is static; masked probability entries are zeroed
explicitly (no ``-inf`` arithmetic on the MXU path).  Off-TPU the default
is an exact dense jnp reference with identical masking/lse semantics —
NOT interpret-mode kernels: the interpret machinery's cross-core barriers
deadlock when the op runs inside ``shard_map`` over multiple virtual CPU
devices (the federated round does exactly that).  Pass ``interpret=True``
to force the kernel code path (what the unit tests do, outside shard_map).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_kernels import _resolve_interpret

_LANES = 128
# row statistics (lse/delta/glse) ride lane-broadcast over the trailing
# dim.  PR-12 retile: the stat streams use FULL (8, 128)-aligned tiles —
# the old 8-lane blocks saved VMEM but made every stat load/store a
# sub-tile access, which mosaic serviced with masked sub-lane ops on the
# hot dq/dkv inner loops (device truth measured the kernel at 0.53x of
# dense at seq 2048 before the retile).  VMEM cost per grid step is
# 3 stat blocks x block_q x 128 x 4B — comparable to one head-dim block,
# well inside budget at the block sizes the planner picks.
_STAT_LANES = _LANES
_NEG = -1e30  # "minus infinity" that survives exp/max without NaNs
#: default kernel tile when the caller pins blocks explicitly
_DEF_BLOCK = 128


def _pad_axis(x, axis, to):
    pad = to - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _ceil_to(n, m):
    return int(np.ceil(n / m)) * m


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _rows(stat_ref):
    """Recover a per-row vector from a lane-broadcast [rows, _STAT_LANES]
    scratch/stream (all lanes hold the same value)."""
    return jnp.max(stat_ref[...], axis=-1)


def _bcast_rows(vec, rows):
    return jax.lax.broadcast_in_dim(vec, (rows, _STAT_LANES), (0,))


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_s, l_s, acc_s, *, causal, scale, block_q, block_k,
                l_q, l_k, num_k):
    qi, kj = pl.program_id(2), pl.program_id(3)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(kj == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    def _accumulate():
        q = q_ref[0, 0, :, :].astype(jnp.float32)       # [bq, D]
        k_blk = k_ref[0, 0, :, :].astype(jnp.float32)   # [bk, D]
        v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        k_loc = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_loc < l_k
        if causal:
            q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, q_pos >= k_off + k_loc)
        s = jnp.where(mask, s, _NEG)
        m = _rows(m_s)
        l = _rows(l_s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        # mask p explicitly: for fully-masked rows s == m_new == _NEG and
        # exp(0) would resurrect the masked entries
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        corr = jnp.exp(m - m_new)
        m_s[...] = jax.lax.broadcast_in_dim(m_new, m_s.shape, (0,))
        l_s[...] = jax.lax.broadcast_in_dim(
            l * corr + jnp.sum(p, axis=1), l_s.shape, (0,))
        acc_s[...] = acc_s[...] * corr[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)

    if causal:
        # whole key blocks above the (global) diagonal contribute nothing;
        # their fetch still pipelines but the MXU work is skipped
        @pl.when(k_off + kj * block_k <= q_off + (qi + 1) * block_q - 1)
        def _():
            _accumulate()
    else:
        _accumulate()

    @pl.when(kj == num_k - 1)
    def _finalize():
        m = _rows(m_s)
        l = _rows(l_s)
        out = acc_s[...] / jnp.maximum(l, 1e-30)[:, None]
        o_ref[0, 0, :, :] = out.astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG)
        # TPU mosaic requires the last two BLOCK dims be (8k, 128m)-
        # aligned, so the per-row lse is stored lane-broadcast as
        # [bq, _STAT_LANES] (same trick as jax's own tpu flash kernel)
        lse_ref[0, 0, :, :] = _bcast_rows(lse, block_q)


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------
def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               glse_ref, dq_ref, dq_s, *, causal, scale, block_q, block_k,
               l_q, l_k, num_k):
    qi, kj = pl.program_id(2), pl.program_id(3)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(kj == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    def _accumulate():
        q = q_ref[0, 0, :, :].astype(jnp.float32)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        k_blk = k_ref[0, 0, :, :].astype(jnp.float32)   # [bk, D]
        v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
        # lse/delta/glse arrive lane-broadcast [bq, _STAT_LANES]; any
        # lane-reduce that preserves the (identical) value recovers rows
        lse = _rows(lse_ref[0, 0])
        delta = _rows(delta_ref[0, 0])
        glse = _rows(glse_ref[0, 0])
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_loc = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_loc < l_k
        if causal:
            q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, q_pos >= k_off + k_loc)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # d lse / d s = p, so the lse cotangent adds straight into ds
        ds = p * (dp - delta[:, None] + glse[:, None]) * scale
        dq_s[...] = dq_s[...] + jnp.dot(
            ds, k_blk, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(k_off + kj * block_k <= q_off + (qi + 1) * block_q - 1)
        def _():
            _accumulate()
    else:
        _accumulate()

    @pl.when(kj == num_k - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                glse_ref, dk_ref, dv_ref, dk_s, dv_s, *, causal, scale,
                block_q, block_k, l_q, l_k, num_q):
    ki, qj = pl.program_id(2), pl.program_id(3)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(qj == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    def _accumulate():
        k_blk = k_ref[0, 0, :, :].astype(jnp.float32)   # [bk, D]
        v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
        q = q_ref[0, 0, :, :].astype(jnp.float32)       # [bq, D]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = _rows(lse_ref[0, 0])
        delta = _rows(delta_ref[0, 0])
        glse = _rows(glse_ref[0, 0])
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        q_loc = qj * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_loc = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_loc < l_k
        if causal:
            mask = jnp.logical_and(
                mask, q_off + q_loc >= k_off + k_loc)
        # padded q rows carry lse = _NEG -> exp(s - _NEG) would overflow;
        # mask on the valid-q side too
        mask = jnp.logical_and(mask, q_loc < l_q)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dv_s[...] = dv_s[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta[:, None] + glse[:, None]) * scale
        dk_s[...] = dk_s[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]

    if causal:
        # q blocks strictly above this key block's (global) diagonal
        # start see nothing
        @pl.when(q_off + (qj + 1) * block_q - 1 >= k_off + ki * block_k)
        def _():
            _accumulate()
    else:
        _accumulate()

    @pl.when(qj == num_q - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_s[...].astype(dv_ref.dtype)


# ----------------------------------------------------------------------
# pallas_call plumbing
# ----------------------------------------------------------------------
def _specs(block_q, block_k, d_p):
    # kernel-side layout is [B, H, S, D]: the blocked dims (S, D) sit in
    # the last two positions, as TPU mosaic tiling requires.  Grid is
    # (B, H, q_block, kv_block) — the kv index j is INNERMOST so mosaic
    # double-buffers the kv fetches while q/out/stat blocks (index maps
    # ignoring j) stay resident across the inner sweep.
    q_spec = pl.BlockSpec((1, 1, block_q, d_p),
                          lambda b, h, i, j, *_: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d_p),
                           lambda b, h, i, j, *_: (b, h, j, 0))
    # per-row lse rides lane-broadcast as [B, H, lq_p, _STAT_LANES] —
    # full (8, 128) tiles since the PR-12 retile
    lse_spec = pl.BlockSpec((1, 1, block_q, _STAT_LANES),
                            lambda b, h, i, j, *_: (b, h, i, 0))
    return q_spec, kv_spec, lse_spec


#: grid semantics: batch/head/outer-block axes are parallel; the inner
#: accumulation axis must execute in order (scratch carry).  Older jax
#: spells these as strings and the params class TPUCompilerParams.
if hasattr(pltpu, "GridDimensionSemantics"):
    _PARALLEL = pltpu.GridDimensionSemantics.PARALLEL
    _ARBITRARY = pltpu.GridDimensionSemantics.ARBITRARY
else:
    _PARALLEL, _ARBITRARY = "parallel", "arbitrary"
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams
_SEMANTICS = (_PARALLEL, _PARALLEL, _PARALLEL, _ARBITRARY)


def _bhsd(x):
    """[B, L, H, D] -> [B, H, L, D] (kernel layout)."""
    return x.transpose(0, 2, 1, 3)


def _lanes(x, to):
    """[B, H, L] -> lane-broadcast [B, H, to, _STAT_LANES] (f32)."""
    return jnp.broadcast_to(
        _pad_axis(x.astype(jnp.float32), 2, to)[..., None],
        x.shape[:2] + (to, _STAT_LANES))


def _offs(q_offset, k_offset):
    return jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32)])


def _fwd(q, k, v, q_offset, k_offset, causal, scale, block_q, block_k,
         interpret):
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    lq_p, lk_p = _ceil_to(Lq, block_q), _ceil_to(Lk, block_k)
    d_p = _ceil_to(D, _LANES)
    qp = _bhsd(_pad_axis(_pad_axis(q, 1, lq_p), 3, d_p))
    kp = _bhsd(_pad_axis(_pad_axis(k, 1, lk_p), 3, d_p))
    vp = _bhsd(_pad_axis(_pad_axis(v, 1, lk_p), 3, d_p))
    q_spec, kv_spec, lse_spec = _specs(block_q, block_k, d_p)
    nq, nk = lq_p // block_q, lk_p // block_k
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               l_q=Lq, l_k=Lk, num_k=nk)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, nq, nk),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=[q_spec, lse_spec],
            # m/l scratch at full 128 lanes (the proven shape of jax's
            # own tpu flash kernel's carry scratch); the lse OUTPUT keeps
            # _STAT_LANES — it is a block of a real array, where the
            # equal-to-array-dim rule applies
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, d_p), jnp.float32),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct(qp.shape, q.dtype),
                   jax.ShapeDtypeStruct((B, H, lq_p, _STAT_LANES), jnp.float32)],
        compiler_params=_CompilerParams(dimension_semantics=_SEMANTICS),
        interpret=_resolve_interpret(interpret),
    )(_offs(q_offset, k_offset), qp, kp, vp)
    return _bhsd(out)[:, :Lq, :, :D], lse[:, :, :Lq, 0]


def _bwd(q, k, v, out, lse, q_offset, k_offset, g, g_lse, causal, scale,
         block_q, block_k, interpret):
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    lq_p, lk_p = _ceil_to(Lq, block_q), _ceil_to(Lk, block_k)
    d_p = _ceil_to(D, _LANES)
    qp = _bhsd(_pad_axis(_pad_axis(q, 1, lq_p), 3, d_p))
    kp = _bhsd(_pad_axis(_pad_axis(k, 1, lk_p), 3, d_p))
    vp = _bhsd(_pad_axis(_pad_axis(v, 1, lk_p), 3, d_p))
    gp = _bhsd(_pad_axis(_pad_axis(g, 1, lq_p), 3, d_p))
    lse_p = _lanes(lse, lq_p)
    glse_p = _lanes(g_lse, lq_p)
    # delta_i = sum_d dO_i . O_i  (rowwise), the softmax-grad correction
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=3)                              # [B, Lq, H]
    delta = _lanes(delta.transpose(0, 2, 1), lq_p)
    interp = _resolve_interpret(interpret)
    offs = _offs(q_offset, k_offset)
    q_spec, kv_spec, lse_spec = _specs(block_q, block_k, d_p)
    nq, nk = lq_p // block_q, lk_p // block_k

    dq_kernel = functools.partial(_dq_kernel, causal=causal, scale=scale,
                                  block_q=block_q, block_k=block_k,
                                  l_q=Lq, l_k=Lk, num_k=nk)
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, nq, nk),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, lse_spec, lse_spec,
                      lse_spec],
            out_specs=q_spec,
            scratch_shapes=[pltpu.VMEM((block_q, d_p), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        compiler_params=_CompilerParams(dimension_semantics=_SEMANTICS),
        interpret=interp,
    )(offs, qp, kp, vp, gp, lse_p, delta, glse_p)

    # dk/dv: key blocks on the outer grid axis, query blocks streamed
    # innermost (same pipelining story, axes swapped)
    kq_spec = pl.BlockSpec((1, 1, block_q, d_p),
                           lambda b, h, i, j, *_: (b, h, j, 0))
    kk_spec = pl.BlockSpec((1, 1, block_k, d_p),
                           lambda b, h, i, j, *_: (b, h, i, 0))
    kq_lse_spec = pl.BlockSpec((1, 1, block_q, _STAT_LANES),
                               lambda b, h, i, j, *_: (b, h, j, 0))
    dkv_kernel = functools.partial(_dkv_kernel, causal=causal, scale=scale,
                                   block_q=block_q, block_k=block_k,
                                   l_q=Lq, l_k=Lk, num_q=nq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, nk, nq),
            in_specs=[kq_spec, kk_spec, kk_spec, kq_spec, kq_lse_spec,
                      kq_lse_spec, kq_lse_spec],
            out_specs=[kk_spec, kk_spec],
            scratch_shapes=[pltpu.VMEM((block_k, d_p), jnp.float32),
                            pltpu.VMEM((block_k, d_p), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct(kp.shape, k.dtype),
                   jax.ShapeDtypeStruct(vp.shape, v.dtype)],
        compiler_params=_CompilerParams(dimension_semantics=_SEMANTICS),
        interpret=interp,
    )(offs, qp, kp, vp, gp, lse_p, delta, glse_p)
    return (_bhsd(dq)[:, :Lq, :, :D], _bhsd(dk)[:, :Lk, :, :D],
            _bhsd(dv)[:, :Lk, :, :D])


def _dense_lse(q, k, v, q_offset, k_offset, causal):
    """Exact dense reference with the kernels' masking/lse semantics
    (global-position causal mask; fully-masked rows -> zeros, lse=_NEG).
    The lse cotangent flows naturally through autodiff — no custom VJP."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("blhd,bmhd->bhlm", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        q_pos = jnp.asarray(q_offset, jnp.int32) + jnp.arange(Lq)
        k_pos = jnp.asarray(k_offset, jnp.int32) + jnp.arange(Lk)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
        e_mask = mask[None, None]
    else:
        e_mask = jnp.ones((1, 1, Lq, Lk), bool)
    m = jnp.max(s, axis=3)
    e = jnp.where(e_mask, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(e, axis=3)
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG)
    p = e / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_lse(q, k, v, q_offset, k_offset, causal, block_q, block_k,
               interpret):
    D = q.shape[3]
    scale = float(1.0 / np.sqrt(D))
    return _fwd(q, k, v, q_offset, k_offset, causal, scale, block_q,
                block_k, interpret)


def _flash_lse_fwd(q, k, v, q_offset, k_offset, causal, block_q, block_k,
                   interpret):
    out, lse = _flash_lse(q, k, v, q_offset, k_offset, causal, block_q,
                          block_k, interpret)
    return (out, lse), (q, k, v, out, lse, q_offset, k_offset)


def _flash_lse_bwd(causal, block_q, block_k, interpret, res, cotangents):
    q, k, v, out, lse, q_offset, k_offset = res
    g, g_lse = cotangents
    D = q.shape[3]
    scale = float(1.0 / np.sqrt(D))
    dq, dk, dv = _bwd(q, k, v, out, lse, q_offset, k_offset, g, g_lse,
                      causal, scale, block_q, block_k, interpret)
    zero = np.zeros((), jax.dtypes.float0)
    return dq, dk, dv, zero, zero


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ----------------------------------------------------------------------
# AOT-cost dispatch gate (PR 12): never ship a losing kernel silently.
#
# The round-4 flash path regressed to 0.53x of dense at seq 2048 and
# shipped anyway, because nothing compared the two compiled programs.
# Now every compiled-TPU dispatch goes through a per-shape PLAN: the
# flash forward is AOT-compiled at a handful of candidate (block_q,
# block_k) tilings and the dense reference once, each scored on the
# roofline estimate max(flops/peak, bytes/bandwidth) from the compiled
# cost_analysis (telemetry/xla.py — the same helper PR 7 wired for
# device truth).  The cheapest flash tiling wins the blocks; if DENSE
# wins outright, the op falls back to dense and records an
# ``attention_fallback_dense`` event the server drains into the
# structured-event stream (docs/observability.md) — the regression is
# loud, auditable, and costs nothing but the fallback itself.
# ----------------------------------------------------------------------
#: candidate kernel tilings the planner prices (explicit caller blocks
#: are prepended); all (8, 128)-tile aligned
_BLOCK_CANDIDATES = ((128, 128), (256, 256), (512, 512),
                     (128, 256), (256, 128))
#: shape-signature -> plan dict; one AOT shootout per distinct geometry
_PLAN_CACHE: dict = {}
#: pending ``{"kind": ...}`` structured-event records, drained by the
#: server host tail (engine/server.py) — capped so an undrained CLI
#: session cannot grow it unboundedly
_PENDING_EVENTS: list = []
_EVENTS_CAP = 64


def drain_attention_events() -> list:
    """Hand the buffered dispatch-gate events to the caller (the
    server's host tail, which owns emitting them)."""
    global _PENDING_EVENTS
    out, _PENDING_EVENTS = _PENDING_EVENTS, []
    return out


def reset_attention_plans() -> None:
    """Forget cached plans + pending events (tests)."""
    _PLAN_CACHE.clear()
    del _PENDING_EVENTS[:]


def _roofline_secs(cost: Optional[dict]) -> float:
    """Estimated execution seconds of a compiled program from its cost
    analysis: ``max(flops / chip peak, bytes accessed / HBM bandwidth)``
    — the roofline bound, the one-number score the gate compares."""
    if not cost:
        return float("inf")
    from ..utils.compat import chip_hbm_bytes_per_sec, chip_peak_flops
    flops = float(cost.get("flops") or 0.0)
    bytes_acc = float(cost.get("bytes_accessed") or 0.0)
    if flops <= 0.0 and bytes_acc <= 0.0:
        return float("inf")
    _, peak = chip_peak_flops()
    _, bw = chip_hbm_bytes_per_sec()
    return max(flops / peak, bytes_acc / bw)


def _probe_costs(B, Lq, Lk, H, D, dtype, causal, candidates):
    """Compiled cost analyses for the dense reference and each flash
    candidate tiling, via the AOT path (abstract operands — nothing
    touches device memory)."""
    from ..telemetry.xla import aot_cost
    q_s = jax.ShapeDtypeStruct((B, Lq, H, D), dtype)
    kv_s = jax.ShapeDtypeStruct((B, Lk, H, D), dtype)
    scale = float(1.0 / np.sqrt(D))

    def dense_fn(q, k, v):
        return _dense_lse(q, k, v, 0, 0, causal)

    dense_cost = aot_cost(dense_fn, q_s, kv_s, kv_s)
    flash_costs = {}
    for bq, bk in candidates:
        def flash_fn(q, k, v, _bq=bq, _bk=bk):
            return _fwd(q, k, v, 0, 0, causal, scale, _bq, _bk, None)
        flash_costs[(bq, bk)] = aot_cost(flash_fn, q_s, kv_s, kv_s)
    return dense_cost, flash_costs


def plan_attention(B: int, Lq: int, Lk: int, H: int, D: int, dtype,
                   causal: bool, *, block_q: Optional[int] = None,
                   block_k: Optional[int] = None,
                   cost_probe=None) -> dict:
    """Resolve (and cache) the dispatch plan for one attention geometry:
    ``{"impl": "flash"|"dense", "block_q", "block_k", "flash_secs_est",
    "dense_secs_est"}``.  Explicit ``block_q``/``block_k`` join the
    candidate set in front (so a pinned tiling is honored when it wins)
    but the gate still compares against dense — no silent-regression
    path.  ``cost_probe`` overrides the AOT prober (tests).
    """
    dtype = jnp.dtype(dtype)
    key = (B, Lq, Lk, H, D, str(dtype), bool(causal),
           block_q, block_k, jax.default_backend())
    plan = _PLAN_CACHE.get(key)
    if plan is not None:
        return plan
    candidates = []
    if block_q or block_k:
        candidates.append((int(block_q or _DEF_BLOCK),
                           int(block_k or _DEF_BLOCK)))
    candidates += [c for c in _BLOCK_CANDIDATES if c not in candidates]
    try:
        dense_cost, flash_costs = (cost_probe or _probe_costs)(
            B, Lq, Lk, H, D, dtype, bool(causal), candidates)
        dense_secs = _roofline_secs(dense_cost)
        # min() is stable: on tied roofline scores (cost_analysis often
        # cannot see intra-kernel tiling differences) the FIRST candidate
        # — the caller's pinned tiling when one was given — wins
        scored = [(_roofline_secs(flash_costs[c]), c) for c in candidates
                  if c in flash_costs]
        flash_secs, best_blocks = min(scored, key=lambda t: t[0])
        if not np.isfinite(flash_secs):
            # no usable cost analysis for ANY kernel candidate (e.g. a
            # backend whose cost_analysis() omits custom-call programs):
            # that is a telemetry gap, not a measured loss — same policy
            # as the probe-failure branch below, never a dense fallback
            raise RuntimeError("no cost analysis for any flash candidate")
    except Exception as exc:  # pragma: no cover - backend-specific
        # planning failure is NOT a fallback trigger: keep the caller's
        # pre-gate behavior (flash at the requested/default tiles) and
        # say so — falling back to dense on an exotic probe error would
        # turn a telemetry bug into an O(L^2) memory surprise
        import logging

        from ..utils.logging import print_rank
        print_rank(f"attention plan probe failed ({exc!r}); keeping the "
                   "flash kernel at the requested tiling",
                   loglevel=logging.WARNING)
        plan = {"impl": "flash",
                "block_q": int(block_q or _DEF_BLOCK),
                "block_k": int(block_k or _DEF_BLOCK),
                "flash_secs_est": None, "dense_secs_est": None}
        _PLAN_CACHE[key] = plan
        return plan
    plan = {"impl": "flash" if flash_secs <= dense_secs else "dense",
            "block_q": int(best_blocks[0]), "block_k": int(best_blocks[1]),
            "flash_secs_est": flash_secs, "dense_secs_est": dense_secs}
    _PLAN_CACHE[key] = plan
    if plan["impl"] == "dense":
        import logging

        from ..utils.logging import print_rank
        if len(_PENDING_EVENTS) < _EVENTS_CAP:
            _PENDING_EVENTS.append({
                "kind": "attention_fallback_dense",
                "batch": int(B), "seq_q": int(Lq), "seq_k": int(Lk),
                "heads": int(H), "head_dim": int(D),
                "causal": bool(causal),
                "flash_secs_est": flash_secs,
                "dense_secs_est": dense_secs,
                "block_q": int(best_blocks[0]),
                "block_k": int(best_blocks[1]),
            })
        print_rank(
            "attention dispatch gate: dense beats the flash kernel on "
            f"the compiled cost model at Lq={Lq} Lk={Lk} "
            f"(est {dense_secs:.2e}s vs {flash_secs:.2e}s) — dense "
            "fallback engaged (event: attention_fallback_dense)",
            loglevel=logging.WARNING)
    return plan


def flash_attention_lse(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        causal: bool = False, *, q_offset=0, k_offset=0,
                        block_q: Optional[int] = None,
                        block_k: Optional[int] = None,
                        interpret: Optional[bool] = None,
                        force_flash: bool = False):
    """Like :func:`flash_attention` but also returns the per-row
    logsumexp ``[B, H, Lq]`` (f32), with a VJP that honors its cotangent.
    ``q_offset``/``k_offset`` shift the global positions used by the
    causal mask — dynamic scalars, so ring rotations can jit one program.
    Rows whose keys are ALL masked come back as zeros with lse ≈ -1e30
    (exact identity for the rotation-merge in ring attention).

    ``block_q``/``block_k`` default to the AOT-cost planner's choice on
    the compiled TPU path (explicit ints are priced as the first
    candidate); the planner also compares the kernel against the dense
    reference and falls back to dense — recording an
    ``attention_fallback_dense`` event — when the compiled cost model
    says the kernel loses.  ``force_flash=True`` bypasses the gate (ring
    attention runs inside shard_map where per-shard planning would
    re-probe per trace; its opt-in is explicit)."""
    if q.ndim != 4:
        raise ValueError(f"expected [B, L, H, D], got {q.shape}")
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} vs {v.shape}")
    if interpret is None and jax.default_backend() != "tpu":
        # off-TPU default: exact dense math (see module docstring for why
        # interpret-mode kernels are not safe under shard_map)
        return _dense_lse(q, k, v, q_offset, k_offset, bool(causal))
    if interpret is None and not force_flash:
        # compiled TPU path: the dispatch gate
        B, Lq, H, D = q.shape
        plan = plan_attention(B, Lq, k.shape[1], H, D, q.dtype,
                              bool(causal), block_q=block_q,
                              block_k=block_k)
        if plan["impl"] == "dense":
            return _dense_lse(q, k, v, q_offset, k_offset, bool(causal))
        block_q, block_k = plan["block_q"], plan["block_k"]
    return _flash_lse(q, k, v, q_offset, k_offset, bool(causal),
                      int(block_q or _DEF_BLOCK),
                      int(block_k or _DEF_BLOCK), interpret)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False, *,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    force_flash: bool = False) -> jnp.ndarray:
    """Exact attention over ``[B, L, H, D]`` tensors, tiled in VMEM.

    Softmax scale is ``1/sqrt(D)`` (matching ``models/ringlm.py``).
    ``D`` is padded to the 128-lane width and ``L`` to the block size;
    key/value blocks STREAM through VMEM (O(block_k) residency, see
    module docstring), so single-chip ``L`` is bounded by the HBM
    footprint of the tensors themselves, not by VMEM — for lengths
    beyond one chip's HBM, shard the sequence axis over a mesh and run
    these kernels per ring rotation
    (``ring_self_attention(..., use_flash=True)``).

    On a non-TPU backend with ``interpret=None`` this op computes the SAME
    math via a dense reference — O(Lq*Lk) score memory, not the tiled
    O(L) profile above (see module docstring for why).  The Pallas-tiled
    path runs only on TPU (compiled) or with ``interpret=True``.

    The compiled-TPU path routes through the AOT-cost dispatch gate
    (see :func:`flash_attention_lse`); ``force_flash=True`` bypasses it
    — for kernel-validation tools that must exercise the kernel even
    where the cost model prefers dense.
    """
    return flash_attention_lse(q, k, v, causal, block_q=block_q,
                               block_k=block_k, interpret=interpret,
                               force_flash=force_flash)[0]
