"""Pallas flash attention — the long-context hot op, tiled for the MXU.

Net-new vs the reference (FLUTE has no attention models beyond HF BERT and
no long-context machinery, SURVEY.md §5.7).  This is the TPU-native
answer for the RingLM family: exact attention computed blockwise in VMEM
with an online softmax, O(L) memory instead of the O(L^2) score
materialization of the jnp path (``models/ringlm.py`` local mode).  Both
passes are Pallas kernels (FlashAttention-2 style tiling):

- forward: grid ``(B, H, Lq/block_q)``; each program streams key/value
  blocks through VMEM, carrying ``(m, l, acc)`` in registers and writing
  the output block plus the log-sum-exp row statistics for the backward.
- backward: ``dq`` on the same grid; ``dk``/``dv`` on a
  ``(B, H, Lk/block_k)`` grid — each recomputes the probabilities from
  the saved ``lse`` (no O(L^2) residuals).

Causal masking is GLOBAL-position based: dynamic ``q_offset``/``k_offset``
scalars (SMEM scalar-prefetch) shift the row/column ids, which is what
lets :func:`msrflute_tpu.ops.ring_attention.ring_self_attention` run these
same kernels on rotating chunk pairs whose positions differ per step.
:func:`flash_attention_lse` additionally returns the per-row logsumexp —
with a VJP that honors the lse cotangent — so rotation outputs can be
merged exactly outside the kernel.

Length/feature padding is static; masked probability entries are zeroed
explicitly (no ``-inf`` arithmetic on the MXU path).  Off-TPU the default
is an exact dense jnp reference with identical masking/lse semantics —
NOT interpret-mode kernels: the interpret machinery's cross-core barriers
deadlock when the op runs inside ``shard_map`` over multiple virtual CPU
devices (the federated round does exactly that).  Pass ``interpret=True``
to force the kernel code path (what the unit tests do, outside shard_map).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_kernels import _resolve_interpret

_LANES = 128
# row statistics (lse/delta/glse) ride broadcast over a SMALL trailing dim:
# a block whose last dim EQUALS the array dim is always legal, and 8 lanes
# instead of 128 keeps the dkv pass's three full-length stat streams 16x
# smaller in VMEM at long sequence lengths
_STAT_LANES = 8
_NEG = -1e30  # "minus infinity" that survives exp/max without NaNs


def _pad_axis(x, axis, to):
    pad = to - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _ceil_to(n, m):
    return int(np.ceil(n / m)) * m


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal,
                scale, block_q, block_k, l_q, l_k):
    qi = pl.program_id(2)
    q_off, k_off = offs_ref[0], offs_ref[1]
    q = q_ref[0, 0, :, :].astype(jnp.float32)          # [bq, D]
    q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    num_k = pl.cdiv(l_k, block_k)
    if causal:
        # k blocks entirely above the (global) diagonal contribute nothing
        num_k = jnp.clip(
            (q_off + (qi + 1) * block_q - k_off + block_k - 1) // block_k,
            0, num_k)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)                                # [bk, D]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        k_loc = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_loc < l_k
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_off + k_loc)
        s = jnp.where(mask, s, _NEG)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        # mask p explicitly: for fully-masked rows s == m_new == _NEG and
        # exp(0) would resurrect the masked entries
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0, 0, :, :] = out.astype(o_ref.dtype)
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG)
    # TPU mosaic requires the last two BLOCK dims be (8k, 128m)-aligned, so
    # the per-row lse is stored lane-broadcast as [bq, _STAT_LANES] (the
    # trick as jax's own tpu flash kernel's l/m outputs)
    lse_ref[0, 0, :, :] = jax.lax.broadcast_in_dim(
        lse, (block_q, _STAT_LANES), (0,))


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------
def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               glse_ref, dq_ref, *, causal, scale, block_q, block_k,
               l_q, l_k):
    qi = pl.program_id(2)
    q_off, k_off = offs_ref[0], offs_ref[1]
    q = q_ref[0, 0, :, :].astype(jnp.float32)
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    # lse/delta/glse arrive lane-broadcast [bq, _STAT_LANES]; any lane-reduce
    # that preserves the (identical) value recovers the row vector
    lse = jnp.max(lse_ref[0, 0, :, :], axis=1)
    delta = jnp.max(delta_ref[0, 0, :, :], axis=1)
    glse = jnp.max(glse_ref[0, 0, :, :], axis=1)
    q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    num_k = pl.cdiv(l_k, block_k)
    if causal:
        num_k = jnp.clip(
            (q_off + (qi + 1) * block_q - k_off + block_k - 1) // block_k,
            0, num_k)

    def body(j, dq):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_loc = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_loc < l_k
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_off + k_loc)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # d lse / d s = p, so the lse cotangent adds straight into ds
        ds = p * (dp - delta[:, None] + glse[:, None]) * scale
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    dq = jax.lax.fori_loop(0, num_k, body, dq0)
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                glse_ref, dk_ref, dv_ref, *, causal, scale, block_q,
                block_k, l_q, l_k):
    ki = pl.program_id(2)
    q_off, k_off = offs_ref[0], offs_ref[1]
    k_blk = k_ref[0, 0, :, :].astype(jnp.float32)       # [bk, D]
    v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
    k_pos = k_off + ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    num_q = pl.cdiv(l_q, block_q)
    if causal:
        # q blocks strictly above this key block's (global) diagonal start
        # see nothing: first candidate block index, clipped into range
        i0 = jnp.clip((k_off + ki * block_k - q_off) // block_q, 0, num_q)
    else:
        i0 = 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = jnp.max(
            lse_ref[0, 0, pl.ds(i * block_q, block_q), :], axis=1)
        delta = jnp.max(
            delta_ref[0, 0, pl.ds(i * block_q, block_q), :], axis=1)
        glse = jnp.max(
            glse_ref[0, 0, pl.ds(i * block_q, block_q), :], axis=1)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        q_loc = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_loc = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_loc < l_k
        if causal:
            mask = jnp.logical_and(mask, q_off + q_loc >= k_pos)
        # padded q rows carry lse = _NEG -> exp(s - _NEG) would overflow;
        # mask on the valid-q side too
        mask = jnp.logical_and(mask, q_loc < l_q)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta[:, None] + glse[:, None]) * scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        return dk, dv

    dk0 = jnp.zeros((block_k, k_blk.shape[1]), jnp.float32)
    dv0 = jnp.zeros((block_k, v_blk.shape[1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(i0, num_q, body, (dk0, dv0))
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


# ----------------------------------------------------------------------
# pallas_call plumbing
# ----------------------------------------------------------------------
def _specs(block_q, block_k, lk_p, d_p):
    # kernel-side layout is [B, H, S, D]: the blocked dims (S, D) sit in
    # the last two positions, as TPU mosaic tiling requires
    q_spec = pl.BlockSpec((1, 1, block_q, d_p),
                          lambda b, h, i, *_: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, lk_p, d_p),
                           lambda b, h, i, *_: (b, h, 0, 0))
    # per-row lse rides lane-broadcast as [B, H, lq_p, _STAT_LANES]
    lse_spec = pl.BlockSpec((1, 1, block_q, _STAT_LANES),
                            lambda b, h, i, *_: (b, h, i, 0))
    return q_spec, kv_spec, lse_spec


def _bhsd(x):
    """[B, L, H, D] -> [B, H, L, D] (kernel layout)."""
    return x.transpose(0, 2, 1, 3)


def _lanes(x, to):
    """[B, H, L] -> lane-broadcast [B, H, to, _STAT_LANES] (f32)."""
    return jnp.broadcast_to(
        _pad_axis(x.astype(jnp.float32), 2, to)[..., None],
        x.shape[:2] + (to, _STAT_LANES))


def _offs(q_offset, k_offset):
    return jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32)])


def _fwd(q, k, v, q_offset, k_offset, causal, scale, block_q, block_k,
         interpret):
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    lq_p, lk_p = _ceil_to(Lq, block_q), _ceil_to(Lk, block_k)
    d_p = _ceil_to(D, _LANES)
    qp = _bhsd(_pad_axis(_pad_axis(q, 1, lq_p), 3, d_p))
    kp = _bhsd(_pad_axis(_pad_axis(k, 1, lk_p), 3, d_p))
    vp = _bhsd(_pad_axis(_pad_axis(v, 1, lk_p), 3, d_p))
    q_spec, kv_spec, lse_spec = _specs(block_q, block_k, lk_p, d_p)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               l_q=Lq, l_k=Lk)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, lq_p // block_q),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=[q_spec, lse_spec],
        ),
        out_shape=[jax.ShapeDtypeStruct(qp.shape, q.dtype),
                   jax.ShapeDtypeStruct((B, H, lq_p, _STAT_LANES), jnp.float32)],
        interpret=_resolve_interpret(interpret),
    )(_offs(q_offset, k_offset), qp, kp, vp)
    return _bhsd(out)[:, :Lq, :, :D], lse[:, :, :Lq, 0]


def _bwd(q, k, v, out, lse, q_offset, k_offset, g, g_lse, causal, scale,
         block_q, block_k, interpret):
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    lq_p, lk_p = _ceil_to(Lq, block_q), _ceil_to(Lk, block_k)
    d_p = _ceil_to(D, _LANES)
    qp = _bhsd(_pad_axis(_pad_axis(q, 1, lq_p), 3, d_p))
    kp = _bhsd(_pad_axis(_pad_axis(k, 1, lk_p), 3, d_p))
    vp = _bhsd(_pad_axis(_pad_axis(v, 1, lk_p), 3, d_p))
    gp = _bhsd(_pad_axis(_pad_axis(g, 1, lq_p), 3, d_p))
    lse_p = _lanes(lse, lq_p)
    glse_p = _lanes(g_lse, lq_p)
    # delta_i = sum_d dO_i . O_i  (rowwise), the softmax-grad correction
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=3)                              # [B, Lq, H]
    delta = _lanes(delta.transpose(0, 2, 1), lq_p)
    interp = _resolve_interpret(interpret)
    offs = _offs(q_offset, k_offset)
    q_spec, kv_spec, lse_spec = _specs(block_q, block_k, lk_p, d_p)

    dq_kernel = functools.partial(_dq_kernel, causal=causal, scale=scale,
                                  block_q=block_q, block_k=block_k,
                                  l_q=Lq, l_k=Lk)
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, lq_p // block_q),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, lse_spec, lse_spec,
                      lse_spec],
            out_specs=q_spec,
        ),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        interpret=interp,
    )(offs, qp, kp, vp, gp, lse_p, delta, glse_p)

    # dk/dv: grid over key blocks; q/do/lse/delta stream in full
    kq_spec = pl.BlockSpec((1, 1, lq_p, d_p),
                           lambda b, h, i, *_: (b, h, 0, 0))
    kk_spec = pl.BlockSpec((1, 1, block_k, d_p),
                           lambda b, h, i, *_: (b, h, i, 0))
    full_lse_spec = pl.BlockSpec((1, 1, lq_p, _STAT_LANES),
                                 lambda b, h, i, *_: (b, h, 0, 0))
    dkv_kernel = functools.partial(_dkv_kernel, causal=causal, scale=scale,
                                   block_q=block_q, block_k=block_k,
                                   l_q=Lq, l_k=Lk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, lk_p // block_k),
            in_specs=[kq_spec, kk_spec, kk_spec, kq_spec, full_lse_spec,
                      full_lse_spec, full_lse_spec],
            out_specs=[kk_spec, kk_spec],
        ),
        out_shape=[jax.ShapeDtypeStruct(kp.shape, k.dtype),
                   jax.ShapeDtypeStruct(vp.shape, v.dtype)],
        interpret=interp,
    )(offs, qp, kp, vp, gp, lse_p, delta, glse_p)
    return (_bhsd(dq)[:, :Lq, :, :D], _bhsd(dk)[:, :Lk, :, :D],
            _bhsd(dv)[:, :Lk, :, :D])


def _dense_lse(q, k, v, q_offset, k_offset, causal):
    """Exact dense reference with the kernels' masking/lse semantics
    (global-position causal mask; fully-masked rows -> zeros, lse=_NEG).
    The lse cotangent flows naturally through autodiff — no custom VJP."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("blhd,bmhd->bhlm", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        q_pos = jnp.asarray(q_offset, jnp.int32) + jnp.arange(Lq)
        k_pos = jnp.asarray(k_offset, jnp.int32) + jnp.arange(Lk)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
        e_mask = mask[None, None]
    else:
        e_mask = jnp.ones((1, 1, Lq, Lk), bool)
    m = jnp.max(s, axis=3)
    e = jnp.where(e_mask, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(e, axis=3)
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG)
    p = e / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_lse(q, k, v, q_offset, k_offset, causal, block_q, block_k,
               interpret):
    D = q.shape[3]
    scale = float(1.0 / np.sqrt(D))
    return _fwd(q, k, v, q_offset, k_offset, causal, scale, block_q,
                block_k, interpret)


def _flash_lse_fwd(q, k, v, q_offset, k_offset, causal, block_q, block_k,
                   interpret):
    out, lse = _flash_lse(q, k, v, q_offset, k_offset, causal, block_q,
                          block_k, interpret)
    return (out, lse), (q, k, v, out, lse, q_offset, k_offset)


def _flash_lse_bwd(causal, block_q, block_k, interpret, res, cotangents):
    q, k, v, out, lse, q_offset, k_offset = res
    g, g_lse = cotangents
    D = q.shape[3]
    scale = float(1.0 / np.sqrt(D))
    dq, dk, dv = _bwd(q, k, v, out, lse, q_offset, k_offset, g, g_lse,
                      causal, scale, block_q, block_k, interpret)
    zero = np.zeros((), jax.dtypes.float0)
    return dq, dk, dv, zero, zero


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        causal: bool = False, *, q_offset=0, k_offset=0,
                        block_q: int = 128, block_k: int = 128,
                        interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but also returns the per-row
    logsumexp ``[B, H, Lq]`` (f32), with a VJP that honors its cotangent.
    ``q_offset``/``k_offset`` shift the global positions used by the
    causal mask — dynamic scalars, so ring rotations can jit one program.
    Rows whose keys are ALL masked come back as zeros with lse ≈ -1e30
    (exact identity for the rotation-merge in ring attention)."""
    if q.ndim != 4:
        raise ValueError(f"expected [B, L, H, D], got {q.shape}")
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} vs {v.shape}")
    if interpret is None and jax.default_backend() != "tpu":
        # off-TPU default: exact dense math (see module docstring for why
        # interpret-mode kernels are not safe under shard_map)
        return _dense_lse(q, k, v, q_offset, k_offset, bool(causal))
    return _flash_lse(q, k, v, q_offset, k_offset, bool(causal),
                      int(block_q), int(block_k), interpret)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False, *, block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Exact attention over ``[B, L, H, D]`` tensors, tiled in VMEM.

    Softmax scale is ``1/sqrt(D)`` (matching ``models/ringlm.py``).
    ``D`` is padded to the 128-lane width and ``L`` to the block size; the
    key/value stream for one head must fit VMEM, which bounds local
    sequence length at roughly 16k (f32) per chip — beyond that, shard the
    sequence axis over a mesh and run these kernels per ring rotation
    (``ring_self_attention(..., use_flash=True)``).

    On a non-TPU backend with ``interpret=None`` this op computes the SAME
    math via a dense reference — O(Lq*Lk) score memory, not the tiled
    O(L) profile above (see module docstring for why).  The Pallas-tiled
    path runs only on TPU (compiled) or with ``interpret=True``.
    """
    return flash_attention_lse(q, k, v, causal, block_q=block_q,
                               block_k=block_k, interpret=interpret)[0]
