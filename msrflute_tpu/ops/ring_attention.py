"""Ring attention — sequence-parallel exact attention over a mesh axis.

Net-new vs the reference (FLUTE has no long-context machinery, SURVEY.md
§5.7); this is the TPU-native long-sequence path: shard the sequence over a
``sequence`` mesh axis and rotate key/value blocks around the ring with
``ppermute`` while accumulating a numerically-stable online softmax — exact
attention with O(L/N) memory per chip and N-1 rotations total.  (The
blockwise-computation idea follows the public ring attention literature;
implementation is independent, in pure jax/shard_map.)

Usage — on GLOBAL arrays (the function applies its own shard_map):

    attn = ring_self_attention(q, k, v, mesh, axis="sequence")

with q/k/v of global shape ``[B, L, H, D]`` sharded on L.  Code already
running *inside* a shard_map body should call :func:`ring_attention_local`
on its local chunks instead.  Causal masking uses global position ids, so
it is correct regardless of which chunk a block lives on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

SEQUENCE_AXIS = "sequence"


def _ring_scan(k0, v0, acc0, axis_name: str, n, accumulate):
    """Shared ring choreography: accumulate the held chunk, rotate k/v to
    the next device, N-1 times; accumulate the final chunk without a dead
    rotation.  ``accumulate(acc, k_cur, v_cur, owner_shift) -> acc`` is
    the per-rotation kernel (``owner = (idx - owner_shift) % n`` is where
    the held chunk originated)."""
    def step(carry, owner_shift):
        k_cur, v_cur, acc = carry
        acc = accumulate(acc, k_cur, v_cur, owner_shift)
        rotation = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, rotation)
        v_next = jax.lax.ppermute(v_cur, axis_name, rotation)
        return (k_next, v_next, acc), None

    (k_last, v_last, acc), _ = jax.lax.scan(
        step, (k0, v0, acc0), jnp.arange(n - 1))
    return accumulate(acc, k_last, v_last, n - 1)


def ring_flash_attention_local(q, k0, v0, axis_name: str, causal: bool,
                               q_offset, chunk: int, block_q: int = 128,
                               block_k: int = 128):
    """Blockwise-ring attention: each rotation's chunk pair runs through
    the Pallas flash kernels (:func:`msrflute_tpu.ops.pallas_attention.
    flash_attention_lse` with dynamic position offsets), and the
    per-rotation normalized outputs are merged EXACTLY via their
    logsumexps — never materializing a score matrix anywhere, forward or
    backward.  This is the composition of the two long-context levers:
    the ring bounds per-device residency at O(L/N) chunks, the kernel
    bounds per-rotation working set at O(block) tiles.
    """
    from .pallas_attention import _NEG, flash_attention_lse

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)

    def merge(acc, k_cur, v_cur, owner_shift):
        out_acc, lse_acc = acc
        owner = (idx - owner_shift) % n
        # force_flash: the gate's AOT probe would re-run inside every
        # shard_map trace, and use_flash=True is an explicit opt-in here
        # (the crossover resolve in models/ringlm.py owns the choice)
        out_r, lse_r = flash_attention_lse(
            q, k_cur, v_cur, causal, q_offset=q_offset,
            k_offset=owner * chunk, block_q=int(block_q or 128),
            block_k=int(block_k or 128), force_flash=True)
        # exact merge of independently-normalized rotation outputs:
        # out = sum_r exp(lse_r - lse_tot) * out_r
        lse_new = jnp.logaddexp(lse_acc, lse_r)
        w_acc = jnp.exp(lse_acc - lse_new).transpose(0, 2, 1)[..., None]
        w_r = jnp.exp(lse_r - lse_new).transpose(0, 2, 1)[..., None]
        return out_acc * w_acc + out_r.astype(jnp.float32) * w_r, lse_new

    B, Lq, H, D = q.shape
    acc0 = (jnp.zeros((B, Lq, H, D), jnp.float32),
            jnp.full((B, H, Lq), _NEG, jnp.float32))
    out, _ = _ring_scan(k0, v0, acc0, axis_name, n, merge)
    return out.astype(q.dtype)


def ring_attention_local(q, k0, v0, axis_name: str, causal: bool,
                         q_offset, chunk: int):
    """Online-softmax ring accumulation over local chunks.

    For use INSIDE a shard_map body whose mesh has ``axis_name``: ``q`` /
    ``k0`` / ``v0`` are this device's ``[B, L/N, H, D]`` chunks and
    ``q_offset`` the global position of ``q``'s first row.  Performs N-1
    ``ppermute`` rotations (the final block is accumulated without a
    further rotation).  For the fully-tiled variant (no per-rotation
    score matrix at all) see :func:`ring_flash_attention_local`.
    """
    B, Lq, H, D = q.shape
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
    q_pos = q_offset + jnp.arange(Lq)

    def accumulate(state, k_cur, v_cur, owner_shift):
        m, l, acc = state
        # the held k/v block originated at owner = idx - shift on the ring
        owner = (idx - owner_shift) % n
        k_pos = owner * chunk + jnp.arange(k_cur.shape[1])
        scores = jnp.einsum("blhd,bmhd->bhlm", q, k_cur) * scale
        if causal:
            mask = (q_pos[:, None] >= k_pos[None, :])  # [Lq, Lk]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_blk = jnp.max(scores, axis=-1)  # [B,H,Lq]
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows (all -inf)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr.transpose(0, 2, 1)[..., None] + \
            jnp.einsum("bhlm,bmhd->blhd", p, v_cur)
        return (m_new, l_new, acc_new)

    # remat the blockwise accumulate: under reverse-mode AD a scan stores
    # every step's residuals — here the [B,H,Lq,chunk] probability matrix
    # per rotation, i.e. O(L^2/N) per device, exactly the memory wall this
    # op exists to avoid.  Recomputing scores from the (q, k, v) chunks in
    # the backward keeps live memory at O(L/N) state per rotation for ~1/3
    # extra FLOPs (the blockwise-recompute backward of the ring/flash
    # attention literature).
    # prevent_cse=False: inside lax.scan the CSE-prevention barriers are
    # unnecessary (per the jax.checkpoint docs) and would inhibit fusion
    accumulate_ckpt = jax.checkpoint(accumulate, prevent_cse=False)

    state0 = (jnp.full((B, H, Lq), -jnp.inf, q.dtype),
              jnp.zeros((B, H, Lq), q.dtype),
              jnp.zeros_like(q))
    m, l, acc = _ring_scan(k0, v0, state0, axis_name, n, accumulate_ckpt)
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return acc / denom


def ring_self_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        mesh: Mesh, axis: str = SEQUENCE_AXIS,
                        causal: bool = False,
                        batch_axis: "str | None" = None,
                        use_flash: bool = False, flash_block_q: int = 128,
                        flash_block_k: int = 128) -> jnp.ndarray:
    """Exact attention with GLOBAL q/k/v ``[B, L, H, D]`` sharded on L over
    ``axis``.  Returns the output with the same sharding.  Must be called
    outside shard_map (it applies its own); inside a shard_map body use
    :func:`ring_attention_local`.

    ``batch_axis`` additionally shards B over another mesh axis (combined
    data + sequence parallelism): the ring rotations stay within each
    batch shard's ring, no cross-batch communication.

    ``use_flash`` runs each rotation through
    :func:`ring_flash_attention_local` — the Pallas flash kernels on TPU
    (no per-rotation score matrix), the dense-lse reference elsewhere;
    same numerics either way (kernel/dense parity incl. the lse cotangent
    is pinned by ``test_pallas_attention.py``).
    """
    n = mesh.shape[axis]
    L = q.shape[1]
    if k.shape[1] != L or v.shape[1] != L:
        raise ValueError(
            f"q/k/v sequence lengths differ: {L}, {k.shape[1]}, {v.shape[1]}")
    if L % n:
        raise ValueError(f"sequence length {L} not divisible by {axis}={n}")
    if batch_axis is not None:
        if batch_axis not in mesh.shape:
            raise ValueError(f"batch_axis {batch_axis!r} not in mesh axes "
                             f"{tuple(mesh.shape)}")
        if q.shape[0] % mesh.shape[batch_axis]:
            raise ValueError(f"batch {q.shape[0]} not divisible by "
                             f"{batch_axis}={mesh.shape[batch_axis]}")
    chunk = L // n
    spec = P(batch_axis, axis, None, None)

    def body(q_l, k_l, v_l):
        idx = jax.lax.axis_index(axis)
        q_offset = idx * chunk
        if use_flash:
            return ring_flash_attention_local(q_l, k_l, v_l, axis, causal,
                                              q_offset, chunk,
                                              block_q=flash_block_q,
                                              block_k=flash_block_k)
        return ring_attention_local(q_l, k_l, v_l, axis, causal, q_offset,
                                    chunk)

    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)
