from .quantization import quantize_pytree, quantize_array  # noqa: F401
