"""Pipeline parallelism — GPipe-style SPMD microbatch schedule.

Net-new vs the reference (FLUTE replicates whole models per worker and has
no model partitioning at all); together with the clients axis (dp), GSPMD
tensor sharding (tp) and ring attention (sp) this completes the classic
parallelism toolbox on the same ``jax.sharding.Mesh`` machinery.

Design: stages live on a ``stage`` mesh axis; every device holds ONE
stage's params (stacked pytree sharded on its leading axis).  One
``lax.scan`` runs M + N - 1 ticks; each tick every stage applies itself
once and activations rotate one hop around the ring with ``ppermute`` —
fully SPMD (identical program on every device), pipeline bubbles handled by
masking, outputs collected on the last stage and ``psum``-broadcast.  XLA
differentiates through the whole schedule, so the same function trains.

This is the microbatch *schedule* only — it composes with dp (batch axis)
and tp (sharded stage params) through the enclosing mesh.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

STAGE_AXIS = "stage"


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stage_params: Any, microbatches: jnp.ndarray,
                   mesh: Mesh, axis: str = STAGE_AXIS) -> jnp.ndarray:
    """Run ``microbatches`` through N pipelined stages.

    ``stage_fn(params_i, x) -> y`` must preserve ``x``'s shape (homogeneous
    stages — the usual transformer-block case).  ``stage_params`` is a
    pytree whose leaves have leading axis N (one slice per stage), sharded
    over ``axis``; ``microbatches`` is ``[M, mb, ...]`` (replicated).
    Returns ``[M, mb, ...]`` outputs, replicated.

    Wall-clock per call is (M + N - 1) stage steps vs M * N sequential —
    the standard GPipe bubble; use M >> N to amortize.
    """
    N = mesh.shape[axis]
    M = int(microbatches.shape[0])
    if jax.tree.leaves(stage_params) and \
            jax.tree.leaves(stage_params)[0].shape[0] != N:
        raise ValueError(
            f"stage_params leading axis "
            f"{jax.tree.leaves(stage_params)[0].shape[0]} != {axis}={N}")

    p_spec = jax.tree.map(lambda _: P(axis), stage_params)
    r_spec = P()

    def body(params_stage, mbs):
        params_local = jax.tree.map(lambda a: a[0], params_stage)
        idx = lax.axis_index(axis)
        is_first = (idx == 0)
        is_last = (idx == N - 1)
        perm = [(i, (i + 1) % N) for i in range(N)]

        def tick(carry, t):
            act, out_buf = carry
            # previous stage's activation arrives over the ring
            act_prev = lax.ppermute(act, axis, perm)
            feed = lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            inp = jnp.where(is_first, feed, act_prev)
            y = stage_fn(params_local, inp)
            # the last stage finishes microbatch t-(N-1) at this tick
            w = t - (N - 1)
            updated = lax.dynamic_update_index_in_dim(
                out_buf, y, jnp.clip(w, 0, M - 1), axis=0)
            out_buf = jnp.where((w >= 0) & is_last, updated, out_buf)
            return (y, out_buf), None

        init = (jnp.zeros_like(mbs[0]), jnp.zeros_like(mbs))
        (_, out_buf), _ = lax.scan(tick, init, jnp.arange(M + N - 1))
        # only the last stage holds real outputs; broadcast to everyone
        return lax.psum(out_buf, axis)

    fn = shard_map(body, mesh=mesh, in_specs=(p_spec, r_spec),
                   out_specs=r_spec, check_vma=False)
    return fn(stage_params, microbatches)
