"""Differential privacy — on-device mechanisms + host-side accounting.

Parity target: reference ``extensions/privacy/__init__.py``:

- LDP noise std from (eps, sensitivity, delta)  (``:15-16``)
- ``apply_local_dp`` (``:154-201``): flatten the update; eps < 0 => clip-only
  to ``max_grad``; else normalize the flat update to norm ``max_grad``,
  append the (scaled, clamped) aggregation weight when weight noising is on,
  add Gaussian noise calibrated to the joint sensitivity
  ``sqrt(max_grad^2 + max_weight^2)``, then unclamp/unscale the weight.
- ``apply_global_dp`` (``:128-151``): server-side Gaussian noise with scale
  ``global_sigma * max_grad / num_clients`` on the aggregated update.
- ``update_privacy_accountant`` (``:204-260``): host-side RDP accounting —
  our own implementation of the sampled-Gaussian-mechanism RDP bound in
  :mod:`msrflute_tpu.privacy.accountant` (the reference vendors
  TF-Privacy's; we reimplement from the published formulas).

TPU-native: the mechanisms are pure jnp over ``ravel_pytree``-flattened
updates (the functional replacement of ``unroll_network``/``update_network``,
``:105-125``) and run *inside* the jitted round program under vmap — one
fused pass instead of host-side tensor surgery.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

# NOTE: the near-exact PRV accountant lives in .prv and is NOT re-exported
# here — it is offline-only (tools/compute_dp_epsilon.py) and importing it
# would put scipy.stats on every training-process startup path.
from .accountant import DEFAULT_ORDERS, compute_rdp, get_privacy_spent  # noqa: F401


def compute_ldp_noise_std(eps: float, max_sensitivity: float, delta: float) -> float:
    """Gaussian-mechanism sigma (reference ``:15-16``)."""
    return float(np.sqrt(2.0 * np.log(1.25 / delta)) * max_sensitivity / eps)


def add_gaussian_noise(flat: jnp.ndarray, eps: float, max_sensitivity: float,
                       delta: float, rng: jax.Array) -> Tuple[jnp.ndarray, float]:
    sigma = compute_ldp_noise_std(eps, max_sensitivity, delta)
    return flat + sigma * jax.random.normal(rng, flat.shape, flat.dtype), sigma


# ---------------------------------------------------------------------
# "unused extras" kept for parity (reference :51-102): alternative local
# mechanisms — the d-sphere PrivateUnit2 sampler, discrete scalar DP and
# Laplace noise.  Host-side numpy like the reference.

def privacy_parameters(eps0: float, eps: float, d: int):
    """Split epsilons into (sampling prob, gamma) for PrivateUnit2
    (reference ``:37-48``)."""
    exp_eps0 = np.exp(eps0)
    exp_eps = np.exp(eps)
    p0 = 1.0 if np.isinf(exp_eps0) else exp_eps0 / (1 + exp_eps0)
    base = np.sqrt(np.pi / (2 * (d - 1)))
    gamma = base if np.isinf(exp_eps) else \
        ((exp_eps - 1) / (exp_eps + 1)) * base
    return p0, gamma


def private_unit2(grad: np.ndarray, gamma: float, prob: float,
                  rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """d-sphere mechanism for a unit vector (reference ``:51-66``):
    rejection-sample a unit direction correlated with ``grad`` w.p.
    ``prob``, anti-correlated otherwise, unbiased via the 1/m factor."""
    from scipy.special import betainc, betaln
    rng = rng if rng is not None else np.random.default_rng()
    grad = np.asarray(grad, np.float64)
    assert abs(np.linalg.norm(grad) - 1.0) < 1e-4
    assert prob >= 0.5 and 0.0 <= gamma <= 1.0
    p = rng.random()
    while True:
        v = rng.normal(size=grad.shape)
        v /= np.linalg.norm(v)
        dot = float(v @ grad)
        if (dot >= gamma and p < prob) or (dot < gamma and p >= prob):
            break
    d = grad.shape[0]
    alpha = (d - 1) / 2
    tau = (1 + gamma) / 2
    ratio = 1.0 / betainc(alpha, alpha, tau)
    log_m1 = alpha * np.log(1 - gamma ** 2) - (d - 2) * np.log(2) - \
        np.log(d - 1)
    log_m2 = (np.log(prob / (ratio - 1) - (1 - prob)) + np.log(ratio) -
              betaln(alpha, alpha))
    m = np.exp(log_m1 + log_m2)
    return v / m


def add_private_unit2_noise(eps: float, grad: np.ndarray,
                            rng: Optional[np.random.Generator] = None):
    """Reference ``:75-79``: split eps 1%/99% between sampling and gamma."""
    p0, gamma = privacy_parameters(0.01 * eps, 0.99 * eps, grad.shape[0])
    return private_unit2(grad, gamma, p0, rng)


def scalar_dp(r: float, eps: float, k: int, r_max: float,
              rng: Optional[np.random.Generator] = None) -> float:
    """Discrete scalar DP mechanism (reference ``scalar_DP``, ``:82-98``):
    stochastic rounding to k levels + randomized response, debiased."""
    rng = rng if rng is not None else np.random.default_rng()
    r = min(r, r_max)
    val = k * r / r_max
    f_val, c_val = math.floor(val), math.ceil(val)
    j = f_val if rng.random() < (c_val - val) else c_val
    exp_eps = np.exp(eps)
    if rng.random() >= exp_eps / (exp_eps + k):
        while True:
            j_new = int(rng.integers(0, k + 1))
            if j_new != j:
                j = j_new
                break
    a = ((exp_eps + k) / (exp_eps - 1)) * (r_max / k)
    b = (k * (k + 1)) / (2 * (exp_eps + k))
    return float(a * (j - b))


def laplace_noise(max_sens: float, eps: float, size: int,
                  rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Reference ``laplace_noise`` (``:101-102``)."""
    rng = rng if rng is not None else np.random.default_rng()
    return rng.laplace(0.0, max_sens / eps, size)


def apply_local_dp(pseudo_grad: Any, weight: jnp.ndarray, dp_config,
                   add_weight_noise: bool, rng: jax.Array,
                   clip_override=None) -> Tuple[Any, jnp.ndarray]:
    """Client-side DP on the flattened pseudo-gradient (traced; vmap-safe).

    Reproduces reference ``apply_local_dp`` (``:154-201``) including the
    weight scale/clamp/noise/unscale dance.  ``clip_override`` (a traced
    scalar) substitutes the static ``max_grad`` — the adaptive-clipping
    hook (strategies/fedavg.py).  NOTE: with eps >= 0 the noise sigma uses
    the STATIC max_grad sensitivity bound, which stays valid as long as
    the adaptive clip <= max_grad (enforced by the caller).
    """
    flat, unravel = ravel_pytree(pseudo_grad)
    eps = float(dp_config.get("eps", -1.0))
    static_max_grad = float(dp_config.get("max_grad", 1.0))
    max_grad = static_max_grad
    if clip_override is not None:
        max_grad = jnp.minimum(jnp.asarray(clip_override, jnp.float32),
                               static_max_grad)

    if eps < 0:
        # clip-only mode
        norm = jnp.linalg.norm(flat)
        scale = jnp.minimum(1.0, max_grad / jnp.maximum(norm, 1e-12))
        return unravel(flat * scale), weight

    delta = float(dp_config.get("delta", 1e-7))
    max_weight = float(dp_config.get("max_weight", 100.0))
    min_weight = float(dp_config.get("min_weight", 0.0))
    weight_scaler = float(dp_config.get("weight_scaler", 1.0))

    orig_weight = weight
    scaled_weight = jnp.minimum(weight * weight_scaler, max_weight)
    # normalize the update to exactly max_grad norm (reference :182)
    normed = max_grad * flat / jnp.maximum(jnp.linalg.norm(flat), 1e-12)
    # sensitivity stays the STATIC bound: sigma must not depend on the
    # (traced) adaptive clip, and static >= adaptive keeps it an upper bound
    max_sensitivity = math.sqrt(static_max_grad ** 2 +
                                (max_weight ** 2 if add_weight_noise else 0.0))
    joint = jnp.concatenate([normed, scaled_weight[None]])
    noisy, _sigma = add_gaussian_noise(joint, eps, max_sensitivity, delta, rng)
    noisy_weight = jnp.clip(noisy[-1], min_weight, max_weight) / weight_scaler
    new_weight = noisy_weight if add_weight_noise else orig_weight
    return unravel(noisy[:-1]), new_weight


def apply_global_dp(agg_grad: Any, dp_config, rng: jax.Array,
                    num_clients: jnp.ndarray) -> Any:
    """Server-side Gaussian noise on the aggregate (reference ``:128-151``):
    per-element std ``global_sigma * max_grad / num_clients``.

    On TPU this runs the fused Pallas kernel (noise generated on-core,
    never materialized in HBM); elsewhere the jnp path.
    """
    flat, unravel = ravel_pytree(agg_grad)
    sigma = float(dp_config.get("global_sigma", 0.0))
    max_grad = float(dp_config.get("max_grad", 1.0))
    noise_scale = sigma * max_grad / jnp.maximum(num_clients, 1.0)
    if jax.default_backend() == "tpu":
        from ..ops.pallas_kernels import fused_gaussian_noise
        seed = jax.random.randint(rng, (), 0, 2**31 - 1)
        noisy = fused_gaussian_noise(flat, jnp.asarray(1.0, flat.dtype),
                                     noise_scale, seed)
    else:
        noisy = flat + noise_scale * jax.random.normal(rng, flat.shape,
                                                       flat.dtype)
    return unravel(noisy)


def update_privacy_accountant(config, num_clients: int, curr_iter: int,
                              num_clients_curr_iter: int) -> Optional[float]:
    """Host-side RDP accounting (reference ``:204-260``): log K/B/n/T/sigma/mu
    and return the RDP epsilon for the run so far."""
    dp_config = config.dp_config
    if dp_config is None or not (dp_config.get("enable_global_dp", False) or
                                 dp_config.get("enable_local_dp", False)):
        return None

    from ..utils.logging import log_metric, print_rank

    K = 1
    B = num_clients_curr_iter
    n = max(num_clients, 2)
    T_iters = curr_iter + 1
    delta = float(dp_config.get("delta") or min(1e-7, 1.0 / (n * math.log(n))))
    if dp_config.get("global_sigma") in (None, 0.0):
        max_sensitivity = math.sqrt(float(dp_config.get("max_grad", 1.0)) ** 2 +
                                    float(dp_config.get("max_weight", 100.0)) ** 2)
        noise_scale = compute_ldp_noise_std(float(dp_config.get("eps", 1.0)),
                                            max_sensitivity, delta)
        global_sigma = noise_scale * math.sqrt(B) / max_sensitivity
    else:
        global_sigma = float(dp_config.get("global_sigma"))
        noise_scale = global_sigma * float(dp_config.get("max_grad", 1.0)) / B

    try:
        mu = K * B / n * math.sqrt(T_iters * math.exp((1.0 / global_sigma) ** 2 - 1))
    except OverflowError:
        mu = -1.0

    q = B / n
    rdp = compute_rdp(q, global_sigma, T_iters, DEFAULT_ORDERS)
    rdp_epsilon, opt_order = get_privacy_spent(DEFAULT_ORDERS, rdp, delta)

    props = {
        "dp_global_K": K, "dp_global_B": B, "dp_global_n": n,
        "dp_global_T": T_iters, "dp_sigma": global_sigma, "dp_global_mu": mu,
        "dp_epsilon_rdp": rdp_epsilon, "dp_opt_order": opt_order,
        "dp_delta": delta, "dp_noise_scale": noise_scale,
    }
    print_rank(f"DP accounting: {props}", loglevel=logging.DEBUG)
    for key, value in props.items():
        log_metric(key, value, step=curr_iter)
    return rdp_epsilon
