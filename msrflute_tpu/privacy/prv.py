"""PRV accountant — numerical composition of differential privacy.

Role parity: the reference vendors microsoft/prv_accountant as the
``utils/dp-accountant`` git submodule for *offline* accounting
(reference ``.gitmodules:1-3``, ``README.md:162-171``: "A better
accounting method is in the dp-accountant submodule", exposing
``compute-dp-epsilon -p SAMPLING_PROBABILITY -s NOISE_MULTIPLIER
-i ITERATIONS -d DELTA``).  This module is an independent clean-room
implementation of the same technique from the published algorithm
(Gopi, Lee & Wutschitz 2021, "Numerical Composition of Differential
Privacy", NeurIPS): discretize the privacy-loss random variable (PRV) of
one mechanism invocation, self-compose ``T`` times by raising its FFT to
the ``T``-th power, and read ``delta(eps)`` — and its inverse — off the
composed distribution.  Unlike the Renyi bound in
:mod:`msrflute_tpu.privacy.accountant`, the result is a near-exact
two-sided *bracket* ``(eps_lower, eps_estimate, eps_upper)``.

Mechanism: Poisson-subsampled Gaussian (the mechanism FLUTE's DP actually
runs — per-round client sampling + Gaussian noise).  Its dominating pair
is ``P = (1-q) N(0, s^2) + q N(1, s^2)`` vs ``Q = N(0, s^2)`` (noise
multiplier ``s``, sampling rate ``q``); both adjacency directions
(remove: ``log dP/dQ`` under ``P``; add: ``log dQ/dP`` under ``Q``) are
composed and the worse epsilon reported.

Everything is host-side numpy/scipy — accounting is offline by design
(reference ``README.md:160``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Tuple

import numpy as np
from scipy.stats import norm


# ----------------------------------------------------------------------
# single-step PRV CDFs (analytic)
# ----------------------------------------------------------------------
def _remove_direction_cdf(q: float, sigma: float) -> Callable:
    """CDF of ``L = log dP/dQ (x)`` with ``x ~ P``.

    ``dP/dQ(x) = (1-q) + q exp((2x-1)/(2 sigma^2))`` is increasing in
    ``x``, so ``P(L <= t) = P(x <= x(t))`` with
    ``x(t) = sigma^2 log((e^t - (1-q))/q) + 1/2`` for ``t > log(1-q)``.
    """
    def cdf(t: np.ndarray) -> np.ndarray:
        t = np.asarray(t, dtype=np.float64)
        out = np.zeros_like(t)
        # threshold: below log(1-q) the loss is unattainable (CDF = 0)
        lo = math.log1p(-q) if q < 1.0 else -np.inf
        ok = t > lo
        with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
            u = np.where(ok, np.expm1(t) + q, q)  # e^t - (1-q)
            x = sigma * sigma * (np.log(u) - math.log(q)) + 0.5
        mass = (1.0 - q) * norm.cdf(x / sigma) + q * norm.cdf((x - 1) / sigma)
        return np.where(ok, mass, 0.0)
    return cdf


def _add_direction_cdf(q: float, sigma: float) -> Callable:
    """CDF of ``L' = log dQ/dP (x)`` with ``x ~ Q = N(0, sigma^2)``.

    ``L' = -log((1-q) + q exp((2x-1)/(2 sigma^2)))`` is decreasing in
    ``x``, so ``P(L' <= t) = P(x >= x(-t))`` with the same ``x(.)``.
    """
    def cdf(t: np.ndarray) -> np.ndarray:
        t = np.asarray(t, dtype=np.float64)
        # L' ranges in (-inf, -log(1-q)); at/above that bound CDF = 1
        hi = -math.log1p(-q) if q < 1.0 else np.inf
        ok = t < hi
        with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
            u = np.where(ok, np.expm1(-t) + q, q)
            x = sigma * sigma * (np.log(u) - math.log(q)) + 0.5
        mass = norm.sf(x / sigma)
        return np.where(ok, mass, 1.0)
    return cdf


# ----------------------------------------------------------------------
# discretization + FFT self-composition
# ----------------------------------------------------------------------
@dataclass
class _ComposedPRV:
    """Discretized distribution of the T-fold composed PRV.

    ``delta(eps)`` splits as ``sum_{y>eps} p_y - e^eps sum_{y>eps} p_y e^-y``;
    both suffix sums are precomputed once so each evaluation is a binary
    search, which makes the bisection in :meth:`epsilon` cheap.
    """
    grid: np.ndarray   # bin centers (absolute, after un-centering)
    pmf: np.ndarray    # probability mass per bin
    tail_low: float    # mass truncated below the grid (maps to delta=0 side)
    tail_high: float   # mass truncated above the grid (counts fully in delta)

    def __post_init__(self):
        # suffix sums from the high-y end; e^-y clipped at y=-50 (those
        # entries are only reachable for eps < -50, never queried)
        w = np.exp(-np.clip(self.grid, -50.0, None)) * self.pmf
        self._suffix_p = np.cumsum(self.pmf[::-1])[::-1]
        self._suffix_pe = np.cumsum(w[::-1])[::-1]

    def delta(self, eps: float, pessimistic: bool = True) -> float:
        """``delta(eps) = E[(1 - e^(eps - Y))_+]`` over the composed PRV.

        ``pessimistic`` adds the truncated upper-tail mass in full (each
        such sample contributes at most 1); the optimistic variant drops
        it.  The lower tail contributes nothing either way.
        """
        i = int(np.searchsorted(self.grid, eps, side="right"))
        if i >= self.grid.size:
            d = 0.0
        else:
            d = float(self._suffix_p[i] - math.exp(eps) * self._suffix_pe[i])
        if pessimistic:
            d += self.tail_high
        return min(max(d, 0.0), 1.0)

    def epsilon(self, target_delta: float, pessimistic: bool) -> float:
        """Invert ``delta(eps)`` by bisection (delta is non-increasing)."""
        lo, hi = 0.0, 1.0
        while self.delta(hi, pessimistic) > target_delta:
            hi *= 2.0
            if hi > 1e6:
                return math.inf
        if self.delta(lo, pessimistic) <= target_delta:
            return 0.0
        for _ in range(80):
            mid = 0.5 * (lo + hi)
            if self.delta(mid, pessimistic) > target_delta:
                lo = mid
            else:
                hi = mid
        return hi


def _discretize(cdf: Callable, lo: float, hi: float, n_bins: int
                ) -> Tuple[np.ndarray, np.ndarray, float, float]:
    """Exact bin masses from CDF differences on ``n_bins`` uniform bins."""
    edges = np.linspace(lo, hi, n_bins + 1)
    c = np.clip(cdf(edges), 0.0, 1.0)
    c = np.maximum.accumulate(c)  # guard tiny numeric non-monotonicity
    pmf = np.diff(c)
    centers = 0.5 * (edges[:-1] + edges[1:])
    return centers, pmf, float(c[0]), float(1.0 - c[-1])


def _compose(cdf: Callable, steps: int, eps_max: float, eps_error: float
             ) -> _ComposedPRV:
    """T-fold self-composition of the discretized PRV via FFT powering.

    The single-step PRV is discretized on a wide bracket, re-centered on
    its (grid-aligned) mean so the composed deviation stays small, and
    convolved by raising its DFT to the ``steps``-th power on a grid large
    enough that the concentrated composed mass cannot wrap around.
    """
    # --- moment probe on a coarse wide grid to size the final domain ---
    probe_g, probe_p, _, _ = _discretize(cdf, -80.0, 80.0, 1 << 14)
    tot = probe_p.sum()
    if tot <= 0:
        raise ValueError("degenerate PRV (no mass in probe window)")
    mu = float((probe_g * probe_p).sum() / tot)
    var = float((((probe_g - mu) ** 2) * probe_p).sum() / tot)
    std = math.sqrt(max(var, 1e-30))

    # mesh: fine enough for the eps budget after sqrt(T) random-walk
    # accumulation AND fine enough to resolve the single-step bulk — for
    # small sampling rates the PRV's std is tiny and a mesh sized only to
    # eps_error quantizes the whole distribution into a handful of bins,
    # biasing the composed mean by O(T * h)
    h = max(min(eps_error / math.sqrt(steps), std / 16.0), 1e-6)

    # composed deviation from T*mu concentrates in O(sqrt(T))*std; cover
    # 12 sigma, the single-step support, the queried eps range, and the
    # worst-case accumulated grid-alignment offset (h/2 per step)
    half = 12.0 * std * math.sqrt(steps) + 4.0 * std + eps_max + 4.0 \
        + 0.5 * steps * h
    n = int(2 ** math.ceil(math.log2(max(2.0 * half / h, 1024.0))))
    # n bins whose CENTERS are shift + (k - n//2) * h exactly: offsets from
    # the grid-aligned mean are integer multiples of h, so T-fold index
    # sums are exact
    shift = round(mu / h) * h  # grid-aligned single-step mean
    lo = shift - (n // 2) * h - 0.5 * h
    hi = shift + (n - n // 2) * h - 0.5 * h
    _, pmf, t_lo, t_hi = _discretize(cdf, lo, hi, n)

    # circular convolution is in OFFSET space: roll so offset 0 (the bin at
    # the single-step mean) sits at index 0, power the DFT, then roll back.
    # Without this, the T-fold center lands at (T*(n//2)) mod n, not n//2.
    rolled = np.roll(pmf, -(n // 2))
    f = np.fft.rfft(rolled)
    composed = np.fft.irfft(f ** steps, n=n)
    composed = np.maximum(np.roll(composed, n // 2), 0.0)
    # index j holds composed offset (j - n//2); each step contributed shift
    grid = (np.arange(n) - n // 2) * h + steps * shift
    # truncated single-step tails compound at most linearly
    return _ComposedPRV(grid=grid, pmf=composed,
                        tail_low=min(steps * t_lo, 1.0),
                        tail_high=min(steps * t_hi, 1.0))


# ----------------------------------------------------------------------
# public API (mirrors the submodule's PRVAccountant surface)
# ----------------------------------------------------------------------
class PRVAccountant:
    """Near-exact ``(eps_lower, eps_estimate, eps_upper)`` for T-fold
    Poisson-subsampled Gaussian composition.

    ``eps_error`` controls the discretization mesh: the pessimistic /
    optimistic readings differ by O(mesh * sqrt(T)) plus truncated tail
    mass, and the bracket returned is (optimistic, midpoint, pessimistic).
    """

    def __init__(self, noise_multiplier: float, sampling_probability: float,
                 max_steps: int, eps_error: float = 0.1):
        if noise_multiplier <= 0:
            raise ValueError("noise_multiplier must be > 0")
        if not 0.0 < sampling_probability <= 1.0:
            raise ValueError("sampling_probability must be in (0, 1]")
        if max_steps < 1:
            raise ValueError("max_steps must be >= 1")
        self.sigma = float(noise_multiplier)
        self.q = float(sampling_probability)
        self.max_steps = int(max_steps)
        self.eps_error = float(eps_error)
        self._cache = {}

    def _composed(self, direction: str, steps: int) -> _ComposedPRV:
        key = (direction, steps)
        if key not in self._cache:
            make = (_remove_direction_cdf if direction == "remove"
                    else _add_direction_cdf)
            self._cache[key] = _compose(make(self.q, self.sigma), steps,
                                        eps_max=64.0,
                                        eps_error=self.eps_error)
        return self._cache[key]

    def compute_delta(self, eps: float, num_steps: int) -> float:
        """Pessimistic ``delta(eps)`` after ``num_steps`` compositions
        (worse of the two adjacency directions)."""
        self._check(num_steps)
        return max(self._composed(d, num_steps).delta(eps, True)
                   for d in ("remove", "add"))

    def compute_epsilon(self, delta: float, num_steps: int
                        ) -> Tuple[float, float, float]:
        """``(eps_lower, eps_estimate, eps_upper)`` at ``delta`` after
        ``num_steps`` compositions — the submodule's CLI contract
        (reference ``README.md:168-171``)."""
        self._check(num_steps)
        if not 0.0 < delta < 1.0:
            raise ValueError("delta must be in (0, 1)")
        lowers, uppers = [], []
        for d in ("remove", "add"):
            prv = self._composed(d, num_steps)
            uppers.append(prv.epsilon(delta, pessimistic=True))
            lowers.append(prv.epsilon(delta, pessimistic=False))
        # midpoint-quantization of the single-step PRV contributes at most
        # mesh/2 per step; accumulated as a random walk its 4-sigma spread
        # is 2 * mesh * sqrt(T) <= 2 * eps_error — widen the bracket by it
        margin = 2.0 * self.eps_error
        eps_up = max(uppers) + margin
        eps_lo = max(0.0, max(lowers) - margin)
        return eps_lo, 0.5 * (eps_lo + eps_up), eps_up

    def _check(self, num_steps: int) -> None:
        if num_steps > self.max_steps:
            raise ValueError(
                f"num_steps={num_steps} exceeds max_steps={self.max_steps} "
                "the accountant was sized for")


def compute_dp_epsilon(sampling_probability: float, noise_multiplier: float,
                       iterations: int, delta: float,
                       eps_error: float = 0.1) -> dict:
    """One-call helper backing ``tools/compute_dp_epsilon.py`` (the
    submodule's ``compute-dp-epsilon`` CLI, reference ``README.md:168``)."""
    acc = PRVAccountant(noise_multiplier, sampling_probability,
                        max_steps=iterations, eps_error=eps_error)
    lo, est, up = acc.compute_epsilon(delta, iterations)
    return {"eps_lower": lo, "eps_estimate": est, "eps_upper": up,
            "delta": delta, "iterations": iterations,
            "sampling_probability": sampling_probability,
            "noise_multiplier": noise_multiplier}
