"""Privacy attack metrics — run on-device, per client, inside the round.

Parity target: reference ``extensions/privacy/metrics.py``:

- ``extract_indices_from_embeddings`` (``metrics.py:10-22``): the embedding
  rows of tokens present in a batch get larger gradient norms; sort rows by
  pseudo-gradient norm, take the top-``num_tokens``, and measure the overlap
  with the batch's true (non-pad) tokens.
- ``practical_epsilon_leakage`` (``metrics.py:33-76``): per-token
  log-softmax scores of the round's data under the *pre-training* model vs
  the model after an attacker optimizer step (Adamax, high LR) applied to
  the client's pseudo-gradient; leakage = max over tokens of
  ``clamp((pre+tol)/(post+tol), 0, max_ratio)`` — optionally weighted by
  ``max(exp(pre), exp(post))`` — and the returned value is
  ``max(log(max_leakage), 0)``.

The reference runs these in client Python between training and payload
shipping (``core/client.py:466-508``); here they are traced into the round
program (vmapped per client), and client dropping is a weight mask.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..optim import make_optimizer


def extract_indices_from_embeddings(pseudo_grad_embedding: jnp.ndarray,
                                    token_batch: jnp.ndarray,
                                    num_tokens: Optional[jnp.ndarray] = None,
                                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Embedding-gradient token-extraction attack.

    Args:
        pseudo_grad_embedding: ``[vocab, embed]`` pseudo-gradient of the
            embedding table.
        token_batch: integer token ids of the client's round data (any
            shape); ids <= 0 are padding.
        num_tokens: the client's *actual* token count (the reference's
            ``len(batch)``, ``metrics.py:15``) — may be traced.  The static
            grid is padded per round, so callers must pass the real count
            (e.g. ``sum(sample_mask) * seq_len``); defaults to the grid
            size for parity with naive callers.

    Returns:
        (overlap_ratio, per_vocab_extracted_mask) — overlap of the top-k
        extracted rows with the true tokens (k = token count), and a
        ``[vocab]`` 0/1 mask of extracted rows for word-rank stats.
    """
    flat = token_batch.reshape(-1)
    valid = flat > 0
    if num_tokens is None:
        num_tokens = jnp.asarray(flat.shape[0], jnp.float32)
    norms = jnp.linalg.norm(pseudo_grad_embedding, axis=-1)
    vocab = norms.shape[0]
    # rank of every vocab row by descending grad norm; "extracted" = rank <
    # k with k dynamic (top_k needs a static k, ranks do not)
    order = jnp.argsort(-norms)
    ranks = jnp.zeros((vocab,), jnp.float32).at[order].set(
        jnp.arange(vocab, dtype=jnp.float32))
    extracted_mask = (ranks < jnp.minimum(num_tokens, vocab)).astype(
        jnp.float32)
    hit = extracted_mask[jnp.clip(flat, 0, vocab - 1)] * valid
    overlap = jnp.sum(hit) / jnp.maximum(jnp.sum(valid), 1.0)
    return overlap, extracted_mask


def practical_epsilon_leakage(original_params: Any, pseudo_grad: Any,
                              token_logprobs_fn, arrays: Dict[str, jnp.ndarray],
                              sample_mask: jnp.ndarray,
                              is_weighted: bool = True,
                              max_ratio: float = 1e9,
                              attacker_optimizer_config=None) -> jnp.ndarray:
    """Perplexity-ratio leakage of one client's update (traced).

    ``token_logprobs_fn(params, batch) -> (logp [.., L], mask [.., L])``
    scores the client's own batches.  The attacker step applies the
    configured optimizer (default Adamax lr 0.03, ``metrics.py:54-56``) to
    ``original_params`` using the pseudo-gradient.
    """
    if attacker_optimizer_config is None:
        from ..config import OptimizerConfig
        attacker_optimizer_config = OptimizerConfig(type="adamax", lr=0.03)
    tx = make_optimizer(attacker_optimizer_config)
    opt_state = tx.init(original_params)
    updates, _ = tx.update(pseudo_grad, opt_state, original_params)
    import optax
    attacked_params = optax.apply_updates(original_params, updates)

    tol = 1.0 / max_ratio

    def score(params):
        total_lp = []
        total_mask = []
        S = sample_mask.shape[0]
        for s in range(S):  # static unroll over the packed step grid
            batch = {k: v[s] for k, v in arrays.items()}
            batch["sample_mask"] = sample_mask[s]
            lp, mask = token_logprobs_fn(params, batch)
            total_lp.append(lp.reshape(-1))
            total_mask.append(mask.reshape(-1))
        return jnp.concatenate(total_lp), jnp.concatenate(total_mask)

    pre, mask = score(original_params)
    post, _ = score(attacked_params)
    leakage = jnp.clip((pre + tol) / (post + tol), 0.0, max_ratio)
    if is_weighted:
        leakage = jnp.maximum(jnp.exp(pre), jnp.exp(post)) * leakage
    leakage = jnp.where(mask > 0, leakage, -jnp.inf)
    max_leakage = jnp.max(leakage)
    return jnp.maximum(jnp.log(jnp.maximum(max_leakage, 1e-30)), 0.0)
