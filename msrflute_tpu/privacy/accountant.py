"""Renyi-DP accountant for the sampled Gaussian mechanism.

Role parity: reference ``extensions/privacy/analysis.py`` (vendored
TF-Privacy/Opacus math).  This is an independent implementation from the
published formulas (Mironov 2017, "Renyi Differential Privacy"; Mironov,
Talwar & Zhang 2019, "Renyi Differential Privacy of the Sampled Gaussian
Mechanism", eq. 7):

For integer order ``alpha >= 2`` and sampling rate ``q``::

    RDP(alpha) = 1/(alpha-1) * log( sum_{k=0}^{alpha}
                 C(alpha,k) (1-q)^(alpha-k) q^k exp(k(k-1)/(2 sigma^2)) )

computed in log space.  Composition over T steps multiplies RDP by T.
Conversion to (eps, delta)-DP uses the standard bound
``eps = rdp + log(1/delta)/(alpha-1)`` minimized over orders.

We restrict to integer orders (fractional orders need the continuous-series
bound and buy little accuracy); callers pass the same order grid either way.
"""

from __future__ import annotations

import math
from typing import List, Sequence, Tuple

import numpy as np
from scipy.special import gammaln, logsumexp


# shared order grid for RDP accounting (integer-order mechanism family;
# fractional entries below 2 are rounded up by compute_rdp anyway, so the
# grid is integers with a coarse high-order tail)
DEFAULT_ORDERS: Tuple[int, ...] = tuple(range(2, 64)) + (128, 256, 512)


def _log_comb(n: int, k: int) -> float:
    return gammaln(n + 1) - gammaln(k + 1) - gammaln(n - k + 1)


def _rdp_integer_order(q: float, sigma: float, alpha: int) -> float:
    """RDP of one sampled-Gaussian step at integer order alpha."""
    if q == 0:
        return 0.0
    if q == 1.0:
        return alpha / (2.0 * sigma ** 2)
    log_terms = []
    for k in range(alpha + 1):
        log_b = _log_comb(alpha, k)
        log_q = k * math.log(q) if k > 0 else 0.0
        log_1mq = (alpha - k) * math.log1p(-q) if alpha - k > 0 else 0.0
        log_e = k * (k - 1) / (2.0 * sigma ** 2)
        log_terms.append(log_b + log_q + log_1mq + log_e)
    log_sum = logsumexp(log_terms)
    return float(log_sum / (alpha - 1))


def compute_rdp(q: float, noise_multiplier: float, steps: int,
                orders: Sequence[float]) -> np.ndarray:
    """RDP at each order after ``steps`` compositions of subsampled Gaussian
    with sampling rate ``q`` and noise multiplier ``noise_multiplier``.

    Non-integer orders are rounded up to the next integer (a valid upper
    bound since RDP is monotone in the order for this mechanism family).
    """
    if noise_multiplier <= 0:
        return np.full(len(orders), np.inf)
    out = []
    for order in orders:
        alpha = int(math.ceil(order))
        alpha = max(alpha, 2)
        out.append(_rdp_integer_order(q, noise_multiplier, alpha) * steps)
    return np.asarray(out)


def get_privacy_spent(orders: Sequence[float], rdp: Sequence[float],
                      target_delta: float) -> Tuple[float, float]:
    """(epsilon, optimal order) for a target delta:
    ``eps(alpha) = rdp(alpha) + log(1/delta)/(alpha-1)`` minimized over
    orders (Mironov 2017, Prop. 3)."""
    orders = np.asarray(orders, dtype=float)
    rdp = np.asarray(rdp, dtype=float)
    with np.errstate(over="ignore", invalid="ignore"):
        eps = rdp + math.log(1.0 / target_delta) / (orders - 1.0)
    eps = np.where(np.isnan(eps), np.inf, eps)
    idx = int(np.argmin(eps))
    return float(eps[idx]), float(orders[idx])
