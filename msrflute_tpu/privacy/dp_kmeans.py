"""Differentially-private k-means.

Parity target: reference ``extensions/privacy/dp_kmeans.py`` — a research
tool with (a) sphere-packing initialization: centers sampled uniformly in a
ball, rejecting candidates within ``2 * min_cluster_radius`` of existing
centers and halving the radius after ``max_failed_cases`` rejections
(``dp_kmeans.py:23-48``); and (b) noisy Lloyd iterations: per iteration the
cluster sums and weights get Gaussian noise calibrated to
``sqrt(max_cluster_l2^2 + max_sample_weight^2)`` sensitivity with the
optional ``cluster_to_weight_ratio`` weight re-scaling trick
(``dp_kmeans.py:51-74``).

The reference monkey-patches sklearn's Lloyd internals; here the Lloyd loop
is a self-contained numpy implementation (the tool is host-side and tiny —
clustering client embeddings, not a hot path).  Per-iteration epsilon, so
total privacy loss <= eps * n_iter as in the reference docstring.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
from scipy.special import gammainc


def _sample_ball(rng: np.random.Generator, ndim: int, radius: float,
                 num_samples: int = 1) -> np.ndarray:
    """Uniform samples in an ``ndim``-ball (reference ``sample``,
    ``dp_kmeans.py:14-20``)."""
    x = rng.normal(size=(num_samples, ndim))
    ssq = np.sum(x ** 2, axis=1)
    fr = radius * gammainc(ndim / 2, ssq / 2) ** (1 / ndim) / \
        np.maximum(np.sqrt(ssq), 1e-12)
    return x * fr[:, None]


def sphere_packing_initialization(n_clusters: int, n_dim: int,
                                  min_cluster_radius: float,
                                  max_space_size: float,
                                  max_failed_cases: int = 300,
                                  rng: Optional[np.random.Generator] = None,
                                  verbose: bool = False
                                  ) -> Tuple[np.ndarray, float]:
    """Rejection-sample centers at pairwise distance >= 2a
    (reference ``dp_kmeans.py:23-48``)."""
    rng = rng or np.random.default_rng(0)
    a = min_cluster_radius
    centers = np.empty((n_clusters, n_dim))
    cluster_id = 0
    fail_count = 0
    r = max_space_size - a
    while cluster_id < n_clusters:
        v = _sample_ball(rng, n_dim, r)[0]
        if cluster_id > 0 and np.min(np.linalg.norm(
                centers[:cluster_id] - v, axis=-1)) < 2 * a:
            fail_count += 1
            if fail_count >= max_failed_cases:
                fail_count = 0
                cluster_id = 0
                a = a / 2
                if verbose:
                    print(f"halving min_cluster_radius to {a}")
                r = max_space_size - a
            continue
        centers[cluster_id] = v
        cluster_id += 1
    return centers, a


def _noisy_update(x: np.ndarray, labels: np.ndarray, n_clusters: int,
                  eps: float, max_cluster_l2: float, max_sample_weight: float,
                  cluster_to_weight_ratio: float, delta: float,
                  rng: np.random.Generator) -> np.ndarray:
    """One DP Lloyd M-step (reference ``add_gaussian_noise``,
    ``dp_kmeans.py:51-74``)."""
    scaler = 1.0
    if cluster_to_weight_ratio > 0:
        scaler = max_cluster_l2 / (max_sample_weight * cluster_to_weight_ratio)
    scaled_max_weight = max_sample_weight * scaler
    sensitivity = np.sqrt(max_cluster_l2 ** 2 + scaled_max_weight ** 2)
    sigma = np.sqrt(2 * np.log(1.25 / delta)) * sensitivity / eps

    sums = np.zeros((n_clusters, x.shape[1]))
    weights = np.zeros((n_clusters,))
    for c in range(n_clusters):
        members = x[labels == c]
        sums[c] = members.sum(axis=0)
        weights[c] = len(members)
    sums += rng.normal(scale=sigma, size=sums.shape)
    weights = np.maximum(
        1e-10, weights * scaler + rng.normal(scale=sigma, size=weights.shape)
    ) / scaler
    return sums / weights[:, None]


def dp_kmeans(x: np.ndarray, n_clusters: int = 8, eps: float = 1.0,
              max_cluster_l2: float = 1.0, max_sample_weight: float = 1.0,
              max_iter: int = 300, tol: float = 1e-4,
              cluster_to_weight_ratio: float = -1.0, delta: float = 1e-7,
              max_failed_cases: int = 300,
              min_cluster_radius: Optional[float] = None,
              seed: int = 0, verbose: bool = False
              ) -> Tuple[np.ndarray, np.ndarray, int]:
    """DP k-means over row vectors clipped to ``max_cluster_l2``.

    Returns (centers, labels, n_iter).  Total privacy loss <=
    ``eps * n_iter`` (per-iteration epsilon, as in the reference).
    """
    rng = np.random.default_rng(seed)
    x = np.asarray(x, np.float64)
    norms = np.linalg.norm(x, axis=1, keepdims=True)
    x = x * np.minimum(1.0, max_cluster_l2 / np.maximum(norms, 1e-12))

    if min_cluster_radius is None:
        min_cluster_radius = max_cluster_l2 / (2.0 * n_clusters)
    centers, _ = sphere_packing_initialization(
        n_clusters, x.shape[1], min_cluster_radius, max_cluster_l2,
        max_failed_cases, rng, verbose)

    labels = np.zeros((len(x),), np.int64)
    for it in range(1, max_iter + 1):
        dists = np.linalg.norm(x[:, None, :] - centers[None], axis=-1)
        labels = np.argmin(dists, axis=1)
        new_centers = _noisy_update(
            x, labels, n_clusters, eps, max_cluster_l2, max_sample_weight,
            cluster_to_weight_ratio, delta, rng)
        shift = np.linalg.norm(new_centers - centers)
        centers = new_centers
        if shift < tol:
            break
    return centers, labels, it
