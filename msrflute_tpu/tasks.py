"""Task dataset assembly — config-driven user-blob loading + featurization.

Parity target: the reference's dataloader factory chain
(``utils/dataloaders_utils.py:9-115``: dynamic import of each task's
``DataLoader``/``Dataset`` + mode-based data-config selection).  Here the
split files named in the config are read by the shared user-blob reader and
featurized by the task (``BaseTask.make_dataset`` hook; numeric passthrough
by default) into :class:`~msrflute_tpu.data.dataset.ArraysDataset`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .config import FLUTEConfig
from .data import ArraysDataset, load_user_blob
from .data.dataset import scrub_empty_clients
from .data.user_blob import UserBlob
from .models.base import BaseTask


def default_featurize(blob: UserBlob, model_config) -> ArraysDataset:
    """Numeric passthrough: samples -> float32 ``x``, labels -> int32 ``y``."""
    per_user = []
    for i in range(len(blob)):
        x = np.asarray(blob.user_data[i], dtype=np.float32)
        entry = {"x": x}
        if blob.user_labels is not None:
            entry["y"] = np.asarray(blob.user_labels[i]).astype(np.int32)
        per_user.append(entry)
    return ArraysDataset(blob.user_list, per_user, blob.num_samples)


def make_dataset_for(task: BaseTask, blob: UserBlob, model_config,
                     split: str, data_config=None) -> ArraysDataset:
    hook = getattr(task, "make_dataset", None)
    if hook is not None:
        return hook(blob, model_config, split, data_config=data_config)
    return default_featurize(blob, model_config)


def build_task_datasets(cfg: FLUTEConfig, task: BaseTask) -> Tuple[
        ArraysDataset, Optional[ArraysDataset], Optional[ArraysDataset]]:
    """Load (train, val, test) datasets from the config's data paths.

    Mirrors the reference's split selection: client train data from
    ``client_config.data_config.train`` (``list_of_train_data`` or
    ``train_data``), evals from ``server_config.data_config.{val,test}``
    (``utils/dataloaders_utils.py:57-98``).
    """
    cc_train = cfg.client_config.data_config.train
    train_path = cc_train.get("list_of_train_data") or cc_train.get("train_data")
    if not train_path:
        raise ValueError("client_config.data_config.train needs "
                         "list_of_train_data or train_data")
    if cc_train.get("lazy"):
        # scale path: per-user on-demand hdf5 reads; a round only touches
        # its sampled clients (reference "millions of clients",
        # README.md:9), so never materialize the whole blob
        import os as _os
        if _os.path.splitext(train_path)[1].lower() not in (".hdf5", ".h5"):
            raise ValueError("data_config.train.lazy requires an hdf5 blob "
                             f"(got {train_path})")
        featurize = getattr(task, "featurize_user", None)
        if featurize is None and getattr(task, "make_dataset", None) \
                is not None:
            raise ValueError(
                f"task {task.name!r} has a whole-blob featurizer and no "
                "per-user featurize_user hook; lazy loading needs one")
        if featurize is not None and cc_train.get("augment"):
            raise ValueError("augment needs a shared rng stream; use the "
                             "eager loader (lazy: false) with augment")
        from .data.dataset import LazyUserDataset
        from .data.user_blob import LazyHDF5Users
        train = scrub_empty_clients(LazyUserDataset(
            LazyHDF5Users(train_path), featurize=featurize,
            cache_users=int(cc_train.get("lazy_cache_users", 256))))
    else:
        train = scrub_empty_clients(make_dataset_for(
            task, load_user_blob(train_path), cfg.model_config, "train",
            data_config=cc_train))

    def _load(split_cfg, key, split):
        path = split_cfg.get(key)
        if not path:
            return None
        return make_dataset_for(task, load_user_blob(path), cfg.model_config,
                                split, data_config=split_cfg)

    val = _load(cfg.server_config.data_config.val, "val_data", "val")
    test = _load(cfg.server_config.data_config.test, "test_data", "test")
    return train, val, test


def build_server_train_dataset(cfg: FLUTEConfig, task: BaseTask):
    """Server-replay dataset from ``train_data_server``
    (reference ``utils/dataloaders_utils.py:57-84`` server-side loader)."""
    path = cfg.server_config.data_config.train.get("train_data_server")
    if not path:
        return None
    return make_dataset_for(task, load_user_blob(path), cfg.model_config,
                            "train")
