"""ResNet-18 with GroupNorm for Fed-CIFAR-100.

Parity target: reference ``experiments/cv_resnet_fedcifar100/model.py`` +
``group_normalization.py`` — a FedML-style ResNet with GroupNorm in place of
BatchNorm (no running stats: the right normalization for federated clients,
and for vmap-over-clients here — every client's stats stay self-contained).

Flax implementation, NHWC, GroupNorm native (``nn.GroupNorm``).  The stem is
the ImageNet-style 7x7/stride-2 + maxpool of the reference; CIFAR inputs
(32x32) pass through it exactly as they do in the reference.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from .base import parse_dtype, to_float_image
from .cv import ClassificationTask


#: He fan-out init, the reference's ``normal_(0, sqrt(2/n))`` on convs
#: (``model.py:139-140``)
_he_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def _gn(channels: int, channels_per_group: int = 32,
        zero_scale: bool = False, dtype=jnp.float32) -> nn.GroupNorm:
    groups = max(channels // max(channels_per_group, 1), 1)
    # flax GroupNorm computes its statistics in float32 regardless of
    # ``dtype``; passing the compute dtype only keeps activations bf16.
    # epsilon matches the reference's F.batch_norm default 1e-5
    # (group_normalization.py:19 via _BatchNorm) — flax's own default is
    # 1e-6, a visible round-0 forward delta.  NOTE a deliberate
    # divergence kept per-channel: the reference's GroupNorm affine is
    # per-GROUP (weight shape c/32, group_normalization.py:104-112);
    # ours is flax-standard per-channel (strictly more expressive;
    # identical at init, transplant repeats each group scalar across its
    # channels — see tests/test_parity_harness.py resnet transplant).
    return nn.GroupNorm(num_groups=groups, dtype=dtype, epsilon=1e-5,
                        scale_init=(nn.initializers.zeros if zero_scale
                                    else nn.initializers.ones))


class _BasicBlock(nn.Module):
    planes: int
    stride: int = 1
    channels_per_group: int = 32
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                    padding=1, use_bias=False, kernel_init=_he_init,
                    dtype=self.dtype)(x)
        y = _gn(self.planes, self.channels_per_group, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), padding=1, use_bias=False,
                    kernel_init=_he_init, dtype=self.dtype)(y)
        # block-final norm scale starts at zero so every block begins as
        # identity (the reference's zero_init_residual,
        # ``model.py:148-152``) — without it the 8-block stack amplifies
        # activations and early SGD diverges
        y = _gn(self.planes, self.channels_per_group, zero_scale=True,
                dtype=self.dtype)(y)
        if residual.shape[-1] != self.planes or self.stride != 1:
            residual = nn.Conv(self.planes, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, kernel_init=_he_init,
                               dtype=self.dtype)(x)
            residual = _gn(self.planes, self.channels_per_group,
                           dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class _ResNetGN(nn.Module):
    stage_sizes: Sequence[int] = (2, 2, 2, 2)  # ResNet-18
    num_classes: int = 100
    channels_per_group: int = 32
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = to_float_image(x, self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3, use_bias=False,
                    kernel_init=_he_init, dtype=self.dtype)(x)
        x = _gn(64, self.channels_per_group, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        planes = 64
        for stage, blocks in enumerate(self.stage_sizes):
            for block in range(blocks):
                stride = 2 if stage > 0 and block == 0 else 1
                x = _BasicBlock(planes, stride,
                                self.channels_per_group, self.dtype)(x)
            planes *= 2
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


def make_resnet_task(model_config) -> ClassificationTask:
    num_classes = int(model_config.get("num_classes", 100))
    side = int(model_config.get("image_size", 32))
    depth = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3)}[
        int(model_config.get("depth", 18))]
    module = _ResNetGN(
        stage_sizes=depth, num_classes=num_classes,
        channels_per_group=int(model_config.get("channels_per_group", 32)),
        dtype=parse_dtype(model_config))
    # in_channels: the reference model is RGB-only; grayscale corpora
    # (e.g. the bundled digits convergence probe) need 1 here
    chans = int(model_config.get("in_channels", 3))
    return ClassificationTask(module, example_shape=(side, side, chans),
                              name="cv_resnet_fedcifar100",
                              num_classes=num_classes)
