"""Task registry — maps ``model_config.model_type`` to a task factory.

Parity target: the reference's dynamic plugin loader
(``experiments/__init__.py:8-43`` + ``utils/dataloaders_utils.py:16-23``,
which ``SourceFileLoader``-import ``experiments/<task>/model.py`` and look up
the class named by ``model_type``).  Here built-in tasks register by name;
external plugins can either call :func:`register_task` or provide a
``model_folder`` with a ``task.py`` exposing ``make_task(model_config)``.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Callable, Dict

from .base import BaseTask

TASK_REGISTRY: Dict[str, Callable[[Any], BaseTask]] = {}


def register_task(name: str):
    def deco(factory: Callable[[Any], BaseTask]):
        TASK_REGISTRY[name] = factory
        return factory
    return deco


def _apply_plugin_config(model_config, folder: str) -> None:
    """Model-specific config discovery (reference ``core/config.py:100-116``):
    a ``config.py`` in the model folder may define ``<model_type>Config``
    whose attributes/defaults are merged into the model config (explicit
    YAML keys win)."""
    cfg_path = os.path.join(folder, "config.py")
    if not os.path.exists(cfg_path):
        return
    spec = importlib.util.spec_from_file_location("flute_tpu_plugin_cfg",
                                                  cfg_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    cls = getattr(mod, model_config.get("model_type", "LR") + "Config", None)
    if cls is None:
        return
    defaults = getattr(cls, "defaults", None)
    if defaults is None:
        defaults = {k: v for k, v in vars(cls).items()
                    if not k.startswith("_") and not callable(v)}
    for key, value in defaults.items():
        if model_config.get(key) is None:
            model_config[key] = value


def make_task(model_config) -> BaseTask:
    """Instantiate the task named by ``model_config.model_type``."""
    model_type = model_config.get("model_type", "LR")
    folder = model_config.get("model_folder")
    if folder:
        _apply_plugin_config(model_config, folder)
        plugin = os.path.join(folder, "task.py")
        if os.path.exists(plugin):
            spec = importlib.util.spec_from_file_location("flute_tpu_plugin", plugin)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)  # type: ignore[union-attr]
            return mod.make_task(model_config)
    if model_type not in TASK_REGISTRY:
        _load_builtins()
    if model_type not in TASK_REGISTRY:
        raise KeyError(
            f"unknown model_type {model_type!r}; known: {sorted(TASK_REGISTRY)}")
    return TASK_REGISTRY[model_type](model_config)


def _load_builtins() -> None:
    from . import cv  # noqa: F401  (registers on import)
    for name, factory in {
        "LR": cv.make_lr_task,
        "CNN": cv.make_cnn_femnist_task,
        "CNN_FEMNIST": cv.make_cnn_femnist_task,
        "CIFAR_CNN": cv.make_cifar_cnn_task,
    }.items():
        TASK_REGISTRY.setdefault(name, factory)
    try:
        from . import resnet
        TASK_REGISTRY.setdefault("RESNET", resnet.make_resnet_task)
        TASK_REGISTRY.setdefault("ResNet", resnet.make_resnet_task)
    except ImportError:
        pass
    try:
        from . import nlp
        TASK_REGISTRY.setdefault("RNN", nlp.make_shakespeare_lstm_task)
        TASK_REGISTRY.setdefault("LSTM", nlp.make_shakespeare_lstm_task)
        TASK_REGISTRY.setdefault("GRU", nlp.make_gru_lm_task)
    except ImportError:
        pass
    try:
        from . import ecg
        TASK_REGISTRY.setdefault("ECG_CNN", ecg.make_ecg_task)
    except ImportError:
        pass
    try:
        from . import bert
        TASK_REGISTRY.setdefault("BERT", bert.make_bert_mlm_task)
    except ImportError:
        pass
    try:
        from . import fednewsrec
        TASK_REGISTRY.setdefault("NRMS", fednewsrec.make_fednewsrec_task)
        TASK_REGISTRY.setdefault("FEDNEWSREC", fednewsrec.make_fednewsrec_task)
    except ImportError:
        pass
    try:
        from . import ringlm
        TASK_REGISTRY.setdefault("RINGLM", ringlm.make_ringlm_task)
    except ImportError:
        pass
