"""NLP tasks: Shakespeare char LSTM and the Reddit GRU word LM.

Parity targets:

- ``RNN`` (reference ``experiments/nlp_rnn_fedshakespeare/model.py:12-40``):
  embedding(90 -> 8, pad id 0) -> 2-layer LSTM(256) -> per-position dense to
  vocab; cross-entropy with ``ignore_index=0``; accuracy over non-pad
  positions.
- ``GRU`` (reference ``experiments/nlg_gru/model.py:11-133``): custom GRU
  cell (convex-combination update ``hy = n + i*(h - n)``), tied
  embedding/unembedding through a ``squeeze`` projection, negative ids mark
  padding, and OOV-rejecting accuracy: a prediction of the unk id (0) counts
  as wrong even when the target is 0 (``model.py:118-121``).

TPU-native: recurrences are ``nn.RNN``/``lax.scan`` (single compiled cell
per layer), embeddings gathered on-device, losses masked — no ragged
batches, no ``pack_padded_sequence``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils.metrics import Metric
from .base import BaseTask, Batch, parse_dtype, softmax_xent


class _ShakespeareLSTM(nn.Module):
    vocab_size: int = 90
    embed_dim: int = 8
    hidden: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):  # x: [B, L] int32
        emb = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype)(x)
        h = emb
        for _ in range(2):
            h = nn.RNN(nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype))(h)
        return nn.Dense(self.vocab_size, dtype=self.dtype)(h)  # [B, L, V]


class _ConvexGRUCell(nn.Module):
    """The reference's GRU2 cell (``nlg_gru/model.py:11-28``):
    ``hy = new + input_gate * (hidden - new)``."""

    hidden: int

    @nn.compact
    def __call__(self, carry, x):
        h = carry
        gi = nn.Dense(3 * self.hidden, use_bias=True, name="w_ih")(x)
        gh = nn.Dense(3 * self.hidden, use_bias=True, name="w_hh")(h)
        i_r, i_i, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_i, h_n = jnp.split(gh, 3, axis=-1)
        reset = jax.nn.sigmoid(i_r + h_r)
        inp = jax.nn.sigmoid(i_i + h_i)
        new = jnp.tanh(i_n + reset * h_n)
        hy = new + inp * (h - new)
        return hy, hy

    @staticmethod
    def init_carry(batch, hidden):
        return jnp.zeros((batch, hidden))


class _GRUWordLM(nn.Module):
    """Tied-embedding GRU LM (``nlg_gru/model.py:39-83``)."""

    vocab_size: int = 10000
    embed_dim: int = 160
    hidden_dim: int = 512

    @nn.compact
    def __call__(self, x):  # x: [B, L] int32 (already clamped non-negative)
        table = self.param(
            "embedding",
            lambda key, shape: jax.random.uniform(
                key, shape, minval=-(3 / shape[1]) ** 0.5,
                maxval=(3 / shape[1]) ** 0.5),
            (self.vocab_size, self.embed_dim))
        unembed_bias = self.param("unembedding_bias", nn.initializers.zeros,
                                  (self.vocab_size,))
        emb = jnp.take(table, x, axis=0)  # [B, L, E]

        carry = _ConvexGRUCell.init_carry(x.shape[0], self.hidden_dim)
        _, hiddens = nn.scan(
            _ConvexGRUCell, variable_broadcast="params",
            split_rngs={"params": False}, in_axes=1, out_axes=1,
        )(hidden=self.hidden_dim)(carry, emb)
        # the reference stacks [h0, h1, ..., hL] (``GRU2.forward``,
        # ``nlg_gru/model.py:31-36``): the zero INITIAL state's prediction
        # — the marginal next-word distribution — is part of the output
        # and of the loss (``model.py:92-100`` pairs output[:, t] with
        # input[:, t], including t=0 from h0)
        hiddens = jnp.concatenate(
            [jnp.zeros_like(hiddens[:, :1]), hiddens], axis=1)
        squeezed = nn.Dense(self.embed_dim, use_bias=False, name="squeeze")(hiddens)
        logits = squeezed @ table.T + unembed_bias
        return logits  # [B, L+1, V]


class SequenceLMTask(BaseTask):
    """Shared masked seq-to-seq LM task.

    ``batch['x']``: ``[B, L]`` int ids, 0 = padding.  If ``batch['y']`` is
    present it is the per-position target (fed_shakespeare ships explicit
    targets); otherwise targets are ``x`` shifted left by one.
    Per-sequence ``sample_mask`` gates whole padded sequences; position mask
    is ``target != 0`` (the reference's ``ignore_index=0`` / ``>= 0``
    masking).
    """

    #: x/y/tok_mask are 0-padded ``[n, L]`` rows: the round packer may crop
    #: their common all-pad tail (length bucketing).  tok_mask MUST be in
    #: the set — its nonzeros mark real positions even where x holds the
    #: unk id 0, so it both gets cropped in lockstep with x and keeps the
    #: bucket from under-counting unk tokens.
    seq_pad_keys = ("x", "y", "tok_mask")

    #: reference-GRU loss alignment (``nlg_gru/model.py:92-100``): the
    #: module emits one MORE position than its input (the initial zero
    #: state's prediction), the forward consumes ``x[:, :-1]``, and the
    #: targets are the FULL ``x`` — position 0 is predicted from h0.
    #: False = standard shift alignment (Shakespeare implicit / RingLM).
    ref_initial_prediction: bool = False

    def __init__(self, module: nn.Module, seq_len: int, name: str,
                 oov_reject: bool = False):
        self.module = module
        self.seq_len = seq_len
        self.name = name
        self.oov_reject = oov_reject

    def init_params(self, rng: jax.Array):
        dummy = jnp.zeros((1, self.seq_len - 1), jnp.int32)
        return self.module.init(rng, dummy)["params"]

    def _logits_targets(self, params, batch: Batch):
        x = batch["x"].astype(jnp.int32)
        if "y" in batch and batch["y"].ndim == x.ndim:
            # explicit per-position targets: with ref_initial_prediction
            # the module emits len(inputs)+1 positions, so feed L-1
            # inputs to keep logits aligned with the [B, L] targets
            # (y[t] is predicted from the state after x[0..t-1], with
            # y[0] from the initial state)
            inputs = x[:, :-1] if self.ref_initial_prediction else x
            targets = batch["y"].astype(jnp.int32)
            tok_mask = batch.get("tok_mask")
            tok_mask = (tok_mask.astype(jnp.float32) if tok_mask is not None
                        else (targets != 0).astype(jnp.float32))
        elif self.ref_initial_prediction:
            # reference-GRU alignment: module([B, L-1]) -> [B, L, V]
            # (initial-state prediction included); targets = full x
            inputs, targets = x[:, :-1], x
            tok_mask = batch.get("tok_mask")
            tok_mask = (tok_mask.astype(jnp.float32) if tok_mask is not None
                        else (targets != 0).astype(jnp.float32))
        else:
            inputs, targets = x[:, :-1], x[:, 1:]
            tok_mask = batch.get("tok_mask")
            if tok_mask is not None:
                # mask for the shifted targets: a target is real iff its
                # position was real (keeps unk id 0 in the denominator, as
                # the reference's >=0 padding rule does)
                tok_mask = tok_mask.astype(jnp.float32)[:, 1:]
            else:
                tok_mask = (targets != 0).astype(jnp.float32)
        # f32 logits regardless of the module's compute dtype (bf16 MXU
        # matmuls, float32 softmax/xent — see models.base.parse_dtype)
        logits = self.module.apply({"params": params},
                                   inputs).astype(jnp.float32)
        tok_mask = tok_mask * batch["sample_mask"][:, None]
        return logits, targets, tok_mask

    #: how the TRAINER counts this task's samples for aggregation weights
    #: and the DGA softmax metric (reference ``core/trainer.py:397-405``:
    #: rows by default, ``total_frames`` — real token positions — when the
    #: batch ships them, as nlg_gru's does).  fed_shakespeare batches ship
    #: neither key, so the LSTM task keeps row counting.
    count_frames = False

    def loss(self, params, batch: Batch, rng: Optional[jax.Array] = None,
             train: bool = True):
        logits, targets, tok_mask = self._logits_targets(params, batch)
        per_tok = softmax_xent(logits, targets)
        total = jnp.sum(per_tok * tok_mask)
        count = jnp.maximum(jnp.sum(tok_mask), 1.0)
        aux = {"sample_count": jnp.sum(batch["sample_mask"])}
        if self.count_frames:
            # reference total_frames = sum of real INPUT positions
            # (``experiments/nlg_gru/dataloaders/dataloader.py:83``); the
            # input-position mask counts them regardless of unk ids
            inp = batch.get("tok_mask")
            frames = (jnp.sum(inp.astype(jnp.float32)
                              * batch["sample_mask"][:, None])
                      if inp is not None else
                      jnp.sum((batch["x"] != 0).astype(jnp.float32)
                              * batch["sample_mask"][:, None]))
            aux["train_sample_count"] = frames
        return total / count, aux

    def topk_predictions(self, params, batch: Batch, k: int = 1):
        """Top-K predictions per target position (the reference GRU's
        ``wantLogits`` output payload, ``nlg_gru/model.py:113-130``):
        returns ``(probabilities, predictions, labels)`` with shapes
        ``[..., k]`` / ``[..., k]`` / ``[...]``; padded positions carry
        label -1."""
        logits, targets, tok_mask = self._logits_targets(params, batch)
        probs = jax.nn.softmax(logits, axis=-1)
        top_p, top_ids = jax.lax.top_k(probs, k)
        labels = jnp.where(tok_mask > 0, targets, -1)
        return top_p, top_ids, labels

    def token_logprobs(self, params, batch: Batch):
        """Per-token log-prob of the target under the model + validity mask
        (the ``compute_perplexity`` hook for the leakage attack, reference
        ``extensions/privacy/metrics.py:25-30``)."""
        logits, targets, tok_mask = self._logits_targets(params, batch)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return picked, tok_mask

    def eval_stats(self, params, batch: Batch) -> Dict[str, jnp.ndarray]:
        logits, targets, tok_mask = self._logits_targets(params, batch)
        per_tok = softmax_xent(logits, targets)
        pred = jnp.argmax(logits, axis=-1)
        correct = (pred == targets).astype(jnp.float32)
        if self.oov_reject:
            # predictions of the unk id count as wrong (nlg_gru model.py:118-121)
            correct = correct * (pred != 0)
        return {
            "loss_sum": jnp.sum(per_tok * tok_mask),
            "correct_sum": jnp.sum(correct * tok_mask),
            "sample_count": jnp.sum(tok_mask),
            "seq_count": jnp.sum(batch["sample_mask"]),
        }


class _TokenDatasetMixin:
    """make_dataset for token-sequence tasks: raw strings are tokenized
    (chars for Shakespeare, vocab words for the GRU LM), int sequences pass
    through 0-padded to ``seq_len``."""

    tokenizer: str = "words"  # or "chars"

    def make_dataset(self, blob, model_config, split, data_config=None):
        import numpy as np
        from ..data.dataset import ArraysDataset
        from ..data import featurize

        vocab = None
        vocab_path = (model_config.get("vocab_dict") or
                      (data_config.get("vocab_dict") if data_config else None))
        if self.tokenizer == "words" and vocab_path:
            vocab = featurize.load_vocab(vocab_path)
        L = self.seq_len

        def encode_rows(samples):
            rows = []
            for s in samples:
                if isinstance(s, str):
                    if self.tokenizer == "chars":
                        rows.append(featurize.encode_chars(s, L))
                    else:
                        if vocab is None:
                            raise ValueError(
                                "word task needs vocab_dict for raw text")
                        rows.append(featurize.encode_words(s, vocab, L))
                elif isinstance(s, (list, tuple)) and s and \
                        isinstance(s[0], str):
                    if vocab is None:
                        raise ValueError(
                            "word task needs vocab_dict for raw tokens")
                    rows.append(featurize.encode_words(s, vocab, L))
                else:
                    rows.append(np.asarray(s))
            return featurize.pad_token_matrix(rows, L)

        per_user = []
        for i in range(len(blob)):
            x, tok_mask = encode_rows(blob.user_data[i])
            entry = {"x": x, "tok_mask": tok_mask}
            if blob.user_labels is not None and \
                    blob.user_labels[i] is not None:
                # fed_shakespeare-style explicit target sequences
                y, y_mask = encode_rows(blob.user_labels[i])
                entry["y"] = y
                entry["tok_mask"] = y_mask
            per_user.append(entry)
        return ArraysDataset(blob.user_list, per_user,
                             [len(u["x"]) for u in per_user])


class ShakespeareTask(_TokenDatasetMixin, SequenceLMTask):
    tokenizer = "chars"


class GRUWordTask(_TokenDatasetMixin, SequenceLMTask):
    tokenizer = "words"
    # the reference GRU trains position 0 from the zero initial state
    ref_initial_prediction = True
    # nlg_gru batches carry total_frames: the trainer counts WORDS, not
    # utterances (invisible under equal-sized users — the normalized
    # aggregate cancels a constant factor — but load-bearing for FedAvg
    # weights on unequal users and for DGA's train_loss/num_samples)
    count_frames = True


def make_shakespeare_lstm_task(model_config) -> SequenceLMTask:
    vocab = int(model_config.get("vocab_size", 90))
    module = _ShakespeareLSTM(
        vocab_size=vocab,
        embed_dim=int(model_config.get("embed_dim", 8)),
        hidden=int(model_config.get("hidden_dim", 256)),
        dtype=parse_dtype(model_config))
    return ShakespeareTask(module,
                           seq_len=int(model_config.get("seq_len", 80)),
                           name="nlp_rnn_fedshakespeare")


def make_gru_lm_task(model_config) -> SequenceLMTask:
    module = _GRUWordLM(
        vocab_size=int(model_config.get("vocab_size", 10000)),
        embed_dim=int(model_config.get("embed_dim", 160)),
        hidden_dim=int(model_config.get("hidden_dim", 512)))
    return GRUWordTask(module,
                       seq_len=int(model_config.get("max_num_words", 25)),
                       name="nlg_gru", oov_reject=True)
