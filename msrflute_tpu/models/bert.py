"""BERT masked-LM task (mlm_bert).

Parity target: reference ``experiments/mlm_bert/model.py`` — an HF
``AutoModelForMaskedLM`` wrapper with label smoothing, MLM masking via the HF
collator (``dataloaders/dataloader.py:23,60``: ``mlm_probability``), gradient
accumulation and masked-token accuracy.

TPU-native:

- the model is HF **Flax** BERT (``FlaxBertForMaskedLM``), instantiated from
  a local ``BertConfig`` (``model_name_or_path`` is honored when a local
  checkpoint path is given; fresh init otherwise — this container is
  zero-egress);
- MLM masking is *dynamic, on-device*: the 80/10/10 mask/random/keep rule of
  the HF collator is applied inside ``loss`` from the per-step RNG, so it
  jits and re-masks every epoch like the torch collator re-collates;
- label smoothing follows HF ``LabelSmoother`` semantics (epsilon spread
  over the vocabulary, masked positions excluded);
- gradient accumulation is subsumed by the engine's ``lax.scan`` over
  steps (an explicit knob is unnecessary when the whole epoch is compiled);
- with a ``model`` mesh axis > 1 the engine shards BERT params via
  :func:`msrflute_tpu.parallel.sharding.infer_model_sharding` (net-new:
  the reference has no tensor parallelism, SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..utils.metrics import Metric
from .base import BaseTask, Batch


class BertMLMTask(BaseTask):

    name = "mlm_bert"

    def __init__(self, model_config):
        from transformers import BertConfig, FlaxBertForMaskedLM

        bert_cfg = (model_config.get("BERT") or {}).get("model", {})
        training_cfg = (model_config.get("BERT") or {}).get("training", {})
        path = bert_cfg.get("model_name_or_path")
        hidden = int(bert_cfg.get("hidden_size", 128))
        self.seq_len = int(bert_cfg.get("max_seq_length",
                                        model_config.get("max_seq_length", 128)))
        self.mlm_probability = float(bert_cfg.get("mlm_probability", 0.15))
        self.label_smoothing = float(
            training_cfg.get("label_smoothing_factor", 0.0))
        self.mask_token_id = int(bert_cfg.get("mask_token_id", 103))
        self.premasked = bool(bert_cfg.get("premasked", False))
        # MLM head mode: "full" projects every position into vocab space
        # (HF semantics); "gathered" projects ONLY the masked positions —
        # MLM loss reads ~mlm_probability of positions, so the full-vocab
        # logits tensor ([B, L, V] f32, the model's dominant HBM traffic
        # AND a large FLOP share) shrinks by ~1/p.  See _gather_masked.
        self.mlm_head = str(bert_cfg.get("mlm_head", "full")).lower()
        if self.mlm_head not in ("full", "gathered"):
            raise ValueError(
                f"BERT.model.mlm_head must be 'full' or 'gathered', "
                f"got {self.mlm_head!r}")
        # static per-sequence slot budget for the gathered head: 2x the
        # expected Binomial(L, p) masked count (≈5 sigma at L=128, p=.15)
        # rounded up to a lane-friendly multiple of 8
        default_slots = int(
            -(-(self.seq_len * self.mlm_probability * 2.0) // 8) * 8)
        self.gathered_slots = int(
            bert_cfg.get("gathered_slots",
                         min(max(default_slots, 8), self.seq_len)))
        if not 1 <= self.gathered_slots <= self.seq_len:
            raise ValueError(
                f"BERT.model.gathered_slots must be in [1, "
                f"{self.seq_len}] (seq_len), got {self.gathered_slots} — "
                "0 slots would silently train on an empty loss")
        from .base import parse_dtype
        # compute dtype (bf16 MXU path; HF Flax threads it through every
        # layer — params stay f32, logits are upcast in the loss)
        dtype = parse_dtype(bert_cfg if "dtype" in bert_cfg else model_config)
        self._pretrained_params = None
        if path:
            try:
                self.model = FlaxBertForMaskedLM.from_pretrained(path,
                                                                 dtype=dtype)
            except (OSError, EnvironmentError):
                # torch-format checkpoint dir (pytorch_model.bin /
                # model.safetensors only): the reference saves these and a
                # switching user points us at the same path
                self.model = FlaxBertForMaskedLM.from_pretrained(
                    path, dtype=dtype, from_pt=True)
            self.config = self.model.config
            self._pretrained_params = self.model.params
        else:
            self.config = BertConfig(
                vocab_size=int(bert_cfg.get("vocab_size", 30522)),
                hidden_size=hidden,
                num_hidden_layers=int(bert_cfg.get("num_hidden_layers", 2)),
                num_attention_heads=int(bert_cfg.get("num_attention_heads", 2)),
                intermediate_size=int(bert_cfg.get("intermediate_size",
                                                   4 * hidden)),
                max_position_embeddings=max(self.seq_len, 512),
            )
            self.model = FlaxBertForMaskedLM(self.config, _do_init=True,
                                             dtype=dtype)
        self.vocab_size = int(self.config.vocab_size)

    # ------------------------------------------------------------------
    def init_params(self, rng: jax.Array):
        if self._pretrained_params is not None:
            # honor model_name_or_path (reference loads pretrained weights,
            # experiments/mlm_bert/model.py:119-123)
            return jax.tree.map(jnp.asarray, self._pretrained_params)
        dummy = jnp.ones((1, self.seq_len), jnp.int32)
        return self.model.module.init(
            {"params": rng, "dropout": rng},
            dummy, jnp.ones_like(dummy), jnp.zeros_like(dummy),
            jnp.broadcast_to(jnp.arange(self.seq_len), (1, self.seq_len)),
            None, deterministic=True, return_dict=False)["params"]

    def _apply(self, params, input_ids, attention_mask, deterministic=True,
               rng=None, output_hidden_states=False):
        rngs = {"dropout": rng} if rng is not None else {}
        return self.model.module.apply(
            {"params": params}, input_ids, attention_mask,
            jnp.zeros_like(input_ids),
            jnp.broadcast_to(jnp.arange(input_ids.shape[-1]),
                             input_ids.shape),
            None, deterministic=deterministic,
            output_hidden_states=output_hidden_states, return_dict=True,
            rngs=rngs)

    def _logits(self, params, input_ids, attention_mask, deterministic=True,
                rng=None):
        out = self._apply(params, input_ids, attention_mask,
                          deterministic=deterministic, rng=rng)
        # f32 logits regardless of compute dtype (bf16 matmuls, f32 xent)
        return out.logits.astype(jnp.float32)

    def apply(self, params, input_ids):
        return self._logits(params, input_ids.astype(jnp.int32),
                            jnp.ones_like(input_ids, jnp.int32))

    # ------------------------------------------------------------------
    # gathered MLM head: encoder hidden states -> vocab logits at masked
    # positions only
    # ------------------------------------------------------------------
    def _hidden_states(self, params, input_ids, attention_mask,
                       deterministic=True, rng=None):
        """Final-layer encoder hidden states ``[B, L, H]`` (the tensor the
        HF cls head consumes), without running the vocab projection."""
        out = self._apply(params, input_ids, attention_mask,
                          deterministic=deterministic, rng=rng,
                          output_hidden_states=True)
        return out.hidden_states[-1]

    def _head_params(self, params):
        """The HF Flax BertForMaskedLM head leaves (transform dense +
        LayerNorm, decoder kernel, decoder bias).  With
        ``tie_word_embeddings`` (the BERT default) the decoder kernel is
        the word-embedding matrix transposed; an UNTIED checkpoint stores
        its own ``cls/predictions/decoder/kernel``, which takes
        precedence.  Raises with the actual tree layout on mismatch so a
        transformers version bump fails loudly, not with a silent wrong
        projection."""
        try:
            pred = params["cls"]["predictions"]
            dense = pred["transform"]["dense"]
            ln = pred["transform"]["LayerNorm"]
            decoder = pred.get("decoder", {})
            if "kernel" in decoder:
                kernel = decoder["kernel"]          # untied checkpoint
            elif getattr(self.config, "tie_word_embeddings", True):
                kernel = params["bert"]["embeddings"][
                    "word_embeddings"]["embedding"].T
            else:
                raise KeyError(
                    "'cls/predictions/decoder/kernel' (config says "
                    "tie_word_embeddings=False but no decoder kernel "
                    "is stored)")
            bias = pred["bias"]
        except KeyError as exc:
            raise ValueError(
                "unexpected FlaxBertForMaskedLM param layout (missing "
                f"{exc}); the gathered MLM head mirrors cls/predictions/"
                "{transform,decoder,bias} — fix _head_params for this "
                "transformers version or use mlm_head: full") from exc
        return dense, ln, kernel, bias

    def _mlm_head_logits(self, params, hidden):
        """Apply the MLM head to ``hidden [..., H]`` exactly as HF's
        ``FlaxBertLMPredictionHead`` does (dense -> activation ->
        LayerNorm -> tied-embedding decoder + bias), in the model's
        compute dtype with f32 logits out."""
        from transformers.modeling_flax_utils import ACT2FN
        dense, ln, kernel, bias = self._head_params(params)
        dtype = self.model.dtype
        h = hidden.astype(dtype) @ dense["kernel"].astype(dtype) \
            + dense["bias"].astype(dtype)
        h = ACT2FN[self.config.hidden_act](h)
        # HF FlaxBertPredictionHeadTransform LayerNorm (eps from config)
        mean = jnp.mean(h.astype(jnp.float32), axis=-1, keepdims=True)
        var = jnp.var(h.astype(jnp.float32), axis=-1, keepdims=True)
        h = ((h.astype(jnp.float32) - mean)
             * jax.lax.rsqrt(var + self.config.layer_norm_eps))
        h = h.astype(dtype) * ln["scale"].astype(dtype) \
            + ln["bias"].astype(dtype)
        logits = h @ kernel.astype(dtype)
        return logits.astype(jnp.float32) + bias.astype(jnp.float32)

    def _gather_masked(self, hidden, labels):
        """Pack each sequence's masked positions (label != -100) into a
        static ``[B, gathered_slots]`` window, selected-first in original
        order (stable sort).  The Binomial(L, p) masked count exceeds the
        2x-mean slot budget with ~5-sigma rarity; overflow positions are
        DROPPED from the loss (documented deviation of the gathered mode;
        raise ``gathered_slots`` to trade memory for exactness — at
        ``gathered_slots == seq_len`` the mode is exact)."""
        m = self.gathered_slots
        sel = labels != -100
        idx = jnp.argsort(~sel, axis=-1, stable=True)[:, :m]
        g_labels = jnp.where(
            jnp.take_along_axis(sel, idx, axis=1),
            jnp.take_along_axis(labels, idx, axis=1), -100)
        g_hidden = jnp.take_along_axis(hidden, idx[..., None], axis=1)
        return g_hidden, g_labels

    # ------------------------------------------------------------------
    def _mlm_mask(self, rng, input_ids, attention_mask):
        """HF DataCollatorForLanguageModeling rule: select
        ``mlm_probability`` of real tokens; of those 80% -> [MASK], 10% ->
        random token, 10% -> unchanged; labels = original ids at selected
        positions, -100 elsewhere."""
        r1, r2, r3 = jax.random.split(rng, 3)
        select = (jax.random.uniform(r1, input_ids.shape) <
                  self.mlm_probability) & (attention_mask > 0)
        labels = jnp.where(select, input_ids, -100)
        roll = jax.random.uniform(r2, input_ids.shape)
        masked = jnp.where(select & (roll < 0.8), self.mask_token_id,
                           input_ids)
        random_ids = jax.random.randint(r3, input_ids.shape, 0,
                                        self.vocab_size)
        masked = jnp.where(select & (roll >= 0.8) & (roll < 0.9),
                           random_ids, masked)
        return masked, labels

    def _masked_xent(self, logits, labels):
        """Label-smoothed CE over positions with label != -100 (HF
        LabelSmoother semantics), in logsumexp form:
        ``-logp[y] = lse(logits) - logits[y]`` and
        ``-mean(logp) = lse - mean(logits)`` — mathematically identical
        to ``log_softmax`` + gather but never materializes the
        ``[..., V]`` log-prob tensor, which for a 30k vocab is the
        loss's dominant HBM traffic."""
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        at = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll = lse - at
        if self.label_smoothing > 0:
            smooth = lse - jnp.mean(logits, axis=-1)
            nll = (1 - self.label_smoothing) * nll + self.label_smoothing * smooth
        return nll, valid.astype(jnp.float32)

    # ------------------------------------------------------------------
    def _premasked(self, batch: Batch):
        """Pre-masked mode (config ``BERT.model.premasked: true``): the
        blob ships already-masked input ids plus MLM labels under ``y``
        (-100 at unmasked positions) and the collator RNG is bypassed
        entirely — the parity harness uses this to make the BERT family
        deterministic (the reference's
        ``DataCollatorForLanguageModeling`` re-rolls masks per epoch,
        which no cross-framework RNG can match).  The mode is an
        EXPLICIT opt-in: inferring it from the presence of a ``y`` key
        would silently disable dynamic masking for any blob that happens
        to ship labels."""
        if not self.premasked:
            return None
        input_ids = batch["x"].astype(jnp.int32)
        attention_mask = batch.get(
            "attention_mask", (input_ids != 0).astype(jnp.int32))
        attention_mask = (attention_mask
                          * batch["sample_mask"][:, None].astype(
                              attention_mask.dtype)).astype(jnp.int32)
        labels = jnp.where(batch["sample_mask"][:, None] > 0,
                           batch["y"].astype(jnp.int32), -100)
        return input_ids, attention_mask, labels

    def loss(self, params, batch: Batch, rng: Optional[jax.Array] = None,
             train: bool = True):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        mask_rng, drop_rng = jax.random.split(rng)
        pre = self._premasked(batch)
        if pre is not None:
            masked_ids, attention_mask, labels = pre
        else:
            input_ids = batch["x"].astype(jnp.int32)
            attention_mask = batch.get(
                "attention_mask", (input_ids != 0).astype(jnp.int32))
            attention_mask = attention_mask * batch["sample_mask"][:, None] \
                .astype(attention_mask.dtype)
            masked_ids, labels = self._mlm_mask(mask_rng, input_ids,
                                                attention_mask)
        if self.mlm_head == "gathered":
            hidden = self._hidden_states(params, masked_ids, attention_mask,
                                         deterministic=not train,
                                         rng=drop_rng if train else None)
            g_hidden, g_labels = self._gather_masked(hidden, labels)
            logits = self._mlm_head_logits(params, g_hidden)
            nll, valid = self._masked_xent(logits, g_labels)
        else:
            logits = self._logits(params, masked_ids, attention_mask,
                                  deterministic=not train,
                                  rng=drop_rng if train else None)
            nll, valid = self._masked_xent(logits, labels)
        loss = jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)
        return loss, {
            "sample_count": jnp.sum(batch["sample_mask"]),
            # the reference trainer counts mlm samples as attention
            # POSITIONS, not sequences (core/trainer.py:400-401) — this
            # feeds aggregation weights and the DGA softmax metric
            "train_sample_count": jnp.sum(
                attention_mask.astype(jnp.float32)),
        }

    def eval_stats(self, params, batch: Batch) -> Dict[str, jnp.ndarray]:
        pre = self._premasked(batch)
        if pre is not None:
            masked_ids, attention_mask, labels = pre
        else:
            input_ids = batch["x"].astype(jnp.int32)
            attention_mask = batch.get(
                "attention_mask", (input_ids != 0).astype(jnp.int32))
            attention_mask = attention_mask * batch["sample_mask"][:, None] \
                .astype(attention_mask.dtype)
            # deterministic eval masking so metrics are reproducible
            masked_ids, labels = self._mlm_mask(jax.random.PRNGKey(1234),
                                                input_ids, attention_mask)
        if self.mlm_head == "gathered":
            hidden = self._hidden_states(params, masked_ids, attention_mask)
            g_hidden, labels = self._gather_masked(hidden, labels)
            logits = self._mlm_head_logits(params, g_hidden)
        else:
            logits = self._logits(params, masked_ids, attention_mask)
        nll, valid = self._masked_xent(logits, labels)
        pred = jnp.argmax(logits, axis=-1)
        correct = (pred == jnp.where(labels == -100, -1, labels)).astype(
            jnp.float32)
        stats = {
            "loss_sum": jnp.sum(nll * valid),
            "correct_sum": jnp.sum(correct * valid),
            "sample_count": jnp.sum(valid),
            "seq_count": jnp.sum(batch["sample_mask"]),
        }
        if pre is not None:
            # reference-compatible accuracy denominator: its ComputeMetrics
            # divides correct masked predictions by ALL B*L positions, not
            # by the masked count (experiments/mlm_bert/utils/
            # trainer_utils.py:86 — `.float().mean()` over the full grid),
            # so masked accuracy is deflated by the masking rate.  The
            # pre-masked path mirrors that so cross-framework numbers align.
            stats["pos_count"] = (jnp.sum(batch["sample_mask"])
                                  * batch["x"].shape[-1])
        return stats

    def finalize_metrics(self, sums):
        metrics = super().finalize_metrics(sums)
        if "pos_count" in sums and float(sums["pos_count"]) > 0:
            from ..utils.metrics import Metric
            metrics["acc"] = Metric(
                float(sums["correct_sum"]) / float(sums["pos_count"]),
                higher_is_better=True)
        return metrics


def make_bert_mlm_task(model_config) -> BertMLMTask:
    return BertMLMTask(model_config)
