"""BERT masked-LM task (mlm_bert).

Parity target: reference ``experiments/mlm_bert/model.py`` — an HF
``AutoModelForMaskedLM`` wrapper with label smoothing, MLM masking via the HF
collator (``dataloaders/dataloader.py:23,60``: ``mlm_probability``), gradient
accumulation and masked-token accuracy.

TPU-native:

- the model is HF **Flax** BERT (``FlaxBertForMaskedLM``), instantiated from
  a local ``BertConfig`` (``model_name_or_path`` is honored when a local
  checkpoint path is given; fresh init otherwise — this container is
  zero-egress);
- MLM masking is *dynamic, on-device*: the 80/10/10 mask/random/keep rule of
  the HF collator is applied inside ``loss`` from the per-step RNG, so it
  jits and re-masks every epoch like the torch collator re-collates;
- label smoothing follows HF ``LabelSmoother`` semantics (epsilon spread
  over the vocabulary, masked positions excluded);
- gradient accumulation is subsumed by the engine's ``lax.scan`` over
  steps (an explicit knob is unnecessary when the whole epoch is compiled);
- with a ``model`` mesh axis > 1 the engine shards BERT params via
  :func:`msrflute_tpu.parallel.sharding.infer_model_sharding` (net-new:
  the reference has no tensor parallelism, SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..utils.metrics import Metric
from .base import BaseTask, Batch


class BertMLMTask(BaseTask):

    name = "mlm_bert"

    def __init__(self, model_config):
        from transformers import BertConfig, FlaxBertForMaskedLM

        bert_cfg = (model_config.get("BERT") or {}).get("model", {})
        training_cfg = (model_config.get("BERT") or {}).get("training", {})
        path = bert_cfg.get("model_name_or_path")
        hidden = int(bert_cfg.get("hidden_size", 128))
        self.seq_len = int(bert_cfg.get("max_seq_length",
                                        model_config.get("max_seq_length", 128)))
        self.mlm_probability = float(bert_cfg.get("mlm_probability", 0.15))
        self.label_smoothing = float(
            training_cfg.get("label_smoothing_factor", 0.0))
        self.mask_token_id = int(bert_cfg.get("mask_token_id", 103))
        self.premasked = bool(bert_cfg.get("premasked", False))
        from .base import parse_dtype
        # compute dtype (bf16 MXU path; HF Flax threads it through every
        # layer — params stay f32, logits are upcast in the loss)
        dtype = parse_dtype(bert_cfg if "dtype" in bert_cfg else model_config)
        self._pretrained_params = None
        if path:
            try:
                self.model = FlaxBertForMaskedLM.from_pretrained(path,
                                                                 dtype=dtype)
            except (OSError, EnvironmentError):
                # torch-format checkpoint dir (pytorch_model.bin /
                # model.safetensors only): the reference saves these and a
                # switching user points us at the same path
                self.model = FlaxBertForMaskedLM.from_pretrained(
                    path, dtype=dtype, from_pt=True)
            self.config = self.model.config
            self._pretrained_params = self.model.params
        else:
            self.config = BertConfig(
                vocab_size=int(bert_cfg.get("vocab_size", 30522)),
                hidden_size=hidden,
                num_hidden_layers=int(bert_cfg.get("num_hidden_layers", 2)),
                num_attention_heads=int(bert_cfg.get("num_attention_heads", 2)),
                intermediate_size=int(bert_cfg.get("intermediate_size",
                                                   4 * hidden)),
                max_position_embeddings=max(self.seq_len, 512),
            )
            self.model = FlaxBertForMaskedLM(self.config, _do_init=True,
                                             dtype=dtype)
        self.vocab_size = int(self.config.vocab_size)

    # ------------------------------------------------------------------
    def init_params(self, rng: jax.Array):
        if self._pretrained_params is not None:
            # honor model_name_or_path (reference loads pretrained weights,
            # experiments/mlm_bert/model.py:119-123)
            return jax.tree.map(jnp.asarray, self._pretrained_params)
        dummy = jnp.ones((1, self.seq_len), jnp.int32)
        return self.model.module.init(
            {"params": rng, "dropout": rng},
            dummy, jnp.ones_like(dummy), jnp.zeros_like(dummy),
            jnp.broadcast_to(jnp.arange(self.seq_len), (1, self.seq_len)),
            None, deterministic=True, return_dict=False)["params"]

    def _logits(self, params, input_ids, attention_mask, deterministic=True,
                rng=None):
        rngs = {"dropout": rng} if rng is not None else {}
        out = self.model.module.apply(
            {"params": params}, input_ids, attention_mask,
            jnp.zeros_like(input_ids),
            jnp.broadcast_to(jnp.arange(input_ids.shape[-1]),
                             input_ids.shape),
            None, deterministic=deterministic, return_dict=True, rngs=rngs)
        # f32 logits regardless of compute dtype (bf16 matmuls, f32 xent)
        return out.logits.astype(jnp.float32)

    def apply(self, params, input_ids):
        return self._logits(params, input_ids.astype(jnp.int32),
                            jnp.ones_like(input_ids, jnp.int32))

    # ------------------------------------------------------------------
    def _mlm_mask(self, rng, input_ids, attention_mask):
        """HF DataCollatorForLanguageModeling rule: select
        ``mlm_probability`` of real tokens; of those 80% -> [MASK], 10% ->
        random token, 10% -> unchanged; labels = original ids at selected
        positions, -100 elsewhere."""
        r1, r2, r3 = jax.random.split(rng, 3)
        select = (jax.random.uniform(r1, input_ids.shape) <
                  self.mlm_probability) & (attention_mask > 0)
        labels = jnp.where(select, input_ids, -100)
        roll = jax.random.uniform(r2, input_ids.shape)
        masked = jnp.where(select & (roll < 0.8), self.mask_token_id,
                           input_ids)
        random_ids = jax.random.randint(r3, input_ids.shape, 0,
                                        self.vocab_size)
        masked = jnp.where(select & (roll >= 0.8) & (roll < 0.9),
                           random_ids, masked)
        return masked, labels

    def _masked_xent(self, logits, labels):
        """Label-smoothed CE over positions with label != -100 (HF
        LabelSmoother semantics)."""
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        if self.label_smoothing > 0:
            smooth = -jnp.mean(logp, axis=-1)
            nll = (1 - self.label_smoothing) * nll + self.label_smoothing * smooth
        return nll, valid.astype(jnp.float32)

    # ------------------------------------------------------------------
    def _premasked(self, batch: Batch):
        """Pre-masked mode (config ``BERT.model.premasked: true``): the
        blob ships already-masked input ids plus MLM labels under ``y``
        (-100 at unmasked positions) and the collator RNG is bypassed
        entirely — the parity harness uses this to make the BERT family
        deterministic (the reference's
        ``DataCollatorForLanguageModeling`` re-rolls masks per epoch,
        which no cross-framework RNG can match).  The mode is an
        EXPLICIT opt-in: inferring it from the presence of a ``y`` key
        would silently disable dynamic masking for any blob that happens
        to ship labels."""
        if not self.premasked:
            return None
        input_ids = batch["x"].astype(jnp.int32)
        attention_mask = batch.get(
            "attention_mask", (input_ids != 0).astype(jnp.int32))
        attention_mask = (attention_mask
                          * batch["sample_mask"][:, None].astype(
                              attention_mask.dtype)).astype(jnp.int32)
        labels = jnp.where(batch["sample_mask"][:, None] > 0,
                           batch["y"].astype(jnp.int32), -100)
        return input_ids, attention_mask, labels

    def loss(self, params, batch: Batch, rng: Optional[jax.Array] = None,
             train: bool = True):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        mask_rng, drop_rng = jax.random.split(rng)
        pre = self._premasked(batch)
        if pre is not None:
            masked_ids, attention_mask, labels = pre
        else:
            input_ids = batch["x"].astype(jnp.int32)
            attention_mask = batch.get(
                "attention_mask", (input_ids != 0).astype(jnp.int32))
            attention_mask = attention_mask * batch["sample_mask"][:, None] \
                .astype(attention_mask.dtype)
            masked_ids, labels = self._mlm_mask(mask_rng, input_ids,
                                                attention_mask)
        logits = self._logits(params, masked_ids, attention_mask,
                              deterministic=not train,
                              rng=drop_rng if train else None)
        nll, valid = self._masked_xent(logits, labels)
        loss = jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)
        return loss, {
            "sample_count": jnp.sum(batch["sample_mask"]),
            # the reference trainer counts mlm samples as attention
            # POSITIONS, not sequences (core/trainer.py:400-401) — this
            # feeds aggregation weights and the DGA softmax metric
            "train_sample_count": jnp.sum(
                attention_mask.astype(jnp.float32)),
        }

    def eval_stats(self, params, batch: Batch) -> Dict[str, jnp.ndarray]:
        pre = self._premasked(batch)
        if pre is not None:
            masked_ids, attention_mask, labels = pre
        else:
            input_ids = batch["x"].astype(jnp.int32)
            attention_mask = batch.get(
                "attention_mask", (input_ids != 0).astype(jnp.int32))
            attention_mask = attention_mask * batch["sample_mask"][:, None] \
                .astype(attention_mask.dtype)
            # deterministic eval masking so metrics are reproducible
            masked_ids, labels = self._mlm_mask(jax.random.PRNGKey(1234),
                                                input_ids, attention_mask)
        logits = self._logits(params, masked_ids, attention_mask)
        nll, valid = self._masked_xent(logits, labels)
        pred = jnp.argmax(logits, axis=-1)
        correct = (pred == jnp.where(labels == -100, -1, labels)).astype(
            jnp.float32)
        stats = {
            "loss_sum": jnp.sum(nll * valid),
            "correct_sum": jnp.sum(correct * valid),
            "sample_count": jnp.sum(valid),
            "seq_count": jnp.sum(batch["sample_mask"]),
        }
        if pre is not None:
            # reference-compatible accuracy denominator: its ComputeMetrics
            # divides correct masked predictions by ALL B*L positions, not
            # by the masked count (experiments/mlm_bert/utils/
            # trainer_utils.py:86 — `.float().mean()` over the full grid),
            # so masked accuracy is deflated by the masking rate.  The
            # pre-masked path mirrors that so cross-framework numbers align.
            stats["pos_count"] = (jnp.sum(batch["sample_mask"])
                                  * batch["x"].shape[-1])
        return stats

    def finalize_metrics(self, sums):
        metrics = super().finalize_metrics(sums)
        if "pos_count" in sums and float(sums["pos_count"]) > 0:
            from ..utils.metrics import Metric
            metrics["acc"] = Metric(
                float(sums["correct_sum"]) / float(sums["pos_count"]),
                higher_is_better=True)
        return metrics


def make_bert_mlm_task(model_config) -> BertMLMTask:
    return BertMLMTask(model_config)
