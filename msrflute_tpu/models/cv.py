"""Computer-vision tasks: LR (MNIST), CNN (FEMNIST), CIFAR CNN.

Parity targets:
- ``LR`` logistic regression — reference ``experiments/cv_lr_mnist/model.py:23-47``
- ``CNN`` 2conv+2fc — reference ``experiments/cv_cnn_femnist/model.py``
- ``CNN`` CIFAR with custom f1 — reference ``experiments/classif_cnn/model.py:33-62``

All flax.linen, NHWC layouts (TPU conv-friendly), bfloat16-ready matmuls via
jax default precision; parameters stay float32.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils.metrics import Metric
from .base import (BaseTask, Batch, masked_mean, parse_dtype, softmax_xent,
                   to_float_image)


class _LRModule(nn.Module):
    """Logistic regression (reference ``experiments/cv_lr_mnist/model.py:12-21``,
    the FedML ``LogisticRegression``).  ``sigmoid_output=True`` reproduces the
    reference's quirk of passing sigmoid activations (not raw logits) into
    cross-entropy — needed for trajectory-exact cross-framework parity."""

    num_classes: int = 10
    input_dim: int = 784
    dtype: Any = jnp.float32
    sigmoid_output: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = to_float_image(x, self.dtype).reshape((x.shape[0], -1))
        out = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        if self.sigmoid_output:
            out = jax.nn.sigmoid(out)
        return out


class _CNNFEMNISTModule(nn.Module):
    """The FEMNIST benchmark CNN (reference
    ``experiments/cv_cnn_femnist/model.py:12-82``, FedML ``CNN_DropOut``
    recommended by "Adaptive Federated Optimization", arXiv:2003.00295):
    conv3x3x32 VALID -> relu -> conv3x3x64 VALID -> relu -> maxpool2 ->
    dropout(.25) -> flatten(9216) -> fc128 -> relu -> dropout(.5) -> fc62."""

    num_classes: int = 62
    dtype: Any = jnp.float32
    # the reference hardcodes 0.25/0.5; configurable here so the parity
    # harness can run a dropout-free, fully deterministic variant
    drop1: float = 0.25
    drop2: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = to_float_image(x, self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(self.drop1, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dropout(self.drop2, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class _CIFARCNNModule(nn.Module):
    """CIFAR-10 CNN (reference ``experiments/classif_cnn/model.py:33-62``):
    conv3x32 -> conv3x64 -> pool -> conv3x64 -> fc64 -> fc10."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = to_float_image(x, self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), dtype=self.dtype)(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(64, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class ClassificationTask(BaseTask):
    """Generic masked classification task over a flax module."""

    def __init__(self, module: nn.Module, example_shape: Tuple[int, ...],
                 name: str = "classification", num_classes: int = 10,
                 with_f1: bool = False):
        self.module = module
        self.example_shape = example_shape
        self.name = name
        self.num_classes = num_classes
        self.with_f1 = with_f1

    def init_params(self, rng: jax.Array):
        dummy = jnp.zeros((1,) + self.example_shape, dtype=jnp.float32)
        return self.module.init(rng, dummy)["params"]

    def apply(self, params, x, rng: Optional[jax.Array] = None,
              train: bool = False):
        # logits upcast: with a bfloat16 compute dtype the matmuls run on
        # the MXU in bf16, but softmax/xent/metric math stays float32.
        # Dropout needs an rng stream: train mode without one is a caller
        # bug — fail loudly rather than silently dropping dropout (a
        # quiet train/reference divergence; ADVICE r3).
        if train and rng is None:
            raise ValueError(
                f"{self.name}: apply(train=True) requires an rng for the "
                "dropout stream; pass rng= or call with train=False")
        rngs = {"dropout": rng} if train else None
        return self.module.apply({"params": params}, x, train,
                                 rngs=rngs).astype(jnp.float32)

    def predict(self, params, batch: Batch):
        """Concatenatable eval outputs (the reference's
        ``run_validation_generic`` ``output_tot``, ``core/trainer.py:690-723``):
        per-sample logits + predictions, with padded rows labeled -1."""
        logits = self.apply(params, batch["x"])
        pred = jnp.argmax(logits, axis=-1)
        labels = jnp.where(batch["sample_mask"] > 0,
                           batch["y"].astype(jnp.int32), -1)
        return logits, pred, labels

    def loss(self, params, batch: Batch, rng: Optional[jax.Array] = None,
             train: bool = True):
        logits = self.apply(params, batch["x"], rng=rng, train=train)
        labels = batch["y"].astype(jnp.int32)
        per_sample = softmax_xent(logits, labels)
        mask = batch["sample_mask"]
        loss = masked_mean(per_sample, mask)
        aux = {"sample_count": jnp.sum(mask)}
        return loss, aux

    def eval_stats(self, params, batch: Batch) -> Dict[str, jnp.ndarray]:
        logits = self.apply(params, batch["x"])
        labels = batch["y"].astype(jnp.int32)
        mask = batch["sample_mask"]
        per_sample = softmax_xent(logits, labels)
        pred = jnp.argmax(logits, axis=-1)
        correct = (pred == labels).astype(jnp.float32)
        stats = {
            "loss_sum": jnp.sum(per_sample * mask),
            "correct_sum": jnp.sum(correct * mask),
            "sample_count": jnp.sum(mask),
        }
        if self.with_f1:
            # per-class tp/fp/fn sums -> F1 at finalize.  The reference
            # computes sklearn ``f1_score(..., average='micro')`` per
            # batch (experiments/classif_cnn/model.py:55) — micro, not
            # macro; global micro from summed tp/fp/fn equals the
            # reference's sample-weighted batch aggregation exactly
            onehot_true = jax.nn.one_hot(labels, self.num_classes) * mask[..., None]
            onehot_pred = jax.nn.one_hot(pred, self.num_classes) * mask[..., None]
            stats["tp"] = jnp.sum(onehot_true * onehot_pred, axis=0)
            stats["fp"] = jnp.sum((1 - onehot_true) * onehot_pred, axis=0)
            stats["fn"] = jnp.sum(onehot_true * (1 - onehot_pred), axis=0)
        return stats

    def finalize_metrics(self, sums):
        metrics = super().finalize_metrics(sums)
        if self.with_f1 and "tp" in sums:
            tp, fp, fn = (jnp.asarray(sums[k]) for k in ("tp", "fp", "fn"))
            # parity: the reference's f1_score is MICRO
            # (sklearn average='micro', classif_cnn/model.py:55) — from
            # the global sums, 2*sum(tp)/(2*sum(tp)+sum(fp)+sum(fn))
            micro = float(2 * jnp.sum(tp) / jnp.maximum(
                2 * jnp.sum(tp) + jnp.sum(fp) + jnp.sum(fn), 1e-8))
            metrics["f1_score"] = Metric(micro, higher_is_better=True)
            # net-new extra: macro (per-class mean) — the fairness-facing
            # variant micro hides under class imbalance.  sklearn macro
            # averages only classes OBSERVED in labels or predictions
            # (2tp+fp+fn > 0); absent classes are excluded, not scored 0
            denom = 2 * tp + fp + fn
            f1c = 2 * tp / jnp.maximum(denom, 1e-8)
            present = (denom > 0).astype(jnp.float32)
            metrics["f1_macro"] = Metric(
                float(jnp.sum(f1c * present)
                      / jnp.maximum(jnp.sum(present), 1.0)),
                higher_is_better=True)
        return metrics

    def make_dataset(self, blob, model_config, split, data_config=None):
        """Featurize an image/vector user blob (reshapes flat or CHW samples
        to this task's HWC example shape).

        Semisupervision blobs ship per-user dicts with an unlabeled stream
        ``ux`` (reference ``experiments/semisupervision/dataloaders/
        dataset.py``); when ``data_config.augment`` is configured (train
        split only) the augmented view ``ux_rand`` for the FedLabels
        ``uda: 1`` path is produced here with RandAugment — the TPU-design
        analogue of the reference's per-__getitem__ transform.
        """
        import numpy as np
        from ..data.dataset import ArraysDataset
        from ..data.featurize import to_image
        aug_cfg = dict((data_config or {}).get("augment") or {}) \
            if split == "train" else {}
        aug_rng = np.random.default_rng(int(aug_cfg.get("seed", 0)))
        per_user = []
        for i in range(len(blob)):
            label = (blob.user_labels[i] if blob.user_labels is not None
                     else None)
            per_user.append(self.featurize_user(
                blob.user_data[i], label, aug_cfg=aug_cfg, aug_rng=aug_rng))
        return ArraysDataset(blob.user_list, per_user, blob.num_samples)

    def featurize_user(self, data, label, aug_cfg=None, aug_rng=None):
        """Featurize ONE user's raw blob entry — the per-user unit of
        :meth:`make_dataset`, exposed separately so lazy datasets
        (``data/dataset.py::LazyUserDataset``) can featurize on access.
        Augmentation needs a shared rng stream, so lazy callers leave
        ``aug_cfg`` unset."""
        import numpy as np
        from ..data.featurize import to_image
        aug_cfg = aug_cfg or {}
        raw_x = data["x"] if isinstance(data, dict) else data
        x = to_image(np.asarray(raw_x), self.example_shape)
        y = (np.asarray(label).astype(np.int32) if label is not None
             else np.zeros((len(x),), np.int32))
        user = {"x": x, "y": y}
        if isinstance(data, dict) and "ux" in data:
            ux = to_image(np.asarray(data["ux"]), self.example_shape)
            user["ux"] = ux
            if "ux_rand" in data:
                user["ux_rand"] = to_image(np.asarray(data["ux_rand"]),
                                           self.example_shape)
            elif aug_cfg:
                from ..data.augment import rand_augment
                user["ux_rand"] = rand_augment(
                    ux, num_ops=int(aug_cfg.get("num_ops", 2)),
                    magnitude=int(aug_cfg.get("magnitude", 9)),
                    rng=aug_rng)
        return user


def make_lr_task(model_config) -> ClassificationTask:
    num_classes = int(model_config.get("num_classes", 10))
    input_dim = int(model_config.get("input_dim", 784))
    return ClassificationTask(
        _LRModule(num_classes=num_classes, input_dim=input_dim,
                  dtype=parse_dtype(model_config),
                  sigmoid_output=bool(model_config.get("sigmoid_output",
                                                       False))),
        example_shape=(input_dim,), name="cv_lr_mnist", num_classes=num_classes)


def make_cnn_femnist_task(model_config) -> ClassificationTask:
    num_classes = int(model_config.get("num_classes", 62))
    side = int(model_config.get("image_size", 28))
    return ClassificationTask(
        _CNNFEMNISTModule(num_classes=num_classes,
                          dtype=parse_dtype(model_config),
                          drop1=float(model_config.get("dropout1", 0.25)),
                          drop2=float(model_config.get("dropout2", 0.5))),
        example_shape=(side, side, 1), name="cv_cnn_femnist",
        num_classes=num_classes)


def make_cifar_cnn_task(model_config) -> ClassificationTask:
    num_classes = int(model_config.get("num_classes", 10))
    return ClassificationTask(
        _CIFARCNNModule(num_classes=num_classes,
                        dtype=parse_dtype(model_config)),
        example_shape=(32, 32, 3), name="classif_cnn",
        num_classes=num_classes, with_f1=True)
