"""RingLM — long-context causal transformer LM with ring attention.

Net-new vs the reference (FLUTE has no long-context machinery, SURVEY.md
§5.7).  The model is a standard pre-LN causal transformer; its attention
runs in one of two modes:

- **local** (default): full softmax attention — used when the model rides
  the federated round engine (short per-client sequences, clients-axis
  parallelism);
- **sequence-parallel**: :func:`msrflute_tpu.ops.ring_attention.
  ring_self_attention` over a mesh's ``sequence`` axis, optionally combined
  with a data-parallel batch axis — the long-context central-training path
  where one sequence doesn't fit a chip.  O(L/N) activation memory per
  device, N-1 ``ppermute`` rotations per layer.

``build_sp_train_step`` turns a RingLM task into one jitted
loss+grad+optimizer step over a ``(data, sequence)`` mesh.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.moe import MoEFFN
from ..ops.pallas_attention import flash_attention
from ..ops.ring_attention import ring_self_attention
from .base import masked_mean, parse_dtype, softmax_xent
from .nlp import SequenceLMTask, _TokenDatasetMixin


class _MHA(nn.Module):
    heads: int
    head_dim: int
    dtype: Any = jnp.float32
    # sequence-parallel mode: mesh + axis names (None = local attention)
    ring_mesh: Optional[Mesh] = None
    seq_axis: str = "sequence"
    batch_axis: Optional[str] = None
    #: tile attention in VMEM via the Pallas flash kernels
    #: (ops/pallas_attention.py) instead of materializing score matrices.
    #: Local mode: the single-chip long-context lever.  Ring mode: each
    #: rotation's chunk pair runs through the same kernels with position
    #: offsets (ring_flash_attention_local) — the two levers compose.
    use_flash: bool = False
    #: Pallas kernel tiles (``flash_block_q`` x ``flash_block_k``) — the
    #: knobs tools/flash_crossover_sweep.py searches; config-settable so
    #: a sweep's winning tiles apply without code edits.  0 = let the
    #: AOT-cost planner pick (local mode; ring mode needs concrete tiles
    #: and treats 0 as 128)
    flash_block_q: int = 0
    flash_block_k: int = 0

    @nn.compact
    def __call__(self, x):  # [B, L, E]
        B, L, _ = x.shape
        H, D = self.heads, self.head_dim
        qkv = nn.Dense(3 * H * D, use_bias=False, dtype=self.dtype)(x)
        q, k, v = jnp.split(qkv.reshape(B, L, 3 * H, D), 3, axis=2)
        if self.ring_mesh is not None:
            attn = ring_self_attention(q, k, v, self.ring_mesh,
                                       axis=self.seq_axis, causal=True,
                                       batch_axis=self.batch_axis,
                                       use_flash=self.use_flash,
                                       flash_block_q=self.flash_block_q,
                                       flash_block_k=self.flash_block_k)
        elif self.use_flash:
            # block 0 -> None: the dispatch gate prices candidate tiles
            # against dense on the compiled cost model (and may fall
            # back to dense with an attention_fallback_dense event)
            attn = flash_attention(q, k, v, causal=True,
                                   block_q=self.flash_block_q or None,
                                   block_k=self.flash_block_k or None)
        else:
            scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
            scores = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
            mask = jnp.tril(jnp.ones((L, L), bool))
            scores = jnp.where(mask[None, None], scores,
                               jnp.finfo(scores.dtype).min)
            p = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("bhlm,bmhd->blhd", p, v)
        return nn.Dense(x.shape[-1], use_bias=False,
                        dtype=self.dtype)(attn.reshape(B, L, H * D))


class _Block(nn.Module):
    heads: int
    head_dim: int
    mlp_dim: int
    dtype: Any = jnp.float32
    ring_mesh: Optional[Mesh] = None
    seq_axis: str = "sequence"
    batch_axis: Optional[str] = None
    #: >0 replaces the dense MLP with a switch MoE FFN (ops/moe.py);
    #: federated/local mode evaluates experts densely, expert-parallel
    #: dispatch engages when moe_ep_axis names a mesh axis (sp_module)
    moe_experts: int = 0
    moe_ep_axis: Optional[str] = None
    moe_capacity_factor: float = 2.0
    use_flash: bool = False
    flash_block_q: int = 128
    flash_block_k: int = 128

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + _MHA(self.heads, self.head_dim, self.dtype, self.ring_mesh,
                     self.seq_axis, self.batch_axis,
                     use_flash=self.use_flash,
                     flash_block_q=self.flash_block_q,
                     flash_block_k=self.flash_block_k)(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.moe_experts > 0:
            ep_mesh = (self.ring_mesh if self.moe_ep_axis is not None
                       else None)
            return x + MoEFFN(self.moe_experts, self.mlp_dim,
                              dtype=self.dtype, ep_mesh=ep_mesh,
                              expert_axis=self.moe_ep_axis or "expert",
                              capacity_factor=self.moe_capacity_factor,
                              name="moe_ffn")(h)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        return x + nn.Dense(x.shape[-1], dtype=self.dtype)(h)


class _RingLM(nn.Module):
    vocab_size: int = 256
    embed_dim: int = 64
    heads: int = 4
    head_dim: int = 16
    mlp_dim: int = 256
    num_layers: int = 2
    dtype: Any = jnp.float32
    ring_mesh: Optional[Mesh] = None
    seq_axis: str = "sequence"
    batch_axis: Optional[str] = None
    #: per-block rematerialization (jax.checkpoint via nn.remat): backward
    #: recomputes each block's forward instead of keeping its residuals —
    #: O(num_layers) fewer live activations, ~1/3 extra FLOPs.  The right
    #: altitude for remat: wrapping the whole loss would save nothing.
    remat: bool = False
    #: allocation length for the learned positional table.  When set, the
    #: table is allocated at this size and sliced to the input's L, so the
    #: same params serve length-bucketed (cropped) grids — the
    #: ``BaseTask.seq_pad_keys`` contract.  None keeps the legacy
    #: input-sized allocation (then every apply must use one fixed L).
    max_len: Optional[int] = None
    moe_experts: int = 0
    moe_ep_axis: Optional[str] = None
    moe_capacity_factor: float = 2.0
    use_flash: bool = False
    flash_block_q: int = 128
    flash_block_k: int = 128

    @nn.compact
    def __call__(self, x):  # [B, L] int32
        h = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype)(x)
        # additive learned positions, allocated at max_len and sliced to
        # the input length (length-bucketed grids apply with L < max_len;
        # the param shape — and so every checkpoint — is unchanged)
        pos = self.param("pos", nn.initializers.normal(0.02),
                         (self.max_len or x.shape[1], self.embed_dim))
        h = h + pos[:x.shape[1]].astype(self.dtype)[None]
        block_cls = nn.remat(_Block) if self.remat else _Block
        for i in range(self.num_layers):
            # explicit names keep the param tree identical with remat on
            # or off (nn.remat's auto-names would prefix "Checkpoint_");
            # "block_{i}" is the STABLE checkpoint key contract for this
            # family — renaming breaks every saved RingLM checkpoint
            h = block_cls(self.heads, self.head_dim, self.mlp_dim,
                          self.dtype, self.ring_mesh, self.seq_axis,
                          self.batch_axis, self.moe_experts,
                          self.moe_ep_axis, self.moe_capacity_factor,
                          self.use_flash, self.flash_block_q,
                          self.flash_block_k, name=f"block_{i}")(h)
        h = nn.LayerNorm(dtype=self.dtype)(h)
        return nn.Dense(self.vocab_size, dtype=self.dtype)(h)


class RingLMTask(_TokenDatasetMixin, SequenceLMTask):
    """Causal-LM task over the RingLM module (local attention mode — the
    federated engine path).  ``sp_module(mesh)`` clones the module into
    sequence-parallel mode for long-context training.  Blobs featurize as
    char sequences (long-context documents ship as raw text)."""

    tokenizer = "chars"

    #: the raw ``model_config.flash_attention`` value (bool or "auto"),
    #: kept so sequence-parallel cloning can RE-resolve "auto" against the
    #: per-device sequence length — the crossover constant is calibrated
    #: per device, and under SP each shard sees only L/shards tokens
    flash_flag = None

    def sp_module(self, mesh: Mesh, seq_axis: str = "sequence",
                  batch_axis: Optional[str] = None,
                  expert_axis: Optional[str] = None) -> _RingLM:
        """Clone into sequence-parallel mode; ``expert_axis`` additionally
        engages expert-parallel MoE dispatch on that mesh axis (requires
        ``moe_experts == mesh.shape[expert_axis]``)."""
        kwargs = dict(ring_mesh=mesh, seq_axis=seq_axis,
                      batch_axis=batch_axis, moe_ep_axis=expert_axis)
        if isinstance(self.flash_flag, str):
            # "auto" was resolved against the GLOBAL length at task build;
            # under sequence parallelism the kernel runs on per-device
            # blocks of L/shards, which is the length the crossover was
            # measured at — re-resolve so 'auto' cannot pick flash in the
            # regime where dense measured faster
            shards = int(mesh.shape[seq_axis])
            kwargs["use_flash"] = _resolve_flash(
                self.flash_flag, max(self.module.max_len // shards, 1))
        return self.module.clone(**kwargs)


#: dense/flash crossover: below this per-device sequence length XLA's
#: fused dense-softmax attention beats the Pallas kernels on measured
#: fwd+bwd wall time (committed `bench_tpu_longctx.json`: flash_speedup
#: 0.83-0.93 at L=2048); above it flash's O(L) VMEM streaming wins and
#: dense's O(L^2) score materialization eventually cannot fit at all.
#: The constant is STATIC — nothing reads a sweep artifact at runtime; it
#: was chosen from the committed L=2048 measurements and is re-derived by
#: hand from `flash_crossover.json` (tools/flash_crossover_sweep.py)
#: whenever a new sweep lands.
FLASH_AUTO_MIN_LEN = 4096


def _resolve_flash(flag, seq_len: int) -> bool:
    """``flash_attention`` config: bool, or "auto" = flash iff the
    sequence length reaches the measured dense/flash crossover."""
    if isinstance(flag, str):
        if flag.lower() != "auto":
            raise ValueError(
                f"model_config.flash_attention must be bool or 'auto', "
                f"got {flag!r}")
        return seq_len >= FLASH_AUTO_MIN_LEN
    return bool(flag)


def make_ringlm_task(model_config) -> RingLMTask:
    seq_len = int(model_config.get("seq_len", 128))
    module = _RingLM(
        vocab_size=int(model_config.get("vocab_size", 256)),
        embed_dim=int(model_config.get("embed_dim", 64)),
        heads=int(model_config.get("num_heads", 4)),
        head_dim=int(model_config.get("head_dim", 16)),
        mlp_dim=int(model_config.get("mlp_dim", 256)),
        num_layers=int(model_config.get("num_layers", 2)),
        dtype=parse_dtype(model_config),
        remat=bool(model_config.get("remat", False)),
        max_len=seq_len - 1,
        moe_experts=int(model_config.get("moe_experts", 0) or 0),
        use_flash=_resolve_flash(
            model_config.get("flash_attention", False), seq_len - 1),
        flash_block_q=int(model_config.get("flash_block_q", 0) or 0),
        flash_block_k=int(model_config.get("flash_block_k", 0) or 0))
    task = RingLMTask(module, seq_len=seq_len, name="ringlm")
    task.flash_flag = model_config.get("flash_attention", False)
    return task


def build_sp_train_step(task: RingLMTask, mesh: Mesh,
                        learning_rate: float = 1e-3,
                        seq_axis: str = "sequence",
                        batch_axis: Optional[str] = None):
    """One jitted sequence-parallel training step.

    Returns ``(step, init)``: ``init(rng, batch_shape)`` builds replicated
    params + optimizer state; ``step(params, opt_state, tokens)`` shards
    ``tokens [B, L]`` over ``(batch_axis, seq_axis)``, runs loss+grad with
    ring attention (XLA differentiates through the ppermute ring), and
    applies an adam update.  Gradients are summed across the mesh by XLA's
    sharding propagation — no hand-written collectives.
    """
    sp_mod = task.sp_module(mesh, seq_axis=seq_axis, batch_axis=batch_axis)
    tx = optax.adam(learning_rate)
    token_sharding = NamedSharding(mesh, P(batch_axis, seq_axis))
    replicated = NamedSharding(mesh, P())

    def init(rng, seq_len: int):
        # init through the SEQUENCE-PARALLEL module: the local module's
        # full-softmax forward would materialize O(L^2) scores on one
        # device — the very thing this path exists to avoid at long L
        b = mesh.shape[batch_axis] if batch_axis is not None else 1
        dummy = jnp.zeros((b, seq_len - 1), jnp.int32)
        params = sp_mod.init(rng, dummy)["params"]
        params = jax.device_put(params, replicated)
        return params, jax.jit(tx.init, out_shardings=replicated)(params)

    def loss_fn(params, tokens):
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = sp_mod.apply({"params": params},
                              inputs).astype(jnp.float32)
        mask = (targets != 0).astype(jnp.float32)
        return masked_mean(softmax_xent(logits, targets), mask)

    @jax.jit
    def step(params, opt_state, tokens):
        tokens = jax.lax.with_sharding_constraint(tokens, token_sharding)
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step, init
