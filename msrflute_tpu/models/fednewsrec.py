"""FedNewsRec — federated news recommendation (NRMS-style).

Parity target: reference ``experiments/fednewsrec`` (FedNewsRec,
EMNLP-Findings 2020, ported there from TF): a news encoder (word embeddings
-> multi-head self-attention -> attentive pooling) and a user encoder
(self-attention over clicked-news vectors -> attentive pooling), trained
with ``npratio``-negative sampling (softmax over 1 positive + 4 negatives,
``fednewsrec_model.py:5``), evaluated with AUC / MRR / nDCG@5 / nDCG@10
(``model.py:19-51``).

Batch contract (featurized by the MIND-style loader):
- ``clicked``  [B, H, L]  token ids of the user's click history
- ``cands``    [B, C, L]  candidate news token ids (C = 1 + npratio for
  training; padded impression slate for eval)
- ``y``        [B]        index of the positive candidate (train)
- ``labels``   [B, C]     0/1 relevance (eval slates)
- ``cand_mask``[B, C]     real-candidate mask (eval slates)

All ranking metrics are computed *per impression* and summed, so they
aggregate exactly across shards via the engine's psum.
"""

from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils.metrics import Metric
from .base import BaseTask, Batch


class _AttentivePooling(nn.Module):
    """tanh-MLP attention pooling (reference ``AttentivePooling``)."""

    hidden: int = 200

    @nn.compact
    def __call__(self, x, deterministic=True):  # x: [..., T, D]
        att = jnp.tanh(nn.Dense(self.hidden)(x))
        att = nn.Dense(1)(att)[..., 0]
        att = jax.nn.softmax(att, axis=-1)
        return jnp.einsum("...td,...t->...d", x, att)


class _NewsEncoder(nn.Module):
    vocab_size: int
    embed_dim: int = 300
    heads: int = 20
    head_dim: int = 20

    @nn.compact
    def __call__(self, tokens):  # [..., L]
        emb = nn.Embed(self.vocab_size, self.embed_dim)(tokens)
        h = nn.SelfAttention(num_heads=self.heads,
                             qkv_features=self.heads * self.head_dim,
                             out_features=self.heads * self.head_dim,
                             use_bias=False)(emb)
        return _AttentivePooling()(h)


class _UserEncoder(nn.Module):
    heads: int = 20
    head_dim: int = 20

    @nn.compact
    def __call__(self, news_vecs):  # [..., H, D]
        h = nn.SelfAttention(num_heads=self.heads,
                             qkv_features=self.heads * self.head_dim,
                             out_features=self.heads * self.head_dim,
                             use_bias=False)(news_vecs)
        return _AttentivePooling()(h)


class _NRMS(nn.Module):
    vocab_size: int
    embed_dim: int = 300
    heads: int = 20
    head_dim: int = 20

    @nn.compact
    def __call__(self, clicked, cands):
        news_enc = _NewsEncoder(self.vocab_size, self.embed_dim, self.heads,
                                self.head_dim)
        clicked_vecs = news_enc(clicked)         # [B, H, D]
        cand_vecs = news_enc(cands)              # [B, C, D]
        user_vec = _UserEncoder(self.heads, self.head_dim)(clicked_vecs)
        return jnp.einsum("bcd,bd->bc", cand_vecs, user_vec)  # scores


class FedNewsRecTask(BaseTask):

    name = "fednewsrec"

    def __init__(self, model_config):
        self.vocab_size = int(model_config.get("vocab_size", 40000))
        self.seq_len = int(model_config.get("max_title_length", 30))
        self.history = int(model_config.get("max_history", 50))
        self.npratio = int(model_config.get("npratio", 4))
        self.module = _NRMS(
            vocab_size=self.vocab_size,
            embed_dim=int(model_config.get("embed_dim", 300)),
            heads=int(model_config.get("num_heads", 20)),
            head_dim=int(model_config.get("head_dim", 20)))

    def init_params(self, rng: jax.Array):
        clicked = jnp.zeros((1, self.history, self.seq_len), jnp.int32)
        cands = jnp.zeros((1, self.npratio + 1, self.seq_len), jnp.int32)
        return self.module.init(rng, clicked, cands)["params"]

    def _scores(self, params, batch):
        return self.module.apply({"params": params},
                                 batch["clicked"].astype(jnp.int32),
                                 batch["cands"].astype(jnp.int32))

    def loss(self, params, batch: Batch, rng: Optional[jax.Array] = None,
             train: bool = True):
        scores = self._scores(params, batch)
        y = batch["y"].astype(jnp.int32)
        logp = jax.nn.log_softmax(scores, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
        mask = batch["sample_mask"]
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss, {"sample_count": jnp.sum(mask)}

    # -- ranking metrics, one impression at a time ---------------------
    def eval_stats(self, params, batch: Batch) -> Dict[str, jnp.ndarray]:
        scores = self._scores(params, batch)
        labels = batch.get("labels")
        if labels is None:
            labels = jax.nn.one_hot(batch["y"].astype(jnp.int32),
                                    scores.shape[-1])
        labels = labels.astype(jnp.float32)
        cand_mask = batch.get("cand_mask",
                              jnp.ones_like(labels)).astype(jnp.float32)
        mask = batch["sample_mask"]
        neg_inf = jnp.finfo(scores.dtype).min
        masked_scores = jnp.where(cand_mask > 0, scores, neg_inf)

        def per_impression(s, l, cm):
            # rank of each candidate (1 = best) among real candidates
            order = jnp.argsort(-s)
            ranks = jnp.empty_like(order).at[order].set(
                jnp.arange(1, s.shape[0] + 1))
            pos = l * cm
            n_pos = jnp.sum(pos)
            n_neg = jnp.sum((1 - l) * cm)
            # AUC: P(pos ranked above neg) = (sum of neg ranks below each pos)
            pairs = jnp.sum(pos[:, None] * ((1 - l) * cm)[None, :] *
                            (s[:, None] > s[None, :]))
            auc = pairs / jnp.maximum(n_pos * n_neg, 1.0)
            # MRR over positives
            mrr = jnp.sum(pos / ranks) / jnp.maximum(n_pos, 1.0)
            # nDCG@k
            def ndcg(k):
                gains = pos / jnp.log2(ranks + 1.0) * (ranks <= k)
                ideal_ranks = jnp.arange(1, s.shape[0] + 1)
                ideal = jnp.sum((ideal_ranks <= jnp.minimum(n_pos, k)) /
                                jnp.log2(ideal_ranks + 1.0))
                return jnp.sum(gains) / jnp.maximum(ideal, 1e-12)
            valid = (n_pos > 0) & (n_neg > 0)
            return (jnp.where(valid, auc, 0.0),
                    jnp.where(n_pos > 0, mrr, 0.0),
                    jnp.where(n_pos > 0, ndcg(5), 0.0),
                    jnp.where(n_pos > 0, ndcg(10), 0.0),
                    valid.astype(jnp.float32))

        auc, mrr, ndcg5, ndcg10, valid = jax.vmap(per_impression)(
            masked_scores, labels, cand_mask)
        valid = valid * mask
        # loss over slates as well
        logp = jax.nn.log_softmax(masked_scores, axis=-1)
        nll = -jnp.sum(labels * cand_mask * logp, axis=-1) / \
            jnp.maximum(jnp.sum(labels * cand_mask, axis=-1), 1.0)
        return {
            "loss_sum": jnp.sum(nll * mask),
            "auc_sum": jnp.sum(auc * valid),
            "mrr_sum": jnp.sum(mrr * valid),
            "ndcg5_sum": jnp.sum(ndcg5 * valid),
            "ndcg10_sum": jnp.sum(ndcg10 * valid),
            "sample_count": jnp.sum(valid),
        }

    def finalize_metrics(self, sums):
        n = max(float(sums["sample_count"]), 1.0)
        return {
            "loss": Metric(float(sums["loss_sum"]) / n, higher_is_better=False),
            "auc": Metric(float(sums["auc_sum"]) / n),
            "mrr": Metric(float(sums["mrr_sum"]) / n),
            "ndcg@5": Metric(float(sums["ndcg5_sum"]) / n),
            "ndcg@10": Metric(float(sums["ndcg10_sum"]) / n),
        }

    # -- MIND-style featurizer -----------------------------------------
    def _pad_title(self, title) -> "np.ndarray":
        import numpy as np
        ids = np.zeros((self.seq_len,), np.int32)
        toks = np.asarray(title, np.int64).reshape(-1)[:self.seq_len]
        ids[:len(toks)] = np.clip(toks, 0, self.vocab_size - 1)
        return ids

    def _pad_history(self, clicked) -> "np.ndarray":
        import numpy as np
        hist = np.zeros((self.history, self.seq_len), np.int32)
        # most-recent H clicks (reference keeps the trailing window,
        # preprocess_mind.py click-history truncation)
        for j, title in enumerate(list(clicked)[-self.history:]):
            hist[j] = self._pad_title(title)
        return hist

    def make_dataset(self, blob, model_config, split, data_config=None):
        """Featurize a MIND-style user blob into the batch contract above
        (reference ``experiments/fednewsrec/dataloaders/``: per-user click
        histories + impression slates; train samples are npratio-negative
        slates with the positive at a random slot, eval samples are the
        full impression padded to a static candidate count).

        Blob format per user:
        ``{"clicked": [[tok,...], ...],``
        `` "impressions": [{"cands": [[tok,...], ...],``
        ``                  "labels": [0/1, ...]}, ...]}``
        """
        import numpy as np
        from ..data.dataset import ArraysDataset

        dc = data_config or {}
        max_cands = int(dc.get("max_candidates",
                               model_config.get("max_candidates", 20)))
        rng = np.random.default_rng(int(dc.get("seed", 0)))
        users, per_user, counts = [], [], []
        truncated = 0
        for i in range(len(blob)):
            entry = blob.user_data[i]
            if not isinstance(entry, dict) or "impressions" not in entry:
                raise ValueError(
                    "fednewsrec expects MIND-style user dicts with "
                    "'clicked' and 'impressions' (see docstring)")
            hist = self._pad_history(entry.get("clicked", []))
            clicked_rows, cand_rows, y_rows = [], [], []
            label_rows, mask_rows = [], []
            for imp in entry["impressions"]:
                titles = [self._pad_title(t) for t in imp["cands"]]
                labels = np.asarray(imp["labels"], np.int32).reshape(-1)
                if split == "train":
                    pos = np.flatnonzero(labels > 0)
                    neg = np.flatnonzero(labels == 0)
                    if pos.size == 0:
                        continue
                    # one slate per positive: positive + npratio sampled
                    # negatives at a random slot (reference newsample())
                    for p in pos:
                        if neg.size:
                            take = rng.choice(
                                neg, self.npratio,
                                replace=neg.size < self.npratio)
                            slate = [titles[j] for j in take]
                        else:  # all-positive slate: pad-id negatives
                            slate = [np.zeros_like(titles[0])] * self.npratio
                        slot = int(rng.integers(self.npratio + 1))
                        slate.insert(slot, titles[p])
                        clicked_rows.append(hist)
                        cand_rows.append(np.stack(slate))
                        y_rows.append(slot)
                else:
                    keep = np.arange(len(titles))
                    if len(titles) > max_cands:
                        # subsample negatives but NEVER drop positives —
                        # real MIND slates run long (~37 avg) and losing a
                        # positive silently voids the impression's metrics
                        pos_i = np.flatnonzero(labels > 0)[:max_cands]
                        neg_i = np.flatnonzero(labels == 0)
                        neg_i = neg_i[:max_cands - len(pos_i)]
                        keep = np.sort(np.concatenate([pos_i, neg_i]))
                        truncated += 1
                    cands = np.zeros((max_cands, self.seq_len), np.int32)
                    lab = np.zeros((max_cands,), np.float32)
                    msk = np.zeros((max_cands,), np.float32)
                    c = len(keep)
                    cands[:c] = np.stack([titles[j] for j in keep])
                    lab[:c] = labels[keep]
                    msk[:c] = 1.0
                    clicked_rows.append(hist)
                    cand_rows.append(cands)
                    label_rows.append(lab)
                    mask_rows.append(msk)
            if not clicked_rows:
                continue
            user = {"clicked": np.stack(clicked_rows),
                    "cands": np.stack(cand_rows)}
            if split == "train":
                user["y"] = np.asarray(y_rows, np.int32)
            else:
                user["labels"] = np.stack(label_rows)
                user["cand_mask"] = np.stack(mask_rows)
            users.append(blob.user_list[i])
            per_user.append(user)
            counts.append(len(clicked_rows))
        if truncated:
            from ..utils.logging import print_rank
            print_rank(f"fednewsrec {split}: {truncated} impressions longer "
                       f"than max_candidates={max_cands}; negatives "
                       "subsampled (positives kept)")
        return ArraysDataset(users, per_user, counts)


def make_fednewsrec_task(model_config) -> FedNewsRecTask:
    return FedNewsRecTask(model_config)
