"""FedNewsRec — federated news recommendation (NRMS-style).

Parity target: reference ``experiments/fednewsrec`` (FedNewsRec,
EMNLP-Findings 2020, ported there from TF): a news encoder (word embeddings
-> multi-head self-attention -> attentive pooling) and a user encoder
(self-attention over clicked-news vectors -> attentive pooling), trained
with ``npratio``-negative sampling (softmax over 1 positive + 4 negatives,
``fednewsrec_model.py:5``), evaluated with AUC / MRR / nDCG@5 / nDCG@10
(``model.py:19-51``).

Batch contract (featurized by the MIND-style loader):
- ``clicked``  [B, H, L]  token ids of the user's click history
- ``cands``    [B, C, L]  candidate news token ids (C = 1 + npratio for
  training; padded impression slate for eval)
- ``y``        [B]        index of the positive candidate (train)
- ``labels``   [B, C]     0/1 relevance (eval slates)
- ``cand_mask``[B, C]     real-candidate mask (eval slates)

All ranking metrics are computed *per impression* and summed, so they
aggregate exactly across shards via the engine's psum.
"""

from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils.metrics import Metric
from .base import BaseTask, Batch


class _AttentivePooling(nn.Module):
    """tanh-MLP attention pooling (reference ``AttentivePooling``).

    ``dropout > 0`` reproduces the reference's input dropout — and its
    quirk that the weighted sum runs over the DROPPED vectors
    (``fednewsrec_model.py:25-31``), not the raw input."""

    hidden: int = 200
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, deterministic=True):  # x: [..., T, D]
        if self.dropout:
            x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        att = jnp.tanh(nn.Dense(self.hidden)(x))
        att = nn.Dense(1)(att)[..., 0]
        att = jax.nn.softmax(att, axis=-1)
        return jnp.einsum("...td,...t->...d", x, att)


class _NewsEncoder(nn.Module):
    vocab_size: int
    embed_dim: int = 300
    heads: int = 20
    head_dim: int = 20

    @nn.compact
    def __call__(self, tokens):  # [..., L]
        emb = nn.Embed(self.vocab_size, self.embed_dim)(tokens)
        h = nn.SelfAttention(num_heads=self.heads,
                             qkv_features=self.heads * self.head_dim,
                             out_features=self.heads * self.head_dim,
                             use_bias=False)(emb)
        return _AttentivePooling()(h)


class _UserEncoder(nn.Module):
    heads: int = 20
    head_dim: int = 20

    @nn.compact
    def __call__(self, news_vecs):  # [..., H, D]
        h = nn.SelfAttention(num_heads=self.heads,
                             qkv_features=self.heads * self.head_dim,
                             out_features=self.heads * self.head_dim,
                             use_bias=False)(news_vecs)
        return _AttentivePooling()(h)


class _NRMS(nn.Module):
    vocab_size: int
    embed_dim: int = 300
    heads: int = 20
    head_dim: int = 20

    @nn.compact
    def __call__(self, clicked, cands):
        news_enc = _NewsEncoder(self.vocab_size, self.embed_dim, self.heads,
                                self.head_dim)
        clicked_vecs = news_enc(clicked)         # [B, H, D]
        cand_vecs = news_enc(cands)              # [B, C, D]
        user_vec = _UserEncoder(self.heads, self.head_dim)(clicked_vecs)
        return jnp.einsum("bcd,bd->bc", cand_vecs, user_vec)  # scores


# ----------------------------------------------------------------------
# Reference-faithful architecture (``arch: fednewsrec``): the exact net
# the reference ships (``fednewsrec_model.py:316-360`` — the TF port),
# selected per-config; the NRMS default above is the TPU-first
# simplification of the same published model family (no conv phase, flax
# fused attention with output projection).  Faithful pieces:
# conv1d(300->400, k=3, valid) news phase, PROJECTION-LESS multi-head
# attention (``Attention``, ``fednewsrec_model.py:44-108``: per-head
# q/k/v, concat heads, no out-proj), and the dual-path user encoder
# (attention->pool alongside a tail-20 GRU's last output, the two
# stacked and attention-pooled, ``fednewsrec_model.py:208-255``).  The
# word embedding is FROZEN pretrained glove in the reference
# (``from_pretrained(..., freeze=True)``) — here the matrix is a task
# constant applied outside the module, so it is never a trainable leaf.

class _RefAttention(nn.Module):
    """The reference's projection-less multi-head self-attention."""

    heads: int = 20
    head_dim: int = 20

    @nn.compact
    def __call__(self, x):  # [B, T, D]
        od = self.heads * self.head_dim
        B, T = x.shape[0], x.shape[1]

        def split(t):
            return t.reshape(B, T, self.heads,
                             self.head_dim).transpose(0, 2, 1, 3)

        q = split(nn.Dense(od, use_bias=False, name="WQ")(x))
        k = split(nn.Dense(od, use_bias=False, name="WK")(x))
        v = split(nn.Dense(od, use_bias=False, name="WV")(x))
        a = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(self.head_dim, x.dtype))
        a = jax.nn.softmax(a, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
        return o.transpose(0, 2, 1, 3).reshape(B, T, od)


class _RefDocEncoder(nn.Module):
    heads: int = 20
    head_dim: int = 20
    conv_filters: int = 400

    @nn.compact
    def __call__(self, wv, deterministic=True):  # [B, L, E] title words
        # dropout sites mirror the reference exactly: phase1 input,
        # post-relu, post-attention-relu, then the pooling's own input
        # dropout (``fednewsrec_model.py:131-151``)
        drop = lambda t: nn.Dropout(0.2)(t, deterministic=deterministic)
        h = drop(wv)
        h = nn.Conv(self.conv_filters, (3,), padding="VALID",
                    name="conv")(h)
        h = nn.relu(h)
        h = drop(h)
        h = _RefAttention(self.heads, self.head_dim)(h)
        h = nn.relu(h)
        h = drop(h)
        return _AttentivePooling(dropout=0.2)(h,
                                              deterministic=deterministic)


class _RefUserEncoder(nn.Module):
    heads: int = 20
    head_dim: int = 20
    gru_tail: int = 20

    @nn.compact
    def __call__(self, news_vecs, deterministic=True):  # [B, H, D]
        u2 = _RefAttention(self.heads, self.head_dim)(news_vecs)
        u2 = nn.Dropout(0.2)(u2, deterministic=deterministic)
        u2 = _AttentivePooling(dropout=0.2)(u2,
                                            deterministic=deterministic)
        # the GRU path reads the RAW input tail (the reference's
        # dropout1 is commented out, ``fednewsrec_model.py:212-236``)
        tail = news_vecs[:, -self.gru_tail:, :]
        outs = nn.RNN(nn.GRUCell(news_vecs.shape[-1]))(tail)
        u1 = outs[:, -1, :]
        return _AttentivePooling(dropout=0.2)(
            jnp.stack([u1, u2], axis=1), deterministic=deterministic)


class _RefFedNewsRec(nn.Module):
    """Reference ``FedNewsRec.forward`` on pre-embedded word vectors."""

    heads: int = 20
    head_dim: int = 20
    gru_tail: int = 20
    conv_filters: int = 400

    @nn.compact
    def __call__(self, clicked_wv, cand_wv, deterministic=True):
        # clicked_wv [B, H, L, E], cand_wv [B, C, L, E]
        doc = _RefDocEncoder(self.heads, self.head_dim,
                             self.conv_filters)
        B, H, L, E = clicked_wv.shape
        C = cand_wv.shape[1]
        clicked_vecs = doc(clicked_wv.reshape(B * H, L, E),
                           deterministic).reshape(B, H, -1)
        cand_vecs = doc(cand_wv.reshape(B * C, L, E),
                        deterministic).reshape(B, C, -1)
        user_vec = _RefUserEncoder(self.heads, self.head_dim,
                                   self.gru_tail)(clicked_vecs,
                                                  deterministic)
        return jnp.einsum("bcd,bd->bc", cand_vecs, user_vec)


class FedNewsRecTask(BaseTask):

    name = "fednewsrec"

    def __init__(self, model_config):
        self.vocab_size = int(model_config.get("vocab_size", 40000))
        self.seq_len = int(model_config.get("max_title_length", 30))
        self.history = int(model_config.get("max_history", 50))
        self.npratio = int(model_config.get("npratio", 4))
        embed_dim = int(model_config.get("embed_dim", 300))
        heads = int(model_config.get("num_heads", 20))
        head_dim = int(model_config.get("head_dim", 20))
        self.arch = str(model_config.get("arch", "nrms"))
        self._frozen_emb = None
        if self.arch == "fednewsrec":
            # the reference's exact net; the word table is FROZEN glove
            # (``nn.Embedding.from_pretrained(..., freeze=True)``) — an
            # ``embedding_matrix`` config value (ndarray) mirrors the
            # glove load; absent one, a fixed-seed random table stands in
            # (zero-egress environments have no glove file)
            emb = model_config.get("embedding_matrix")
            if emb is None:
                import numpy as _np
                emb = _np.random.default_rng(0).normal(
                    scale=0.1, size=(self.vocab_size, embed_dim))
            self._frozen_emb = jnp.asarray(emb, jnp.float32)
            self.module = _RefFedNewsRec(
                heads=heads, head_dim=head_dim,
                gru_tail=int(model_config.get("gru_tail", 20)),
                conv_filters=int(model_config.get("conv_filters", 400)))
        elif self.arch == "nrms":
            self.module = _NRMS(vocab_size=self.vocab_size,
                                embed_dim=embed_dim, heads=heads,
                                head_dim=head_dim)
        else:
            raise ValueError(
                f"model_config.arch must be 'nrms' or 'fednewsrec', "
                f"got {self.arch!r}")

    def init_params(self, rng: jax.Array):
        if self._frozen_emb is not None:
            E = self._frozen_emb.shape[-1]
            clicked = jnp.zeros((1, self.history, self.seq_len, E))
            cands = jnp.zeros((1, self.npratio + 1, self.seq_len, E))
            return self.module.init(rng, clicked, cands)["params"]
        clicked = jnp.zeros((1, self.history, self.seq_len), jnp.int32)
        cands = jnp.zeros((1, self.npratio + 1, self.seq_len), jnp.int32)
        return self.module.init(rng, clicked, cands)["params"]

    def _scores(self, params, batch, rng=None, train=False):
        clicked = batch["clicked"].astype(jnp.int32)
        cands = batch["cands"].astype(jnp.int32)
        if self._frozen_emb is not None:
            train = bool(train) and rng is not None
            return self.module.apply(
                {"params": params},
                jnp.take(self._frozen_emb, clicked, axis=0),
                jnp.take(self._frozen_emb, cands, axis=0),
                deterministic=not train,
                rngs={"dropout": rng} if train else None)
        return self.module.apply({"params": params}, clicked, cands)

    def loss(self, params, batch: Batch, rng: Optional[jax.Array] = None,
             train: bool = True):
        scores = self._scores(params, batch, rng=rng, train=train)
        y = batch["y"].astype(jnp.int32)
        logp = jax.nn.log_softmax(scores, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
        mask = batch["sample_mask"]
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss, {"sample_count": jnp.sum(mask)}

    # -- ranking metrics, one impression at a time ---------------------
    def eval_stats(self, params, batch: Batch) -> Dict[str, jnp.ndarray]:
        scores = self._scores(params, batch)
        labels = batch.get("labels")
        if labels is None:
            labels = jax.nn.one_hot(batch["y"].astype(jnp.int32),
                                    scores.shape[-1])
        labels = labels.astype(jnp.float32)
        cand_mask = batch.get("cand_mask",
                              jnp.ones_like(labels)).astype(jnp.float32)
        mask = batch["sample_mask"]
        neg_inf = jnp.finfo(scores.dtype).min
        masked_scores = jnp.where(cand_mask > 0, scores, neg_inf)

        def per_impression(s, l, cm):
            # rank of each candidate (1 = best) among real candidates
            order = jnp.argsort(-s)
            ranks = jnp.empty_like(order).at[order].set(
                jnp.arange(1, s.shape[0] + 1))
            pos = l * cm
            n_pos = jnp.sum(pos)
            n_neg = jnp.sum((1 - l) * cm)
            # AUC: P(pos ranked above neg) = (sum of neg ranks below each pos)
            pairs = jnp.sum(pos[:, None] * ((1 - l) * cm)[None, :] *
                            (s[:, None] > s[None, :]))
            auc = pairs / jnp.maximum(n_pos * n_neg, 1.0)
            # MRR over positives
            mrr = jnp.sum(pos / ranks) / jnp.maximum(n_pos, 1.0)
            # nDCG@k
            def ndcg(k):
                gains = pos / jnp.log2(ranks + 1.0) * (ranks <= k)
                ideal_ranks = jnp.arange(1, s.shape[0] + 1)
                ideal = jnp.sum((ideal_ranks <= jnp.minimum(n_pos, k)) /
                                jnp.log2(ideal_ranks + 1.0))
                return jnp.sum(gains) / jnp.maximum(ideal, 1e-12)
            valid = (n_pos > 0) & (n_neg > 0)
            return (jnp.where(valid, auc, 0.0),
                    jnp.where(n_pos > 0, mrr, 0.0),
                    jnp.where(n_pos > 0, ndcg(5), 0.0),
                    jnp.where(n_pos > 0, ndcg(10), 0.0),
                    valid.astype(jnp.float32))

        auc, mrr, ndcg5, ndcg10, valid = jax.vmap(per_impression)(
            masked_scores, labels, cand_mask)
        valid = valid * mask
        # loss over slates as well
        logp = jax.nn.log_softmax(masked_scores, axis=-1)
        nll = -jnp.sum(labels * cand_mask * logp, axis=-1) / \
            jnp.maximum(jnp.sum(labels * cand_mask, axis=-1), 1.0)
        return {
            "loss_sum": jnp.sum(nll * mask),
            "auc_sum": jnp.sum(auc * valid),
            "mrr_sum": jnp.sum(mrr * valid),
            "ndcg5_sum": jnp.sum(ndcg5 * valid),
            "ndcg10_sum": jnp.sum(ndcg10 * valid),
            "sample_count": jnp.sum(valid),
        }

    def finalize_metrics(self, sums):
        n = max(float(sums["sample_count"]), 1.0)
        return {
            "loss": Metric(float(sums["loss_sum"]) / n, higher_is_better=False),
            "auc": Metric(float(sums["auc_sum"]) / n),
            "mrr": Metric(float(sums["mrr_sum"]) / n),
            "ndcg@5": Metric(float(sums["ndcg5_sum"]) / n),
            "ndcg@10": Metric(float(sums["ndcg10_sum"]) / n),
        }

    # -- MIND-style featurizer -----------------------------------------
    def _pad_title(self, title) -> "np.ndarray":
        import numpy as np
        ids = np.zeros((self.seq_len,), np.int32)
        toks = np.asarray(title, np.int64).reshape(-1)[:self.seq_len]
        ids[:len(toks)] = np.clip(toks, 0, self.vocab_size - 1)
        return ids

    def _pad_history(self, clicked) -> "np.ndarray":
        import numpy as np
        hist = np.zeros((self.history, self.seq_len), np.int32)
        # most-recent H clicks, FRONT-padded so the newest click sits at
        # the LAST row (reference ``preprocess_mind.py``:
        # ``click = [0]*(MAX_ALL-len(click)) + click``) — the faithful
        # user encoder's tail-GRU reads the trailing window, so end
        # padding would hand it pad vectors for every short history
        titles = list(clicked)[-self.history:]
        for j, title in enumerate(titles):
            hist[self.history - len(titles) + j] = self._pad_title(title)
        return hist

    def make_dataset(self, blob, model_config, split, data_config=None):
        """Featurize a MIND-style user blob into the batch contract above
        (reference ``experiments/fednewsrec/dataloaders/``: per-user click
        histories + impression slates; train samples are npratio-negative
        slates with the positive at a random slot, eval samples are the
        full impression padded to a static candidate count).

        Blob format per user:
        ``{"clicked": [[tok,...], ...],``
        `` "impressions": [{"cands": [[tok,...], ...],``
        ``                  "labels": [0/1, ...]}, ...]}``
        """
        import numpy as np
        from ..data.dataset import ArraysDataset

        dc = data_config or {}
        max_cands = int(dc.get("max_candidates",
                               model_config.get("max_candidates", 20)))
        rng = np.random.default_rng(int(dc.get("seed", 0)))
        users, per_user, counts = [], [], []
        truncated = 0
        for i in range(len(blob)):
            entry = blob.user_data[i]
            if not isinstance(entry, dict) or "impressions" not in entry:
                raise ValueError(
                    "fednewsrec expects MIND-style user dicts with "
                    "'clicked' and 'impressions' (see docstring)")
            hist = self._pad_history(entry.get("clicked", []))
            clicked_rows, cand_rows, y_rows = [], [], []
            label_rows, mask_rows = [], []
            for imp in entry["impressions"]:
                titles = [self._pad_title(t) for t in imp["cands"]]
                labels = np.asarray(imp["labels"], np.int32).reshape(-1)
                if split == "train":
                    pos = np.flatnonzero(labels > 0)
                    neg = np.flatnonzero(labels == 0)
                    if pos.size == 0:
                        continue
                    # one slate per positive: positive + npratio sampled
                    # negatives at a random slot (reference newsample())
                    for p in pos:
                        if neg.size:
                            take = rng.choice(
                                neg, self.npratio,
                                replace=neg.size < self.npratio)
                            slate = [titles[j] for j in take]
                        else:  # all-positive slate: pad-id negatives
                            slate = [np.zeros_like(titles[0])] * self.npratio
                        slot = int(rng.integers(self.npratio + 1))
                        slate.insert(slot, titles[p])
                        clicked_rows.append(hist)
                        cand_rows.append(np.stack(slate))
                        y_rows.append(slot)
                else:
                    keep = np.arange(len(titles))
                    if len(titles) > max_cands:
                        # subsample negatives but NEVER drop positives —
                        # real MIND slates run long (~37 avg) and losing a
                        # positive silently voids the impression's metrics
                        pos_i = np.flatnonzero(labels > 0)[:max_cands]
                        neg_i = np.flatnonzero(labels == 0)
                        neg_i = neg_i[:max_cands - len(pos_i)]
                        keep = np.sort(np.concatenate([pos_i, neg_i]))
                        truncated += 1
                    cands = np.zeros((max_cands, self.seq_len), np.int32)
                    lab = np.zeros((max_cands,), np.float32)
                    msk = np.zeros((max_cands,), np.float32)
                    c = len(keep)
                    cands[:c] = np.stack([titles[j] for j in keep])
                    lab[:c] = labels[keep]
                    msk[:c] = 1.0
                    clicked_rows.append(hist)
                    cand_rows.append(cands)
                    label_rows.append(lab)
                    mask_rows.append(msk)
            if not clicked_rows:
                continue
            user = {"clicked": np.stack(clicked_rows),
                    "cands": np.stack(cand_rows)}
            if split == "train":
                user["y"] = np.asarray(y_rows, np.int32)
            else:
                user["labels"] = np.stack(label_rows)
                user["cand_mask"] = np.stack(mask_rows)
            users.append(blob.user_list[i])
            per_user.append(user)
            counts.append(len(clicked_rows))
        if truncated:
            from ..utils.logging import print_rank
            print_rank(f"fednewsrec {split}: {truncated} impressions longer "
                       f"than max_candidates={max_cands}; negatives "
                       "subsampled (positives kept)")
        return ArraysDataset(users, per_user, counts)


def make_fednewsrec_task(model_config) -> FedNewsRecTask:
    return FedNewsRecTask(model_config)
