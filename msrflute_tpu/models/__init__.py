from .base import BaseTask  # noqa: F401
from .registry import make_task, register_task, TASK_REGISTRY  # noqa: F401
