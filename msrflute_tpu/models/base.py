"""Task/model contract.

Parity target: reference ``core/model.py:7-51`` — ``BaseModel`` with
``loss(input)``, ``inference(input)`` -> ``{'output', 'acc', 'batch_size'}``
(plus custom metrics as ``{'value', 'higher_is_better'}``), and
``set_train``/``set_eval`` mode toggles.

TPU-native redesign: a task is a bundle of *pure functions* over explicit
params (no mutable module state, no train/eval mode flags — train-ness is an
argument so everything jits):

- ``init_params(rng)``                        -> params pytree
- ``loss(params, batch, rng, train)``         -> (scalar, aux)  masked mean
- ``eval_stats(params, batch)``               -> dict of scalar SUMS
- ``finalize_metrics(sums)``                  -> {name: Metric}

``batch`` is a dict of arrays with leading batch axis plus ``sample_mask``;
every reduction must be mask-weighted so padded samples are invisible.
``eval_stats`` returns *sums* (not means) so the engine can ``psum`` them
across devices and finalize once — this reproduces the reference's
sample-weighted metric merge (``core/evaluation.py:160-183``) exactly while
staying associative.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.metrics import Metric, MetricsDict

Params = Any
Batch = Dict[str, jnp.ndarray]


class BaseTask:
    """Abstract task: model + loss + metrics, all pure."""

    name: str = "base"
    #: feature keys holding 0-padded ``[..., L]`` token sequences whose tail
    #: padding may be cropped per round (``data.batching.seq_length_bucket``);
    #: the model must derive its position mask from the ids, never from L
    seq_pad_keys: Tuple[str, ...] = ()

    def init_params(self, rng: jax.Array) -> Params:
        raise NotImplementedError

    def loss(self, params: Params, batch: Batch, rng: Optional[jax.Array] = None,
             train: bool = True) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        """Masked mean loss over the batch + aux stats (e.g. sample count)."""
        raise NotImplementedError

    def eval_stats(self, params: Params, batch: Batch) -> Dict[str, jnp.ndarray]:
        """Scalar *sums* for evaluation; must include ``loss_sum`` and
        ``sample_count``."""
        raise NotImplementedError

    def finalize_metrics(self, sums: Dict[str, jnp.ndarray]) -> MetricsDict:
        """Turn psum'd eval sums into the reference metric dict
        (``{'value','higher_is_better'}``, ``core/metrics.py:35-56``)."""
        n = max(float(sums["sample_count"]), 1.0)
        metrics = {"loss": Metric(float(sums["loss_sum"]) / n, higher_is_better=False)}
        if "correct_sum" in sums:
            metrics["acc"] = Metric(float(sums["correct_sum"]) / n, higher_is_better=True)
        return metrics


def to_float_image(x: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """Cast image batches to float; uint8 pixels normalize to [0, 1] so hosts
    can ship raw bytes (4x less transfer) and normalization fuses on-device."""
    if x.dtype == jnp.uint8:
        return x.astype(dtype) * (1.0 / 255.0)
    return x.astype(dtype)


def parse_dtype(model_config):
    """``model_config.dtype`` -> jnp dtype for activations/compute.

    TPU-native knob with no reference equivalent: ``bfloat16`` runs the
    matmuls/convs on the MXU at full rate while parameters (and the
    loss/metric math, which tasks upcast) stay float32 — the standard
    mixed-precision recipe.
    """
    name = str(model_config.get("dtype", "float32") or "float32").lower()
    table = {"float32": jnp.float32, "f32": jnp.float32,
             "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
             "float16": jnp.float16, "f16": jnp.float16}
    if name not in table:
        raise ValueError(f"model_config.dtype={name!r}; "
                         f"expected one of {sorted(table)}")
    return table[name]


def masked_mean(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Mean over real samples only; padded entries contribute nothing."""
    total = jnp.sum(values * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


def softmax_xent(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-sample cross entropy with integer labels."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
