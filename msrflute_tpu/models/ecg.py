"""ECG heartbeat classifier: CNN + LSTM + attention.

Parity target: reference ``experiments/ecg_cnn/model.py`` (polomarco's
Kaggle CNN-LSTM-attention architecture adapted to FLUTE): two ConvNormPool
stacks (1D conv k=5, norm, swish, causal pads, conv1+conv3 skip, maxpool-2),
an LSTM over the pooled feature map with the channel axis as time, an
attention mix ``tanh(W [h;c]) @ outputs``, adaptive max-pool and a dense
head.

Divergences (deliberate, documented):
- GroupNorm instead of BatchNorm (the reference exposes
  ``norm_type='group'`` as an option; GN has no cross-client running stats,
  which is both more correct for FL and vmap-safe).
- The reference applies ``F.softmax`` *before* ``F.cross_entropy``
  (``model.py:151-158``) — a double-softmax; we feed logits to the loss.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .cv import ClassificationTask


def _swish(x):
    return x * nn.sigmoid(x)


def _gn():
    # epsilon matches the reference's torch GroupNorm default (1e-5) so
    # weight-transplant forward comparisons are exact
    return nn.GroupNorm(num_groups=8, epsilon=1e-5)


class _ConvNormPool(nn.Module):
    hidden: int
    kernel: int = 5

    @nn.compact
    def __call__(self, x, train: bool = False):  # x: [B, L, C]
        pad = self.kernel - 1
        conv1 = nn.Conv(self.hidden, (self.kernel,), padding="VALID")(x)
        y = _gn()(conv1)
        y = _swish(y)
        y = jnp.pad(y, ((0, 0), (pad, 0), (0, 0)))
        y = nn.Conv(self.hidden, (self.kernel,), padding="VALID")(y)
        y = _gn()(y)
        y = _swish(y)
        y = jnp.pad(y, ((0, 0), (pad, 0), (0, 0)))
        conv3 = nn.Conv(self.hidden, (self.kernel,), padding="VALID")(y)
        y = _gn()(conv1[:, :conv3.shape[1]] + conv3)
        y = _swish(y)
        y = jnp.pad(y, ((0, 0), (pad, 0), (0, 0)))
        # maxpool k=2 stride 2
        return nn.max_pool(y, (2,), strides=(2,))


class _ECGNet(nn.Module):
    hidden: int = 64
    num_classes: int = 5
    kernel: int = 5

    @nn.compact
    def __call__(self, x, train: bool = False):  # x: [B, L] or [B, L, 1]
        if x.ndim == 2:
            x = x[..., None]
        x = x.astype(jnp.float32)
        x = _ConvNormPool(self.hidden, self.kernel)(x)
        x = _ConvNormPool(self.hidden, self.kernel)(x)
        # reference treats channels as LSTM time axis (model.py:139-146):
        # [B, L', H] -> transpose -> steps over H features of length L'
        x = jnp.swapaxes(x, 1, 2)  # [B, H, L']
        outs = nn.RNN(nn.OptimizedLSTMCell(self.hidden),
                      return_carry=True)(x)
        (c_fin, h_fin), outputs = outs
        hc = jnp.concatenate([h_fin[:, None, :], c_fin[:, None, :]], axis=1)
        attn = jnp.tanh(nn.Dense(self.hidden, use_bias=False)(hc))  # [B,2,H]
        mixed = attn @ outputs  # [B,2,H] @ [B,T,H] with T==H -> [B,2,H]
        # reference: transpose then AdaptiveMaxPool1d(1) == max over the two
        # attention rows (model.py:146-150)
        feat = jnp.max(mixed, axis=1)  # [B, H]
        return nn.Dense(self.num_classes)(feat)


def make_ecg_task(model_config) -> ClassificationTask:
    num_classes = int(model_config.get("num_classes", 5))
    seq_len = int(model_config.get("num_frames", 187))
    module = _ECGNet(hidden=int(model_config.get("hidden_dim", 64)),
                     num_classes=num_classes)
    return ClassificationTask(module, example_shape=(seq_len,),
                              name="ecg_cnn", num_classes=num_classes)
