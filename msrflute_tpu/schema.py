"""Config schema validation for msrflute_tpu.

Parity target: reference ``core/schema.py`` (a 299-line cerberus schema dict
loaded with ``eval`` at ``core/config.py:766-769``).  We validate the same
classes of constraint with a small hand-rolled checker:

- required sections and keys;
- enum values (optimizer types per ``core/schema.py:90``, annealing types
  per ``utils/utils.py:151-186``, strategies per
  ``core/strategies/__init__.py:9-23``);
- **unknown-key detection**: cerberus rejects keys outside the schema; we do
  the same for every structured section, with a did-you-mean suggestion, so
  a typo'd ``initial_lr_clients:`` fails loudly instead of silently falling
  back to the default.  Free-form surfaces (``model_config`` plugin params,
  ``semisupervision``, ``augment``, ``mesh_config``) stay open by design.
- an **applied-defaults report** (:func:`applied_defaults`) mirroring the
  reference's printout of the diff between the user config and the config
  with defaults applied (``core/config.py:771-779``).

Raises :class:`SchemaError` with every violation collected, like cerberus
reports all errors at once.  ``strict=False`` (or env
``MSRFLUTE_ALLOW_UNKNOWN=1``) downgrades unknown-key errors to warnings for
forward-compat with configs written for newer versions.
"""

from __future__ import annotations

import difflib
import os
import warnings
from typing import Any, Dict, Iterable, List, Optional

ALLOWED_OPTIMIZERS = [
    # reference core/schema.py:90
    "sgd", "adam", "adamax", "lars", "LarsSGD", "lamb", "adamW",
    # accepted aliases
    "adamw", "larssgd",
    # net-new: FedYogi server optimizer (arXiv:2003.00295)
    "yogi",
]

ALLOWED_ANNEALING = [
    # reference utils/utils.py:151-186
    "step_lr", "multi_step_lr", "rampup-keep-expdecay-keep", "val_loss",
    # alias
    "constant",
]

ALLOWED_STRATEGIES = [
    # reference core/strategies/__init__.py:9-23
    "dga", "DGA", "fedavg", "FedAvg", "fedprox", "FedProx",
    "fedlabels", "FedLabels", "fedac", "FedAC", "scaffold", "Scaffold",
    # net-new: q-FFL fairness weighting (arXiv:1905.10497)
    "qffl", "QFFL",
    # net-new: secure aggregation simulation (Bonawitz et al., CCS'17)
    "secure_agg", "secagg", "SecureAgg",
    # net-new: error-feedback quantization (arXiv:1901.09847)
    "ef_quant", "efquant", "EFQuant",
    # net-new: buffered async aggregation (arXiv:2106.06639)
    "fedbuff", "FedBuff",
]

ALLOWED_SERVER_TYPES = [
    # reference core/server.py:581-597
    "optimization", "model_optimization", "personalization",
]

# ----------------------------------------------------------------------
# known keys per structured section.  Sources: the dataclass fields in
# config.py plus every documented TPU-native extension key the engine
# consumes (grep ``.get("<key>")`` over msrflute_tpu/).
# ----------------------------------------------------------------------
OPTIMIZER_KEYS = {
    "type", "lr", "momentum", "nesterov", "weight_decay", "amsgrad", "eps",
    "betas", "dampening",
}

ANNEALING_KEYS = {
    "type", "step_interval", "step_size", "gamma", "milestones", "patience",
    "factor", "peak_lr", "floor_lr", "rampup_steps", "hold_steps",
    "decay_steps",
}

DATASET_KEYS = {
    # reference per-split blocks
    "batch_size", "loader_type", "list_of_train_data", "test_data",
    "val_data", "train_data", "train_data_server", "vocab_dict",
    "pin_memory", "num_workers", "prefetch_factor", "desired_max_samples",
    "max_batch_size", "max_num_words", "max_seq_length",
    "min_words_per_utt", "num_frames", "max_samples_per_user",
    "max_grad_norm", "utterance_mvn", "unsorted_batch",
    # TPU-native extensions
    "device_resident", "lazy", "lazy_cache_users", "augment", "wantLogits",
    "step_bucketing", "length_bucketing", "per_user_stats",
}

DATACONFIG_KEYS = {"train", "val", "test", "num_clients"}

DP_KEYS = {
    "enable_local_dp", "enable_global_dp", "eps", "delta", "max_grad",
    "max_weight", "min_weight", "weight_scaler", "global_sigma",
    # reference extras (extensions/privacy/__init__.py)
    "enable_prod", "max_bound", "min_bound",
    # TPU-native: quantile-tracking adaptive clipping (arXiv:1905.03871)
    "adaptive_clipping",
}

ADAPTIVE_CLIP_KEYS = {
    "target_quantile", "clip_lr", "initial_clip", "count_sigma",
}

PRIVACY_METRICS_KEYS = {
    "apply_metrics", "apply_indices_extraction", "allowed_word_rank",
    "apply_leakage_metric", "max_leakage", "max_allowed_leakage",
    "adaptive_leakage_threshold", "is_leakage_weighted",
    "attacker_optimizer_config", "max_allowed_overlap",
}

SERVER_REPLAY_KEYS = {"server_iterations", "optimizer_config", "data_config"}

CHAOS_KEYS = {
    "enable", "seed", "dropout_rate", "straggler_rate",
    "straggler_inflation", "ckpt_io_error_rate", "preempt_at_round",
    # adversarial update-corruption streams (fluteshield's attack half,
    # resilience/chaos.py corrupt_modes)
    "corrupt_nan_rate", "corrupt_scale_rate", "corrupt_sign_flip_rate",
    "corrupt_scale_factor", "corrupt_sign_flip_scale",
    # flutearmor's infrastructure fault plane (nested mapping,
    # CHAOS_INFRA_KEYS / resilience/chaos.py InfraFaults)
    "infra",
}

#: ``server_config.chaos.infra`` — seeded host-service fault streams
#: (flutearmor): each knob arms one surface's call-indexed stream
CHAOS_INFRA_KEYS = {
    "store_write_error_rate", "store_read_error_rate",
    "prefetch_error_rate", "prefetch_delay_rate", "prefetch_delay_s",
    "writer_error_rate", "writeback_error_rate",
}

ROBUST_KEYS = {
    "enable", "screen_nonfinite", "norm_multiplier", "aggregator",
    "trim_fraction",
}

# mirrors strategies/secure_agg.py SECURE_AGG_KEYS (schema_drift keeps
# the docs table in sync): a misspelled masking knob silently running
# the defaults is the quiet failure this schema exists to prevent
SECURE_AGG_KEYS = {
    "frac_bits", "clip", "seed", "graph", "min_survivors",
}

SECURE_AGG_FIELD_SPECS = {
    "frac_bits": ("int", 1, 24),
    "clip": ("number", None, None),
    "seed": ("int", None, None),
    "min_survivors": ("int", 0, None),
}

COHORT_BUCKETING_KEYS = {
    "enable", "max_buckets", "boundaries", "slack",
}

COHORT_BUCKETING_FIELD_SPECS = {
    "enable": ("bool", None, None),
    # distinct compiled bucket grids the run may hold (1 == monolithic
    # shape discipline); the recompile sentinel + bench A/B gate closure
    "max_buckets": ("int", 1, None),
    # per-bucket capacity headroom over the expected cohort mix: lower
    # = tighter grids (better padding efficiency) but more spill-up and
    # occasional extra top-bucket grids; < 1 would under-provision the
    # EXPECTED occupancy and spill every round
    "slack": ("num", 1.0, None),
    # `boundaries` (explicit step-bucket S values) keeps a bespoke check
    # in validate(): a strictly-increasing positive-int LIST is a shape
    # the scalar spec table cannot express
}

MEGABATCH_KEYS = {
    "enable", "lanes", "slack", "min_gain", "autotune",
}

MEGABATCH_FIELD_SPECS = {
    "enable": ("bool", None, None),
    # explicit lane count applied to EVERY bucket's super-batch tape
    # (power users / A-Bs); absent = auto-sized per bucket from the
    # population's expected tape occupancy
    "lanes": ("int", 1, None),
    # lane-capacity headroom over the expected per-round tape entries:
    # lower = tighter tapes (better utilization) but more same-shape
    # overflow grids when sampling runs hot
    "slack": ("num", 1.0, None),
    # analytic-gate margin: the megabatch arm must price at least this
    # fraction cheaper (in padded sample slots) than per-client vmap
    # before a bucket repacks — covers the per-step gather/reset
    # overhead the slot count cannot see
    "min_gain": ("num", 0.0, None),
    # price both arms with telemetry.xla aot cost analyses at first
    # dispatch (when the xla introspector is on) instead of trusting
    # the slot heuristic; the loser falls back loudly
    # (`megabatch_fallback` instant event)
    "autotune": ("bool", None, None),
}

FLEET_KEYS = {
    "enable", "page_pool_slots", "host_cache_rows", "spill_freq",
    "sampling", "prefetch",
}

#: fleet cohort-draw vocabulary (data/fleet.py sample_cohort):
#: `uniform` = numpy Generator.choice (O(cohort) via Floyd's algorithm,
#: trail-identical to the non-fleet path); `floyd` = the explicit Floyd
#: implementation; `by_samples` = sample-count-weighted reservoir —
#: the latter two start new rng trails
ALLOWED_FLEET_SAMPLING = ["uniform", "floyd", "by_samples"]

FLEET_FIELD_SPECS = {
    "enable": ("bool", None, None),
    # device page-pool rows per carry table (HBM = slots x row bytes,
    # independent of population); must cover (pipeline_depth + 1)
    # in-flight cohorts or dispatch refuses — default auto-sizes from
    # the cohort geometry
    "page_pool_slots": ("int", 1, None),
    # host RAM rows before LRU spill-through to the durable .npz store
    "host_cache_rows": ("int", 1, None),
    # rounds between durable spill + round-marker commits (the
    # scaffold_flush_freq tradeoff: > 1 amortizes disk IO, a stop
    # inside the window resets carry rows on resume)
    "spill_freq": ("int", 1, None),
    # stage the next chunk's missing carry rows on the fleet-prefetch
    # worker thread while the current chunk executes (bit-identical to
    # the cold path; default on — off only for the prefetch A/B)
    "prefetch": ("bool", None, None),
    # `sampling` keeps a bespoke enum check in validate()
}

MEGAKERNEL_KEYS = {
    "enable", "fused_epochs", "pallas_apply",
}

MEGAKERNEL_FIELD_SPECS = {
    "enable": ("bool", None, None),
    # epoch/step loop fusion (default ON, block absent or not): one
    # lax.scan over the flattened [num_epochs * steps] grid — program
    # size and compile time stay flat in num_epochs
    "fused_epochs": ("bool", None, None),
    # opt-in pallas fused SGD apply over the flattened param vector
    # (plain-SGD client optimizers only; TPU-targeted)
    "pallas_apply": ("bool", None, None),
}

# mirrors traffic/schedule.py _SCHEDULE_KEYS + the trace knobs consumed
# by traffic/traces.py make_trace (schema_drift keeps the docs table in
# sync): a misspelled arrival knob silently running the Poisson defaults
# is the quiet failure this schema exists to prevent
TRAFFIC_KEYS = {
    "enable", "mode", "seed", "buffer_size", "duration_lo",
    "duration_hi", "max_idle_ticks", "target_accuracy",
    # trace selection + per-trace knobs (traffic/traces.py)
    "trace", "rate", "period", "depth", "burst_rate", "burst_every",
    "burst_len", "classes",
}

#: arrival-plane mode vocabulary (traffic/schedule.py TRAFFIC_MODES):
#: `buffered` = FedBuff-style async firing with true traced staleness;
#: `sync` = the barrier baseline (stale deliveries discarded, counted)
ALLOWED_TRAFFIC_MODES = ["sync", "buffered"]

#: trace catalogue (traffic/traces.py TRACE_NAMES)
ALLOWED_TRAFFIC_TRACES = ["poisson", "diurnal", "bursty",
                          "device_classes"]

TRAFFIC_FIELD_SPECS = {
    "enable": ("bool", None, None),
    "seed": ("int", None, None),
    # arrivals needed to fire a round — must equal the run's (fixed)
    # num_clients_per_iteration: the fused [K, S, B] grid is compiled
    # for exactly K client slots, so the buffer IS the cohort (the
    # server refuses a mismatch at construction)
    "buffer_size": ("int", 1, None),
    # training-duration draw bounds, in ticks (per-class duration_scale
    # multiplies on top for device_classes)
    "duration_lo": ("int", 1, None),
    "duration_hi": ("int", 1, None),
    # starvation tripwire: ticks without a fire before the schedule
    # raises instead of spinning forever on an undersubscribed trace
    "max_idle_ticks": ("int", 1, None),
    # bench.py rounds_to_target_accuracy threshold (traffic_ab arm)
    "target_accuracy": ("num", 0.0, 1.0),
    # mean arrivals per tick across the population (trace-specific
    # baseline; bursty's off-burst floor)
    "rate": ("num", 0.0, None),
    # diurnal / device_classes cycle length, ticks
    "period": ("int", 1, None),
    # diurnal modulation depth: 0 = flat, 1 = full swing through zero
    "depth": ("num", 0.0, None),
    # bursty flash-crowd knobs: in-burst rate + burst geometry
    "burst_rate": ("num", 0.0, None),
    "burst_every": ("int", 1, None),
    "burst_len": ("int", 1, None),
    # `mode`/`trace` keep enum checks in validate(); `classes` (a list
    # of per-class mappings) keeps a bespoke check — the scalar spec
    # table cannot express it
}

PRECISION_KEYS = {
    "enable", "params", "compute", "stats",
}

#: precision-policy dtype vocabulary (engine/client_update.py): each
#: entry defaults to float32, the bit-identity spelling of "absent"
ALLOWED_PRECISION_DTYPES = ["float32", "bfloat16", "float16"]

#: robust aggregator vocabulary (mirrors robust.shield.AGGREGATORS)
ALLOWED_ROBUST_AGGREGATORS = ["mean", "trimmed_mean", "median"]

ROBUST_FIELD_SPECS = {
    "enable": ("bool", None, None),
    "screen_nonfinite": ("bool", None, None),
    # scales the cohort's median payload norm; 0 disables the norm
    # screen.  The (0, 1) gap is rejected by a bespoke check in
    # validate() — the inclusive range table cannot express {0} ∪ [1,∞)
    "norm_multiplier": ("num", 0.0, None),
    # per-side trim; == 0.5 (nothing left to average) is rejected by a
    # bespoke check in validate() — the range table is inclusive
    "trim_fraction": ("num", 0.0, 0.5),
}

CHECKPOINT_RETRY_KEYS = {
    "retries", "backoff_base_s", "backoff_max_s", "jitter",
    "escalation_threshold",
}

TELEMETRY_KEYS = {
    "enable", "trace", "devbus", "profile_rounds", "watchdog",
    "xla", "scorecard",
    # endurance layer (ISSUE 13): windowed rollups, flight recorder,
    # size-capped log rotation
    "rollup", "rollup_window", "flight", "flight_events", "max_log_mb",
}

WATCHDOG_KEYS = {
    "nan_loss", "round_time_action", "round_time_factor",
    "round_time_window", "ckpt_failure_action", "ckpt_failure_streak",
    "quarantine_rate_action", "quarantine_rate_threshold",
    "recompile_storm_action", "recompile_storm_threshold",
    "recompile_storm_warmup_rounds",
    # longitudinal detectors (ISSUE 13)
    "stall_action", "stall_factor", "stall_poll_secs",
    "stall_grace_secs", "rss_leak_action", "rss_leak_window",
    "rss_leak_mb_per_round", "throughput_drift_action",
    "throughput_drift_window", "throughput_drift_factor",
}

TELEMETRY_FIELD_SPECS = {
    "enable": ("bool", None, None),
    "trace": ("bool", None, None),
    "devbus": ("bool", None, None),
    # device-truth layer (telemetry/xla.py): compiled cost/memory
    # capture + recompile sentinel + live MFU
    "xla": ("bool", None, None),
    # compact per-run regression surface (telemetry/scorecard.json)
    "scorecard": ("bool", None, None),
    # endurance rollups (telemetry/rollup.py): one rollups.jsonl record
    # per rollup_window rounds, O(window) host memory
    "rollup": ("bool", None, None),
    "rollup_window": ("int", 1, None),
    # flight recorder: ring of the last flight_events structured events
    # persisted as flight.json on abort/preemption/exception
    "flight": ("bool", None, None),
    "flight_events": ("int", 8, None),
    # size-capped metrics.jsonl/events.jsonl rotation (MB; 0 = off)
    "max_log_mb": ("num", 0, None),
    # profile_rounds keeps a bespoke check in validate(): int | "lo:hi"
    # | [lo, hi] is a union type the scalar spec table cannot express
}

WATCHDOG_FIELD_SPECS = {
    # a slowdown factor < 1 would flag every round faster than median
    "round_time_factor": ("num", 1.0, None),
    "round_time_window": ("int", 4, None),
    "ckpt_failure_streak": ("int", 1, None),
    # fluteshield: fraction of the live cohort quarantined in one round
    "quarantine_rate_threshold": ("num", 0.0, 1.0),
    # recompile sentinel storm: fire after this many recompile events
    # past the warmup rounds (a steady-state loop recompiles ZERO times)
    "recompile_storm_threshold": ("int", 1, None),
    "recompile_storm_warmup_rounds": ("int", 0, None),
    # stall: no round-completion heartbeat within
    # max(stall_factor x trailing-median round time, stall_grace_secs)
    "stall_factor": ("num", 1.0, None),
    "stall_poll_secs": ("num", 0.01, None),
    "stall_grace_secs": ("num", 0.0, None),
    # rss_leak: least-squares host-RSS slope over a trailing window
    "rss_leak_window": ("int", 4, None),
    "rss_leak_mb_per_round": ("num", 0.0, None),
    # throughput_drift: trailing-median secs/round vs the anchor window
    "throughput_drift_window": ("int", 4, None),
    "throughput_drift_factor": ("num", 1.0, None),
}

#: watchdog detector actions (telemetry/watchdog.py ACTIONS)
ALLOWED_WATCHDOG_ACTIONS = ["off", "log", "mark", "abort"]

#: documented upper bound on ``server_config.pipeline_depth`` (the ring
#: of in-flight dispatched-but-undrained round chunks): each slot holds
#: a full set of staged round inputs + a packed-stats output buffer in
#: HBM, and past the point where the host tail is fully hidden extra
#: depth only adds memory and preemption-drain latency.  Validation
#: REFUSES larger values (the PR-1 silent clamp is gone).
MAX_PIPELINE_DEPTH = 8

CHAOS_FIELD_SPECS = {
    "enable": ("bool", None, None),
    "seed": ("int", 0, None),
    "dropout_rate": ("num", 0.0, 1.0),
    "straggler_rate": ("num", 0.0, 1.0),
    # divides the steps a straggler completes before the round barrier
    "straggler_inflation": ("num", 1.0, None),
    "ckpt_io_error_rate": ("num", 0.0, 1.0),
    "preempt_at_round": ("int", 0, None),
    "corrupt_nan_rate": ("num", 0.0, 1.0),
    "corrupt_scale_rate": ("num", 0.0, 1.0),
    "corrupt_sign_flip_rate": ("num", 0.0, 1.0),
    # the multiplier a scaling attacker applies (also useful < 1 to
    # rehearse shrink attacks); strictly positive
    "corrupt_scale_factor": ("num", 0.0, None),
    "corrupt_sign_flip_scale": ("num", 0.0, None),
}

CHAOS_INFRA_FIELD_SPECS = {
    "store_write_error_rate": ("num", 0.0, 1.0),
    "store_read_error_rate": ("num", 0.0, 1.0),
    "prefetch_error_rate": ("num", 0.0, 1.0),
    "prefetch_delay_rate": ("num", 0.0, 1.0),
    # seconds a delayed prefetch staging stalls (superseded-generation
    # drill); any non-negative duration
    "prefetch_delay_s": ("num", 0.0, None),
    "writer_error_rate": ("num", 0.0, 1.0),
    "writeback_error_rate": ("num", 0.0, 1.0),
}

CHECKPOINT_RETRY_FIELD_SPECS = {
    "retries": ("int", 1, None),
    "backoff_base_s": ("num", 0, None),
    "backoff_max_s": ("num", 0, None),
    "jitter": ("num", 0, 1.0),
    "escalation_threshold": ("int", 1, None),
}

RL_KEYS = {
    "marginal_update_RL", "RL_path", "RL_path_global", "model_descriptor_RL",
    "network_params", "initial_epsilon", "final_epsilon", "epsilon_gamma",
    "max_replay_memory_size", "minibatch_size", "gamma", "optimizer_config",
    "annealing_config", "wantLSTM", "runningAvg_param", "resume_from_checkpoint",
}

SERVER_KEYS = {
    "type", "max_iteration", "num_clients_per_iteration", "initial_lr_client",
    "lr_decay_factor", "val_freq", "rec_freq", "initial_val", "initial_rec",
    "best_model_criterion", "fall_back_to_best_model", "model_backup_freq",
    "resume_from_checkpoint", "send_dicts", "max_grad_norm", "do_profiling",
    "wantRL", "aggregate_median", "softmax_beta", "initial_lr",
    "weight_train_loss", "stale_prob", "num_skip_decoding", "data_config",
    "optimizer_config", "annealing_config", "server_replay_config", "RL",
    "nbest_task_scheduler", "best_model_metric",
    # TPU-native extensions
    # pipeline_depth: overlapped host/device round pipeline (0 = serial
    # loop, 1 = default: drain round k's host tail — stats decode, metric
    # logging, privacy processing, checkpoint submit — while the device
    # executes round k+1).  Bit-identical params/metrics either way
    # (tests/test_server_pipeline.py); host-orchestrated paths (wantRL,
    # scaffold/ef strategies, server replay, personalization) and the
    # adaptive leakage threshold fall back to serial automatically.  Set
    # 0 to debug host-tail timing or to keep the per-round `latest`
    # checkpoint synchronous (pipelined mode defaults checkpoint_async on,
    # which widens the crash window: after a hard crash status_log.json
    # may be one round ahead of latest_model — see docs/RUNBOOK.md).
    "pipeline_depth",
    # fused_carry: universal overlap (PR 6) — move cross-round strategy
    # state (SCAFFOLD controls, EF residuals, personalization
    # heads/alphas, the RL weight tuner) into device-resident carry
    # operands of the fused round program so those strategies run
    # pipelined instead of host-orchestrated serial; see
    # docs/config_extensions.md for the per-strategy tradeoffs
    "fused_carry",
    # input_staging: single-buffer host->device dispatch staging (one
    # packed transfer per dtype group instead of ~8-10 per-leaf
    # device_puts per round) — default on; set false to A/B the legacy
    # per-leaf path (tools/dispatch_cost_probe.py)
    "input_staging",
    "rounds_per_step", "clients_per_chunk", "checkpoint_backend",
    "checkpoint_async", "compilation_cache_dir", "secure_agg", "fedbuff",
    "dump_norm_stats", "scaffold_device_controls", "scaffold_flush_freq",
    "ef_device_residuals", "ef_flush_freq",
    # resilience: seeded deterministic fault injection (dropout/straggler
    # faults fold into the fused round program; IO faults exercise the
    # checkpoint retry/fallback machinery; preempt_at_round drives the
    # kill/resume drill) and the checkpoint retry/backoff/escalation
    # policy — see docs/config_extensions.md and docs/RUNBOOK.md
    "chaos", "checkpoint_retry",
    # fluteflow: event-driven arrival plane (traffic/) — seeded traffic
    # traces decide WHO trains and WHEN aggregation fires (buffered
    # async with true traced staleness, or the sync barrier baseline);
    # see docs/config_extensions.md
    "traffic",
    # flutescope telemetry: round spans + Perfetto trace export, the
    # packed-stats device-metric bus, opt-in jax.profiler round windows,
    # and the NaN/round-time/checkpoint watchdogs — default off, zero
    # overhead when absent (docs/observability.md)
    "telemetry",
    # fluteshield screened aggregation: on-device NaN/Inf + norm-outlier
    # quarantine and Byzantine-robust aggregators (trimmed mean /
    # median) — default off; disabled is bit-identical to pre-fluteshield
    # behavior (docs/config_extensions.md)
    "robust",
    # cohort shape-bucketing: partition each round's cohort into a
    # config-bounded set of power-of-two step buckets and dispatch one
    # compact [K_b, S_b, B] grid per bucket + an on-device finalize,
    # instead of padding every client to the slowest one — default off;
    # per-client updates stay bit-identical to the monolithic grid
    # (docs/config_extensions.md, RUNBOOK "Tuning cohort buckets")
    "cohort_bucketing",
    # cross-client megabatching: within each step bucket, repack many
    # small clients' batches into device-saturating super-batch lanes
    # (a segment-carrying scan replaces the per-client vmap when the
    # per-bucket dispatch gate prices it cheaper) — default off;
    # requires cohort_bucketing (docs/config_extensions.md, RUNBOOK
    # "Closing the MFU gap")
    "megabatch",
    # megakernel local SGD: epoch/step loop fusion (default on) + the
    # opt-in pallas fused SGD apply — `enable: false` restores the
    # legacy per-epoch unrolled trace (docs/config_extensions.md)
    "megakernel",
    # fleet mode: million-client populations — O(cohort) cohort draws
    # (Floyd / weighted reservoir) and, with fused_carry, a fixed-
    # capacity device page pool + durable host backing store replacing
    # the [N, n_params] resident carry tables — default off; see
    # docs/config_extensions.md and RUNBOOK "Running a fleet-scale
    # population"
    "fleet",
    # precision policy: params/compute/stats dtypes for the client
    # inner loop — absent is the bit-identical f32 path; compute:
    # bfloat16 keeps f32 master params + f32 stats accumulators
    # (docs/config_extensions.md, RUNBOOK "Choosing a precision policy")
    "precision",
    "semisupervision", "updatable_names",
    "fedac_eta", "fedac_gamma", "fedac_alpha", "fedac_beta",
    "qffl_q",
    "personalization_init", "personalization_interp",
}

CLIENT_KEYS = {
    "type", "meta_learning", "copying_train_data", "do_profiling",
    "ignore_subtask", "num_skip_decoding", "desired_max_samples",
    "max_grad_norm", "freeze_layer", "data_config", "optimizer_config",
    "annealing_config", "fedprox_mu", "convex_model_interp",
    "meta_optimizer_config", "ss_config",
    # TPU-native extensions
    "num_epochs", "step_bucketing", "quant_thresh", "quant_threshold",
    "quant_bits", "quant_approx", "quant_anneal", "updatable_layers",
    "semisupervision",
}

TOP_KEYS = {
    "model_config", "dp_config", "privacy_metrics_config", "strategy",
    "server_config", "client_config", "mesh_config", "task", "data_path",
    "output_path", "experiment",
}

# sections whose contents are free-form by design (plugin surfaces)
_FREEFORM = "model_config", "semisupervision", "augment", "mesh_config", \
    "nbest_task_scheduler", "ss_config", "experiment"

# ----------------------------------------------------------------------
# per-field type/range rules (the cerberus per-field ``type``/``min``/
# ``max`` declarations, reference core/schema.py): spec is
# ("bool" | "int" | "num", lo, hi) with inclusive bounds, None = open.
# Only fields with an unambiguous scalar contract are listed — fields
# with union types (num_clients_per_iteration int|"lo:hi") keep their
# bespoke checks in validate().
# ----------------------------------------------------------------------
SERVER_FIELD_SPECS = {
    "initial_lr_client": ("num", 0, None),
    "lr_decay_factor": ("num", 0, None),
    "softmax_beta": ("num", 0, None),
    "stale_prob": ("num", 0.0, 1.0),
    "initial_lr": ("num", 0, None),
    "max_grad_norm": ("num", 0, None),
    "initial_val": ("bool", None, None),
    "initial_rec": ("bool", None, None),
    "wantRL": ("bool", None, None),
    "fall_back_to_best_model": ("bool", None, None),
    "send_dicts": ("bool", None, None),
    "do_profiling": ("bool", None, None),
    "resume_from_checkpoint": ("bool", None, None),
    "scaffold_device_controls": ("bool", None, None),
    "dump_norm_stats": ("bool", None, None),
    "pipeline_depth": ("int", 0, None),
    "fused_carry": ("bool", None, None),
    "input_staging": ("bool", None, None),
    "rounds_per_step": ("int", 1, None),
    "clients_per_chunk": ("int", 1, None),
    "model_backup_freq": ("int", 1, None),
    "scaffold_flush_freq": ("int", 1, None),
    "ef_device_residuals": ("bool", None, None),
    "ef_flush_freq": ("int", 1, None),
    "qffl_q": ("num", 0, None),
}

CLIENT_FIELD_SPECS = {
    "fedprox_mu": ("num", 0, None),
    "max_grad_norm": ("num", 0, None),
    "quant_anneal": ("num", 0, 1.0),
    # quantile of |g| (jnp.quantile q arg, ops/quantization.py): [0, 1]
    "quant_thresh": ("num", 0, 1.0),
    "convex_model_interp": ("num", 0.0, 1.0),
    "num_epochs": ("int", 1, None),
    "desired_max_samples": ("int", 0, None),
    "quant_bits": ("int", 1, 32),
    "quant_approx": ("bool", None, None),
    "copying_train_data": ("bool", None, None),
    "do_profiling": ("bool", None, None),
    "ignore_subtask": ("bool", None, None),
    "step_bucketing": ("bool", None, None),
}

DATASET_FIELD_SPECS = {
    "batch_size": ("int", 1, None),
    "desired_max_samples": ("int", 0, None),
    "num_workers": ("int", 0, None),
    "prefetch_factor": ("int", 1, None),
    "max_seq_length": ("int", 1, None),
    "max_num_words": ("int", 1, None),
    "max_samples_per_user": ("int", 1, None),
    "lazy_cache_users": ("int", 1, None),
    "device_resident": ("bool", None, None),
    "lazy": ("bool", None, None),
    "wantLogits": ("bool", None, None),
    "pin_memory": ("bool", None, None),
    "unsorted_batch": ("bool", None, None),
    "step_bucketing": ("bool", None, None),
    "length_bucketing": ("bool", None, None),
    "per_user_stats": ("bool", None, None),
}

OPTIMIZER_FIELD_SPECS = {
    "lr": ("num", 0, None),
    "momentum": ("num", 0, 1.0),
    "weight_decay": ("num", 0, None),
    "dampening": ("num", 0, 1.0),
    "eps": ("num", 0, None),
    "nesterov": ("bool", None, None),
    "amsgrad": ("bool", None, None),
}

ANNEALING_FIELD_SPECS = {
    "gamma": ("num", 0, None),
    "step_size": ("int", 1, None),
    "patience": ("int", 0, None),
    "factor": ("num", 0, None),
    "peak_lr": ("num", 0, None),
    "floor_lr": ("num", 0, None),
    "rampup_steps": ("int", 0, None),
    "hold_steps": ("int", 0, None),
    "decay_steps": ("int", 1, None),
}

DP_FIELD_SPECS = {
    # eps < 0 is the documented clip-only sentinel
    # (privacy/__init__.py::apply_local_dp) — numeric but unbounded
    "eps": ("num", None, None),
    "delta": ("num", 0.0, 1.0),
    "max_grad": ("num", 0, None),
    "max_weight": ("num", 0, None),
    "min_weight": ("num", 0, None),
    "weight_scaler": ("num", 0, None),
    "global_sigma": ("num", 0, None),
    "enable_local_dp": ("bool", None, None),
    "enable_global_dp": ("bool", None, None),
    "enable_prod": ("bool", None, None),
}


class SchemaError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("config schema violations:\n  " + "\n  ".join(errors))


def _check_enum(errors: List[str], raw: Dict[str, Any], path: str, key: str,
                allowed: List[str]) -> None:
    val = raw.get(key)
    if val is not None and val not in allowed:
        errors.append(f"{path}.{key}: {val!r} not in {allowed}")


def _check_unknown(errors: List[str], raw: Any, path: str,
                   known: Iterable[str]) -> None:
    """Flag keys outside ``known`` with a did-you-mean suggestion (the
    cerberus ``unknown field`` behavior, reference ``core/schema.py``)."""
    if not isinstance(raw, dict):
        return
    known = set(known)
    for key in raw:
        if key in known or key in _FREEFORM:
            continue
        hint = difflib.get_close_matches(str(key), known, n=1, cutoff=0.6)
        suggest = f" (did you mean {hint[0]!r}?)" if hint else ""
        errors.append(f"{path}.{key}: unknown key{suggest}")


def _check_fields(errors: List[str], raw: Any, path: str,
                  specs: Dict[str, tuple]) -> None:
    """Per-field type + inclusive-range checks (the cerberus ``type`` /
    ``min`` / ``max`` rules).  ``None`` values skip — optionality is the
    dataclass default's job, not the schema's."""
    if not isinstance(raw, dict):
        return
    for key, (kind, lo, hi) in specs.items():
        val = raw.get(key)
        if val is None:
            continue
        if kind == "bool":
            if not isinstance(val, bool):
                errors.append(f"{path}.{key}: must be a boolean, got "
                              f"{type(val).__name__}")
            continue
        # bool is an int subclass: a stray `true` must not pass as 1
        if isinstance(val, bool) or not isinstance(
                val, int if kind == "int" else (int, float)):
            want = "an integer" if kind == "int" else "a number"
            errors.append(f"{path}.{key}: must be {want}, got "
                          f"{type(val).__name__}")
            continue
        if (lo is not None or hi is not None) and val != val:
            # NaN compares False against any bound — reject it explicitly
            # or `stale_prob: .nan` would sail through a [0, 1] range
            errors.append(f"{path}.{key}: must be a finite number, got NaN")
            continue
        if lo is not None and val < lo:
            errors.append(f"{path}.{key}: must be >= {lo}, got {val}")
        if hi is not None and val > hi:
            errors.append(f"{path}.{key}: must be <= {hi}, got {val}")


def _check_optimizer(errors: List[str], raw: Any, path: str,
                     unknown: Optional[List[str]] = None) -> None:
    if not isinstance(raw, dict):
        return
    _check_enum(errors, raw, path, "type", ALLOWED_OPTIMIZERS)
    _check_unknown(unknown if unknown is not None else errors, raw, path,
                   OPTIMIZER_KEYS)
    _check_fields(errors, raw, path, OPTIMIZER_FIELD_SPECS)


def _check_annealing(errors: List[str], raw: Any, path: str,
                     unknown: Optional[List[str]] = None) -> None:
    if not isinstance(raw, dict):
        return
    _check_enum(errors, raw, path, "type", ALLOWED_ANNEALING)
    _check_unknown(unknown if unknown is not None else errors, raw, path,
                   ANNEALING_KEYS)
    _check_fields(errors, raw, path, ANNEALING_FIELD_SPECS)


def _check_data_config(errors: List[str], raw: Any, path: str) -> None:
    if not isinstance(raw, dict):
        return
    _check_unknown(errors, raw, path, DATACONFIG_KEYS)
    for split in ("train", "val", "test"):
        blk = raw.get(split)
        if isinstance(blk, dict):
            _check_unknown(errors, blk, f"{path}.{split}", DATASET_KEYS)


def _check_data_fields(errors: List[str], raw: Any, path: str) -> None:
    """Type/range rules for the per-split dataset blocks (always hard
    errors, unlike the unknown-key pass which can be downgraded)."""
    if not isinstance(raw, dict):
        return
    for split in ("train", "val", "test"):
        blk = raw.get(split)
        if isinstance(blk, dict):
            _check_fields(errors, blk, f"{path}.{split}",
                          DATASET_FIELD_SPECS)


def validate(raw: Dict[str, Any], strict: Optional[bool] = None) -> None:
    """Validate a raw (YAML-loaded) config dict in place.

    Required sections follow reference ``core/schema.py``: ``model_config``
    and ``server_config`` are required; everything else optional with
    defaults supplied by the dataclass tree.  Unknown keys in structured
    sections are errors (``strict=True``, the default) or warnings
    (``strict=False`` / env ``MSRFLUTE_ALLOW_UNKNOWN=1``).
    """
    if strict is None:
        strict = not os.environ.get("MSRFLUTE_ALLOW_UNKNOWN")
    errors: List[str] = []
    unknown: List[str] = []

    if "model_config" not in raw:
        errors.append("model_config: required section missing")
    elif not isinstance(raw["model_config"], dict):
        errors.append("model_config: must be a mapping")
    elif "model_type" not in raw["model_config"]:
        errors.append("model_config.model_type: required key missing")

    if "server_config" not in raw:
        errors.append("server_config: required section missing")

    strategy = raw.get("strategy")
    if strategy is not None and strategy not in ALLOWED_STRATEGIES:
        errors.append(f"strategy: {strategy!r} not in {ALLOWED_STRATEGIES}")
    # cross-field: secure_agg options without the strategy would be
    # SILENTLY ignored — the user believes masking is on when per-client
    # payloads flow unmasked (the exact quiet failure this schema exists
    # to prevent)
    sc_raw = raw.get("server_config")
    if isinstance(sc_raw, dict) and sc_raw.get("secure_agg") is not None \
            and str(strategy or "fedavg").lower() not in (
                "secure_agg", "secagg", "secureagg"):
        errors.append(
            "server_config.secure_agg is set but strategy is "
            f"{strategy!r} — only strategy: secure_agg reads it; "
            "payloads would flow UNMASKED")
    # same quiet-failure rule for fedbuff: its options under another
    # strategy would leave the run fully synchronous while the user
    # believes they are simulating async staleness
    if isinstance(sc_raw, dict) and sc_raw.get("fedbuff") is not None \
            and str(strategy or "fedavg").lower() != "fedbuff":
        errors.append(
            "server_config.fedbuff is set but strategy is "
            f"{strategy!r} — only strategy: fedbuff reads it; the run "
            "would be fully synchronous")

    _check_unknown(unknown, raw, "config", TOP_KEYS)

    sc = raw.get("server_config")
    if isinstance(sc, dict):
        _check_enum(errors, sc, "server_config", "type", ALLOWED_SERVER_TYPES)
        _check_enum(errors, sc, "server_config", "personalization_init",
                    ["global", "random", "initial"])
        _check_enum(errors, sc, "server_config", "personalization_interp",
                    ["probs", "logprobs"])
        _check_unknown(unknown, sc, "server_config", SERVER_KEYS)
        _check_optimizer(errors, sc.get("optimizer_config"), "server_config.optimizer_config", unknown)
        _check_annealing(errors, sc.get("annealing_config"), "server_config.annealing_config", unknown)
        _check_data_config(unknown, sc.get("data_config"), "server_config.data_config")
        _check_fields(errors, sc, "server_config", SERVER_FIELD_SPECS)
        _check_data_fields(errors, sc.get("data_config"),
                           "server_config.data_config")
        replay = sc.get("server_replay_config")
        if isinstance(replay, dict):
            _check_unknown(unknown, replay, "server_config.server_replay_config",
                           SERVER_REPLAY_KEYS)
            _check_optimizer(errors, replay.get("optimizer_config"),
                             "server_config.server_replay_config.optimizer_config",
                             unknown)
        rl = sc.get("RL")
        if isinstance(rl, dict):
            _check_unknown(unknown, rl, "server_config.RL", RL_KEYS)
        chaos = sc.get("chaos")
        if isinstance(chaos, dict):
            _check_unknown(unknown, chaos, "server_config.chaos",
                           CHAOS_KEYS)
            _check_fields(errors, chaos, "server_config.chaos",
                          CHAOS_FIELD_SPECS)
            # the spec table's ranges are inclusive; ChaosSchedule
            # requires these strictly positive, and the validation layer
            # must not bless a config the constructor will refuse
            for key in ("corrupt_scale_factor", "corrupt_sign_flip_scale"):
                val = chaos.get(key)
                if isinstance(val, (int, float)) and \
                        not isinstance(val, bool) and float(val) == 0.0:
                    errors.append(
                        f"server_config.chaos.{key}: must be > 0")
            infra = chaos.get("infra")
            if infra is not None and not isinstance(infra, dict):
                errors.append(
                    "server_config.chaos.infra: must be a mapping of "
                    "infrastructure fault rates (see "
                    "docs/config_extensions.md), got "
                    f"{type(infra).__name__}")
            if isinstance(infra, dict):
                _check_unknown(unknown, infra,
                               "server_config.chaos.infra",
                               CHAOS_INFRA_KEYS)
                _check_fields(errors, infra,
                              "server_config.chaos.infra",
                              CHAOS_INFRA_FIELD_SPECS)
        robust = sc.get("robust")
        if robust is not None and not isinstance(robust, dict):
            errors.append(
                "server_config.robust: must be a mapping (see "
                "docs/config_extensions.md), got "
                f"{type(robust).__name__}")
        if isinstance(robust, dict):
            _check_unknown(unknown, robust, "server_config.robust",
                           ROBUST_KEYS)
            _check_fields(errors, robust, "server_config.robust",
                          ROBUST_FIELD_SPECS)
            _check_enum(errors, robust, "server_config.robust",
                        "aggregator", ALLOWED_ROBUST_AGGREGATORS)
            # valid domain is {0} ∪ [1, inf) — a union the inclusive
            # spec table cannot express; Shield.__init__ enforces the
            # same invariant, this keeps config load from blessing a
            # value server construction will refuse
            nm = robust.get("norm_multiplier")
            if isinstance(nm, (int, float)) and not isinstance(nm, bool) \
                    and 0.0 < float(nm) < 1.0:
                errors.append(
                    "server_config.robust.norm_multiplier: must be >= 1 "
                    "(it scales the cohort's median payload norm; < 1 "
                    "would quarantine the median client itself) or 0 to "
                    "disable the norm screen")
            # the range table is inclusive but Shield requires < 0.5
            tf = robust.get("trim_fraction")
            if isinstance(tf, (int, float)) and not isinstance(tf, bool) \
                    and float(tf) == 0.5:
                errors.append(
                    "server_config.robust.trim_fraction: must be < 0.5 "
                    "— trimming half or more from each side leaves "
                    "nothing to average")
            # quiet-failure rule (the secure_agg/fedbuff discipline): a
            # robust block under a strategy whose combine it cannot
            # screen means the user believes the cohort is defended
            # while poisoned payloads aggregate untouched
            if robust.get("enable", True) and \
                    str(strategy or "fedavg").lower() not in (
                        "fedavg", "fedprox",
                        "secure_agg", "secagg", "secureagg"):
                errors.append(
                    "server_config.robust is set but strategy is "
                    f"{strategy!r} — screened aggregation plugs into the "
                    "fedavg/fedprox combine (or secure_agg's submitted-"
                    "norm screening); payloads would aggregate "
                    "UNSCREENED")
            if robust.get("enable", True) and \
                    str(robust.get("aggregator", "mean")) in (
                        "trimmed_mean", "median") and \
                    str(strategy or "fedavg").lower() in (
                        "secure_agg", "secagg", "secureagg"):
                errors.append(
                    "server_config.robust.aggregator: "
                    f"{robust.get('aggregator')!r} sorts per-client "
                    "payload coordinates, but secure_agg submissions "
                    "are masked int32 group elements — use aggregator: "
                    "mean (submitted-norm screening still applies)")
        sa = sc.get("secure_agg")
        if isinstance(sa, dict):
            _check_unknown(unknown, sa, "server_config.secure_agg",
                           SECURE_AGG_KEYS)
            _check_fields(errors, sa, "server_config.secure_agg",
                          SECURE_AGG_FIELD_SPECS)
            graph = sa.get("graph")
            if graph is not None and str(graph).lower() not in ("full",
                                                                "log"):
                errors.append(
                    "server_config.secure_agg.graph: must be 'full' or "
                    f"'log', got {graph!r}")
            clip = sa.get("clip")
            if isinstance(clip, (int, float)) and \
                    not isinstance(clip, bool) and float(clip) <= 0.0:
                errors.append(
                    "server_config.secure_agg.clip: must be > 0")
        cb = sc.get("cohort_bucketing")
        if cb is not None and not isinstance(cb, dict):
            errors.append(
                "server_config.cohort_bucketing: must be a mapping (see "
                "docs/config_extensions.md), got "
                f"{type(cb).__name__}")
        if isinstance(cb, dict):
            _check_unknown(unknown, cb, "server_config.cohort_bucketing",
                           COHORT_BUCKETING_KEYS)
            _check_fields(errors, cb, "server_config.cohort_bucketing",
                          COHORT_BUCKETING_FIELD_SPECS)
            bounds = cb.get("boundaries")
            if bounds is not None:
                # bespoke: a strictly-increasing positive-int list — a
                # non-increasing list would assign clients to a bucket
                # too small for their data (silent truncation), which
                # the server also refuses; validation must not bless it
                if not isinstance(bounds, (list, tuple)) or not bounds:
                    errors.append(
                        "server_config.cohort_bucketing.boundaries: "
                        "must be a non-empty list of step counts")
                elif any(isinstance(b, bool) or not isinstance(b, int)
                         or b < 1 for b in bounds):
                    errors.append(
                        "server_config.cohort_bucketing.boundaries: "
                        "every boundary must be a positive integer, "
                        f"got {list(bounds)!r}")
                elif any(y <= x for x, y in zip(bounds, bounds[1:])):
                    errors.append(
                        "server_config.cohort_bucketing.boundaries: "
                        f"must be strictly increasing, got "
                        f"{list(bounds)!r}")
                mb = cb.get("max_buckets")
                if isinstance(mb, int) and not isinstance(mb, bool) and \
                        isinstance(bounds, (list, tuple)) and \
                        len(bounds) > mb:
                    errors.append(
                        "server_config.cohort_bucketing: "
                        f"{len(bounds)} boundaries exceed "
                        f"max_buckets={mb}")
        fl = sc.get("fleet")
        if fl is not None and not isinstance(fl, dict):
            errors.append(
                "server_config.fleet: must be a mapping (see "
                "docs/config_extensions.md), got "
                f"{type(fl).__name__}")
        if isinstance(fl, dict):
            _check_unknown(unknown, fl, "server_config.fleet",
                           FLEET_KEYS)
            _check_fields(errors, fl, "server_config.fleet",
                          FLEET_FIELD_SPECS)
            _check_enum(errors, fl, "server_config.fleet", "sampling",
                        ALLOWED_FLEET_SAMPLING)
        mgb = sc.get("megabatch")
        if mgb is not None and not isinstance(mgb, dict):
            errors.append(
                "server_config.megabatch: must be a mapping (see "
                "docs/config_extensions.md), got "
                f"{type(mgb).__name__}")
        if isinstance(mgb, dict):
            _check_unknown(unknown, mgb, "server_config.megabatch",
                           MEGABATCH_KEYS)
            _check_fields(errors, mgb, "server_config.megabatch",
                          MEGABATCH_FIELD_SPECS)
            _cb_blk = sc.get("cohort_bucketing") or {}
            _cb_on = bool(_cb_blk) and (not isinstance(_cb_blk, dict)
                                        or _cb_blk.get("enable", True))
            if mgb.get("enable", True) and not _cb_on:
                # decidable at config load (the quiet-failure rule):
                # the tape geometry is a per-bucket quantity, so an
                # unbucketed run has nothing to repack
                errors.append(
                    "server_config.megabatch requires "
                    "server_config.cohort_bucketing — the super-batch "
                    "tape repacks per-bucket grids; add the "
                    "cohort_bucketing block or drop megabatch")
            if mgb.get("enable", True) and \
                    str(strategy or "fedavg").lower() == "fedlabels":
                # also decidable at config load: fedlabels' dual
                # sup/unsup training loop steps outside the
                # client_update contract the lane scan reproduces
                errors.append(
                    "server_config.megabatch is set but strategy is "
                    "'fedlabels' — its dual sup/unsup loop steps "
                    "outside the client_update contract the lane scan "
                    "reproduces; drop megabatch or change strategy")
        mk = sc.get("megakernel")
        if mk is not None and not isinstance(mk, dict):
            errors.append(
                "server_config.megakernel: must be a mapping (see "
                "docs/config_extensions.md), got "
                f"{type(mk).__name__}")
        if isinstance(mk, dict):
            _check_unknown(unknown, mk, "server_config.megakernel",
                           MEGAKERNEL_KEYS)
            _check_fields(errors, mk, "server_config.megakernel",
                          MEGAKERNEL_FIELD_SPECS)
        traffic = sc.get("traffic")
        if traffic is not None and not isinstance(traffic, dict):
            errors.append(
                "server_config.traffic: must be a mapping (see "
                "docs/config_extensions.md), got "
                f"{type(traffic).__name__}")
        if isinstance(traffic, dict):
            _check_unknown(unknown, traffic, "server_config.traffic",
                           TRAFFIC_KEYS)
            _check_fields(errors, traffic, "server_config.traffic",
                          TRAFFIC_FIELD_SPECS)
            _check_enum(errors, traffic, "server_config.traffic",
                        "mode", ALLOWED_TRAFFIC_MODES)
            _check_enum(errors, traffic, "server_config.traffic",
                        "trace", ALLOWED_TRAFFIC_TRACES)
            lo, hi = traffic.get("duration_lo"), traffic.get("duration_hi")
            if isinstance(lo, int) and isinstance(hi, int) and hi < lo:
                errors.append(
                    "server_config.traffic: duration_hi "
                    f"({hi}) < duration_lo ({lo})")
            classes = traffic.get("classes")
            if classes is not None and (
                    not isinstance(classes, (list, tuple)) or
                    not all(isinstance(c, dict) for c in classes)):
                errors.append(
                    "server_config.traffic.classes: expected a list of "
                    "per-class mappings (fraction/rate/window/phase/"
                    f"duration_scale), got {classes!r}")
            if traffic.get("enable", True):
                # decidable at config load (the quiet-failure rule):
                # the liveness floor can never be met when it exceeds
                # the fire size — every round would abort
                _sa_blk = sc.get("secure_agg") or {}
                if isinstance(_sa_blk, dict) and \
                        _sa_blk.get("enable", True):
                    ms = _sa_blk.get("min_survivors")
                    bs = traffic.get("buffer_size",
                                     sc.get("num_clients_per_iteration"))
                    if isinstance(ms, int) and isinstance(bs, int) and \
                            ms > bs:
                        errors.append(
                            "server_config.secure_agg.min_survivors "
                            f"({ms}) exceeds traffic.buffer_size ({bs}) "
                            "— a buffered fire delivers exactly "
                            "buffer_size clients, so every round would "
                            "abort below the liveness floor")
        prec = sc.get("precision")
        if prec is not None and not isinstance(prec, dict):
            errors.append(
                "server_config.precision: must be a mapping (see "
                "docs/config_extensions.md), got "
                f"{type(prec).__name__}")
        if isinstance(prec, dict):
            _check_unknown(unknown, prec, "server_config.precision",
                           PRECISION_KEYS)
            for key in ("params", "compute", "stats"):
                _check_enum(errors, prec, "server_config.precision", key,
                            ALLOWED_PRECISION_DTYPES)
            en = prec.get("enable")
            if en is not None and not isinstance(en, bool):
                errors.append(
                    "server_config.precision.enable: expected bool, got "
                    f"{en!r}")
        ckpt_retry = sc.get("checkpoint_retry")
        if isinstance(ckpt_retry, dict):
            _check_unknown(unknown, ckpt_retry,
                           "server_config.checkpoint_retry",
                           CHECKPOINT_RETRY_KEYS)
            _check_fields(errors, ckpt_retry,
                          "server_config.checkpoint_retry",
                          CHECKPOINT_RETRY_FIELD_SPECS)
        telemetry = sc.get("telemetry")
        if telemetry is not None and not isinstance(telemetry, dict):
            errors.append(
                "server_config.telemetry: must be a mapping "
                f"(see docs/observability.md), got "
                f"{type(telemetry).__name__}")
        if isinstance(telemetry, dict):
            _check_unknown(unknown, telemetry, "server_config.telemetry",
                           TELEMETRY_KEYS)
            _check_fields(errors, telemetry, "server_config.telemetry",
                          TELEMETRY_FIELD_SPECS)
            if telemetry.get("profile_rounds") is not None:
                # union type (int | "lo:hi" | [lo, hi]) — reuse the one
                # parser the profiler itself runs, so config load and
                # round `lo` can never disagree about validity
                from .telemetry.profiling import parse_profile_rounds
                try:
                    parse_profile_rounds(telemetry["profile_rounds"])
                except (ValueError, TypeError) as exc:
                    errors.append(
                        f"server_config.telemetry.profile_rounds: {exc}")
            wd = telemetry.get("watchdog")
            if wd is not None and not isinstance(wd, dict):
                # a bare string like `watchdog: abort` would otherwise
                # sail through here and die cryptically in
                # Watchdog.__init__ at server construction
                errors.append(
                    "server_config.telemetry.watchdog: must be a mapping "
                    f"of detector knobs, got {type(wd).__name__}")
            if isinstance(wd, dict):
                _check_unknown(unknown, wd,
                               "server_config.telemetry.watchdog",
                               WATCHDOG_KEYS)
                _check_fields(errors, wd,
                              "server_config.telemetry.watchdog",
                              WATCHDOG_FIELD_SPECS)
                for key in ("nan_loss", "round_time_action",
                            "ckpt_failure_action",
                            "quarantine_rate_action",
                            "recompile_storm_action", "stall_action",
                            "rss_leak_action",
                            "throughput_drift_action"):
                    _check_enum(errors, wd,
                                "server_config.telemetry.watchdog", key,
                                ALLOWED_WATCHDOG_ACTIONS)
        # pipeline_depth keeps a bespoke upper bound the inclusive range
        # table cannot document: the donated ring costs HBM per slot and
        # the old engine-side min(depth, 1) clamp silently ignored the
        # config — refusal with the bound beats clamping
        pd = sc.get("pipeline_depth")
        if isinstance(pd, int) and not isinstance(pd, bool) and \
                pd > MAX_PIPELINE_DEPTH:
            errors.append(
                f"server_config.pipeline_depth: {pd} exceeds the "
                f"supported maximum {MAX_PIPELINE_DEPTH} — each depth "
                "slot keeps a full round chunk's staged inputs and "
                "packed stats resident in device memory, and depth past "
                "the host-tail/device-round ratio buys nothing; lower "
                "it (see docs/RUNBOOK.md pipeline tuning)")
        ncpi = sc.get("num_clients_per_iteration")
        if ncpi is not None and not isinstance(ncpi, int):
            if not (isinstance(ncpi, str) and ":" in ncpi):
                errors.append(
                    "server_config.num_clients_per_iteration: must be int or 'lo:hi'")
        for key in ("max_iteration", "val_freq", "rec_freq"):
            val = sc.get(key)
            if val is not None and (not isinstance(val, int) or val < 0):
                errors.append(f"server_config.{key}: must be a non-negative int")

    cc = raw.get("client_config")
    if isinstance(cc, dict):
        _check_unknown(unknown, cc, "client_config", CLIENT_KEYS)
        _check_optimizer(errors, cc.get("optimizer_config"), "client_config.optimizer_config", unknown)
        if cc.get("annealing_config") is not None:
            _check_annealing(errors, cc.get("annealing_config"), "client_config.annealing_config", unknown)
        _check_data_config(unknown, cc.get("data_config"), "client_config.data_config")
        _check_fields(errors, cc, "client_config", CLIENT_FIELD_SPECS)
        _check_data_fields(errors, cc.get("data_config"),
                           "client_config.data_config")

    dp = raw.get("dp_config")
    if isinstance(dp, dict):
        _check_unknown(unknown, dp, "dp_config", DP_KEYS)
        ac = dp.get("adaptive_clipping")
        if isinstance(ac, dict):
            _check_unknown(unknown, ac, "dp_config.adaptive_clipping",
                           ADAPTIVE_CLIP_KEYS)
        _check_fields(errors, dp, "dp_config", DP_FIELD_SPECS)

    pm = raw.get("privacy_metrics_config")
    if isinstance(pm, dict):
        _check_unknown(unknown, pm, "privacy_metrics_config",
                       PRIVACY_METRICS_KEYS)
        _check_optimizer(errors, pm.get("attacker_optimizer_config"),
                         "privacy_metrics_config.attacker_optimizer_config",
                         unknown)

    if unknown:
        if strict:
            errors.extend(unknown)
        else:
            warnings.warn("config has unknown keys (MSRFLUTE_ALLOW_UNKNOWN "
                          "set; would be errors otherwise):\n  "
                          + "\n  ".join(unknown), stacklevel=2)
    if errors:
        raise SchemaError(errors)


# ----------------------------------------------------------------------
# applied-defaults report (reference core/config.py:771-779 prints the
# diff between the user YAML and the config with defaults applied)
# ----------------------------------------------------------------------
def applied_defaults(raw: Dict[str, Any], cfg: Any,
                     _path: str = "") -> Dict[str, Any]:
    """Return ``{dotted.path: default}`` for every structured field the user
    did NOT set, i.e. the defaults the framework filled in.  ``cfg`` is the
    built dataclass tree; ``raw`` the original YAML dict."""
    import dataclasses

    out: Dict[str, Any] = {}
    if not dataclasses.is_dataclass(cfg):
        return out
    raw = raw if isinstance(raw, dict) else {}
    for f in dataclasses.fields(cfg):
        if f.name == "extra":
            continue
        val = getattr(cfg, f.name)
        path = f"{_path}.{f.name}" if _path else f.name
        if dataclasses.is_dataclass(val):
            out.update(applied_defaults(raw.get(f.name), val, path))
        elif f.name not in raw and val is not None:
            out[path] = val
    return out
