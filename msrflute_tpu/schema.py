"""Config schema validation for msrflute_tpu.

Parity target: reference ``core/schema.py`` (a cerberus schema dict loaded
with ``eval`` at ``core/config.py:766-769``).  We validate the same
constraints with a small hand-rolled checker: required sections, allowed
enum values (optimizer types per ``core/schema.py:90``, annealing types per
``utils/utils.py:151-186``, strategies per ``core/strategies/__init__.py:9-23``)
and defaults.  Raises :class:`SchemaError` with every violation collected,
like cerberus reports all errors at once.
"""

from __future__ import annotations

from typing import Any, Dict, List

ALLOWED_OPTIMIZERS = [
    # reference core/schema.py:90
    "sgd", "adam", "adamax", "lars", "LarsSGD", "lamb", "adamW",
    # accepted aliases
    "adamw", "larssgd",
]

ALLOWED_ANNEALING = [
    # reference utils/utils.py:151-186
    "step_lr", "multi_step_lr", "rampup-keep-expdecay-keep", "val_loss",
    # alias
    "constant",
]

ALLOWED_STRATEGIES = [
    # reference core/strategies/__init__.py:9-23
    "dga", "DGA", "fedavg", "FedAvg", "fedprox", "FedProx",
    "fedlabels", "FedLabels", "fedac", "FedAC", "scaffold", "Scaffold",
]

ALLOWED_SERVER_TYPES = [
    # reference core/server.py:581-597
    "optimization", "model_optimization", "personalization",
]


class SchemaError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("config schema violations:\n  " + "\n  ".join(errors))


def _check_enum(errors: List[str], raw: Dict[str, Any], path: str, key: str,
                allowed: List[str]) -> None:
    val = raw.get(key)
    if val is not None and val not in allowed:
        errors.append(f"{path}.{key}: {val!r} not in {allowed}")


def _check_optimizer(errors: List[str], raw: Any, path: str) -> None:
    if not isinstance(raw, dict):
        return
    _check_enum(errors, raw, path, "type", ALLOWED_OPTIMIZERS)
    lr = raw.get("lr")
    if lr is not None and not isinstance(lr, (int, float)):
        errors.append(f"{path}.lr: must be a number, got {type(lr).__name__}")


def _check_annealing(errors: List[str], raw: Any, path: str) -> None:
    if not isinstance(raw, dict):
        return
    _check_enum(errors, raw, path, "type", ALLOWED_ANNEALING)


def validate(raw: Dict[str, Any]) -> None:
    """Validate a raw (YAML-loaded) config dict in place.

    Required sections follow reference ``core/schema.py``: ``model_config``
    and ``server_config`` are required; everything else optional with
    defaults supplied by the dataclass tree.
    """
    errors: List[str] = []

    if "model_config" not in raw:
        errors.append("model_config: required section missing")
    elif not isinstance(raw["model_config"], dict):
        errors.append("model_config: must be a mapping")
    elif "model_type" not in raw["model_config"]:
        errors.append("model_config.model_type: required key missing")

    if "server_config" not in raw:
        errors.append("server_config: required section missing")

    strategy = raw.get("strategy")
    if strategy is not None and strategy not in ALLOWED_STRATEGIES:
        errors.append(f"strategy: {strategy!r} not in {ALLOWED_STRATEGIES}")

    sc = raw.get("server_config")
    if isinstance(sc, dict):
        _check_enum(errors, sc, "server_config", "type", ALLOWED_SERVER_TYPES)
        _check_optimizer(errors, sc.get("optimizer_config"), "server_config.optimizer_config")
        _check_annealing(errors, sc.get("annealing_config"), "server_config.annealing_config")
        ncpi = sc.get("num_clients_per_iteration")
        if ncpi is not None and not isinstance(ncpi, int):
            if not (isinstance(ncpi, str) and ":" in ncpi):
                errors.append(
                    "server_config.num_clients_per_iteration: must be int or 'lo:hi'")
        for key in ("max_iteration", "val_freq", "rec_freq"):
            val = sc.get(key)
            if val is not None and (not isinstance(val, int) or val < 0):
                errors.append(f"server_config.{key}: must be a non-negative int")

    cc = raw.get("client_config")
    if isinstance(cc, dict):
        _check_optimizer(errors, cc.get("optimizer_config"), "client_config.optimizer_config")
        if cc.get("annealing_config") is not None:
            _check_annealing(errors, cc.get("annealing_config"), "client_config.annealing_config")

    dp = raw.get("dp_config")
    if isinstance(dp, dict):
        for key in ("eps", "delta", "max_grad", "max_weight", "min_weight",
                    "weight_scaler", "global_sigma"):
            val = dp.get(key)
            if val is not None and not isinstance(val, (int, float)):
                errors.append(f"dp_config.{key}: must be a number")

    if errors:
        raise SchemaError(errors)
