"""Checkpoint integrity + bounded-retry primitives.

Three small, composable pieces the checkpoint backends share:

- **checksums**: crc32 of a serialized blob (msgpack files) or of a
  checkpoint directory tree (orbax slots), recorded in a ``.sum``
  sidecar / the ``latest_model.orbax.ptr`` pointer and verified at load
  time.  A mismatch means corruption or a torn write — the loader falls
  back to the surviving slot instead of resuming garbage.
- **RetryPolicy**: bounded retry with exponential backoff + jitter for
  transient IO failures (NFS blips, disk-full races), replacing the
  fixed 3x1s loop.  Config-capped via ``server_config.checkpoint_retry``.
- **FailureEscalator**: counts CONSECUTIVE fully-failed saves; at the
  configured threshold it raises :class:`CheckpointEscalationError`
  instead of letting training run uncheckpointed forever behind
  warn-and-continue logs nobody reads.
"""

from __future__ import annotations

import json
import logging
import os
import random
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils.logging import print_rank

#: suffix of the checksum sidecar written next to msgpack checkpoints
SIDECAR_SUFFIX = ".sum"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed its integrity check (checksum mismatch or an
    unreadable/torn file)."""


class CheckpointEscalationError(RuntimeError):
    """Too many consecutive checkpoint-save failures: the run can no
    longer be considered resumable and must stop instead of silently
    training uncheckpointed."""


# ----------------------------------------------------------------------
# checksums
# ----------------------------------------------------------------------
def blob_checksum(blob: bytes) -> str:
    """crc32 (hex) of a serialized checkpoint blob.  crc32, not a
    cryptographic hash: the threat model is torn writes and bit rot, not
    an adversary, and crc32 streams at memory bandwidth."""
    return f"{zlib.crc32(blob) & 0xFFFFFFFF:08x}"


def tree_checksum(dir_path: str) -> str:
    """crc32 (hex) over a checkpoint DIRECTORY: relative file names and
    contents, walked in sorted order so the digest is layout-stable.
    Used for orbax slots, whose checkpoint is a directory tree."""
    crc = 0
    for root, dirs, files in os.walk(dir_path):
        dirs.sort()
        for name in sorted(files):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, dir_path).replace(os.sep, "/")
            crc = zlib.crc32(rel.encode("utf-8"), crc)
            with open(path, "rb") as fh:
                while True:
                    chunk = fh.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def write_sidecar(path: str, checksum: str, size: int) -> None:
    """Atomically record a blob's checksum next to it (``<path>.sum``).
    Written AFTER the blob itself lands, so a sidecar always describes a
    fully-written file; a missing sidecar downgrades load-time
    verification to a warning (pre-integrity checkpoints stay loadable)."""
    sidecar = path + SIDECAR_SUFFIX
    tmp = sidecar + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"crc32": checksum, "size": size}, fh)
    os.replace(tmp, sidecar)


def read_sidecar(path: str) -> Optional[dict]:
    sidecar = path + SIDECAR_SUFFIX
    if not os.path.exists(sidecar):
        return None
    try:
        with open(sidecar) as fh:
            return json.load(fh)
    except (json.JSONDecodeError, OSError):
        # a torn sidecar must not make a good blob unloadable
        return None


def verify_blob(path: str, blob: bytes) -> None:
    """Raise :class:`CheckpointCorruptionError` if ``blob`` does not
    match the sidecar recorded for ``path``.  No sidecar (pre-integrity
    checkpoint) verifies vacuously."""
    meta = read_sidecar(path)
    if meta is None:
        return
    if meta.get("size") is not None and meta["size"] != len(blob):
        raise CheckpointCorruptionError(
            f"{path}: size {len(blob)} != recorded {meta['size']} "
            "(torn write?)")
    actual = blob_checksum(blob)
    if meta.get("crc32") and actual != meta["crc32"]:
        raise CheckpointCorruptionError(
            f"{path}: crc32 {actual} != recorded {meta['crc32']}")


# ----------------------------------------------------------------------
# retry + escalation
# ----------------------------------------------------------------------
@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + jitter
    (``server_config.checkpoint_retry``).  ``escalation_threshold``
    consecutive fully-failed SAVES (each already retried ``retries``
    times) abort the run via :class:`CheckpointEscalationError`."""

    retries: int = 3
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0
    jitter: float = 0.25          # +- fraction of the computed delay
    escalation_threshold: int = 10

    @classmethod
    def from_config(cls, raw: Optional[dict]) -> "RetryPolicy":
        if not raw:
            return cls()
        return cls(
            retries=int(raw.get("retries", cls.retries)),
            backoff_base_s=float(raw.get("backoff_base_s",
                                         cls.backoff_base_s)),
            backoff_max_s=float(raw.get("backoff_max_s", cls.backoff_max_s)),
            jitter=float(raw.get("jitter", cls.jitter)),
            escalation_threshold=int(raw.get("escalation_threshold",
                                             cls.escalation_threshold)),
        )

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based): exponential,
        capped, jittered.  Jitter decorrelates concurrent writers hitting
        the same overloaded filesystem — it deliberately does NOT come
        from any seeded stream (the chaos schedule's determinism
        guarantee covers which faults fire, never how long IO sleeps)."""
        base = min(self.backoff_max_s, self.backoff_base_s * (2.0 ** attempt))
        if self.jitter <= 0.0:
            return base
        return base * (1.0 + self.jitter * (2.0 * random.random() - 1.0))


def run_with_retry(fn: Callable[[], None], policy: RetryPolicy,
                   what: str = "save",
                   sleep: Callable[[float], None] = time.sleep) -> bool:
    """Run ``fn`` under ``policy``; True on success.  Transient
    exceptions are retried with backoff; ``KeyboardInterrupt`` /
    ``SystemExit`` always propagate (a Ctrl-C mid-save must kill the
    run, not burn the retry budget)."""
    for attempt in range(max(policy.retries, 1)):
        try:
            fn()
            return True
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:  # noqa: BLE001 - deliberate: best-effort IO
            last = attempt == max(policy.retries, 1) - 1
            print_rank(
                f"{what} attempt {attempt + 1}/{policy.retries} failed: "
                f"{exc!r}" + ("" if last else "; backing off"),
                loglevel=logging.WARNING)
            if not last:
                sleep(policy.delay(attempt))
    return False


class DurableIOError(RuntimeError):
    """A durable-IO operation whose loss would corrupt training state
    (row-store read, writeback ``device_get``) exhausted its retry
    budget.  Raised FROM the training thread so the server's
    BaseException tail persists the flight record before aborting."""


class DurableIOLadder:
    """One retry/degradation policy object for ALL durable host IO.

    Generalizes the checkpoint-only RetryPolicy + FailureEscalator pair
    into the explicit degradation table flutearmor documents (RUNBOOK
    "Infrastructure-fault drill"): every surface shares ONE
    :class:`RetryPolicy` (``server_config.checkpoint_retry`` — one knob,
    one ladder), but keeps its OWN consecutive-failure escalator and its
    own exhaustion mode:

    - ``mode="escalate"`` (row-store SPILL, ControlStore marker): the
      failed rows stay host-visible (the caller keeps them dirty / in
      the spilling map), so a lost write degrades capacity, not
      correctness — but ``escalation_threshold`` consecutive exhausted
      writes abort via :class:`CheckpointEscalationError` exactly like
      an uncheckpointable run would.
    - ``mode="raise"`` (row-store READ, writeback ``device_get``):
      exhaustion raises :class:`DurableIOError` immediately — silently
      losing carry rows corrupts training, so the only honest move is a
      flight-recorded abort.
    - ``mode="drop"`` (rollup/metrics writers): exhaustion returns False
      and the caller drops the window + counts it — telemetry loss must
      never become a host-tail exception.
    """

    #: surface -> exhaustion mode; also the registry of valid surfaces
    MODES = {
        "store_write": "escalate",
        "store_read": "raise",
        "marker": "escalate",
        "writeback": "raise",
        "writer": "drop",
    }

    def __init__(self, policy: Optional[RetryPolicy] = None,
                 fault_hooks: Optional[dict] = None):
        self.policy = policy if policy is not None else RetryPolicy()
        #: surface -> zero-arg chaos raise-hook (InfraFaults.hook), run
        #: before each physical attempt so retries redraw fresh decisions
        self.fault_hooks = dict(fault_hooks or {})
        #: optional instant-event emitter ``event(kind, **fields)`` the
        #: server wires to flutescope — every failed attempt on a
        #: store-family surface lands a ``store_io_fault`` event, so the
        #: infra drill's degradations are all structured, never log-only
        self.event: Optional[Callable[..., None]] = None
        self.escalators = {
            name: FailureEscalator(self.policy.escalation_threshold)
            for name, mode in self.MODES.items() if mode == "escalate"
        }

    def run(self, fn: Callable[[], None], surface: str,
            what: str = "") -> bool:
        """Run one durable operation on ``surface`` under the ladder.
        True on success; on exhaustion, behave per the surface's mode
        (see class docstring).  ``what`` labels log lines."""
        mode = self.MODES[surface]
        hook = self.fault_hooks.get(surface)

        def attempt() -> None:
            try:
                if hook is not None:
                    hook()
                fn()
            except Exception as exc:
                # structured observability per failed attempt (injected
                # OR real), on the surfaces whose loss is a store/state
                # problem; writer failures get their own rollup event
                if self.event is not None and surface != "writer":
                    self.event("store_io_fault", surface=surface,
                               what=what, error=repr(exc))
                raise
        ok = run_with_retry(attempt, self.policy,
                            what=what or f"{surface} io")
        if ok:
            if mode == "escalate":
                self.escalators[surface].record_success()
            return True
        if mode == "raise":
            raise DurableIOError(
                f"{surface} IO exhausted its retry budget "
                f"({self.policy.retries} attempts){': ' + what if what else ''}"
                " — losing this data would corrupt training state")
        if mode == "escalate":
            esc = self.escalators[surface]
            esc.record_failure(what or surface)
            esc.check()
        return False


class FailureEscalator:
    """Consecutive-failure counter shared by the checkpoint writer paths.
    Thread-safe enough for its use (int ops under the GIL; the writer
    thread records, the training thread checks)."""

    def __init__(self, threshold: int):
        self.threshold = max(int(threshold), 1)
        self.consecutive = 0
        self.total = 0

    def record_failure(self, what: str) -> None:
        self.consecutive += 1
        self.total += 1
        print_rank(
            f"checkpoint failure #{self.consecutive} (consecutive) in "
            f"{what}; run aborts at {self.threshold}",
            loglevel=logging.WARNING)

    def record_success(self) -> None:
        self.consecutive = 0

    def check(self) -> None:
        """Raise once the consecutive-failure budget is spent.  Called
        from the TRAINING thread (submit/wait points), never from the
        async writer — a daemon thread's exception would vanish."""
        if self.consecutive >= self.threshold:
            raise CheckpointEscalationError(
                f"{self.consecutive} consecutive checkpoint-save failures "
                f"(threshold {self.threshold}): training is no longer "
                "resumable — aborting instead of running uncheckpointed. "
                "Fix the storage path or raise "
                "server_config.checkpoint_retry.escalation_threshold.")
