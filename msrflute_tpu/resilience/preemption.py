"""Graceful preemption: SIGTERM/SIGINT -> drain -> checkpoint -> exit.

Preemptible TPU slices get a SIGTERM and a short grace window.  The
handler here does NOT abort anything itself — it flips a flag the server
round loop polls at chunk boundaries.  On seeing it the loop drains the
in-flight device chunk (the dispatched-but-undrained slot in pipelined
mode — nothing speculative beyond it is ever dispatched), runs that
chunk's normal housekeeping (which writes the per-round ``latest``
checkpoint through the existing two-slot path), forces the async writers
durable, commits the resume anchor (round + rng snapshots) to
``status_log.json``, and returns.  ``e2e_trainer.py`` then exits with
``os.EX_TEMPFAIL`` (75) so schedulers distinguish "preempted, resume me"
from success and from crashes.

Signal handlers only install from the main thread (CPython restriction);
anywhere else — tests driving ``train()`` from a worker thread, notebook
kernels — the handler degrades to the polling flag alone, which the
deterministic ``server_config.chaos.preempt_at_round`` drill and direct
``request()`` calls still exercise end to end.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Optional

from ..utils.logging import print_rank


class GracefulPreemption(Exception):
    """Raised by entry points that want stack unwinding on preemption
    (the server loop itself returns normally instead)."""


class PreemptionHandler:
    """Install/uninstall SIGTERM+SIGINT handlers around a training run.

    Usage::

        handler = PreemptionHandler()
        handler.install()
        try:
            while ...:
                if handler.requested:
                    ...drain + emergency checkpoint...
                    break
        finally:
            handler.uninstall()

    Repeated signals stay graceful until ``escalate_after`` arrivals,
    after which the previous (default) disposition is restored so a
    second Ctrl-C actually kills a wedged run.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, escalate_after: int = 2):
        self.escalate_after = max(int(escalate_after), 1)
        self._event = threading.Event()
        self._reason: Optional[str] = None
        #: epoch seconds of the first request this window (None until
        #: one lands) — the flight recorder / endurance harness read it
        #: to bound how long the drain has been running
        self._requested_at: Optional[float] = None
        self._prev = {}
        self._installed = False
        self._hits = 0
        #: telemetry flush callbacks (trace writer etc.) run at
        #: flush_now(), so a SIGTERM'd run's observability is durable
        #: even if the drain itself later wedges
        self._flush_hooks: list = []
        self._flush_pending = False

    def add_flush_hook(self, fn) -> None:
        """Register a callable run (best-effort) when preemption is
        requested — the server wires the telemetry scope's flush here."""
        self._flush_hooks.append(fn)

    # -- flag side -----------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    @property
    def requested_at(self) -> Optional[float]:
        return self._requested_at

    def reset(self) -> None:
        """Clear a latched request + the signal hit-count — called at the
        start of each training window so a server that preempted once
        (drill or real signal) can train again instead of exiting its
        next ``train()`` instantly with zero progress."""
        self._event.clear()
        self._reason = None
        self._requested_at = None
        self._hits = 0
        self._flush_pending = False

    def request(self, reason: str, _from_signal: bool = False) -> None:
        """Programmatic preemption — the chaos drill
        (``preempt_at_round``) and tests come through here; the signal
        handler is a thin wrapper around it.

        ``_from_signal``: ALL observability — the telemetry flush (file
        IO + tracer locks) AND the log line (``logging`` takes
        module-level locks) — is DEFERRED to :meth:`flush_now`, which
        the round loop calls at its next poll.  A Python signal handler
        interrupting the main thread mid-``Tracer._emit_complete``
        would self-deadlock on the tracer lock, a buffered ``fh.write``
        interrupted mid-call raises a reentrancy error, and a handler
        logging while the main thread holds the logging lock hangs the
        process.  flint's ``signal-safety`` rule machine-checks exactly
        this discipline (and recognizes this guard as the blessed
        deferred-flush pattern).  Programmatic requests flush inline —
        they are not in signal context.
        """
        if not self._event.is_set():
            self._reason = reason
            # time.time() is async-signal-safe enough for a float stamp
            # (no locks, no allocation beyond the float) — unlike the
            # IO/logging deferred to flush_now
            self._requested_at = time.time()
            self._flush_pending = True
            if not _from_signal:
                self.flush_now()
        self._event.set()

    def flush_now(self) -> None:
        """Run the deferred observability flush exactly once per
        request: the log line + structured ``preemption`` record +
        metrics-stream flush + registered trace-writer hooks.  Safe to
        call repeatedly; the round loop calls it when it observes
        ``requested`` (i.e. OUTSIDE signal-handler context), before
        starting the drain, so a SIGTERM'd run's streams are durable
        even if the drain wedges."""
        if not getattr(self, "_flush_pending", False):
            return
        self._flush_pending = False
        print_rank(f"preemption requested ({self._reason}); draining "
                   "and checkpointing", loglevel=logging.WARNING)
        try:
            from ..telemetry.metrics import flush_metrics, log_event
            log_event("preemption", reason=self._reason or "requested")
            flush_metrics()
        except Exception:  # flushing may never block the drain
            pass
        for hook in self._flush_hooks:
            try:
                hook()
            except Exception:
                pass

    # -- signal side ---------------------------------------------------
    def _on_signal(self, signum, frame):  # noqa: ARG002 - signal API
        self._hits += 1
        self.request(f"signal {signal.Signals(signum).name}",
                     _from_signal=True)
        if self._hits >= self.escalate_after:
            # a stuck drain must stay killable: restore the previous
            # dispositions so the NEXT signal behaves as if we were
            # never here.  os.write to the raw stderr fd is the one
            # async-signal-safe way to say so — this message must land
            # even when the process is wedged mid-logging, which is
            # precisely when logging from here would deadlock
            self.uninstall()
            os.write(2, b"repeated preemption signal: handlers "
                        b"restored; the next signal is fatal\n")

    def install(self) -> bool:
        """Install handlers; True when actually installed (main thread
        only — elsewhere the polling flag still works, signals don't)."""
        if self._installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            return False
        for sig in self.SIGNALS:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # non-main thread teardown
                pass
        self._prev.clear()
        self._installed = False
