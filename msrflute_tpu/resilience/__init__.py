"""Resilience layer: graceful preemption, checkpoint integrity/retry,
and deterministic fault injection.

The production deployments this simulator targets (preemptible TPU
slices, remote filesystems, flaky client populations — PAPER.md's
"millions of clients, tens of thousands per round") fail constantly and
partially.  This package is the engine's answer:

- :mod:`.preemption` — SIGTERM/SIGINT-driven graceful shutdown: the
  server loop drains the in-flight device round, writes an emergency
  checkpoint through the existing two-slot path, and exits resumable.
- :mod:`.integrity` — checkpoint checksums + sidecars, bounded
  retry-with-backoff, and the consecutive-failure escalation that turns
  "silently training uncheckpointed forever" into a loud abort.
- :mod:`.chaos` — seeded, config-driven fault schedule
  (``server_config.chaos``): client dropout and straggler step
  truncation fold into the fused round program's ``client_mask`` /
  ``sample_mask`` (no recompile; aggregation weights renormalize on
  device), checkpoint IO faults exercise the retry/fallback machinery,
  and ``preempt_at_round`` drives the kill/resume drill deterministically.
"""

from .chaos import ChaosSchedule, InfraFaults, make_chaos
from .integrity import (CheckpointCorruptionError, CheckpointEscalationError,
                        DurableIOError, DurableIOLadder, FailureEscalator,
                        RetryPolicy, blob_checksum, read_sidecar,
                        tree_checksum, verify_blob, write_sidecar)
from .preemption import GracefulPreemption, PreemptionHandler

__all__ = [
    "ChaosSchedule", "InfraFaults", "make_chaos",
    "CheckpointCorruptionError", "CheckpointEscalationError",
    "DurableIOError", "DurableIOLadder",
    "FailureEscalator", "RetryPolicy", "blob_checksum", "read_sidecar",
    "tree_checksum", "verify_blob", "write_sidecar",
    "GracefulPreemption", "PreemptionHandler",
]
