"""Deterministic fault injection (``server_config.chaos``).

A seeded, config-driven fault schedule for rehearsing the failure modes
real federated deployments hit constantly: clients that drop out
mid-round, stragglers that miss the synchronous barrier with only part
of their local steps done, checkpoint IO that errors transiently, the
scheduler preempting the whole job at an inconvenient round — and,
since fluteshield (``msrflute_tpu/robust/``), ADVERSARIAL update
corruption: clients whose pseudo-gradient comes back NaN, scaled up, or
sign-flipped (:meth:`ChaosSchedule.corrupt_modes`), the attack streams
the screened-aggregation defense is tested against end-to-end.

Determinism guarantee (pinned by ``tests/test_resilience.py``): every
fault decision is a pure function of ``(chaos.seed, fault stream, round
index or call index)`` via ``np.random.SeedSequence`` — NOT of any
process-global RNG, the training RNG, wall-clock, or call order across
streams.  Same seed + same chaos config => identical dropout/straggler
schedule — whether the run is serial or pipelined, fresh or resumed
mid-run (round-keyed, so resume-stable).  The IO-fault stream is
call-indexed from PROCESS start: deterministic within a process, but a
resumed process restarts it at call 0 — acceptable because injected IO
faults exercise the retry machinery and never touch model state (the
write-attempt ordering under the async checkpoint writer is itself not
resume-reproducible, so a persisted counter could not restore the
original alignment anyway).  The schedule is also firewalled
FROM training randomness: enabling chaos never perturbs client sampling
or model RNG streams; a ``dropout_rate: 0`` chaos block is bit-identical
to no chaos block at all.

How the client faults land (see ``engine/round.py``): the per-round
``drop``/``keep_steps`` vectors are data operands of the fused round
program — dropout multiplies into the existing ``client_mask`` (so
aggregation weights renormalize on device exactly like mesh padding) and
straggler truncation multiplies a step-bound mask into ``sample_mask``
(partial work still aggregates, CLIP/FedBuff-style).  No shape changes,
no recompile; the injected-fault counters ride the packed-stats
single-transfer path back to the host.

Under masked secure aggregation (``strategy: secure_agg``, PR 18) the
same fault vectors compose instead of refusing: a dropped or fully
truncated client leaves its pairwise masks STRANDED in the survivors'
submissions, and the strategy's ``cancel_masks`` finalize re-derives
and subtracts exactly those residual edges server-side, so the masked
survivor sum stays bit-identical to the unmasked one on the same
survivor set (``tests/test_secagg_compose.py``).  Every chaos-induced
loss shows up in the strategy's ``recovered_dropout`` counter, which
matches this schedule's ``dropped`` counter round for round — the
cross-check ``tools/chaos_smoke.py``'s secagg drill replays on the
host.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

#: stream tags keeping the fault streams independent of each other (and
#: of anything else seeded from small ints)
_CLIENT_STREAM = 0xC7A05C11
_IO_STREAM = 0xC7A051F0
#: adversarial update-corruption stream (fluteshield's attack half) —
#: its OWN tag so enabling corruption never moves the dropout/straggler
#: schedule an existing seed produces
_CORRUPT_STREAM = 0xC7A0C0DE
#: infrastructure-fault streams (flutearmor, ``chaos.infra``): one tag
#: PER host service, so raising one service's rate never moves another
#: service's schedule — and none of them ever move the client streams
_INFRA_STORE_WRITE_STREAM = 0xC7A05701
_INFRA_STORE_READ_STREAM = 0xC7A05702
_INFRA_PREFETCH_STREAM = 0xC7A0F7EC
_INFRA_WRITER_STREAM = 0xC7A03217
_INFRA_WRITEBACK_STREAM = 0xC7A03B0A

#: corruption mode encoding for the per-round ``[K]`` int32 operand the
#: fused round program consumes (engine/round.py); 0 = clean
CORRUPT_NONE = 0
CORRUPT_NAN = 1        # payload leaves become NaN (corrupted transfer)
CORRUPT_SCALE = 2      # payload x corrupt_scale_factor (scaling attack)
CORRUPT_SIGN_FLIP = 3  # payload x -corrupt_sign_flip_scale (sign flip)

#: "no straggler bound" sentinel — far above any realistic step grid
NO_BOUND = 1e9


class InfraFaults:
    """Seeded infrastructure-fault streams (``server_config.chaos.infra``).

    Where :class:`ChaosSchedule` makes the *cohort* adversarial, this
    makes the *host services* adversarial: the FleetRowStore's ``.npz``
    spill/read pair, the ControlStore round marker, the ``fleet-prefetch``
    daemon, the rollup/metrics writers, and the writeback ``device_get``.
    Each surface draws from its OWN call-indexed SeedSequence stream
    (``[seed, stream, call]``), so raising one service's rate never moves
    another service's schedule, retries of the same operation redraw
    fresh decisions (a schedule that always re-failed the retry would
    make rates < 1 untestable), and none of the draws touch the client
    fault streams — ``chaos.infra`` composes with every existing chaos
    block without perturbing it.  Like the checkpoint IO stream, the
    counters restart at call 0 in a resumed process: injected infra
    faults exercise the retry/degradation ladder and never touch model
    state, so exact cross-resume alignment is not required.
    """

    _STREAMS = {
        "store_write": _INFRA_STORE_WRITE_STREAM,
        "store_read": _INFRA_STORE_READ_STREAM,
        "prefetch": _INFRA_PREFETCH_STREAM,
        "writer": _INFRA_WRITER_STREAM,
        "writeback": _INFRA_WRITEBACK_STREAM,
    }

    def __init__(self, seed: int = 0,
                 store_write_error_rate: float = 0.0,
                 store_read_error_rate: float = 0.0,
                 prefetch_error_rate: float = 0.0,
                 prefetch_delay_rate: float = 0.0,
                 prefetch_delay_s: float = 0.05,
                 writer_error_rate: float = 0.0,
                 writeback_error_rate: float = 0.0):
        rates = {"store_write_error_rate": store_write_error_rate,
                 "store_read_error_rate": store_read_error_rate,
                 "prefetch_error_rate": prefetch_error_rate,
                 "prefetch_delay_rate": prefetch_delay_rate,
                 "writer_error_rate": writer_error_rate,
                 "writeback_error_rate": writeback_error_rate}
        for key, val in rates.items():
            if not 0.0 <= float(val) <= 1.0:
                raise ValueError(f"chaos.infra.{key} must be in [0, 1]")
        if float(prefetch_delay_s) < 0.0:
            raise ValueError("chaos.infra.prefetch_delay_s must be >= 0")
        self.seed = int(seed)
        self.rates = {k: float(v) for k, v in rates.items()}
        self.prefetch_delay_s = float(prefetch_delay_s)
        self._calls = {name: 0 for name in self._STREAMS}
        self._calls["prefetch_delay"] = 0
        #: per-surface injected-fault observability, merged into the
        #: server scorecard next to the client-fault counters
        self.counters: Dict[str, float] = {
            "store_write_faults": 0.0, "store_read_faults": 0.0,
            "prefetch_faults": 0.0, "prefetch_delays": 0.0,
            "writer_faults": 0.0, "writeback_faults": 0.0,
        }

    @property
    def enabled(self) -> bool:
        return any(v > 0.0 for v in self.rates.values())

    def _draw(self, surface: str, rate: float) -> bool:
        """One call-indexed decision on ``surface``'s stream.  The delay
        sub-stream shares the prefetch tag with a salt word appended, so
        delay draws never advance the prefetch *error* schedule."""
        if surface == "prefetch_delay":
            key = [self.seed, _INFRA_PREFETCH_STREAM,
                   self._calls[surface], 1]
        else:
            key = [self.seed, self._STREAMS[surface], self._calls[surface]]
        self._calls[surface] += 1
        rng = np.random.default_rng(np.random.SeedSequence(key))
        return bool(rng.random() < rate)

    def fault(self, surface: str) -> bool:
        """True when ``surface``'s next physical operation should fail."""
        rate = self.rates[f"{surface}_error_rate"]
        if self._draw(surface, rate):
            self.counters[f"{surface}_faults"] += 1
            return True
        return False

    def hook(self, surface: str):
        """A zero-arg raise-hook for ``surface`` (the shape the durable-IO
        ladder's fault probes expect), or None when the rate is 0 — so
        the hot paths stay branch-free with chaos disabled."""
        if self.rates[f"{surface}_error_rate"] <= 0.0:
            return None

        def _probe() -> None:
            if self.fault(surface):
                raise OSError(
                    f"chaos: injected {surface} infra fault "
                    f"#{int(self.counters[f'{surface}_faults'])} "
                    f"({surface}_error_rate="
                    f"{self.rates[f'{surface}_error_rate']})")
        return _probe

    def prefetch_delay(self) -> float:
        """Seconds the prefetch worker should stall before staging this
        chunk (0.0 almost always) — exercises the superseded-generation
        staging path without killing the thread."""
        if self._draw("prefetch_delay", self.rates["prefetch_delay_rate"]):
            self.counters["prefetch_delays"] += 1
            return self.prefetch_delay_s
        return 0.0

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"enabled": self.enabled, "seed": self.seed}
        out.update(self.rates)
        out["prefetch_delay_s"] = self.prefetch_delay_s
        return out


class ChaosSchedule:
    """Seeded fault schedule.  One instance per run; all methods are
    deterministic given the construction args (see module docstring)."""

    def __init__(self, seed: int = 0, dropout_rate: float = 0.0,
                 straggler_rate: float = 0.0,
                 straggler_inflation: float = 2.0,
                 ckpt_io_error_rate: float = 0.0,
                 preempt_at_round: Optional[int] = None,
                 corrupt_nan_rate: float = 0.0,
                 corrupt_scale_rate: float = 0.0,
                 corrupt_sign_flip_rate: float = 0.0,
                 corrupt_scale_factor: float = 10.0,
                 corrupt_sign_flip_scale: float = 1.0,
                 infra: Optional[InfraFaults] = None):
        if not 0.0 <= float(dropout_rate) <= 1.0:
            raise ValueError("chaos.dropout_rate must be in [0, 1]")
        if not 0.0 <= float(straggler_rate) <= 1.0:
            raise ValueError("chaos.straggler_rate must be in [0, 1]")
        if float(straggler_inflation) < 1.0:
            raise ValueError("chaos.straggler_inflation must be >= 1 "
                             "(it divides the steps a straggler completes "
                             "before the round barrier)")
        if not 0.0 <= float(ckpt_io_error_rate) <= 1.0:
            raise ValueError("chaos.ckpt_io_error_rate must be in [0, 1]")
        for key, val in (("corrupt_nan_rate", corrupt_nan_rate),
                         ("corrupt_scale_rate", corrupt_scale_rate),
                         ("corrupt_sign_flip_rate", corrupt_sign_flip_rate)):
            if not 0.0 <= float(val) <= 1.0:
                raise ValueError(f"chaos.{key} must be in [0, 1]")
        if float(corrupt_nan_rate) + float(corrupt_scale_rate) + \
                float(corrupt_sign_flip_rate) > 1.0:
            raise ValueError(
                "chaos corruption rates must sum to <= 1 (each client "
                "draws at most one corruption mode per round)")
        if float(corrupt_scale_factor) <= 0.0:
            raise ValueError("chaos.corrupt_scale_factor must be > 0")
        if float(corrupt_sign_flip_scale) <= 0.0:
            raise ValueError("chaos.corrupt_sign_flip_scale must be > 0")
        self.seed = int(seed)
        self.dropout_rate = float(dropout_rate)
        self.straggler_rate = float(straggler_rate)
        self.straggler_inflation = float(straggler_inflation)
        self.ckpt_io_error_rate = float(ckpt_io_error_rate)
        self.preempt_at_round = (None if preempt_at_round is None
                                 else int(preempt_at_round))
        self.corrupt_nan_rate = float(corrupt_nan_rate)
        self.corrupt_scale_rate = float(corrupt_scale_rate)
        self.corrupt_sign_flip_rate = float(corrupt_sign_flip_rate)
        self.corrupt_scale_factor = float(corrupt_scale_factor)
        self.corrupt_sign_flip_scale = float(corrupt_sign_flip_scale)
        self.infra = infra
        self._io_calls = 0
        #: injected-fault observability, accumulated by the server from
        #: the packed round stats (dropped/straggled/steps_lost +
        #: corruption modes) and by :meth:`io_fault` locally
        self.counters: Dict[str, float] = {
            "dropped": 0.0, "straggled": 0.0, "steps_lost": 0.0,
            "ckpt_io_faults": 0.0,
            "nan_injected": 0.0, "scaled": 0.0, "sign_flipped": 0.0,
        }

    # ------------------------------------------------------------------
    @property
    def has_client_faults(self) -> bool:
        return self.dropout_rate > 0.0 or self.straggler_rate > 0.0

    @property
    def has_corruption(self) -> bool:
        return (self.corrupt_nan_rate > 0.0 or
                self.corrupt_scale_rate > 0.0 or
                self.corrupt_sign_flip_rate > 0.0)

    @property
    def has_infra_faults(self) -> bool:
        return self.infra is not None and self.infra.enabled

    @staticmethod
    def _entropy(seed: int, stream: int, round_no: int,
                 salt: int) -> list:
        """SeedSequence entropy for one (round, salt) draw.  ``salt == 0``
        keeps the historical 3-word key, so existing seeds reproduce their
        exact schedules; non-zero salts (cohort-bucketing's per-bucket
        grids) get their own independent stream per bucket."""
        key = [seed, stream, int(round_no)]
        if salt:
            key.append(int(salt))
        return key

    def _round_rng(self, round_no: int,
                   salt: int = 0) -> np.random.Generator:
        return np.random.default_rng(np.random.SeedSequence(
            self._entropy(self.seed, _CLIENT_STREAM, round_no, salt)))

    def client_faults(self, round_no: int,
                      sample_mask: np.ndarray,
                      salt: int = 0
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Per-round fault vectors for one packed round batch.

        ``sample_mask``: the host-packed ``[K, S, B]`` grid (padded client
        slots included).  Returns ``(drop [K] f32 in {0,1},
        keep_steps [K] f32)`` — ``keep_steps`` is the step budget a
        straggler completes before the barrier
        (``ceil(real_steps / straggler_inflation)``, min 1) and
        :data:`NO_BOUND` for everyone else.  Decisions are keyed on
        (seed, round, client SLOT), so the schedule is identical however
        the host loop is arranged (serial, pipelined, resumed).
        ``salt`` keys an independent sub-stream per bucketed grid."""
        k = int(sample_mask.shape[0])
        rng = self._round_rng(round_no, salt)
        # one per-round stream, fixed draw order (drop then straggle):
        # the determinism guarantee is per (seed, chaos config)
        drop = (rng.random(k) < self.dropout_rate).astype(np.float32)
        straggle = rng.random(k) < self.straggler_rate
        real_steps = (np.asarray(sample_mask).sum(axis=2) > 0).sum(axis=1)
        keep = np.where(
            straggle,
            np.maximum(np.ceil(real_steps / self.straggler_inflation), 1.0),
            NO_BOUND).astype(np.float32)
        return drop, keep

    # ------------------------------------------------------------------
    def corrupt_modes(self, round_no: int, k: int,
                      salt: int = 0) -> np.ndarray:
        """Per-round adversarial corruption assignment for one packed
        round batch: ``[K] int32`` of :data:`CORRUPT_NONE` /
        :data:`CORRUPT_NAN` / :data:`CORRUPT_SCALE` /
        :data:`CORRUPT_SIGN_FLIP`.

        Keyed per ``(seed, corrupt stream, round)`` — its OWN
        SeedSequence stream, so adding corruption to an existing chaos
        config never moves the dropout/straggler schedule, and the
        decisions are call-order independent (serial == pipelined ==
        resumed) exactly like :meth:`client_faults`.  One uniform draw
        per client slot partitions into modes, so each client suffers at
        most one corruption per round.  Padding/dropped slots draw too
        (slot-keyed determinism) — the round program gates corruption on
        the live ``client_mask`` so their draws are inert.
        ``salt`` keys an independent sub-stream per bucketed grid
        (``salt == 0`` reproduces the historical key).
        """
        rng = np.random.default_rng(np.random.SeedSequence(
            self._entropy(self.seed, _CORRUPT_STREAM, round_no, salt)))
        u = rng.random(int(k))
        mode = np.full(int(k), CORRUPT_NONE, np.int32)
        hi = self.corrupt_nan_rate + self.corrupt_scale_rate + \
            self.corrupt_sign_flip_rate
        mode[u < hi] = CORRUPT_SIGN_FLIP
        mode[u < self.corrupt_nan_rate + self.corrupt_scale_rate] = \
            CORRUPT_SCALE
        mode[u < self.corrupt_nan_rate] = CORRUPT_NAN
        return mode

    # ------------------------------------------------------------------
    def io_fault(self) -> bool:
        """One checkpoint-IO fault decision (call-indexed stream): True
        means "this physical write attempt fails".  The counter advances
        on every call, so retries of the same save draw fresh decisions —
        a fault schedule that always re-failed the retry would make
        ``ckpt_io_error_rate < 1`` untestable."""
        rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed, _IO_STREAM, self._io_calls]))
        self._io_calls += 1
        if rng.random() < self.ckpt_io_error_rate:
            self.counters["ckpt_io_faults"] += 1
            return True
        return False

    def io_fault_hook(self) -> None:
        """The :class:`~..engine.checkpoint.CheckpointManager` write hook:
        raises a synthetic ``OSError`` when the schedule says so."""
        if self.io_fault():
            raise OSError(
                f"chaos: injected checkpoint IO fault "
                f"#{int(self.counters['ckpt_io_faults'])} "
                f"(ckpt_io_error_rate={self.ckpt_io_error_rate})")

    # ------------------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        """The bench-contract record: enough to make a chaos run
        impossible to confuse with a clean baseline."""
        return {
            "enabled": True,
            "seed": self.seed,
            "dropout_rate": self.dropout_rate,
            "straggler_rate": self.straggler_rate,
            "straggler_inflation": self.straggler_inflation,
            "ckpt_io_error_rate": self.ckpt_io_error_rate,
            "preempt_at_round": self.preempt_at_round,
            "corrupt_nan_rate": self.corrupt_nan_rate,
            "corrupt_scale_rate": self.corrupt_scale_rate,
            "corrupt_sign_flip_rate": self.corrupt_sign_flip_rate,
            "corrupt_scale_factor": self.corrupt_scale_factor,
            "corrupt_sign_flip_scale": self.corrupt_sign_flip_scale,
            "infra": (self.infra.describe()
                      if self.infra is not None else None),
        }


def make_chaos(server_config) -> Optional[ChaosSchedule]:
    """Build the run's :class:`ChaosSchedule` from
    ``server_config.chaos`` (None when absent or ``enable: false``)."""
    raw = server_config.get("chaos") if server_config is not None else None
    if not raw:
        return None
    raw = dict(raw)
    if not raw.pop("enable", True):
        return None
    infra_raw = raw.get("infra")
    infra = None
    if infra_raw:
        if not isinstance(infra_raw, dict):
            raise ValueError("chaos.infra must be a mapping of "
                             "infrastructure fault rates")
        infra = InfraFaults(
            seed=raw.get("seed", 0),
            store_write_error_rate=infra_raw.get(
                "store_write_error_rate", 0.0),
            store_read_error_rate=infra_raw.get(
                "store_read_error_rate", 0.0),
            prefetch_error_rate=infra_raw.get("prefetch_error_rate", 0.0),
            prefetch_delay_rate=infra_raw.get("prefetch_delay_rate", 0.0),
            prefetch_delay_s=infra_raw.get("prefetch_delay_s", 0.05),
            writer_error_rate=infra_raw.get("writer_error_rate", 0.0),
            writeback_error_rate=infra_raw.get(
                "writeback_error_rate", 0.0),
        )
    return ChaosSchedule(
        seed=raw.get("seed", 0),
        dropout_rate=raw.get("dropout_rate", 0.0),
        straggler_rate=raw.get("straggler_rate", 0.0),
        straggler_inflation=raw.get("straggler_inflation", 2.0),
        ckpt_io_error_rate=raw.get("ckpt_io_error_rate", 0.0),
        preempt_at_round=raw.get("preempt_at_round"),
        corrupt_nan_rate=raw.get("corrupt_nan_rate", 0.0),
        corrupt_scale_rate=raw.get("corrupt_scale_rate", 0.0),
        corrupt_sign_flip_rate=raw.get("corrupt_sign_flip_rate", 0.0),
        corrupt_scale_factor=raw.get("corrupt_scale_factor", 10.0),
        corrupt_sign_flip_scale=raw.get("corrupt_sign_flip_scale", 1.0),
        infra=infra,
    )
