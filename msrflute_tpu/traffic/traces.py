"""Seeded arrival-process traces (``server_config.traffic.trace``).

Every scenario before fluteflow's traffic plane drew a cohort at a
round boundary from a population that was always available.  Real
deployments serve clients that arrive when they arrive: phones come
online in the evening, a push notification triggers a flash crowd, IoT
fleets check in on duty cycles.  A trace models exactly that — a
per-tick arrival probability vector over the whole population — and the
:class:`~.schedule.TrafficSchedule` turns those draws into an
event-driven availability timeline the server samples from.

Determinism guarantee (pinned by ``tests/test_traffic.py``, same
discipline as ``resilience/chaos.py``): every arrival decision is a
pure function of ``(traffic.seed, stream tag, tick)`` via
``np.random.SeedSequence`` — NOT of any process-global RNG, the
training RNG, the chaos streams, or call order.  Traffic has its OWN
stream tags, so enabling the traffic plane never moves the
dropout/straggler/corruption schedule an existing chaos seed produces,
and vice versa.  Draws are slot-keyed over the full population each
tick (in-flight clients consume their draw and discard it), so the
timeline one client sees never shifts because another client's state
changed — serial, pipelined, and resumed runs replay the identical
trace.

Traces are vectorized: :meth:`ArrivalTrace.probs` returns the whole
``[N]`` probability vector for a tick in one NumPy expression, so a
10^6-client fleet population costs one array op per tick, not a Python
loop.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

#: stream tags keeping the arrival plane independent of the chaos
#: streams (0xC7A0....) and of anything else seeded from small ints
_ARRIVAL_STREAM = 0x7AF1CA11
_DURATION_STREAM = 0x7AF1D07A

#: trace names accepted by :func:`make_trace` / the schema enum
TRACE_NAMES = ("poisson", "diurnal", "bursty", "device_classes")


def _entropy(seed: int, stream: int, tick: int) -> list:
    """SeedSequence entropy for one per-tick vector draw — the 3-word
    ``(seed, stream, tick)`` key mirrors chaos' round-keyed scheme, so
    the trace is a pure function of the tick index (resume-stable)."""
    return [int(seed), int(stream), int(tick)]


def tick_rng(seed: int, stream: int, tick: int) -> np.random.Generator:
    return np.random.default_rng(np.random.SeedSequence(
        _entropy(seed, stream, tick)))


class ArrivalTrace:
    """One arrival process over a fixed population.

    Subclasses implement :meth:`probs` — the per-tick, per-client
    probability that an idle client becomes available during that tick.
    ``duration_scale`` is a static per-client training-time multiplier
    (device-class mixtures make their slow classes slow here)."""

    name = "base"

    def __init__(self, population: int):
        if int(population) < 1:
            raise ValueError("traffic trace population must be >= 1")
        self.population = int(population)

    def probs(self, tick: int) -> np.ndarray:
        """``[N] float64`` in ``[0, 1]``: arrival probability per client
        for this tick."""
        raise NotImplementedError

    def duration_scale(self) -> np.ndarray:
        """``[N] float64 >= 1``: per-client training-duration multiplier
        (1.0 = the schedule's base duration draw, untouched)."""
        return np.ones(self.population, np.float64)

    # ------------------------------------------------------------------
    def _uniform_probs(self, rate: float) -> np.ndarray:
        """Spread ``rate`` expected arrivals/tick across the population."""
        return np.full(self.population,
                       min(float(rate) / self.population, 1.0), np.float64)

    def describe(self) -> Dict[str, Any]:
        return {"trace": self.name, "population": self.population}


class PoissonTrace(ArrivalTrace):
    """Homogeneous arrivals: ``rate`` expected arrivals per tick, spread
    uniformly over the population — the memoryless baseline every other
    trace perturbs."""

    name = "poisson"

    def __init__(self, population: int, rate: float = 8.0):
        super().__init__(population)
        if float(rate) <= 0.0:
            raise ValueError("traffic.rate must be > 0")
        self.rate = float(rate)

    def probs(self, tick: int) -> np.ndarray:
        return self._uniform_probs(self.rate)

    def describe(self) -> Dict[str, Any]:
        return dict(super().describe(), rate=self.rate)


class DiurnalTrace(ArrivalTrace):
    """Sinusoidal day/night cycle: the instantaneous rate is
    ``rate * max(0, 1 + depth * sin(2*pi*tick / period))`` — ``depth``
    1.0 means the trough goes fully dark (phones asleep), 0.0 collapses
    to :class:`PoissonTrace`."""

    name = "diurnal"

    def __init__(self, population: int, rate: float = 8.0,
                 period: int = 64, depth: float = 0.8):
        super().__init__(population)
        if float(rate) <= 0.0:
            raise ValueError("traffic.rate must be > 0")
        if int(period) < 2:
            raise ValueError("traffic.period must be >= 2 ticks")
        if not 0.0 <= float(depth) <= 1.0:
            raise ValueError("traffic.depth must be in [0, 1]")
        self.rate = float(rate)
        self.period = int(period)
        self.depth = float(depth)

    def probs(self, tick: int) -> np.ndarray:
        mult = max(0.0, 1.0 + self.depth *
                   np.sin(2.0 * np.pi * tick / self.period))
        return self._uniform_probs(self.rate * mult)

    def describe(self) -> Dict[str, Any]:
        return dict(super().describe(), rate=self.rate,
                    period=self.period, depth=self.depth)


class BurstyTrace(ArrivalTrace):
    """Flash crowd: a quiet baseline of ``rate`` arrivals/tick, and
    every ``burst_every`` ticks a burst window of ``burst_len`` ticks at
    ``burst_rate`` — the push-notification stampede that makes the
    synchronous barrier look worst and a staleness-tolerant buffer look
    best (or not; ``bench.py traffic_ab`` records which)."""

    name = "bursty"

    def __init__(self, population: int, rate: float = 2.0,
                 burst_rate: float = 32.0, burst_every: int = 48,
                 burst_len: int = 8):
        super().__init__(population)
        if float(rate) <= 0.0 or float(burst_rate) <= 0.0:
            raise ValueError("traffic rate/burst_rate must be > 0")
        if int(burst_every) < 1 or int(burst_len) < 1:
            raise ValueError("traffic burst_every/burst_len must be >= 1")
        if int(burst_len) > int(burst_every):
            raise ValueError(
                "traffic.burst_len must be <= burst_every (the burst "
                "window repeats inside the cycle)")
        self.rate = float(rate)
        self.burst_rate = float(burst_rate)
        self.burst_every = int(burst_every)
        self.burst_len = int(burst_len)

    def probs(self, tick: int) -> np.ndarray:
        in_burst = (int(tick) % self.burst_every) < self.burst_len
        return self._uniform_probs(self.burst_rate if in_burst
                                   else self.rate)

    def describe(self) -> Dict[str, Any]:
        return dict(super().describe(), rate=self.rate,
                    burst_rate=self.burst_rate,
                    burst_every=self.burst_every,
                    burst_len=self.burst_len)


#: device-class defaults: a phone-ish fast majority, a tablet-ish
#: evening class, and a slow IoT duty-cycle tail
_DEFAULT_CLASSES = (
    {"fraction": 0.6, "rate": 6.0, "window": 1.0, "phase": 0.0,
     "duration_scale": 1.0},
    {"fraction": 0.3, "rate": 6.0, "window": 0.5, "phase": 0.5,
     "duration_scale": 2.0},
    {"fraction": 0.1, "rate": 2.0, "window": 0.25, "phase": 0.25,
     "duration_scale": 4.0},
)

_CLASS_KEYS = {"fraction", "rate", "window", "phase", "duration_scale"}


class DeviceClassTrace(ArrivalTrace):
    """Population mixture with distinct availability windows: each class
    owns a contiguous id range (``fraction`` of the population, assigned
    deterministically so the partition never depends on draw order),
    arrives at ``rate`` expected arrivals/tick while its window is open
    — open means ``(tick/period + phase) mod 1 < window`` — and trains
    ``duration_scale`` x slower than the base duration draw."""

    name = "device_classes"

    def __init__(self, population: int,
                 classes: Optional[List[Dict[str, Any]]] = None,
                 period: int = 64):
        super().__init__(population)
        if int(period) < 2:
            raise ValueError("traffic.period must be >= 2 ticks")
        self.period = int(period)
        raw = [dict(c) for c in (classes or _DEFAULT_CLASSES)]
        if not raw:
            raise ValueError("traffic.classes must be a non-empty list")
        for i, c in enumerate(raw):
            unknown = set(c) - _CLASS_KEYS
            if unknown:
                raise ValueError(
                    f"traffic.classes[{i}] has unknown keys "
                    f"{sorted(unknown)} (known: {sorted(_CLASS_KEYS)})")
            if not 0.0 < float(c.get("fraction", 0.0)) <= 1.0:
                raise ValueError(
                    f"traffic.classes[{i}].fraction must be in (0, 1]")
            if float(c.get("rate", 1.0)) <= 0.0:
                raise ValueError(f"traffic.classes[{i}].rate must be > 0")
            if not 0.0 < float(c.get("window", 1.0)) <= 1.0:
                raise ValueError(
                    f"traffic.classes[{i}].window must be in (0, 1]")
            if not 0.0 <= float(c.get("phase", 0.0)) < 1.0:
                raise ValueError(
                    f"traffic.classes[{i}].phase must be in [0, 1)")
            if float(c.get("duration_scale", 1.0)) < 1.0:
                raise ValueError(
                    f"traffic.classes[{i}].duration_scale must be >= 1")
        total = sum(float(c["fraction"]) for c in raw)
        if total > 1.0 + 1e-9:
            raise ValueError(
                f"traffic.classes fractions sum to {total:.3f} > 1")
        self.classes = raw
        # contiguous deterministic partition; any remainder after the
        # listed fractions joins the LAST class (never unassigned)
        bounds = np.cumsum([float(c["fraction"]) for c in raw])
        edges = np.minimum(np.round(bounds * self.population),
                           self.population).astype(np.int64)
        edges[-1] = self.population
        self._edges = np.concatenate([[0], edges])
        self._class_of = np.zeros(self.population, np.int64)
        for ci in range(len(raw)):
            self._class_of[self._edges[ci]:self._edges[ci + 1]] = ci

    def probs(self, tick: int) -> np.ndarray:
        p = np.zeros(self.population, np.float64)
        for ci, c in enumerate(self.classes):
            lo, hi = int(self._edges[ci]), int(self._edges[ci + 1])
            n_c = hi - lo
            if n_c <= 0:
                continue
            frac = (float(tick) / self.period +
                    float(c.get("phase", 0.0))) % 1.0
            if frac < float(c.get("window", 1.0)):
                p[lo:hi] = min(float(c.get("rate", 1.0)) / n_c, 1.0)
        return p

    def duration_scale(self) -> np.ndarray:
        scale = np.ones(self.population, np.float64)
        for ci, c in enumerate(self.classes):
            lo, hi = int(self._edges[ci]), int(self._edges[ci + 1])
            scale[lo:hi] = float(c.get("duration_scale", 1.0))
        return scale

    def describe(self) -> Dict[str, Any]:
        return dict(super().describe(), period=self.period,
                    classes=[dict(c) for c in self.classes])


def make_trace(raw: Dict[str, Any], population: int) -> ArrivalTrace:
    """Build the configured trace from a ``server_config.traffic`` dict.

    Unknown trace names raise with the full catalogue (the schema enum
    rejects them at config load; this is the defense for programmatic
    construction)."""
    name = str(raw.get("trace", "poisson")).lower()
    if name == "poisson":
        return PoissonTrace(population, rate=raw.get("rate", 8.0))
    if name == "diurnal":
        return DiurnalTrace(population, rate=raw.get("rate", 8.0),
                            period=raw.get("period", 64),
                            depth=raw.get("depth", 0.8))
    if name == "bursty":
        return BurstyTrace(population, rate=raw.get("rate", 2.0),
                           burst_rate=raw.get("burst_rate", 32.0),
                           burst_every=raw.get("burst_every", 48),
                           burst_len=raw.get("burst_len", 8))
    if name == "device_classes":
        return DeviceClassTrace(population,
                                classes=raw.get("classes"),
                                period=raw.get("period", 64))
    raise ValueError(
        f"traffic.trace: {name!r} not in {TRACE_NAMES}")
