"""fluteflow — the event-driven arrival plane (``server_config.traffic``).

Seeded traffic traces (:mod:`.traces`) model WHEN clients become
available; the :class:`~.schedule.TrafficSchedule` turns arrivals into
buffer-triggered round fires carrying TRUE per-update staleness.  See
``docs/config_extensions.md`` ("traffic") for knobs, the trace
catalogue, and the composition/refusal lists.
"""

from .traces import (ArrivalTrace, BurstyTrace, DeviceClassTrace,
                     DiurnalTrace, PoissonTrace, TRACE_NAMES, make_trace)
from .schedule import (STALE_HIST_BINS, TRAFFIC_MODES, TrafficSchedule,
                       make_traffic)

__all__ = [
    "ArrivalTrace", "PoissonTrace", "DiurnalTrace", "BurstyTrace",
    "DeviceClassTrace", "TRACE_NAMES", "make_trace",
    "TrafficSchedule", "TRAFFIC_MODES", "STALE_HIST_BINS",
    "make_traffic",
]
