"""Event-driven round firing (``server_config.traffic``).

The :class:`TrafficSchedule` replaces "sample a cohort at a round
boundary" with an arrival-plane simulation: clients become available
per a seeded :mod:`trace <.traces>`, train for a drawn duration, and
deliver their update.  Aggregation FIRES when the buffer holds
``buffer_size`` completed updates — one fire == one engine round, so
the fused round program's geometry never changes; only WHO is in the
cohort and HOW STALE each update is comes from the timeline.

Two modes, same trace draws (so an A/B compares orchestration, not
luck):

- ``buffered`` (FedBuff-style async): every delivery enters the buffer
  carrying its TRUE staleness — the number of server fires since the
  broadcast version the client trained from (``fires_now - v_start``),
  not a modeled draw.  The buffer fires as soon as it fills, stale
  work and all.
- ``sync`` (the baseline the async tier is measured against): a
  delivery computed against a superseded version is DISCARDED — the
  synchronous barrier's waste, made explicit and counted
  (``sync_discarded``) — and the buffer fires when ``buffer_size``
  fresh deliveries land, which is exactly the last cohort member
  clearing the barrier.  All sync staleness is 0 by construction.

Determinism (pinned by ``tests/test_traffic.py``): the timeline is a
pure function of ``(traffic.seed, trace config, buffer_size, mode)``.
Fires are simulated once, in tick order, and CACHED — ``cohort(r)`` /
``staleness(r)`` replay identically however the host loop is arranged
(serial, depth-N pipelined with lookahead sampling, or resumed via
:meth:`fast_forward`, which just replays the same cached prefix).
Deliveries within a tick process in client-id order, never arrival
order, so the fire sequence is independent of Python iteration
incidentals.

Observability: per-fire records (tick, wait, staleness) and rollup
counters (arrival rate, buffer occupancy, the staleness histogram)
feed the ``buffer_fired`` instant events and the scorecard's traffic
block; the on-device histogram the packed stats carry (engine) is
cross-checked against :attr:`stale_hist` — the host replay oracle.
"""

from __future__ import annotations

import heapq
from typing import Any, Dict, List, Optional

import numpy as np

from .traces import (ArrivalTrace, _ARRIVAL_STREAM, _DURATION_STREAM,
                     make_trace, tick_rng)

#: staleness-histogram bin count shared by the host oracle and the
#: packed-stats operand path (engine/round.py): bins 0..BINS-2 count
#: exact staleness, the last bin is the open ">= BINS-1" overflow
STALE_HIST_BINS = 8

#: traffic modes accepted by :func:`make_traffic` / the schema enum
TRAFFIC_MODES = ("sync", "buffered")


class TrafficSchedule:
    """Seeded arrival/firing timeline.  One instance per run; every
    accessor is deterministic given the construction args (see module
    docstring)."""

    def __init__(self, trace: ArrivalTrace, buffer_size: int,
                 mode: str = "buffered", seed: int = 0,
                 duration_lo: int = 1, duration_hi: int = 4,
                 max_idle_ticks: int = 50_000):
        if str(mode) not in TRAFFIC_MODES:
            raise ValueError(
                f"traffic.mode: {mode!r} not in {TRAFFIC_MODES}")
        if int(buffer_size) < 1:
            raise ValueError("traffic.buffer_size must be >= 1")
        if int(buffer_size) > trace.population:
            raise ValueError(
                f"traffic.buffer_size ({int(buffer_size)}) exceeds the "
                f"population ({trace.population}) — the buffer could "
                "never fill")
        if int(duration_lo) < 1 or int(duration_hi) < int(duration_lo):
            raise ValueError(
                "traffic duration bounds must satisfy "
                "1 <= duration_lo <= duration_hi")
        if int(max_idle_ticks) < 1:
            raise ValueError("traffic.max_idle_ticks must be >= 1")
        self.trace = trace
        self.population = trace.population
        self.buffer_size = int(buffer_size)
        self.mode = str(mode)
        self.seed = int(seed)
        self.duration_lo = int(duration_lo)
        self.duration_hi = int(duration_hi)
        self.max_idle_ticks = int(max_idle_ticks)

        # --- simulation state (advanced lazily, never rewound) --------
        self._tick = 0
        self._version = 0                 # == fires so far
        self._last_fire_tick = 0
        self._in_flight = np.zeros(self.population, bool)
        self._pending: List[tuple] = []   # heap of (deliver_tick, cid, v0)
        self._buffer: List[tuple] = []    # [(cid, staleness)]
        self._fires: List[Dict[str, Any]] = []
        self._dur_scale = trace.duration_scale()

        #: host-replay-oracle rollups the telemetry drain reads
        self.counters: Dict[str, float] = {
            "arrivals": 0.0, "deliveries": 0.0, "fires": 0.0,
            "sync_discarded": 0.0, "stale_sum": 0.0, "stale_max": 0.0,
            "buffer_occupancy_ticks": 0.0,
        }
        #: staleness histogram over FIRED updates (see STALE_HIST_BINS)
        self.stale_hist = np.zeros(STALE_HIST_BINS, np.int64)

    # ------------------------------------------------------------------
    def _fire(self, tick: int) -> None:
        cohort = np.array([cid for cid, _ in self._buffer], np.int64)
        stale = np.array([s for _, s in self._buffer], np.int32)
        # buffered entries held their clients busy; the fire releases
        # them (guaranteeing each cohort lists a client at most once)
        self._in_flight[cohort] = False
        np.add.at(self.stale_hist,
                  np.minimum(stale, STALE_HIST_BINS - 1), 1)
        self.counters["fires"] += 1
        self.counters["stale_sum"] += float(stale.sum())
        self.counters["stale_max"] = max(self.counters["stale_max"],
                                         float(stale.max(initial=0)))
        self._fires.append({
            "round": len(self._fires),
            "tick": int(tick),
            "wait_ticks": int(tick - self._last_fire_tick),
            "cohort": cohort,
            "staleness": stale,
        })
        self._last_fire_tick = int(tick)
        self._version += 1
        self._buffer = []

    def _step_tick(self) -> None:
        t = self._tick
        # 1) deliveries due this tick, in client-id order (never arrival
        #    order) — a fire mid-tick bumps the version, so later
        #    deliveries in the same tick really are one step staler
        due = []
        while self._pending and self._pending[0][0] <= t:
            due.append(heapq.heappop(self._pending))
        for _, cid, v0 in sorted(due, key=lambda e: e[1]):
            self.counters["deliveries"] += 1
            stale = self._version - v0
            if self.mode == "sync" and stale > 0:
                # the synchronous barrier: work against a superseded
                # broadcast is waste, counted rather than hidden
                self.counters["sync_discarded"] += 1
                self._in_flight[cid] = False
                continue
            # the client stays busy while its update waits in the
            # buffer — released by the fire, never re-drawn before it
            self._buffer.append((int(cid), int(stale)))
            if len(self._buffer) == self.buffer_size:
                self._fire(t)
        # 2) fresh arrivals: full-population slot-keyed draws (in-flight
        #    clients consume theirs inertly, so dedup never shifts the
        #    timeline other clients see)
        u = tick_rng(self.seed, _ARRIVAL_STREAM, t).random(self.population)
        arrive = np.flatnonzero((u < self.trace.probs(t)) &
                                ~self._in_flight)
        if arrive.size:
            ud = tick_rng(self.seed, _DURATION_STREAM,
                          t).random(self.population)
            span = self.duration_hi - self.duration_lo + 1
            base = self.duration_lo + np.floor(ud * span)
            dur = np.maximum(np.ceil(base * self._dur_scale), 1.0)
            self.counters["arrivals"] += float(arrive.size)
            for cid in arrive:
                self._in_flight[cid] = True
                heapq.heappush(self._pending,
                               (t + int(dur[cid]), int(cid),
                                self._version))
        self.counters["buffer_occupancy_ticks"] += len(self._buffer)
        self._tick += 1

    def _advance_to(self, round_no: int) -> None:
        """Simulate until fire ``round_no`` exists (cached thereafter)."""
        while len(self._fires) <= int(round_no):
            if self._tick - self._last_fire_tick > self.max_idle_ticks:
                raise RuntimeError(
                    f"traffic trace starved: no fire for "
                    f"{self.max_idle_ticks} ticks (trace="
                    f"{self.trace.name}, buffer_size={self.buffer_size},"
                    f" arrivals={int(self.counters['arrivals'])}, "
                    f"deliveries={int(self.counters['deliveries'])}) — "
                    "raise the arrival rate, widen the availability "
                    "window, or shrink buffer_size")
            self._step_tick()

    # ------------------------------------------------------------------
    def fire(self, round_no: int) -> Dict[str, Any]:
        """The full fire record for one round (simulating forward as
        needed): round, tick, wait_ticks, cohort, staleness."""
        self._advance_to(round_no)
        return self._fires[int(round_no)]

    def cohort(self, round_no: int) -> np.ndarray:
        """``[buffer_size] int64`` client ids for one fire."""
        return self.fire(round_no)["cohort"]

    def staleness(self, round_no: int) -> np.ndarray:
        """``[buffer_size] int32`` true staleness per cohort member."""
        return self.fire(round_no)["staleness"]

    def staleness_vector(self, round_no: int,
                         client_ids: np.ndarray) -> np.ndarray:
        """Staleness aligned to an arbitrary packed client-id vector
        (the host-packed batch order, padding included): ids outside the
        fire's cohort — padding slots — map to 0, which the engine's
        live-mask gating keeps inert anyway."""
        rec = self.fire(round_no)
        lut = {int(c): int(s) for c, s in zip(rec["cohort"],
                                              rec["staleness"])}
        return np.array([lut.get(int(c), 0) for c in client_ids],
                        np.int32)

    def fast_forward(self, round_no: int) -> None:
        """Resume support: make fires ``[0, round_no)`` available.  The
        timeline is a pure function of the seed, so this is a cache
        warm-up, not a state restore — a resumed process replays the
        identical fire sequence the preempted one saw."""
        if int(round_no) > 0:
            self._advance_to(int(round_no) - 1)

    # ------------------------------------------------------------------
    def arrival_rate(self) -> float:
        """Observed arrivals per tick over the simulated horizon."""
        return (self.counters["arrivals"] / self._tick
                if self._tick else 0.0)

    def mean_buffer_occupancy(self) -> float:
        """Mean end-of-tick buffer fill over the simulated horizon."""
        return (self.counters["buffer_occupancy_ticks"] / self._tick
                if self._tick else 0.0)

    def describe(self) -> Dict[str, Any]:
        """The bench-contract record: enough to make a traffic run
        impossible to confuse with a boundary-sampled baseline."""
        return {
            "enabled": True,
            "mode": self.mode,
            "seed": self.seed,
            "buffer_size": self.buffer_size,
            "duration_lo": self.duration_lo,
            "duration_hi": self.duration_hi,
            **self.trace.describe(),
        }


#: ``server_config.traffic`` keys :func:`make_traffic` consumes itself
#: (everything else in the block parameterizes the trace)
_SCHEDULE_KEYS = ("enable", "mode", "seed", "buffer_size",
                  "duration_lo", "duration_hi", "max_idle_ticks",
                  "target_accuracy")


def make_traffic(server_config, num_clients: int
                 ) -> Optional[TrafficSchedule]:
    """Build the run's :class:`TrafficSchedule` from
    ``server_config.traffic`` (None when absent or ``enable: false``).

    ``buffer_size`` defaults to the run's cohort size — the fused round
    program's ``[K, S, B]`` geometry is compiled for exactly K client
    slots, so the buffer IS the cohort (the FedBuff paper's
    buffer == K mapping); the server refuses a mismatch."""
    raw = (server_config.get("traffic")
           if server_config is not None else None)
    if not raw:
        return None
    raw = dict(raw)
    if not raw.pop("enable", True):
        return None
    cohort = int(server_config.get("num_clients_per_iteration", 1) or 1)
    return TrafficSchedule(
        make_trace(raw, int(num_clients)),
        buffer_size=int(raw.get("buffer_size", cohort)),
        mode=raw.get("mode", "buffered"),
        seed=raw.get("seed", 0),
        duration_lo=raw.get("duration_lo", 1),
        duration_hi=raw.get("duration_hi", 4),
        max_idle_ticks=raw.get("max_idle_ticks", 50_000),
    )
