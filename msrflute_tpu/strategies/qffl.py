"""q-FFL fair aggregation (arXiv:1905.10497 — net-new vs the reference).

The reference ships FedAvg/FedProx/DGA/FedLabels
(``core/strategies/__init__.py:9-23``); q-FFL adds the fairness axis: in
the q-FFL objective ``sum_k (n_k/n) F_k(w)^{q+1} / (q+1)``, clients with
HIGHER loss get proportionally more aggregation weight, flattening the
accuracy distribution across heterogeneous clients instead of optimizing
only the average.  This implements the weighting form used for the
paper's q-FedSGD family: client weight

    w_k = n_k * (mean_loss_k + eps)^q        (server_config.qffl_q)

``mean_loss_k`` is ``stats['mean_sample_loss']``: the per-SAMPLE mean
training loss (``engine/client_update.py`` accumulates
``batch_mean_loss * batch_sample_count``), which is invariant to how
the client's samples were split into batches — a per-step or per-``n_k``
mean would scale with ``ceil(n_k/B)/n_k`` and silently favor clients
whose sample count straddles a batch boundary.  It measures loss
*during* local training rather than exactly at the broadcast weights
``F_k(w^t)`` — the standard cheap estimator; an exact ``F_k(w^t)``
would cost an extra forward epoch per round.

``q = 0`` reduces EXACTLY to FedAvg (the sample-count factor goes
through the same ``filter_weight`` cap FedAvg applies, so the two are
identical weight-for-weight at any ``n_k`` — pinned by test); larger
``q`` interpolates toward minimax fairness (AFL).  The weight is
computed in-jit inside the same vmapped client step every strategy uses
(``base.client_step``), so the fairness reweighting adds zero host
round-trips and composes with the quantization payload transform
unchanged.  DP does NOT compose (local DP's max_weight clamp squashes
the heavy tail; global DP's accounting assumes bounded weights) and is
rejected in ``__init__``.

The ``loss^q`` factor is intentionally heavy-tailed (that is the
mechanism), so it multiplies OUTSIDE the reference MAX_WEIGHT=100 cap —
squashing exactly the high-loss clients would silently degrade the
strategy back toward uniform.  NaN/Inf still zero out; only relative
weights matter (the combine normalizes by the weight sum).
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import filter_weight
from .fedavg import FedAvg

#: guard rail far above any real capped_n * loss^q, not a shaping cap
_QFFL_MAX_WEIGHT = 1e9


class QFFL(FedAvg):
    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        # DP breaks q-FFL in both directions, so reject loudly (the same
        # discipline Scaffold applies): local DP clamps the client weight
        # at dp_config.max_weight, squashing exactly the high-loss heavy
        # tail the objective depends on (silent degradation back toward
        # uniform); global DP's RDP accounting assumes a bounded
        # per-client contribution, which the uncapped loss^q weight
        # violates — one high-loss client can dominate the normalized
        # aggregate far beyond the accounted sensitivity.
        if dp_config is not None and (
                dp_config.get("enable_local_dp", False) or
                dp_config.get("enable_global_dp", False)):
            raise ValueError(
                "strategy: qffl does not compose with "
                "dp_config.enable_local_dp / enable_global_dp — local DP "
                "clamps the loss^q weight at max_weight (degrading q-FFL "
                "toward uniform), global DP's accounting assumes bounded "
                "per-client weight; use fedavg/dga for DP runs")
        self.q = float(config.server_config.get("qffl_q", 1.0))
        if self.q < 0:
            raise ValueError(f"server_config.qffl_q must be >= 0, "
                             f"got {self.q}")

    def client_weight(self, *, num_samples, train_loss, stats, rng):
        mean_loss = stats["mean_sample_loss"]
        # eps floors a zero loss: a fully-fit client keeps an (epsilon)
        # vote instead of dividing the round by zero total weight when
        # every client has converged
        weight = filter_weight(num_samples) * \
            jnp.power(mean_loss + 1e-10, self.q)
        weight = jnp.nan_to_num(weight, nan=0.0, posinf=0.0, neginf=0.0)
        return jnp.clip(weight, 0.0, _QFFL_MAX_WEIGHT)
