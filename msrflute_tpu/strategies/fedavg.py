"""FedAvg / FedProx aggregation.

Parity target: reference ``core/strategies/fedavg.py`` — client weight =
``num_samples`` scaled through the DP ``weight_scaler`` (``fedavg.py:61-91``),
optional ``freeze_layer`` gradient zeroing, server-side weighted average of
pseudo-gradients divided by total weight (``fedavg.py:119-166``).  FedProx
shares this aggregator; its proximal term lives in the client update
(reference ``core/trainer.py:416-501``).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .base import BaseStrategy, filter_weight


class FedAvg(BaseStrategy):

    def client_weight(self, *, num_samples, train_loss, stats, rng):
        return filter_weight(num_samples)

    def transform_payload(self, pseudo_grad: Any, weight: jnp.ndarray,
                          rng: jax.Array,
                          quant_threshold=None) -> Tuple[Any, jnp.ndarray]:
        if self.dp_config is not None and self.dp_config.get("enable_local_dp", False):
            from ..privacy import apply_local_dp
            pseudo_grad, weight = apply_local_dp(
                pseudo_grad, weight, self.dp_config, add_weight_noise=False, rng=rng)
        return pseudo_grad, weight
