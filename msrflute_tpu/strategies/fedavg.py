"""FedAvg / FedProx aggregation.

Parity target: reference ``core/strategies/fedavg.py`` — client weight =
``num_samples`` scaled through the DP ``weight_scaler`` (``fedavg.py:61-91``),
optional ``freeze_layer`` gradient zeroing, server-side weighted average of
pseudo-gradients divided by total weight (``fedavg.py:119-166``).  FedProx
shares this aggregator; its proximal term lives in the client update
(reference ``core/trainer.py:416-501``).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .base import BaseStrategy, filter_weight


class FedAvg(BaseStrategy):
    """FedAvg/FedProx aggregation; optional Andrew-et-al.-style adaptive
    DP clipping (arXiv:1905.03871 — net-new vs the reference, whose clip
    norm is a fixed ``dp_config.max_grad``):

    ``dp_config.adaptive_clipping: {target_quantile: 0.5, clip_lr: 0.2,
    initial_clip: <= max_grad}`` tracks the target quantile of client
    update norms with the geometric update ``C <- C * exp(-lr*(b - q))``
    where ``b`` is the fraction of this round's clients whose update norm
    was <= C.  Everything runs in-jit: the clip rides strategy state, the
    below-clip indicator is aggregated as an extra psum'd payload part,
    and the noise sigma keeps the static max_grad sensitivity bound
    (always >= the adaptive clip).

    Threat model (documented caveat): this follows the paper's CENTRAL-DP
    setting — the below-clip count is noised at the aggregator (sigma_b),
    not per client, and the count query is an additional mechanism that
    the RDP accountant does not yet compose into the reported epsilon.
    Under a strict local-DP threat model the raw indicator leaves the
    client; a warning is logged when eps >= 0 so the budget accounting
    gap is visible.
    """

    supports_adaptive_clipping = True

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        self.adaptive_clip = None
        if dp_config is not None and dp_config.get("adaptive_clipping") and \
                not dp_config.get("enable_local_dp", False):
            raise ValueError(
                "dp_config.adaptive_clipping requires enable_local_dp: true "
                "(the clip applies inside the local-DP transform)")
        if dp_config is not None and dp_config.get("enable_local_dp", False):
            ac = dp_config.get("adaptive_clipping")
            if ac:
                max_grad = float(dp_config.get("max_grad", 1.0))
                self.adaptive_clip = {
                    "target": float(ac.get("target_quantile", 0.5)),
                    "lr": float(ac.get("clip_lr", 0.2)),
                    "init": min(float(ac.get("initial_clip", max_grad)),
                                max_grad),
                    # noise on the below-clip count (paper default m/20
                    # applied at combine time when left unset)
                    "count_sigma": ac.get("count_sigma"),
                }
                self.stateful = True
                if float(dp_config.get("eps", -1.0)) >= 0:
                    from ..utils.logging import print_rank
                    print_rank(
                        "adaptive_clipping: the below-clip count query is "
                        "noised centrally (sigma_b) and is NOT composed "
                        "into the RDP accountant — budget accordingly")

    def init_state(self, params_like: Any) -> Any:
        if self.adaptive_clip is None:
            return super().init_state(params_like)
        return {"dp_clip": jnp.asarray(self.adaptive_clip["init"],
                                       jnp.float32)}

    def client_weight(self, *, num_samples, train_loss, stats, rng):
        return filter_weight(num_samples)

    def client_step(self, client_update, global_params, arrays, sample_mask,
                    client_lr, rng, round_idx=None, leakage_threshold=None,
                    quant_threshold=None, strategy_state=None,
                    grad_offset=None):
        parts, tl, ns, stats = super().client_step(
            client_update, global_params, arrays, sample_mask, client_lr,
            rng, round_idx=round_idx, leakage_threshold=leakage_threshold,
            quant_threshold=quant_threshold, strategy_state=strategy_state,
            grad_offset=grad_offset)
        if self.adaptive_clip is not None and strategy_state is not None:
            # below-clip indicator vs the PRE-clip update norm, which
            # transform_payload recorded in this client's stats dict; it
            # aggregates as its own psum'd part.  The indicator weight
            # mirrors the payload's "was this client dropped" status so
            # the quantile tracks the same population being aggregated.
            clip = strategy_state["dp_clip"]
            norm = stats.pop("update_norm")
            below = (norm <= clip).astype(jnp.float32)
            ind_w = (parts["default"][1] > 0).astype(jnp.float32)
            parts["clip_frac"] = ({"below": below}, ind_w)
        return parts, tl, ns, stats

    def transform_payload(self, pseudo_grad: Any, weight: jnp.ndarray,
                          rng: jax.Array, quant_threshold=None,
                          strategy_state=None,
                          stats=None) -> Tuple[Any, jnp.ndarray]:
        if self.dp_config is not None and self.dp_config.get("enable_local_dp", False):
            from ..privacy import apply_local_dp
            clip = None
            if self.adaptive_clip is not None and strategy_state is not None:
                clip = strategy_state["dp_clip"]
                if stats is not None:
                    import optax
                    stats["update_norm"] = optax.global_norm(pseudo_grad)
            pseudo_grad, weight = apply_local_dp(
                pseudo_grad, weight, self.dp_config, add_weight_noise=False,
                rng=rng, clip_override=clip)
        return pseudo_grad, weight

    def combine_parts(self, part_sums, deferred, state, rng, num_clients,
                      global_params=None):
        if self.adaptive_clip is None or "clip_frac" not in part_sums:
            return super().combine_parts(part_sums, deferred, state, rng,
                                         num_clients,
                                         global_params=global_params)
        agg, _ = self.combine(part_sums["default"]["grad_sum"],
                              part_sums["default"]["weight_sum"],
                              deferred, (), rng, num_clients)
        frac_part = part_sums["clip_frac"]
        below_count = frac_part["grad_sum"]["below"]
        m = jnp.maximum(frac_part["weight_sum"], 1.0)
        ac = self.adaptive_clip
        # privatize the indicator count (Andrew et al. §3: the released
        # clip depends on data, so the count gets Gaussian noise sigma_b;
        # default m/20 per the paper).  Skipped only when the count noise
        # is explicitly disabled (count_sigma: 0) — e.g. clip-only mode
        # where no DP guarantee is claimed anyway.
        sigma_b = ac["count_sigma"]
        sigma_b = m / 20.0 if sigma_b is None else float(sigma_b)
        noisy_count = below_count + sigma_b * jax.random.normal(
            jax.random.fold_in(rng, 23))
        b = jnp.clip(noisy_count / m, 0.0, 1.0)
        new_clip = state["dp_clip"] * jnp.exp(-ac["lr"] * (b - ac["target"]))
        new_clip = jnp.minimum(
            new_clip, float(self.dp_config.get("max_grad", 1.0)))
        bus = getattr(self, "devbus", None)
        if bus is not None and bus.enabled:
            # flutescope ride-along: the (noised) below-clip fraction and
            # the adapted clip leave through the packed-stats single
            # transfer — the dp observability the server previously only
            # had via its separately-stashed dp_clip copy
            bus.publish("dp_clip_frac", b)
            bus.publish("dp_clip", new_clip)
        return agg, {"dp_clip": new_clip}
