"""Aggregation strategies.

Parity target: reference ``core/strategies/`` — ``select_strategy``
(``core/strategies/__init__.py:9-23``) mapping ``'dga'`` -> DGA,
``'fedavg'``/``'fedprox'`` -> FedAvg, ``'fedlabels'`` -> FedLabels.
"""

from __future__ import annotations

from .base import BaseStrategy  # noqa: F401
from .fedavg import FedAvg  # noqa: F401
from .dga import DGA  # noqa: F401


def select_strategy(name: str) -> type:
    key = (name or "fedavg").lower()
    if key == "dga":
        return DGA
    if key in ("fedavg", "fedprox"):
        return FedAvg
    if key == "fedac":
        from .fedac import FedAC
        return FedAC
    if key == "scaffold":
        from .scaffold import Scaffold
        return Scaffold
    if key == "fedlabels":
        from .fedlabels import FedLabels
        return FedLabels
    if key == "qffl":
        from .qffl import QFFL
        return QFFL
    if key in ("secure_agg", "secagg", "secureagg"):
        from .secure_agg import SecureAgg
        return SecureAgg
    if key in ("ef_quant", "efquant"):
        from .ef_quant import EFQuant
        return EFQuant
    if key == "fedbuff":
        from .fedbuff import FedBuff
        return FedBuff
    raise ValueError(f"unknown strategy {name!r}")
