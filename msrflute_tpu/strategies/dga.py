"""DGA — Dynamic Gradient Aggregation (arXiv:2106.07578).

Parity target: reference ``core/strategies/dga.py``:

- client softmax weight ``exp(-beta * metric)`` where metric is
  ``train_loss/num_samples`` or a gradient sufficient stat
  (``mag``/``var``/``mean``) per ``weight_train_loss``
  (``dga.py:110-129``), filtered through ``filter_weight``;
- local DP noising of payload + weight (``dga.py:131-134``);
- gradient quantization (``dga.py:148-149``);
- server-side **staleness simulation**: with probability ``stale_prob`` a
  client's weighted gradient is deferred to the next round
  (``dga.py:260-284``) — here the deferred sum is an explicit pytree state
  threaded through the jitted round step instead of host-side lists;
- global DP after aggregation (``dga.py:222-226``);
- optional RL weight re-estimation stays a host-side hook
  (``dga.py:286-406``, see :mod:`msrflute_tpu.rl`).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import BaseStrategy, filter_weight


class DGA(BaseStrategy):

    stateful = True

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        sc = config.server_config
        self.aggregate_median = sc.get("aggregate_median", "softmax")
        self.softmax_beta = float(sc.get("softmax_beta", 1.0))
        self.weight_metric = sc.get("weight_train_loss", "train_loss")
        self.stale_prob = float(sc.get("stale_prob", 0.0))
        cc = config.client_config
        mc = config.model_config
        self.quant_threshold = cc.get("quant_thresh")
        if self.quant_threshold is None and mc is not None:
            self.quant_threshold = mc.get("quant_threshold")
        bits = cc.get("quant_bits")
        if bits is None and mc is not None:
            bits = mc.get("quant_bits")
        self.quant_bits = int(bits) if bits is not None else 10
        # O(n) histogram-CDF threshold instead of a sort per leaf per
        # client (see ops.quantization.approx_quantile_abs)
        self.quant_approx = bool(cc.get("quant_approx", False))

    def client_weight(self, *, num_samples, train_loss, stats, rng):
        if self.aggregate_median == "softmax":
            if self.weight_metric == "train_loss":
                metric = train_loss / jnp.maximum(num_samples, 1.0)
            elif self.weight_metric == "mag_var_loss":
                metric = stats["var"]
            elif self.weight_metric == "mag_mean_loss":
                metric = stats["mean"]
            else:
                metric = stats["mag"]
            weight = jnp.exp(-self.softmax_beta * metric)
        else:
            weight = jnp.ones_like(train_loss)
        return filter_weight(weight)

    def transform_payload(self, pseudo_grad: Any, weight: jnp.ndarray,
                          rng: jax.Array, quant_threshold=None,
                          strategy_state=None,
                          stats=None) -> Tuple[Any, jnp.ndarray]:
        dp_rng, _ = jax.random.split(rng)
        if self.dp_config is not None and self.dp_config.get("enable_local_dp", False):
            from ..privacy import apply_local_dp
            pseudo_grad, weight = apply_local_dp(
                pseudo_grad, weight, self.dp_config,
                add_weight_noise=(self.aggregate_median == "softmax"), rng=dp_rng)
        if self.quant_threshold is not None:
            from ..ops.quantization import quantize_pytree
            # the threshold may be annealed per round (reference
            # core/server.py:294-298): a dynamic scalar overrides the
            # static config value when >= 0
            thr = (quant_threshold if quant_threshold is not None
                   else float(self.quant_threshold))
            thr = jnp.where(jnp.asarray(thr) >= 0, thr,
                            float(self.quant_threshold))
            pseudo_grad = quantize_pytree(
                pseudo_grad, quant_threshold=thr, quant_bits=self.quant_bits,
                approx=self.quant_approx)
        return pseudo_grad, weight

    # ---- staleness buffer (replaces dga.py:260-284 host lists) --------
    def init_state(self, params_like: Any) -> Any:
        if self.stale_prob <= 0.0:
            return ()
        zeros = jax.tree.map(jnp.zeros_like, params_like)
        return {"stale_grad_sum": zeros, "stale_weight_sum": jnp.zeros(())}

    def combine(self, weighted_grad_sum, weight_sum, deferred, state, rng,
                num_clients=None):
        new_state = state
        if self.stale_prob > 0.0 and deferred is not None:
            # fold in LAST round's deferred contributions; bank this round's
            # deferred sums for next round (dga.py:260-284 semantics).
            weighted_grad_sum = jax.tree.map(
                lambda tot, s: tot + s, weighted_grad_sum, state["stale_grad_sum"])
            weight_sum = weight_sum + state["stale_weight_sum"]
            new_state = {"stale_grad_sum": deferred["grad_sum"],
                         "stale_weight_sum": deferred["weight_sum"]}
        denom = jnp.maximum(weight_sum, 1e-12)
        agg = jax.tree.map(lambda g: g / denom, weighted_grad_sum)
        if self.dp_config is not None and self.dp_config.get("enable_global_dp", False):
            from ..privacy import apply_global_dp
            n = num_clients if num_clients is not None else jnp.ones(())
            agg = apply_global_dp(agg, self.dp_config,
                                  rng=jax.random.fold_in(rng, 1), num_clients=n)
        return agg, new_state
