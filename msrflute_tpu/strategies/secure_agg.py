"""Secure aggregation (Bonawitz et al., CCS'17) — net-new vs the reference.

FLUTE has no secure aggregation; this is the TPU-native simulation of the
pairwise-masking protocol, for research on SecAgg-composed FL: each
client adds pairwise one-time masks to a fixed-point encoding of its
weighted update, the server's sum cancels every mask exactly (modular
int32 arithmetic — the reason real SecAgg works over a finite group, and
what float masks cannot do), and no single client's submission reveals
its update.

What is simulated faithfully:

- **fixed-point group arithmetic**: the weighted pseudo-gradient is
  clipped to ``+-clip`` and encoded as int32 with ``frac_bits``
  fractional bits; all masking/summation is int32 with two's-complement
  wraparound (XLA semantics), decoded once after aggregation.
- **pairwise masks**: for the round's SAMPLED cohort, each pair (i, j)
  shares a mask derived from a public pair key (round, min_id, max_id);
  the lower id adds it, the higher id subtracts it, so the cohort sum
  telescopes to zero.  Masks are full-range uint32 bits — each
  submission is uniformly distributed in the group regardless of the
  payload (perfect hiding within the simulation).
- **mid-round client loss** (dropout, stragglers going fully dark,
  fluteshield quarantine): clients mask toward the round's sampled
  cohort, so a client that vanishes AFTER the masking round leaves its
  pairmates' one-sided masks stranded in the sum.  The server-side
  recovery (:meth:`cancel_masks`) re-derives exactly those residual
  masks — every (survivor, lost) edge — and subtracts them in the same
  int32 group, the simulation-side analogue of the Shamir-share mask
  recovery real SecAgg runs for dropped participants.  The decoded sum
  over the survivors is then BIT-identical to the unmasked path on the
  same survivor set, and aggregation weights renormalize on device over
  survivors only.  Per-cause recovery counters
  (``secagg_recovered_dropout`` / ``secagg_recovered_quarantine``) and
  the ``secagg_abort`` flag ride the packed-stats single transfer.
- **zero-weight clients**: a client zeroed by the privacy filter
  (``filter_weight`` / attack-metric dropping) still submits its masks
  over an encoded zero, exactly like a SecAgg participant that must
  deliver its masked input once it joined the masking round.  Padding
  slots (id -1) never enter the protocol.

What is NOT simulated: the key-agreement / Shamir-recovery transport
(there is no adversarial server in a single-controller simulation — the
controller runs the clients; mask keys derive from public ids).  The
simulated property is the aggregate-only dataflow: the summed payload
is the ONLY place client updates become visible, which is the invariant
SecAgg research composes against.

**Mask graph**: ``graph: "full"`` (default) pairs every two cohort
members — O(K²) mask generations per round, the CCS'17 baseline.
``graph: "log"`` is the log-degree topology of Bell et al. (CCS'20,
"Secure Single-Server Aggregation with (Poly)Logarithmic Overhead"):
each cohort slot masks only toward slots at circulant offsets
``±2^t mod K``, so the per-round cost drops to O(K·log K) mask trees
while the offset set's closure under negation keeps every edge
symmetric — the cohort sum still telescopes to zero exactly.  The
hiding argument weakens from "any K-1 colluders" to "each client has
at least one honest present neighbor", the standard log-degree
tradeoff — and under HEAVY dropout a log-graph client can lose every
neighbor, at which point its submission is protected only by the group
encoding (see the RUNBOOK's "Dropout under the mask" drill); for the
aggregate-only dataflow this simulation exists to study, the sums are
identical (tested bit-for-bit against "full").

Config (``server_config.secure_agg``, bool or dict; weighting
semantics stay FedAvg's)::

    strategy: secure_agg
    server_config:
      secure_agg: {frac_bits: 12, clip: 4.0, seed: 0, graph: full,
                   min_survivors: 0}

``min_survivors > 0`` aborts a round whose surviving cohort shrank
below the threshold (real SecAgg's t-of-K liveness floor): the round's
aggregate zeroes on device — a no-op server step — and the
``secagg_abort`` counter/event records it.

Range contract: the clip applies to the PSEUDO-GRADIENT (before the
public weight), so the int32 group must hold ``sum_k w_k * clip *
2^frac``.  Client weights are capped at ``filter_weight``'s
MAX_WEIGHT=100 and K is known from ``num_clients_per_iteration``, so
the worst case is static — the init RAISES when ``K * 100 * clip *
2^frac >= 2^31`` (defaults admit K up to 1310), pointing at the
offending knob.  Dropout/quarantine only SHRINK the summed cohort and
renormalization happens on the float side of the decode (the weight
denominator), so the full-K bound IS the worst case for every sampled
sub-cohort — a partial round can never overflow a group the full round
fits in.  Within that bound the int32 SUM is exact; decoding splits it
into 15-bit halves so the only float rounding is at the final
aggregate's own magnitude (relative ~2^-24).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .fedavg import FedAvg

#: secure_agg option vocabulary (schema.py's config-load check mirrors
#: this — the quiet-failure rule for misspelled knobs)
SECURE_AGG_KEYS = ("frac_bits", "clip", "seed", "graph", "min_survivors")


class SecureAgg(FedAvg):

    supports_staleness = False
    supports_rl = False
    wants_cohort = True
    unit_weight_parts = frozenset({"default"})

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        sa = config.server_config.get("secure_agg", True)
        if not isinstance(sa, (dict, bool)):
            raise ValueError(
                f"server_config.secure_agg must be a bool or an options "
                f"dict, got {type(sa).__name__}")
        sa = sa if isinstance(sa, dict) else {}
        unknown = set(sa) - set(SECURE_AGG_KEYS)
        if unknown:
            raise ValueError(
                f"server_config.secure_agg has unknown keys {sorted(unknown)}"
                f" (known: {', '.join(SECURE_AGG_KEYS)})")
        self.frac_bits = int(sa.get("frac_bits", 12))
        self.clip = float(sa.get("clip", 4.0))
        self.seed = int(sa.get("seed", 0))
        self.graph = str(sa.get("graph", "full")).lower()
        self.min_survivors = int(sa.get("min_survivors", 0))
        if self.graph not in ("full", "log"):
            raise ValueError(
                f"secure_agg.graph must be 'full' or 'log', "
                f"got {self.graph!r}")
        if not 1 <= self.frac_bits <= 24:
            raise ValueError(
                f"secure_agg.frac_bits must be in [1, 24], "
                f"got {self.frac_bits}")
        if not self.clip > 0:
            raise ValueError(f"secure_agg.clip must be > 0, got {self.clip}")
        if self.min_survivors < 0:
            raise ValueError(
                f"secure_agg.min_survivors must be >= 0, "
                f"got {self.min_survivors}")
        # static range contract: worst-case round sum must fit int32.
        # K from config ("lo:hi" takes hi), weights capped by
        # filter_weight's MAX_WEIGHT=100 (strategies/base.py).  The bound
        # is checked for the FULL sampled cohort: dropout/straggler/
        # quarantine loss only removes addends (mask cancellation is
        # exact in the group, and survivor re-weighting happens in the
        # float decode's denominator), so no partial cohort can exceed
        # the full cohort's sum.
        raw_k = config.server_config.get("num_clients_per_iteration", 10)
        k = int(str(raw_k).split(":")[-1])
        worst = k * 100.0 * self.clip * float(1 << self.frac_bits)
        if worst >= 2.0 ** 31:
            max_k = int((2.0 ** 31 - 1) //
                        (100.0 * self.clip * float(1 << self.frac_bits)))
            raise ValueError(
                f"secure_agg range contract violated: "
                f"num_clients_per_iteration={k} x MAX_WEIGHT=100 x "
                f"clip={self.clip} x 2^{self.frac_bits} = {worst:.3g} >= "
                f"2^31 — the int32 group must hold the worst-case round "
                f"sum (dropout renormalization cannot relax this: it "
                f"divides on the float side, after the group sum).  "
                f"Lower num_clients_per_iteration to <= {max_k}, or "
                f"lower clip / frac_bits")
        if dp_config is not None and (
                dp_config.get("enable_local_dp", False) or
                dp_config.get("enable_global_dp", False)):
            raise ValueError(
                "strategy: secure_agg does not compose with dp_config DP "
                "modes yet — local DP noise breaks the fixed-point range "
                "contract and the RDP accounting assumes the unmasked "
                "pipeline; run one or the other")
        if bool(config.get("dump_norm_stats",
                           config.server_config.get("dump_norm_stats",
                                                    False))):
            raise ValueError(
                "dump_norm_stats reads per-client payloads, which under "
                "secure_agg are masked int32 group elements — the dumped "
                "norms/cosines would be noise.  (Chaos faults, "
                "fluteshield screening, cohort bucketing, and pipelining "
                "now ride the masked path via survivor mask recovery; "
                "the refusals that REMAIN are per-client-payload readers "
                "and re-weighters: dump_norm_stats here, wantRL and the "
                "stack aggregators in the engine, adaptive clipping and "
                "DP modes above.)  Disable one of the two")
        #: run-level recovery observability, accumulated by the server
        #: from the packed round stats (the ChaosSchedule.counters /
        #: Shield.counters discipline)
        self.counters: Dict[str, float] = {
            "recovered_dropout": 0.0,
            "recovered_quarantine": 0.0,
            "aborted_rounds": 0.0,
        }

    # ------------------------------------------------------------------
    @staticmethod
    def _log_offsets(k: int):
        """Circulant offsets ``±2^t mod K`` (Bell et al. CCS'20 topology),
        deduplicated and with 0 removed — a STATIC python list (K is the
        cohort array length, known at trace time).  The set is closed
        under negation mod K, so slot ``p`` lists slot ``q`` iff ``q``
        lists ``p`` — every edge is symmetric and the cohort sum
        telescopes exactly like the full graph's."""
        offs = set()
        t = 1
        while t < k:
            offs.add(t % k)
            offs.add((-t) % k)
            t *= 2
        offs.discard(0)
        return sorted(offs)

    def _pair_masks(self, tree, self_id, cohort_ids, cohort_mask,
                    round_idx):
        """Sum of this client's signed pairwise masks, one tree.

        A ``fori_loop`` folds each partner's mask into a running int32
        sum, so peak memory is ONE mask tree — a vmap over partners
        would materialize [cohort, n_params] intermediates per client
        (O(K^2 x n_params) across the round program).

        ``graph: "full"`` iterates every cohort slot (O(K) masks per
        client); ``graph: "log"`` iterates only the circulant ``±2^t``
        neighbor slots (O(log K) masks per client).  Mask keys derive
        from the PAIR's public ids either way, so which endpoint computes
        an edge never matters.

        ``cohort_mask`` is the round's SAMPLED mask, before chaos
        dropout or quarantine fold in: masking toward the sampled cohort
        (not the surviving one) is what makes mid-round loss a
        server-side recovery problem (:meth:`cancel_masks`) instead of a
        client-side re-keying one — the faithful SecAgg shape."""
        base = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                  jnp.asarray(round_idx, jnp.int32))
        leaves, treedef = jax.tree.flatten(tree)
        k = cohort_ids.shape[0]

        def fold_edge(jid, jm, acc):
            lo = jnp.minimum(self_id, jid)
            hi = jnp.maximum(self_id, jid)
            # public pair key; clamp: padding ids (-1) are gated out but
            # fold_in still traces on them
            key = jax.random.fold_in(
                jax.random.fold_in(base, jnp.maximum(lo, 0)),
                jnp.maximum(hi, 0))
            gate = ((jm > 0) & (jid >= 0) &
                    (jid != self_id)).astype(jnp.int32)
            sign = jnp.where(jid > self_id, jnp.int32(1), jnp.int32(-1))
            out = []
            for li, (a, leaf) in enumerate(zip(acc, leaves)):
                bits = jax.random.bits(jax.random.fold_in(key, li),
                                       leaf.shape, jnp.uint32)
                # uint32 -> int32 reinterpretation keeps the full group
                out.append(a + gate * sign *
                           jax.lax.bitcast_convert_type(bits, jnp.int32))
            return out

        acc0 = [jnp.zeros(leaf.shape, jnp.int32) for leaf in leaves]
        if self.graph == "log" and k > 1:
            # own slot: cohort ids are unique for real clients, so argmax
            # finds it; padding submissions are zeroed by ``present``
            # downstream, their mask sum is irrelevant
            pos = jnp.argmax(
                (cohort_ids == self_id).astype(jnp.int32)).astype(jnp.int32)
            offs = jnp.asarray(self._log_offsets(k), jnp.int32)

            def body(t, acc):
                jidx = jnp.mod(pos + offs[t], k)
                return fold_edge(cohort_ids[jidx], cohort_mask[jidx], acc)

            summed = jax.lax.fori_loop(0, offs.shape[0], body, acc0)
        else:
            summed = jax.lax.fori_loop(
                0, k, lambda j, acc: fold_edge(cohort_ids[j],
                                               cohort_mask[j], acc), acc0)
        return jax.tree.unflatten(treedef, summed)

    # ------------------------------------------------------------------
    def mask_parts(self, parts, self_id, self_mask, cohort_ids,
                   cohort_mask, round_idx):
        """TRACED, per client: fixed-point-encode and pairwise-mask the
        default payload part.

        Called by the engine AFTER the strategy's ``client_step`` and
        the chaos corruption transform (corruption attacks the
        float payload the client would transmit — attacking the int32
        group element would model a transport-integrity failure, not an
        adversarial client), and BEFORE the weighted summation.  Returns
        ``(parts, sub_norm)`` where ``sub_norm`` is the true L2 norm of
        the submitted (post-corruption, pre-mask) payload — the one
        scalar a verified-aggregation scheme (a ZK norm-bound proof)
        reveals to the server, which is exactly what fluteshield's
        masked screening votes on (``Shield.screen_masked``)."""
        pg, w = parts["default"]
        sq = sum(jnp.sum(g ** 2) for g in jax.tree.leaves(pg)
                 if jnp.issubdtype(g.dtype, jnp.floating))
        sub_norm = jnp.sqrt(sq)
        scale = jnp.float32(1 << self.frac_bits)
        # clip the pseudo-gradient THEN weight (clipping the product
        # would silently squash heavy-weight clients and break the
        # FedAvg-match property); a dropped client (w == 0) encodes zero
        enc = jax.tree.map(
            lambda g: jnp.round(
                jnp.clip(g, -self.clip, self.clip) * w * scale
            ).astype(jnp.int32),
            pg)
        masks = self._pair_masks(enc, self_id, cohort_ids, cohort_mask,
                                 round_idx)
        present = (self_mask > 0).astype(jnp.int32)
        masked = jax.tree.map(lambda e, m: (e + m) * present, enc, masks)
        out = dict(parts)
        out["default"] = (masked, w)
        return out, sub_norm

    # ------------------------------------------------------------------
    def cancel_masks(self, grad_sum, cohort_ids, sampled_mask,
                     survivor_mask, round_idx):
        """TRACED, once per round (per bucket): subtract the residual
        one-sided masks of every (survivor, lost) pair from the masked
        int32 ``grad_sum``.

        A client sampled into the masking round but absent from the sum
        (chaos dropout, a quarantined submission) leaves each surviving
        pairmate's signed mask toward it uncancelled.  The residual is

            sum over survivors i, lost j, edge (i, j):
                sign_i(j) * m_{(round, min_id, max_id)}

        re-derivable from public ids — the simulation analogue of the
        Shamir-share recovery real SecAgg performs for dropped clients.
        Subtracting it in the SAME int32 group restores exact
        telescoping: the remaining sum is precisely the survivors'
        encoded payloads.  Both masks (``sampled_mask``/``survivor_mask``)
        are DATA operands, so a dropout pattern never recompiles, and a
        round with no loss runs the edges through a ``lax.cond`` whose
        false branch skips the mask derivation entirely — the no-chaos
        fast path pays K (or K·log K) cheap gate checks, not a second
        round of mask generation."""
        base = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                  jnp.asarray(round_idx, jnp.int32))
        leaves, treedef = jax.tree.flatten(grad_sum)
        k = cohort_ids.shape[0]
        surv = survivor_mask > 0
        samp = sampled_mask > 0

        def edge(p, q, acc):
            iid = cohort_ids[p]
            jid = cohort_ids[q]
            # exactly the edges a surviving i's submission masked toward
            # a sampled-but-lost j: the _pair_masks gate, restricted to
            # (present i, absent j)
            gate = (surv[p] & samp[q] & ~surv[q] &
                    (iid >= 0) & (jid >= 0) & (jid != iid))
            lo = jnp.minimum(iid, jid)
            hi = jnp.maximum(iid, jid)
            key = jax.random.fold_in(
                jax.random.fold_in(base, jnp.maximum(lo, 0)),
                jnp.maximum(hi, 0))
            sign = jnp.where(jid > iid, jnp.int32(1), jnp.int32(-1))

            def sub(a):
                out = []
                for li, al in enumerate(a):
                    bits = jax.random.bits(jax.random.fold_in(key, li),
                                           al.shape, jnp.uint32)
                    out.append(al - sign * jax.lax.bitcast_convert_type(
                        bits, jnp.int32))
                return out

            return jax.lax.cond(gate, sub, lambda a: list(a), acc)

        if self.graph == "log" and k > 1:
            offs = self._log_offsets(k)
            n = len(offs)
            offs_a = jnp.asarray(offs, jnp.int32)

            def body(t, acc):
                p = t // n
                q = jnp.mod(p + offs_a[jnp.mod(t, n)], k)
                return edge(p, q, acc)

            summed = jax.lax.fori_loop(0, k * n, body, leaves)
        else:
            summed = jax.lax.fori_loop(
                0, k * k,
                lambda t, acc: edge(t // k, jnp.mod(t, k), acc), leaves)
        return jax.tree.unflatten(treedef, summed)

    # ------------------------------------------------------------------
    def combine_parts(self, part_sums: Dict[str, Dict[str, Any]],
                      deferred, state, rng, num_clients,
                      global_params=None) -> Tuple[Any, Any]:
        enc_sum = part_sums["default"]["grad_sum"]
        w_sum = part_sums["default"]["weight_sum"]
        denom = jnp.maximum(w_sum, 1e-12)
        scale = jnp.float32(1 << self.frac_bits)

        def decode(e):
            # split decode: a direct int32->f32 cast rounds above 2^24;
            # 15-bit halves are each f32-exact and the one rounding left
            # is at the final aggregate's own magnitude
            hi = jnp.right_shift(e, 15)            # arithmetic: floor
            lo = e - jnp.left_shift(hi, 15)        # in [0, 2^15)
            k = 1.0 / scale / denom
            return (hi.astype(jnp.float32) * (32768.0 * k)
                    + lo.astype(jnp.float32) * k)

        return jax.tree.map(decode, enc_sum), state
