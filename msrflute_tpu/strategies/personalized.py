"""Fused personalization — per-user local models + alphas as carry state.

The host personalization path (``engine/personalization.py``) runs a
separate jitted personal pass inside an overridden ``_sample()`` hook,
which reads the live global params per round and therefore forces the
server's serial fallback.  With ``server_config.fused_carry: true`` the
PersonalizationServer swaps in this strategy instead: the per-user local
models (flat, ravel-pytree order), interpolation ``alpha``s, and a
``seen`` gate live in ``strategy_state`` as donated ``[N, ...]`` device
buffers, and each sampled client's local pass + alpha SGD step runs
inside the SAME vmap'd client body as the global pass — the round
pipelines like FedAvg (universal overlap, PR 6).

Cold-start semantics match ``personalization_init: global`` (the
default): a user's first participation clones the round's live global
params in-program (``seen == 0`` selects the broadcast params over the
table row).  ``random``/``initial`` init would need per-user host state
and stay on the host path.  The personalized convex-interpolation eval
reads the tables back with one explicit fetch at eval boundaries.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .fedavg import FedAvg


class PersonalizedFedAvg(FedAvg):
    """FedAvg aggregation + in-program per-user personalization carry."""

    device_carry = True
    supports_staleness = False
    supports_rl = False
    #: fleet paging: every per-user table pages (local model rows,
    #: alphas, the seen gate)
    carry_tables = ("local", "alpha", "seen")

    def carry_row_defaults(self):
        # a never-seen user cold-starts at alpha0 with seen == 0 (the
        # in-program global-clone init keys off seen, not local)
        return {"local": 0.0, "alpha": self.alpha0, "seen": 0.0}

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        if dp_config is not None and dp_config.get("enable_local_dp", False):
            raise ValueError(
                "fused_carry personalization does not compose with "
                "dp_config.enable_local_dp — the alpha update reads the "
                "raw global pseudo-gradient; use the host personalization "
                "path (drop fused_carry) for DP runs")
        cc = config.client_config
        self.alpha0 = float(cc.get("convex_model_interp", 0.75))
        sc = config.server_config
        init_kind = sc.get("personalization_init", "global")
        if init_kind != "global":
            raise ValueError(
                f"fused_carry personalization supports only "
                f"personalization_init: global (got {init_kind!r}) — "
                "random/initial init needs per-user host state; drop "
                "fused_carry for those modes")

    # ------------------------------------------------------------------
    def init_state(self, params_like: Any) -> Any:
        if not self.carry_clients:
            raise ValueError(
                "fused_carry personalization needs carry_clients (the "
                "total client-pool size) set before init_state — the "
                "server does this from len(train_dataset)")
        n_params = sum(int(np.prod(leaf.shape))
                       for leaf in jax.tree.leaves(params_like))
        # leading dim: page-pool slots under fleet paging, else the pool
        n = self._carry_table_rows()
        return {
            "local": jnp.zeros((n, n_params), jnp.float32),
            "alpha": jnp.full((n,), self.alpha0, jnp.float32),
            # 0 until first participation: cold-start clones the live
            # global params in-program (personalization_init: global)
            "seen": jnp.zeros((n,), jnp.float32),
        }

    # ------------------------------------------------------------------
    def client_step_carry(self, client_update, global_params, arrays,
                          sample_mask, client_lr, rng, *, client_id,
                          live_mask, round_idx=None, leakage_threshold=None,
                          quant_threshold=None, strategy_state=None):
        from jax.flatten_util import ravel_pytree
        parts, tl, ns, stats = super().client_step(
            client_update, global_params, arrays, sample_mask, client_lr,
            rng, round_idx=round_idx, leakage_threshold=leakage_threshold,
            quant_threshold=quant_threshold, strategy_state=None)
        pg_g = parts["default"][0]  # identity transform (no DP): the raw
        # global-pass pseudo-gradient the alpha update needs

        flat_g, unravel = ravel_pytree(global_params)
        n_rows = strategy_state["local"].shape[0]
        idx = jnp.clip(client_id, 0, n_rows - 1)
        valid = (client_id >= 0).astype(jnp.float32)
        seen = strategy_state["seen"][idx] * valid
        lp_flat = jnp.where(seen > 0, strategy_state["local"][idx], flat_g)
        lp = unravel(lp_flat)
        alpha = jnp.where(seen > 0, strategy_state["alpha"][idx],
                          self.alpha0)

        # local-model pass on the same data (engine/personalization.py
        # per_user, fused into the round program)
        pg_p, _, _, _ = client_update(
            lp, arrays, sample_mask, client_lr,
            jax.random.fold_in(rng, 104729))
        new_lp = jax.tree.map(lambda w_, g: w_ - g, lp, pg_p)
        # alpha SGD step on the interpolation objective (reference
        # utils/utils.py:607-617, post-training params on both sides)
        dots = jax.tree.map(
            lambda wg, wp, gg, gp: jnp.sum(
                ((wg - gg) - (wp - gp)) *
                (alpha * gg + (1.0 - alpha) * gp)),
            global_params, lp, pg_g, pg_p)
        grad_alpha = sum(jax.tree.leaves(dots)) + 0.02 * alpha
        new_alpha = jnp.clip(alpha - client_lr * grad_alpha, 1e-4, 0.9999)
        new_alpha = jnp.where(jnp.isfinite(new_alpha), new_alpha,
                              jnp.asarray(self.alpha0))

        keep = valid * live_mask
        carry = {"row": ravel_pytree(new_lp)[0], "alpha": new_alpha,
                 "keep": keep}
        return parts, tl, ns, stats, carry

    def megabatch_passes(self, *, strategy_state, global_params,
                         client_ids, slots, rng):
        """TWO lane-scan passes matching :meth:`client_step_carry`'s two
        ``client_update`` calls: the plain global pass, then the local-
        model pass starting (and anchoring its pseudo-gradient) at each
        user's ``local`` row — the global clone for never-seen users —
        under the same ``fold_in(rng, 104729)`` sub-stream."""
        from jax.flatten_util import ravel_pytree
        flat_g, _ = ravel_pytree(global_params)
        n_rows = strategy_state["local"].shape[0]
        idx = jnp.clip(slots, 0, n_rows - 1)
        valid = (slots >= 0).astype(jnp.float32)
        seen = strategy_state["seen"][idx] * valid
        init_rows = jnp.where(seen[:, None] > 0,
                              strategy_state["local"][idx],
                              flat_g[None, :])
        return ({}, {"init_rows": init_rows, "rng_salt": 104729})

    def apply_carry(self, state, client_ids, carry, rng=None):
        keep_b = carry["keep"] > 0
        n_rows = state["local"].shape[0]
        idx = jnp.where(keep_b, client_ids, n_rows)
        return {
            "local": state["local"].at[idx].set(carry["row"], mode="drop"),
            "alpha": state["alpha"].at[idx].set(carry["alpha"],
                                                mode="drop"),
            "seen": state["seen"].at[idx].set(1.0, mode="drop"),
        }
