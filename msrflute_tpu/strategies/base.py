"""Strategy contract.

Parity target: reference ``core/strategies/base.py:8-57`` — the 3-method
contract ``generate_client_payload`` / ``process_individual_payload`` /
``combine_payloads`` executed on client and server processes.

TPU-native redesign: a strategy contributes *pure traced functions* that the
round engine composes into one jitted SPMD program:

- :meth:`client_weight` — per-client aggregation weight from training
  outcomes (runs inside ``vmap`` over clients; replaces the client-side half
  of ``generate_client_payload``).
- :meth:`transform_payload` — per-client payload post-processing: local DP,
  layer freezing, quantization (the rest of ``generate_client_payload``).
- :meth:`combine` — turn the weighted ``psum`` results into the aggregate
  pseudo-gradient (replaces ``combine_payloads``); may carry strategy state
  (e.g. DGA's staleness buffer) across rounds as an explicit pytree.

Data-dependent, non-traceable behavior (adaptive thresholds, RL) stays in
host-side hooks invoked at round boundaries.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

MAX_WEIGHT = 100.0  # reference core/strategies/utils.py:11-19


def _find_embedding_leaf(tree: Any):
    """Locate the ``[vocab, embed]`` embedding-table leaf by path name."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path).lower()
        if "embed" in name and getattr(leaf, "ndim", 0) == 2:
            return leaf
    return None


def filter_weight(weight: jnp.ndarray) -> jnp.ndarray:
    """NaN/Inf -> 0, cap at MAX_WEIGHT (reference
    ``core/strategies/utils.py:11-19``)."""
    weight = jnp.nan_to_num(weight, nan=0.0, posinf=0.0, neginf=0.0)
    return jnp.clip(weight, 0.0, MAX_WEIGHT)


class BaseStrategy:
    """Base strategy: sample-count weights, identity transforms."""

    #: whether combine() maintains cross-round state (a pytree)
    stateful: bool = False
    #: single-'default'-payload features; FedLabels' dual payload opts out
    supports_staleness: bool = True
    supports_rl: bool = True
    #: probability a client's payload is deferred one round (DGA staleness,
    #: reference core/strategies/dga.py:260-284); the engine draws the
    #: per-client coin and hands combine() separate now/deferred sums.
    stale_prob: float = 0.0
    #: fluteflow traced staleness (traffic/): True when
    #: :meth:`client_step` accepts a ``staleness=`` int32 operand — the
    #: arrival plane's TRUE broadcast-version gap per update.  The
    #: engine compiles the operand in (and the server builds per-fire
    #: staleness vectors) only when this is declared AND
    #: ``server_config.traffic.mode`` is ``buffered`` — staleness-blind
    #: strategies keep their exact call signature under traffic.
    supports_traced_staleness: bool = False
    #: when True the engine skips the server optimizer and calls
    #: :meth:`apply_server_update` instead (multi-sequence schemes: FedAC)
    owns_server_update: bool = False
    #: strategies that implement dp_config.adaptive_clipping set this; the
    #: base init fails loudly instead of silently ignoring the config
    supports_adaptive_clipping: bool = False
    #: part names whose TREES enter the client sum with the 0/1
    #: participation gate instead of the client weight (pre-weighted or
    #: masked payloads — secure aggregation, where every mask must enter
    #: with coefficient exactly 1); ``weight_sum`` still accumulates the
    #: returned weights for normalization
    unit_weight_parts: frozenset = frozenset()
    #: client_step additionally receives ``cohort_ids``/``cohort_mask``
    #: (the round's FULL sampled-id vector, replicated across shards) and
    #: ``self_id``/``self_mask`` — what a secure-aggregation client needs
    #: to derive its pairwise masks
    wants_cohort: bool = False
    #: device-resident carry state (universal overlap, PR 6): the
    #: strategy's cross-round per-client tables (SCAFFOLD controls, EF
    #: residuals, personalization heads/alphas) live INSIDE
    #: ``strategy_state`` as donated device buffers.  The engine then
    #: calls :meth:`client_step_carry` (which gathers this client's table
    #: row in-program) and :meth:`apply_carry` (which scatters the
    #: round's updated rows back), so the round-k -> k+1 data dependency
    #: never touches the host and the server's serial fallback is lifted.
    device_carry: bool = False
    #: total client-pool size for the carry tables; the server sets this
    #: (``len(train_dataset)``) before ``init_state`` builds the tables
    carry_clients: int = 0
    #: fleet paging (server_config.fleet): when nonzero, the per-client
    #: carry tables are sized to THIS many page-pool slots instead of
    #: ``carry_clients`` rows — the engine then indexes them with
    #: host-remapped SLOT ids while population-level math (e.g.
    #: SCAFFOLD's ``c`` normalization) keeps using ``carry_clients``.
    #: 0 (default) = resident ``[N, ...]`` tables, the PR 6 behavior.
    carry_rows: int = 0
    #: names of the ``strategy_state`` dict keys that are per-client
    #: row tables (leading dim == the carry row count) — what the fleet
    #: pager pages in/out; non-listed keys (SCAFFOLD's server control
    #: ``c``) stay resident and replicated
    carry_tables: tuple = ()
    #: cross-client megabatching (server_config.megabatch): True when
    #: every heavy training this strategy performs flows through the
    #: ``client_update`` interface, so the engine's fused lane scan can
    #: stand in for it (see :meth:`megabatch_passes`).  FedLabels opts
    #: out — its VAT pass trains outside that contract.
    supports_megabatch: bool = True

    def carry_row_defaults(self) -> Dict[str, float]:
        """Fill value per carry-table key for a client that has never
        participated (the paged analogue of ``init_state``'s uniform
        fill; zero unless a strategy overrides — personalization's
        ``alpha`` cold-starts at ``alpha0``)."""
        return {k: 0.0 for k in self.carry_tables}

    def _carry_table_rows(self) -> int:
        """Leading dim for the carry tables ``init_state`` builds: the
        fleet page-pool slot count when paging is on, else the full
        client pool."""
        return int(self.carry_rows or self.carry_clients)

    def __init__(self, config, dp_config=None):
        self.config = config
        self.dp_config = dp_config
        if dp_config is not None and dp_config.get("adaptive_clipping") and \
                not self.supports_adaptive_clipping:
            raise ValueError(
                f"{type(self).__name__} does not implement "
                "dp_config.adaptive_clipping — use strategy: fedavg")

    #: set by RoundEngine so strategies can reach model apply()/loss()
    task: Any = None
    #: set by RoundEngine: the flutescope device-metric bus.  Strategies
    #: publish per-round device SCALARS at trace time
    #: (``self.devbus.publish(name, value)`` — combine_parts is the
    #: natural site; from inside vmap'd client_step, psum/mean to a
    #: round scalar first, or the host consumer skips the vector with a
    #: warning) and the values ride the packed-stats single transfer —
    #: NEVER publish via ``.item()``/``float(...)`` (host-sync lint).
    #: A disabled bus no-ops every publish.
    devbus: Any = None

    # ---- traced, per-client (inside vmap) ----------------------------
    def client_step(self, client_update, global_params, arrays, sample_mask,
                    client_lr, rng, round_idx=None, leakage_threshold=None,
                    quant_threshold=None, strategy_state=None,
                    grad_offset=None):
        """Run one client's local work and emit weighted payload parts.

        Returns ``(parts, train_loss, num_samples, stats)`` where ``parts``
        maps part name -> ``(pytree, weight scalar)``.  The engine computes a
        weighted psum per part.  The default single-part flow reproduces the
        reference's ``generate_client_payload`` pipeline, including the
        privacy-attack metrics + client dropping of
        ``core/client.py:466-508`` when ``privacy_metrics_config`` is on.
        ``grad_offset`` (per-client drift correction, SCAFFOLD) forwards to
        the client update's inner steps.
        """
        pg, tl, ns, stats = client_update(global_params, arrays, sample_mask,
                                          client_lr, rng,
                                          grad_offset=grad_offset)
        w = self.client_weight(num_samples=ns, train_loss=tl, stats=stats,
                               rng=jax.random.fold_in(rng, 1))
        w = self._apply_privacy_metrics(
            pg, w, stats, global_params, arrays, sample_mask,
            leakage_threshold)
        pg, w = self.transform_payload(pg, w, jax.random.fold_in(rng, 2),
                                       quant_threshold=quant_threshold,
                                       strategy_state=strategy_state,
                                       stats=stats)
        return {"default": (pg, w)}, tl, ns, stats

    def _apply_privacy_metrics(self, pg, weight, stats, global_params,
                               arrays, sample_mask, leakage_threshold):
        """Attack metrics + ``wt=0`` client dropping
        (reference ``core/client.py:466-508``).  Metrics land in ``stats``
        under ``privacy_*`` keys, which the engine surfaces per client."""
        pm = getattr(self.config, "privacy_metrics_config", None)
        if pm is None or not pm.get("apply_metrics", False):
            return weight
        from .. import privacy
        from ..privacy import attacks

        dropped = jnp.zeros(())
        if pm.get("apply_indices_extraction", False) and "x" in arrays:
            embed_leaf = _find_embedding_leaf(pg)
            if embed_leaf is not None:
                # real token count, not the padded grid (metrics.py:15)
                seq_len = arrays["x"].shape[-1]
                num_tokens = jnp.sum(sample_mask) * seq_len
                overlap, extracted = attacks.extract_indices_from_embeddings(
                    embed_leaf, arrays["x"].astype(jnp.int32),
                    num_tokens=num_tokens)
                stats["privacy_overlap"] = overlap
                rank = int(pm.get("allowed_word_rank", 9000))
                above = extracted[rank:] if rank < extracted.shape[0] else \
                    jnp.zeros((1,))
                stats["privacy_above_rank"] = jnp.sum(above) / jnp.maximum(
                    jnp.sum(extracted), 1.0)
                max_overlap = pm.get("max_allowed_overlap")
                if max_overlap is not None:
                    dropped = jnp.maximum(
                        dropped, (overlap > float(max_overlap)).astype(jnp.float32))

        if pm.get("apply_leakage_metric", False) and \
                getattr(self.task, "token_logprobs", None) is not None:
            leakage = attacks.practical_epsilon_leakage(
                global_params, pg, self.task.token_logprobs, arrays,
                sample_mask,
                is_weighted=bool(pm.get("is_leakage_weighted", False)),
                max_ratio=math.exp(float(pm.get("max_leakage", 30.0))),
                attacker_optimizer_config=pm.attacker_optimizer_config)
            stats["privacy_leakage"] = leakage
            if leakage_threshold is not None:
                dropped = jnp.maximum(
                    dropped, (leakage > leakage_threshold).astype(jnp.float32))

        stats["privacy_dropped"] = dropped
        return weight * (1.0 - dropped)

    def client_weight(self, *, num_samples: jnp.ndarray,
                      train_loss: jnp.ndarray,
                      stats: Dict[str, jnp.ndarray],
                      rng: jax.Array) -> jnp.ndarray:
        raise NotImplementedError

    def transform_payload(self, pseudo_grad: Any, weight: jnp.ndarray,
                          rng: jax.Array, quant_threshold=None,
                          strategy_state=None,
                          stats=None) -> Tuple[Any, jnp.ndarray]:
        """``stats`` (the client's mutable stats dict) lets implementations
        record per-client diagnostics for the same-trace caller (e.g. the
        pre-clip update norm for adaptive clipping)."""
        return pseudo_grad, weight

    # ---- traced, pre-vmap (megabatch lane-scan passes) ---------------
    def megabatch_passes(self, *, strategy_state, global_params,
                         client_ids, slots, rng) -> tuple:
        """Declare the megabatch lane-scan passes this strategy's
        client step needs — one spec dict per ``client_update`` call it
        issues, IN CALL ORDER.  Each spec may set ``init_rows``
        (``[K, n_flat]`` per-client start/anchor rows replacing the
        global params — FedBuff's stale history, personalization's
        local models), ``offset_rows`` (``[K, n_flat]`` SCAFFOLD-style
        grad offsets), and ``rng_salt`` (reproducing a
        ``fold_in(rng_client, salt)`` sub-stream).  Traced: runs inside
        the collect program with the shard-local ``client_ids`` (true
        ids, the rng anchor) and ``slots`` (carry-table rows — pool
        slots under fleet paging, ids otherwise).  The default single
        plain pass matches :meth:`client_step`'s one
        ``client_update(global_params, ...)`` call."""
        del strategy_state, global_params, client_ids, slots, rng
        return ({},)

    # ---- traced, per-client carry (device_carry strategies) ----------
    def client_step_carry(self, client_update, global_params, arrays,
                          sample_mask, client_lr, rng, *, client_id,
                          live_mask, round_idx=None, leakage_threshold=None,
                          quant_threshold=None, strategy_state=None):
        """Carry-mode client step: like :meth:`client_step` but the
        strategy gathers its own per-client table row from
        ``strategy_state`` by ``client_id`` and additionally returns a
        ``carry`` pytree (``{"row": ..., "keep": 0/1, ...}``) that
        :meth:`apply_carry` scatters back after aggregation.
        ``live_mask`` is this client's 0/1 presence (mesh padding + chaos
        dropout already folded in)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement device-carry mode")

    def apply_carry(self, state: Any, client_ids, carry: Any,
                    rng: Optional[jax.Array] = None) -> Any:
        """Scatter the round's per-client carry rows into the state's
        tables (traced, replicated; runs once per round after combine).
        Rows whose ``keep`` gate is 0 must leave the table untouched."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement device-carry mode")

    # ---- traced, pre-dispatch (replicated) ---------------------------
    def broadcast_params(self, params: Any, state: Any) -> Any:
        """The params clients start this round from (default: the server's
        canonical params; FedAC broadcasts its momentum-like md point)."""
        return params

    def apply_server_update(self, params: Any, agg: Any, state: Any,
                            server_lr) -> Tuple[Any, Any]:
        """Custom server update for ``owns_server_update`` strategies."""
        raise NotImplementedError

    # ---- traced, post-psum (replicated) ------------------------------
    def init_state(self, params_like: Any) -> Any:
        return ()

    def combine(self, weighted_grad_sum: Any, weight_sum: jnp.ndarray,
                deferred: Optional[Dict[str, Any]], state: Any,
                rng: jax.Array,
                num_clients: Optional[jnp.ndarray] = None) -> Tuple[Any, Any]:
        """Return (aggregate_pseudo_grad, new_state).

        ``weighted_grad_sum``/``weight_sum`` are the psum'd contributions of
        this round's non-deferred clients; ``deferred`` (when the engine runs
        with ``stale_prob > 0``) holds ``{'grad_sum', 'weight_sum'}`` for the
        clients deferred to next round.
        """
        denom = jnp.maximum(weight_sum, 1e-12)
        agg = jax.tree.map(lambda g: g / denom, weighted_grad_sum)
        return agg, state

    def combine_parts(self, part_sums: Dict[str, Dict[str, Any]],
                      deferred: Optional[Dict[str, Any]], state: Any,
                      rng: jax.Array, num_clients: jnp.ndarray,
                      global_params: Any = None) -> Tuple[Any, Any]:
        """Multi-part entry point; single-part strategies fall through to
        :meth:`combine`."""
        if set(part_sums) == {"default"}:
            return self.combine(part_sums["default"]["grad_sum"],
                                part_sums["default"]["weight_sum"],
                                deferred, state, rng, num_clients)
        raise NotImplementedError(
            f"{type(self).__name__} must override combine_parts for parts "
            f"{sorted(part_sums)}")
