"""Strategy contract.

Parity target: reference ``core/strategies/base.py:8-57`` — the 3-method
contract ``generate_client_payload`` / ``process_individual_payload`` /
``combine_payloads`` executed on client and server processes.

TPU-native redesign: a strategy contributes *pure traced functions* that the
round engine composes into one jitted SPMD program:

- :meth:`client_weight` — per-client aggregation weight from training
  outcomes (runs inside ``vmap`` over clients; replaces the client-side half
  of ``generate_client_payload``).
- :meth:`transform_payload` — per-client payload post-processing: local DP,
  layer freezing, quantization (the rest of ``generate_client_payload``).
- :meth:`combine` — turn the weighted ``psum`` results into the aggregate
  pseudo-gradient (replaces ``combine_payloads``); may carry strategy state
  (e.g. DGA's staleness buffer) across rounds as an explicit pytree.

Data-dependent, non-traceable behavior (adaptive thresholds, RL) stays in
host-side hooks invoked at round boundaries.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

MAX_WEIGHT = 100.0  # reference core/strategies/utils.py:11-19


def filter_weight(weight: jnp.ndarray) -> jnp.ndarray:
    """NaN/Inf -> 0, cap at MAX_WEIGHT (reference
    ``core/strategies/utils.py:11-19``)."""
    weight = jnp.nan_to_num(weight, nan=0.0, posinf=0.0, neginf=0.0)
    return jnp.clip(weight, 0.0, MAX_WEIGHT)


class BaseStrategy:
    """Base strategy: sample-count weights, identity transforms."""

    #: whether combine() maintains cross-round state (a pytree)
    stateful: bool = False
    #: probability a client's payload is deferred one round (DGA staleness,
    #: reference core/strategies/dga.py:260-284); the engine draws the
    #: per-client coin and hands combine() separate now/deferred sums.
    stale_prob: float = 0.0

    def __init__(self, config, dp_config=None):
        self.config = config
        self.dp_config = dp_config

    # ---- traced, per-client (inside vmap) ----------------------------
    def client_weight(self, *, num_samples: jnp.ndarray,
                      train_loss: jnp.ndarray,
                      stats: Dict[str, jnp.ndarray],
                      rng: jax.Array) -> jnp.ndarray:
        raise NotImplementedError

    def transform_payload(self, pseudo_grad: Any, weight: jnp.ndarray,
                          rng: jax.Array) -> Tuple[Any, jnp.ndarray]:
        return pseudo_grad, weight

    # ---- traced, post-psum (replicated) ------------------------------
    def init_state(self, params_like: Any) -> Any:
        return ()

    def combine(self, weighted_grad_sum: Any, weight_sum: jnp.ndarray,
                deferred: Optional[Dict[str, Any]], state: Any,
                rng: jax.Array,
                num_clients: Optional[jnp.ndarray] = None) -> Tuple[Any, Any]:
        """Return (aggregate_pseudo_grad, new_state).

        ``weighted_grad_sum``/``weight_sum`` are the psum'd contributions of
        this round's non-deferred clients; ``deferred`` (when the engine runs
        with ``stale_prob > 0``) holds ``{'grad_sum', 'weight_sum'}`` for the
        clients deferred to next round.
        """
        denom = jnp.maximum(weight_sum, 1e-12)
        agg = jax.tree.map(lambda g: g / denom, weighted_grad_sum)
        return agg, state
