"""FedAC — Federated Accelerated SGD (Yuan & Ma, NeurIPS 2020;
arXiv:2006.08950, listed in PAPERS.md).

Net-new vs the reference (FLUTE ships FedAvg/DGA/FedLabels only): provably
accelerated federated optimization via three coupled sequences.  Per round,
with canonical params ``w`` (the engine's state) and an aggregate sequence
``w_ag`` carried in strategy state:

    w_md   = (1/beta) * w + (1 - 1/beta) * w_ag      (broadcast point)
    Delta  = weighted-avg client pseudo-gradient from w_md
    w_ag'  = w_md - eta   * lr * Delta
    w'     = (1 - 1/alpha) * w + (1/alpha) * w_md - gamma * lr * Delta

``alpha = beta = 1`` with ``gamma = 1`` reduces EXACTLY to FedAvg with a
plain SGD server step (tested), so the strategy is a strict generalization.
When only ``fedac_gamma``/``fedac_eta`` are configured, the couplings
default to the paper's FedAC-I choice ``alpha = gamma/eta``,
``beta = alpha + 1``.

Evaluation/checkpointing use the canonical ``w`` (the engine's params);
``w_ag`` rides the strategy-state pytree through the jitted round exactly
like DGA's staleness buffer.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .fedavg import FedAvg


class FedAC(FedAvg):

    stateful = True
    owns_server_update = True
    # the md-point broadcast + two-sequence update assumes every payload
    # lands in the round it was produced
    supports_staleness = False
    supports_rl = False

    def __init__(self, config, dp_config=None):
        # reject BEFORE the inherited FedAvg checks: their advice
        # ("requires enable_local_dp") would mislead a FedAC user into a
        # second error instead of the real answer (not supported together)
        if dp_config is not None and dp_config.get("adaptive_clipping"):
            raise ValueError(
                "FedAC and dp_config.adaptive_clipping both need the "
                "strategy-state slot (w_ag vs dp_clip) — not supported "
                "together; use strategy: fedavg for adaptive clipping")
        super().__init__(config, dp_config)
        sc = config.server_config
        self.eta = float(sc.get("fedac_eta", 1.0))
        self.gamma = float(sc.get("fedac_gamma", max(self.eta, 1.0)))
        alpha = sc.get("fedac_alpha")
        beta = sc.get("fedac_beta")
        # FedAC-I couplings when not set explicitly (paper §3)
        self.alpha = float(alpha) if alpha is not None else \
            max(self.gamma / max(self.eta, 1e-12), 1.0)
        self.beta = float(beta) if beta is not None else self.alpha + 1.0

    # ---- engine hooks -------------------------------------------------
    def init_state(self, params_like: Any) -> Any:
        # a REAL copy: jnp.asarray would alias the params buffers, and the
        # round step donates params AND strategy state — aliased buffers
        # would be donated twice
        return {"w_ag": jax.tree.map(jnp.copy, params_like)}

    def _md_point(self, params: Any, state: Any) -> Any:
        inv_b = 1.0 / self.beta
        return jax.tree.map(lambda w, ag: inv_b * w + (1.0 - inv_b) * ag,
                            params, state["w_ag"])

    def broadcast_params(self, params: Any, state: Any) -> Any:
        return self._md_point(params, state)

    def apply_server_update(self, params: Any, agg: Any, state: Any,
                            server_lr) -> Tuple[Any, Any]:
        md = self._md_point(params, state)
        lr = jnp.asarray(server_lr, jnp.float32)
        new_ag = jax.tree.map(lambda m, g: m - self.eta * lr * g, md, agg)
        inv_a = 1.0 / self.alpha
        new_w = jax.tree.map(
            lambda w, m, g: (1.0 - inv_a) * w + inv_a * m
            - self.gamma * lr * g,
            params, md, agg)
        return new_w, {"w_ag": new_ag}
