"""FedLabels — semi-supervised federated learning.

Parity target: reference ``core/strategies/fedlabels.py`` +
``Trainer.run_train_epoch_sup`` (``core/trainer.py:503-619``) +
``get_label_VAT`` (``utils/utils.py:620-678``):

- each client trains a **supervised** model on its labeled data, and (after
  ``burnout_round``) an **unsupervised** model starting from the round's
  initial weights on pseudo-labeled unlabeled data;
- pseudo-labels (VAT selection, ``comp='var'``): compare per-sample logit
  variance of the *initial* ("local") model vs the *sup-trained* ("server")
  model at temperature ``temp``; the higher-variance side labels the sample
  iff its max prob exceeds ``thre``; the confidence weight is the variance
  ratio of the losing side;
- unsup loss = ``unsup_lamb * CE(net(aug or clean), est_labels)``
  ``+ vat_consis *`` variance-weighted KL(net || sup-trained) over samples
  where both sides agree ``+ l2_lambda * MSE(net, initial)``;
- payload = full sup weights + full unsup weights
  (``fedlabels.py:82-92``); the server averages sup **uniformly** and unsup
  **sample-weighted**, then loads ``(sup + unsup)/2``
  (``fedlabels.py:190-216``).

TPU-native: dynamic label selection becomes masks (no ragged index lists);
both local trainings are ``lax.scan``s inside the vmapped client step.  The
server "load_state_dict" is expressed as pseudo-gradient
``w0 - (sup_avg/2 + unsup_avg/2)``, which with the canonical SGD(lr=1.0)
server optimizer reproduces the reference's direct load exactly — and
unlike the reference also composes with server momentum/adam if configured.

Client batch contract: labeled arrays ``x``/``y`` plus unlabeled ``ux``
(clean) and optionally ``ux_rand`` (augmented view, used when ``uda: 1``),
all packed on the same ``[S, B]`` grid (the featurizer pads/subsamples the
unlabeled pool to the labeled grid).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from ..models.base import softmax_xent
from .base import BaseStrategy, filter_weight


class FedLabels(BaseStrategy):

    # dual sup/unsup payload — no single 'default' part for the staleness
    # buffer or RL re-weighting to act on
    supports_staleness = False
    supports_rl = False
    # the dual sup/unsup training loop steps outside the client_update
    # contract the megabatch lane scan reproduces
    supports_megabatch = False

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        ss = (config.client_config.get("semisupervision")
              or config.server_config.get("semisupervision")
              or config.extra.get("semisupervision") or {})
        self.eta = float(ss.get("eta", 0.01))
        self.burnout_round = int(ss.get("burnout_round", 0))
        self.temp = float(ss.get("temp", 1.0))
        self.thre = float(ss.get("thre", 0.6))
        self.vat_consis = float(ss.get("vat_consis", 1.0))
        self.l2_lambda = float(ss.get("l2_lambda", 0.0))
        self.unsup_lamb = float(ss.get("unsup_lamb", 1.0))
        self.uda = int(ss.get("uda", 0))
        self.unsuptrain_ep = int(ss.get("unsuptrain_ep", 1))

    # ------------------------------------------------------------------
    def client_step(self, client_update, global_params, arrays, sample_mask,
                    client_lr, rng, round_idx=None, leakage_threshold=None,
                    quant_threshold=None, strategy_state=None,
                    grad_offset=None):
        if grad_offset is not None:
            raise ValueError("FedLabels does not support grad_offset "
                             "(SCAFFOLD drift correction)")
        # 1) supervised pass: the standard local-SGD client update on x/y
        labeled = {k: v for k, v in arrays.items()
                   if k not in ("ux", "ux_rand", "uy")}
        pg_sup, tl, ns, stats = client_update(
            global_params, labeled, sample_mask, client_lr, rng)
        sup_params = jax.tree.map(lambda w, g: w - g, global_params, pg_sup)

        # 2) unsupervised pass (gated by burnout_round)
        if "ux" in arrays:
            unsup_params = self._unsup_train(
                global_params, sup_params, arrays, sample_mask,
                jax.random.fold_in(rng, 11))
            if round_idx is not None:
                active = (round_idx >= self.burnout_round)
                unsup_params = jax.tree.map(
                    lambda u, g: jnp.where(active, u, g),
                    unsup_params, global_params)
        else:
            unsup_params = global_params

        # weight = num samples (fedlabels.py:84: 1 if zero)
        w = filter_weight(jnp.maximum(ns, 1.0))
        parts = {
            "sup": (sup_params, jnp.ones(())),   # uniform ratio 1/N
            "unsup": (unsup_params, w),          # sample-weighted ratio
        }
        return parts, tl, ns, stats

    # ------------------------------------------------------------------
    def _unsup_train(self, initial_params, sup_params, arrays, sample_mask,
                     rng):
        """VAT pseudo-label training of ``net`` (starts at initial params)."""
        task = self.task
        temp, thre = self.temp, self.thre
        ux = arrays["ux"]
        ux_in = arrays.get("ux_rand", ux) if self.uda == 1 else ux
        tx = optax.sgd(self.eta)

        def step(carry, xs):
            net, opt_state = carry
            u_clean, u_in, mask = xs
            local_logits = jax.nn.softmax(
                task.apply(initial_params, u_clean) / temp, axis=-1)
            server_logits = jax.nn.softmax(
                task.apply(sup_params, u_clean) / temp, axis=-1)
            lvar = jnp.var(local_logits, axis=-1)
            svar = jnp.var(server_logits, axis=-1)
            use_local = lvar >= svar
            chosen = jnp.where(use_local[:, None], local_logits, server_logits)
            conf_ok = jnp.max(chosen, axis=-1) > thre
            est_mask = conf_ok.astype(jnp.float32) * mask
            est_labels = jnp.argmax(chosen, axis=-1)
            # confidence weight: losing side's variance / winning side's
            est_var = jnp.where(use_local, svar / jnp.maximum(lvar, 1e-12),
                                lvar / jnp.maximum(svar, 1e-12))
            agree = (jnp.argmax(local_logits, axis=-1) ==
                     jnp.argmax(server_logits, axis=-1)).astype(jnp.float32)
            agree_mask = agree * est_mask

            def loss_fn(net_params):
                out = task.apply(net_params, u_in)
                out_clean = task.apply(net_params, u_clean)
                ce = softmax_xent(out, est_labels)
                unsup_loss = jnp.sum(ce * est_mask) / jnp.maximum(
                    jnp.sum(est_mask), 1.0)
                # pointwise KL(server || net) at temperature, log-target form
                log_p_net = jax.nn.log_softmax(out_clean / temp, axis=-1)
                log_p_srv = jnp.log(jnp.maximum(server_logits, 1e-12))
                kl_point = jnp.sum(
                    jnp.exp(log_p_srv) * (log_p_srv - log_p_net), axis=-1)
                consist = jnp.sum(kl_point * est_var * agree_mask) / \
                    jnp.maximum(jnp.sum(agree_mask), 1.0)
                sq = jax.tree.map(lambda a, b: jnp.mean((a - b) ** 2),
                                  net_params, initial_params)
                reg = sum(jax.tree.leaves(sq))
                return (self.unsup_lamb * unsup_loss +
                        self.vat_consis * consist + self.l2_lambda * reg)

            grads = jax.grad(loss_fn)(net)
            has_data = (jnp.sum(est_mask) > 0).astype(jnp.float32)
            updates, new_opt = tx.update(grads, opt_state, net)
            new_net = optax.apply_updates(net, updates)
            net = jax.tree.map(lambda n, o: jnp.where(has_data > 0, n, o),
                               new_net, net)
            opt_state = jax.tree.map(
                lambda n, o: jnp.where(has_data > 0, n, o), new_opt, opt_state)
            return (net, opt_state), None

        net = initial_params
        carry = (net, tx.init(net))
        for _ in range(max(self.unsuptrain_ep, 1)):
            carry, _ = jax.lax.scan(step, carry, (ux, ux_in, sample_mask))
        return carry[0]

    # ------------------------------------------------------------------
    def combine_parts(self, part_sums, deferred, state, rng, num_clients,
                      global_params=None):
        sup = part_sums["sup"]
        unsup = part_sums["unsup"]
        sup_avg = jax.tree.map(
            lambda g: g / jnp.maximum(sup["weight_sum"], 1e-12),
            sup["grad_sum"])
        unsup_avg = jax.tree.map(
            lambda g: g / jnp.maximum(unsup["weight_sum"], 1e-12),
            unsup["grad_sum"])
        target = jax.tree.map(lambda a, b: a / 2 + b / 2, sup_avg, unsup_avg)
        # express "load (sup+unsup)/2" as a pseudo-gradient for the server
        # optimizer (exact with sgd lr=1.0)
        agg = jax.tree.map(lambda w0, t: w0 - t, global_params, target)
        return agg, state
