"""SCAFFOLD — stochastic controlled averaging (arXiv:1910.06378).

Net-new vs the reference (FLUTE ships FedAvg/FedProx/DGA/FedLabels only):
SCAFFOLD corrects client drift under heterogeneous (non-IID) client data
with control variates — a server control ``c`` and one per-client control
``c_i`` — so multiple local epochs stop pulling the global model toward
each client's local optimum.

Per sampled client (option II of the paper):

    local step:   y <- y - lr * (grad f_i(y) + c - c_i)
    new control:  c_i+ = c_i - c + (x - y_T) / (K_i * lr)
    server:       x <- x - server_lr * weighted_avg(x - y_T)
                  c <- c + sum_i (c_i+ - c_i) / N_total

TPU mapping: the correction ``c - c_i`` is a per-client *gradient offset*
threaded into every inner SGD step of the jitted client update
(``engine/client_update.py`` ``grad_offset``); the per-client pseudo-
gradients come back via the engine's payload program (the same machinery
the RL re-weighting uses), and all control bookkeeping is exact host-side
numpy — ``K_i`` (real local steps) is known from the round batch's sample
mask, so no extra device outputs are needed.

Scale note: controls cost one flat model vector per *participating*
client.  With a ``store_dir`` (the server always sets one) the durable
copy lives on disk (one ``.npy`` per client, crash-safe writes) and the
in-RAM cache is LRU-bounded at ``ControlStore.CACHE_LIMIT`` vectors, so
host memory stays flat for very large pools; the disk copies also make
controls resume-safe.

Transfer note (large models): each round the HOST path ships a dense
``[K, n_params]`` offset matrix to the device and pulls the per-client
payload stack back — on a remote-attached chip these are the round's
dominant transfers.  That is inherent to durable PER-CLIENT controls
(``c_i`` update needs ``pg_i`` on the host); at benchmark scale it is
cheap.  ``server_config.scaffold_device_controls: true`` switches to the
TPU-native ``DeviceControlTable``: the whole ``[N, n_params]`` control
table lives in HBM (sharded over the clients mesh axis), offsets are
gathered and the option-II update is scattered *in-program*, and the only
per-round fetches are the logging scalars — the same transfer-vs-memory
tradeoff as the device-resident dataset pool.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from .fedavg import FedAvg


class ControlStore:
    """Host-side control variates: server ``c`` + per-client ``c_i``.

    Flat f32 vectors in ravel-pytree order.  With ``store_dir`` set, every
    update is persisted (tmp+rename, crash-safe) and missing entries are
    read back from disk — so a resumed run continues with the controls it
    left off with.  Unseen clients start at ``c_i = 0`` (the paper's
    initialization).
    """

    def __init__(self, n_params: int, store_dir: Optional[str] = None,
                 resume: bool = False):
        self.n_params = int(n_params)
        self.store_dir = store_dir
        self._ci: Dict[int, np.ndarray] = {}
        self.c = np.zeros((self.n_params,), np.float32)
        if store_dir is not None:
            os.makedirs(store_dir, exist_ok=True)
            if resume:
                cpath = self._path("server")
                if os.path.exists(cpath):
                    self.c = np.load(cpath).astype(np.float32)
            else:
                # a fresh run must not pick up a previous run's controls:
                # they belong to an abandoned parameter trajectory, and
                # round 1 would no longer match FedAvg at zero controls
                for name in os.listdir(store_dir):
                    if name.startswith("control_"):
                        os.remove(os.path.join(store_dir, name))

    def _path(self, key) -> str:
        return os.path.join(self.store_dir, f"control_{key}.npy")

    def _save(self, key, vec: np.ndarray) -> None:
        if self.store_dir is None:
            return
        path = self._path(key)
        tmp = path + ".tmp.npy"  # .npy suffix stops np.save appending one
        np.save(tmp, vec)
        os.replace(tmp, path)

    #: with a disk store, keep at most this many client controls in RAM
    #: (insertion-ordered dict, LRU eviction: reads and writes re-insert
    #: the key at the tail) — the disk copy is the durable one, so
    #: eviction is free; without a store_dir everything must stay resident
    #: (there is nowhere to spill to)
    CACHE_LIMIT = 1024

    def _cache(self, cid: int, vec: np.ndarray) -> None:
        self._ci.pop(cid, None)  # refresh position: hot clients stay cached
        self._ci[cid] = vec
        if self.store_dir is not None:
            while len(self._ci) > self.CACHE_LIMIT:
                self._ci.pop(next(iter(self._ci)))

    def ci(self, client_id: int) -> np.ndarray:
        cid = int(client_id)
        if cid in self._ci:
            vec = self._ci.pop(cid)  # LRU refresh on read
            self._ci[cid] = vec
            return vec
        if self.store_dir is not None:
            path = self._path(cid)
            if os.path.exists(path):
                vec = np.load(path).astype(np.float32)
                self._cache(cid, vec)
                return vec
        return np.zeros((self.n_params,), np.float32)

    def set_ci(self, client_id: int, vec: np.ndarray) -> None:
        cid = int(client_id)
        self._cache(cid, vec.astype(np.float32))
        self._save(cid, self._ci[cid])

    def reset(self) -> None:
        """Zero all controls and delete persisted files (used when the
        server falls back to a best checkpoint: the accumulated controls
        belong to the abandoned trajectory)."""
        self._ci.clear()
        self.c = np.zeros((self.n_params,), np.float32)
        if self.store_dir is not None:
            for name in os.listdir(self.store_dir):
                if name.startswith("control_"):
                    os.remove(os.path.join(self.store_dir, name))

    # ---- round marker: pairs the controls with a model checkpoint ------
    # Control writes are synchronous; the model checkpoint may be async.
    # The marker records which round the controls belong to, so resume can
    # detect controls that ran ahead of the restored params (crash between
    # a control update and its checkpoint landing) and reset instead of
    # applying another trajectory's drift corrections.
    def set_round(self, round_no: int) -> None:
        self._save("round", np.asarray([round_no], np.int64))

    def round(self) -> Optional[int]:
        if self.store_dir is None:
            return None
        path = self._path("round")
        if not os.path.exists(path):
            return None
        return int(np.load(path)[0])

    def set_c(self, vec: np.ndarray) -> None:
        self.c = vec.astype(np.float32)
        self._save("server", self.c)

    def offsets(self, client_ids) -> np.ndarray:
        """``[K, n_params]`` rows of ``c - c_i``; zero rows for padding
        clients (id < 0) so their (masked) updates stay exact no-ops."""
        out = np.zeros((len(client_ids), self.n_params), np.float32)
        for row, cid in enumerate(client_ids):
            if int(cid) >= 0:
                out[row] = self.c - self.ci(int(cid))
        return out

    def persisted_client_ids(self):
        """Client ids with a durable control file (for table warm-up)."""
        if self.store_dir is None:
            return sorted(self._ci)
        ids = []
        for name in os.listdir(self.store_dir):
            if name.startswith("control_") and name.endswith(".npy"):
                key = name[len("control_"):-len(".npy")]
                if key.lstrip("-").isdigit():
                    ids.append(int(key))
        return sorted(ids)


class DeviceControlTable:
    """HBM-resident SCAFFOLD controls (``scaffold_device_controls``).

    The full ``[N_clients, n_params]`` control table is a device array
    sharded over the clients mesh axis.  Per round:

    - ``offsets(ids)`` gathers the K sampled rows and returns the
      ``(c - c_i)`` offset matrix as a client-sharded device array — it
      feeds ``RoundEngine.client_payloads`` without touching the host;
    - ``update(...)`` runs the option-II control update as one jitted
      program: flatten the per-client pseudo-gradient stack in ravel-pytree
      order, ``c_i+ = c_i - c + pg_i/(K_i·lr)`` for participating clients
      (id >= 0 and aggregation weight > 0 — privacy-dropped clients must
      not leak into the controls), scatter the new rows back (the table
      buffer is donated, so the update is in-place in HBM), and fold the
      deltas into the server control ``c``.  Only the ``‖c‖`` logging
      scalar is fetched.

    Durability: the wrapped :class:`ControlStore` stays the format of
    record.  Mutated rows accumulate in a dirty set and ``flush()`` writes
    them through (one ``[D, n_params]`` fetch) — the server calls it when
    the control-round marker commits, i.e. at checkpoint cadence, so crash
    recovery semantics are identical to the host path.

    Memory: the table costs ``4·N·n_params`` bytes of HBM — the same
    residency tradeoff as the device-resident dataset pool; worth it when
    per-round ``2×[K, n_params]`` transfers dominate (remote-attached
    chips, large models), not when N is huge.
    """

    def __init__(self, store: ControlStore, n_clients: int, mesh):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import CLIENTS_AXIS

        self.store = store
        self.n_clients = int(n_clients)
        axis = int(mesh.shape[CLIENTS_AXIS])
        # pad rows to the clients-axis size so the table shards evenly;
        # padding rows are never gathered (ids < N) and scatters to them
        # are dropped (invalid rows target index n_rows, out of bounds)
        self.n_rows = ((self.n_clients + axis - 1) // axis) * axis
        self._row_sharding = NamedSharding(mesh, P(CLIENTS_AXIS, None))
        self._rep = NamedSharding(mesh, P())
        n_rows, n_params = self.n_rows, store.n_params
        # allocate the (GiB-scale) zero table directly in HBM, sharded —
        # never materialize a dense host copy; only the (typically few)
        # persisted rows transfer
        self._zeros = jax.jit(
            lambda: jnp.zeros((n_rows, n_params), jnp.float32),
            out_shardings=self._row_sharding)
        self.table = self._zeros()
        # warm-up scatters persisted rows in bounded chunks (a long run's
        # resume can have controls for nearly every client — stacking them
        # all would rebuild the dense table on the host and commit it to
        # one device, the exact staging the sharded design avoids); the
        # donated scatter updates the table in place
        self._scatter = jax.jit(
            lambda t, i, v: t.at[i].set(v), donate_argnums=(0,),
            out_shardings=self._row_sharding)
        warm = [cid for cid in store.persisted_client_ids()
                if 0 <= cid < self.n_clients]
        for lo in range(0, len(warm), 512):
            chunk = warm[lo:lo + 512]
            rows = np.stack([store.ci(cid) for cid in chunk])
            self.table = self._scatter(
                self.table, jnp.asarray(chunk, jnp.int32),
                # flint: disable=put-loop one-time table warm-up at construction
                jax.device_put(rows, self._rep))
        self.c = jax.device_put(store.c.copy(), self._rep)
        self._dirty = set()

        def gather_fn(table, c, ids):
            rows = table[jnp.clip(ids, 0, n_rows - 1)]
            valid = (ids >= 0).astype(jnp.float32)[:, None]
            return (c[None, :] - rows) * valid

        self._gather = jax.jit(
            gather_fn, out_shardings=self._row_sharding)

        def update_fn(table, c, ids, pgs, ws, steps, client_lr, inv_total):
            k = ids.shape[0]
            pg_flat = jnp.concatenate(
                [leaf.reshape(k, -1).astype(jnp.float32)
                 for leaf in jax.tree.leaves(pgs)], axis=1)
            valid = (ids >= 0) & (ws > 0.0)
            k_i = jnp.maximum(steps.astype(jnp.float32), 1.0)
            ci_old = table[jnp.clip(ids, 0, n_rows - 1)]
            ci_new = ci_old - c[None, :] + \
                pg_flat / (k_i * client_lr)[:, None]
            delta = jnp.where(valid[:, None], ci_new - ci_old, 0.0)
            new_c = c + delta.sum(axis=0) * inv_total
            new_table = table.at[jnp.where(valid, ids, n_rows)].set(
                ci_new, mode="drop")
            return new_table, new_c, jnp.linalg.norm(new_c)

        self._update = jax.jit(
            update_fn, donate_argnums=(0,),
            out_shardings=(self._row_sharding, self._rep, self._rep))

    def offsets(self, client_ids):
        """Client-sharded ``[K, n_params]`` device array of ``c - c_i``."""
        import jax.numpy as jnp
        return self._gather(self.table, self.c,
                            jnp.asarray(np.asarray(client_ids), jnp.int32))

    def update(self, client_ids, steps, pgs, ws, ws_np, client_lr: float,
               total_clients: int):
        """In-program option-II update; returns ``‖c‖`` for logging as a
        DEVICE scalar — ``float()`` here blocked the host on the freshly
        dispatched update program (fluteguard host-sync); the server
        fetches it bundled with the round's other host-tail reads.

        ``ws`` is the device weight vector from the payload program and
        ``ws_np`` its host copy (the server fetches it for logging anyway)
        — used only to mark participating rows dirty for ``flush()``.
        """
        import jax.numpy as jnp
        ids_np = np.asarray(client_ids)
        self.table, self.c, c_norm = self._update(
            self.table, self.c, jnp.asarray(ids_np, jnp.int32), pgs, ws,
            jnp.asarray(np.asarray(steps), jnp.float32),
            jnp.asarray(client_lr, jnp.float32),
            jnp.asarray(1.0 / max(float(total_clients), 1.0), jnp.float32))
        for row, cid in enumerate(ids_np):
            if int(cid) >= 0 and float(ws_np[row]) > 0.0:
                self._dirty.add(int(cid))
        return c_norm

    def flush(self) -> None:
        """Write dirty rows + server ``c`` through to the ControlStore
        (one bundled fetch — the gather and ``c`` used to pay separate
        transfers)."""
        import jax
        if self._dirty:
            ids = np.asarray(sorted(self._dirty), np.int32)
            rows, c = jax.device_get((self.table[ids], self.c))
            for cid, row in zip(ids, np.asarray(rows)):
                self.store.set_ci(int(cid), row)
            self._dirty.clear()
        else:
            c = jax.device_get(self.c)
        self.store.set_c(np.asarray(c))

    def reset(self) -> None:
        """Zero table + ``c`` and the durable store (fallback semantics)."""
        import jax
        self.table = self._zeros()  # sharded device zeros; no host staging
        self.c = jax.device_put(
            np.zeros((self.store.n_params,), np.float32), self._rep)
        self._dirty.clear()
        self.store.reset()


class Scaffold(FedAvg):
    """Aggregation weights are FedAvg's sample counts; the control-variate
    flow is orchestrated by the server's scaffold round
    (``engine/server.py::_run_scaffold_round``), flagged by ``host_rounds``
    — OR, with ``server_config.fused_carry: true``, runs entirely inside
    the fused round program: the ``[N, n_params]`` control table and the
    server control ``c`` ride ``strategy_state`` as donated device
    buffers, the per-client offset gather and the option-II scatter are
    traced ops (``client_step_carry`` / ``apply_carry``), and the round
    pipelines like FedAvg (universal overlap, PR 6).  In carry mode
    durability rides the model checkpoint (strategy_state is
    checkpointed), replacing the host ControlStore files.
    Payload transforms that would corrupt the control update (local DP,
    adaptive clipping, quantization) and non-SGD client optimizers are
    rejected at construction — see ``__init__``."""

    #: the server routes every round through its host-side scaffold path
    #: (per-client state in/out); round fusion is disabled like RL/replay
    host_rounds = True
    # control updates assume the single-payload flow
    supports_staleness = False
    supports_rl = False
    #: fleet paging: the per-client control table is the pageable state;
    #: the server control ``c`` stays resident/replicated
    carry_tables = ("ci",)

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        sc = getattr(config, "server_config", None)
        self.fused = bool(sc is not None and sc.get("fused_carry", False))
        if self.fused:
            # instance attrs shadow the class flags: the engine sees a
            # carry strategy, the server sees no host rounds to run
            self.host_rounds = False
            self.device_carry = True
        cc = getattr(config, "client_config", None)
        self._epochs = int(cc.get("num_epochs", 1) or 1) if cc is not None \
            else 1
        # The option-II control update reads the PAYLOAD pseudo-gradient as
        # "sum of corrected SGD steps x lr": anything that breaks that
        # identity would bake garbage into the controls and re-inject it
        # into every future client's inner steps.  Reject loudly.
        if dp_config is not None and (
                dp_config.get("enable_local_dp", False) or
                dp_config.get("adaptive_clipping")):
            raise ValueError(
                "strategy: scaffold does not compose with "
                "dp_config.enable_local_dp / adaptive_clipping — the "
                "control update would absorb the DP noise; use fedavg/dga "
                "for DP runs")
        cc = getattr(config, "client_config", None)
        if cc is not None:
            oc = cc.optimizer_config
            opt_type = str(oc.get("type", "sgd")).lower()
            # y_T = x - lr * sum(corrected grads) only holds for PLAIN SGD
            # (the paper's local update): momentum/nesterov/weight-decay
            # variants, other optimizers, and the FedProx proximal term all
            # make (x - y_T)/(K*lr) a different quantity entirely
            plain = (opt_type == "sgd" and
                     not float(oc.get("momentum", 0.0) or 0.0) and
                     not bool(oc.get("nesterov", False)) and
                     not float(oc.get("weight_decay", 0.0) or 0.0))
            if not plain:
                raise ValueError(
                    "strategy: scaffold requires a PLAIN sgd client "
                    "optimizer (no momentum/nesterov/weight_decay), got "
                    f"{dict(oc)!r}")
            if float(cc.get("fedprox_mu", 0.0) or 0.0) > 0.0:
                raise ValueError(
                    "strategy: scaffold does not compose with fedprox_mu "
                    "— the proximal term would be absorbed into the "
                    "controls")
            if cc.get("max_grad_norm") is not None:
                raise ValueError(
                    "strategy: scaffold does not compose with "
                    "client_config.max_grad_norm — per-step clipping "
                    "breaks pg = lr * sum(corrected grads), so the "
                    "controls would absorb the clipping residual")
            if cc.get("freeze_layer") or cc.get("updatable_layers"):
                raise ValueError(
                    "strategy: scaffold does not compose with layer "
                    "freezing — zeroed payload entries would desync the "
                    "controls from the steps actually taken")
            if cc.get("quant_thresh") is not None or \
                    config.model_config.get("quant_threshold") is not None:
                raise ValueError(
                    "strategy: scaffold does not compose with gradient "
                    "quantization — the control update would absorb the "
                    "quantization error; drop quant_thresh or use "
                    "fedavg/dga")

    # ---- fused carry mode (server_config.fused_carry) ----------------
    def init_state(self, params_like):
        if not self.fused:
            return super().init_state(params_like)
        import jax
        import jax.numpy as jnp
        if not self.carry_clients:
            raise ValueError(
                "fused_carry scaffold needs carry_clients (the total "
                "client-pool size) set before init_state — the server "
                "does this from len(train_dataset)")
        n_params = sum(int(np.prod(leaf.shape))
                       for leaf in jax.tree.leaves(params_like))
        return {
            "c": jnp.zeros((n_params,), jnp.float32),
            # per-client controls; scatters to dropped rows target index
            # n_rows (out of bounds -> mode="drop"), like the device
            # table.  Under fleet paging the leading dim is the PAGE
            # POOL's slot count (carry_rows) and rows hold whichever
            # clients the pager made resident — the ``c`` normalization
            # below keeps dividing by the true population.
            "ci": jnp.zeros((self._carry_table_rows(), n_params),
                            jnp.float32),
        }

    def client_step_carry(self, client_update, global_params, arrays,
                          sample_mask, client_lr, rng, *, client_id,
                          live_mask, round_idx=None, leakage_threshold=None,
                          quant_threshold=None, strategy_state=None):
        import jax
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree
        _, unravel = ravel_pytree(global_params)
        n_rows = strategy_state["ci"].shape[0]
        valid = (client_id >= 0).astype(jnp.float32)
        ci = strategy_state["ci"][jnp.clip(client_id, 0, n_rows - 1)] * valid
        # the paper's drift correction c - c_i, zero for padding lanes so
        # their masked updates stay exact no-ops
        offset_flat = (strategy_state["c"] - ci) * valid
        parts, tl, ns, stats = super().client_step(
            client_update, global_params, arrays, sample_mask, client_lr,
            rng, round_idx=round_idx, leakage_threshold=leakage_threshold,
            quant_threshold=quant_threshold, strategy_state=None,
            grad_offset=unravel(offset_flat))
        pg, w = parts["default"]
        pg_flat = ravel_pytree(pg)[0]
        # real local steps K_i: sample-mask rows with >= 1 real sample,
        # per epoch — matches the host path's steps computation AND
        # respects in-program straggler truncation (chaos keeps working)
        steps = jnp.sum((jnp.sum(sample_mask, axis=-1) > 0)
                        .astype(jnp.float32)) * float(self._epochs)
        k_i = jnp.maximum(steps, 1.0)
        ci_new = ci - strategy_state["c"] + pg_flat / (k_i * client_lr)
        # participation gate (id >= 0, live, weight > 0): privacy-dropped
        # and chaos-dropped clients must not leak into the controls
        keep = valid * live_mask * (w > 0).astype(jnp.float32)
        carry = {"row": jnp.where(keep > 0, ci_new, ci), "keep": keep}
        if self.carry_rows:
            # fleet paged pool only: "old" carries the pre-round control
            # row out of the collect so apply_carry's `c` delta never
            # re-gathers from the table — the slot axis is sharded there
            # and a post-collect gather would cost a cross-shard
            # collective (and a partitioner-chosen association).  In
            # resident mode the table is replicated, apply_carry's own
            # gather is local and free, and carrying "old" would only
            # add a [K, n_params] all-gather to every round.
            carry["old"] = ci
        return parts, tl, ns, stats, carry

    def megabatch_passes(self, *, strategy_state, global_params,
                         client_ids, slots, rng):
        """Megabatch lane-scan spec: ONE pass whose per-client grad
        offset is the ``c - c_i`` drift correction — the exact spelling
        :meth:`client_step_carry` feeds ``client_update``, batched per
        table row (zero for padding rows, so their masked updates stay
        exact no-ops)."""
        if not self.fused:
            return super().megabatch_passes(
                strategy_state=strategy_state,
                global_params=global_params, client_ids=client_ids,
                slots=slots, rng=rng)
        import jax.numpy as jnp
        n_rows = strategy_state["ci"].shape[0]
        valid = (slots >= 0).astype(jnp.float32)[:, None]
        ci = strategy_state["ci"][jnp.clip(slots, 0, n_rows - 1)] * valid
        return ({"offset_rows":
                 (strategy_state["c"][None, :] - ci) * valid},)

    def apply_carry(self, state, client_ids, carry, rng=None):
        import jax.numpy as jnp
        rows, keep = carry["row"], carry["keep"]
        n_rows = state["ci"].shape[0]
        ci_old = carry.get("old")
        if ci_old is None:
            ci_old = state["ci"][jnp.clip(client_ids, 0, n_rows - 1)]
        keep_b = keep > 0
        delta = jnp.where(keep_b[:, None], rows - ci_old, 0.0)
        new_c = state["c"] + delta.sum(axis=0) / max(
            float(self.carry_clients), 1.0)
        idx = jnp.where(keep_b, client_ids, n_rows)
        new_ci = state["ci"].at[idx].set(rows, mode="drop")
        bus = getattr(self, "devbus", None)
        if bus is not None and bus.enabled:
            # ‖c‖ rides the packed-stats single transfer (the host path
            # bundled it into its own fetch; carry mode has no host fetch)
            bus.publish("scaffold_c_norm", jnp.linalg.norm(new_c))
        return {"c": new_c, "ci": new_ci}

    def update_controls(self, store: ControlStore, client_ids,
                        steps_per_client, pgs_flat: np.ndarray,
                        client_lr: float, total_clients: int,
                        weights=None) -> None:
        """Option-II control update after a round (host-side, exact).

        ``pgs_flat``: ``[K, n_params]`` per-client pseudo-gradients
        ``x - y_T``; ``steps_per_client``: real (non-padding) local steps
        ``K_i`` each client took.  ``weights`` (the aggregation weights,
        when given) gate the update: clients excluded from aggregation —
        privacy-dropped (``wt=0``, ``core/client.py:479-504`` semantics)
        or empty — must not leak their update into the controls either.
        """
        delta_sum = np.zeros_like(store.c)
        for row, cid in enumerate(client_ids):
            cid = int(cid)
            if cid < 0:
                continue
            if weights is not None and float(weights[row]) <= 0.0:
                continue
            k_i = max(float(steps_per_client[row]), 1.0)
            ci_old = store.ci(cid)
            ci_new = ci_old - store.c + pgs_flat[row] / (k_i * client_lr)
            delta_sum += ci_new - ci_old
            store.set_ci(cid, ci_new)
        store.set_c(store.c + delta_sum / max(float(total_clients), 1.0))
