"""Error-feedback quantized aggregation (EF-SGD / EF14-style memory) —
net-new vs the reference.

The reference's quantization (``extensions/quantization/quant.py:9-50``)
is memoryless: what the binning throws away each round is gone, which
biases the aggregate and stalls convergence at aggressive bit widths.
Error feedback is the standard fix (Seide et al. 2014; Karimireddy et
al. 2019 arXiv:1901.09847): each client keeps the residual of its last
compression and folds it into the next payload before compressing —

    corrected_k = pg_k + e_k
    q_k         = Q(corrected_k)          (sent; aggregated as usual)
    e_k'        = corrected_k - q_k       (kept on the client)

so quantization error is delayed, never dropped, and compressed SGD
recovers the uncompressed rate.

Cross-device FL needs the residual to SURVIVE between a client's
participations, so ``e_k`` rides the same durable per-client row store
discipline as SCAFFOLD's control variates: flat f32 rows in
ravel-pytree order, crash-safe files under the model dir, reloaded on
resume only with a matching checkpoint (``engine/server.py``).  The
round runs on the host-orchestrated path (``client_payloads`` -> one
jitted EF step over the ``[K, n_params]`` payload stack ->
``apply_custom_weights``), exactly like SCAFFOLD/RL rounds.

Config::

    strategy: ef_quant
    client_config:
      quant_bits: 4          # 2^bits levels; EF is what makes 2-4 viable
      quant_thresh: 0.0      # |.|-quantile zeroed before binning
      quant_anneal: 1.0      # per-round threshold multiplier (DGA's knob)
      quant_approx: false    # O(n) histogram quantile instead of sort

Composition: local DP runs inside ``client_payloads``'s per-client
transform BEFORE the EF step, so the noised payload is what gets
compressed — the DP guarantee is unaffected by EF (the residual never
leaves the client).  RL re-weighting and staleness use the fused path
and do not compose with EF rounds.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .fedavg import FedAvg


class ResidualStore:
    """Durable per-client EF residual rows (flat f32, ravel-pytree
    order).  Same file discipline as ``scaffold.ControlStore``: tmp+rename
    writes, unseen clients start at zero, LRU-bounded RAM when a disk
    store exists."""

    _MAX_RESIDENT = 4096

    def __init__(self, n_params: int, store_dir: Optional[str] = None,
                 resume: bool = False):
        self.n_params = int(n_params)
        self.store_dir = store_dir
        self._rows: Dict[int, np.ndarray] = {}
        #: residual rows dropped by store-less eviction (each drop degrades
        #: that client to memoryless quantization for its next round)
        self.dropped_rows = 0
        if store_dir is not None:
            os.makedirs(store_dir, exist_ok=True)
            if not resume:
                for name in os.listdir(store_dir):
                    if name.startswith("residual_"):
                        os.remove(os.path.join(store_dir, name))

    def _path(self, cid: int) -> str:
        return os.path.join(self.store_dir, f"residual_{cid}.npy")

    def _evict(self) -> None:
        # RAM is bounded in BOTH modes.  With a disk store eviction is
        # free (the durable copy is the record).  Without one there is
        # nowhere to spill: evicting DROPS the LRU client's residual —
        # that client quantizes memorylessly next time (the EF guarantee
        # degrades gracefully, never the aggregate's correctness).  The
        # server always runs with a store_dir; store-less mode is the
        # library/test path, where unbounded growth past _MAX_RESIDENT
        # rows of n_params f32 would be the worse failure.
        while len(self._rows) > self._MAX_RESIDENT:
            self._rows.pop(next(iter(self._rows)))
            if self.store_dir is None:
                self.dropped_rows += 1

    def _touch(self, cid: int, row: np.ndarray) -> None:
        # true LRU: re-insert at the tail on every read AND write, like
        # ControlStore — eviction pops the head (least recently used)
        self._rows.pop(cid, None)
        self._rows[cid] = row

    def rows(self, ids) -> np.ndarray:
        """[K, n_params] residual matrix; zeros for unseen/padding."""
        out = np.zeros((len(ids), self.n_params), np.float32)
        for i, cid in enumerate(np.asarray(ids)):
            cid = int(cid)
            if cid < 0:
                continue
            row = self._rows.get(cid)
            if row is None and self.store_dir is not None and \
                    os.path.exists(self._path(cid)):
                row = np.load(self._path(cid)).astype(np.float32)
            if row is not None:
                self._touch(cid, row)
                out[i] = row
        self._evict()
        return out

    def update(self, ids, new_rows: np.ndarray, keep_mask) -> None:
        for i, cid in enumerate(np.asarray(ids)):
            cid = int(cid)
            if cid < 0 or not keep_mask[i]:
                continue
            row = np.asarray(new_rows[i], np.float32)
            self._touch(cid, row)
            if self.store_dir is not None:
                path = self._path(cid)
                tmp = path + ".tmp.npy"
                np.save(tmp, row)
                os.replace(tmp, path)
        self._evict()

    # -- trajectory marker (same crash semantics as ControlStore): -1
    # sentinel while residual files mutate; the server commits the real
    # round only after the paired model checkpoint is durable
    def set_round(self, round_no: int) -> None:
        if self.store_dir is None:
            return
        path = os.path.join(self.store_dir, "residual_round.npy")
        tmp = path + ".tmp.npy"
        np.save(tmp, np.asarray([round_no], np.int64))
        os.replace(tmp, path)

    def round(self):
        if self.store_dir is None:
            return None
        path = os.path.join(self.store_dir, "residual_round.npy")
        if not os.path.exists(path):
            return None
        return int(np.load(path)[0])

    def reset(self) -> None:
        """Zero every residual and the files (fallback / trajectory
        mismatch: accumulated compression error belongs to the abandoned
        params)."""
        self._rows.clear()
        if self.store_dir is not None:
            for name in os.listdir(self.store_dir):
                if name.startswith("residual_"):
                    os.remove(os.path.join(self.store_dir, name))

    def persisted_client_ids(self):
        """Client ids with a durable residual file (device-table warm-up)."""
        if self.store_dir is None:
            return sorted(self._rows)
        ids = []
        for name in os.listdir(self.store_dir):
            if name.startswith("residual_") and name.endswith(".npy"):
                key = name[len("residual_"):-len(".npy")]
                if key.lstrip("-").isdigit():
                    ids.append(int(key))
        return sorted(ids)


class DeviceResidualTable:
    """HBM-resident EF residuals (``server_config.ef_device_residuals``).

    The host ``ResidualStore`` path materializes a dense ``[K, n_params]``
    f32 matrix on the host every EF round and ships it to the device (and
    the new residuals back) — at BERT scale that is GB-class host traffic
    per round, the exact transfer profile the SCAFFOLD
    ``DeviceControlTable`` was built to kill.  This is the same cure on
    the same pattern: the full ``[N_clients, n_params]`` residual table
    lives in HBM sharded over the clients mesh axis; per round

    - ``rows(ids)`` gathers the K sampled residual rows as a
      client-sharded device array that feeds the jitted EF step directly,
    - ``update(...)`` scatters the step's new-residual output (already a
      device array) back in-program with the table buffer donated —
      participation-gated (id >= 0 and aggregation weight > 0) with
      out-of-bounds drop for padding slots,

    so the ROUND PATH no longer stages residuals through the host in
    either direction.  Durability: the wrapped :class:`ResidualStore`
    stays the format of record; dirty rows flush through when the
    residual-round marker commits — and that flush is itself a
    ``[K, n_params]`` fetch + K file writes, so at the default
    ``ef_flush_freq: 1`` roughly half of the host traffic remains.  The
    full transfer win needs ``ef_flush_freq > 1`` (amortizes the flush;
    the rounds in between keep the -1 marker sentinel, so a crash inside
    the window resets ALL residuals on resume — the same
    durability-vs-transfer tradeoff as ``scaffold_flush_freq``).  HBM
    cost is ``4·N·n_params`` bytes — worth it when per-round residual
    transfers dominate, not when the client pool is huge and the model
    small.
    """

    def __init__(self, store: ResidualStore, n_clients: int, mesh):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import CLIENTS_AXIS

        self.store = store
        self.n_clients = int(n_clients)
        axis = int(mesh.shape[CLIENTS_AXIS])
        # pad rows to shard evenly; padding rows are never gathered
        # (valid ids < N) and scatters to them drop out of bounds
        self.n_rows = ((self.n_clients + axis - 1) // axis) * axis
        self._row_sharding = NamedSharding(mesh, P(CLIENTS_AXIS, None))
        self._rep = NamedSharding(mesh, P())
        n_rows, n_params = self.n_rows, store.n_params
        self._zeros = jax.jit(
            lambda: jnp.zeros((n_rows, n_params), jnp.float32),
            out_shardings=self._row_sharding)
        self.table = self._zeros()
        self._scatter = jax.jit(
            lambda t, i, v: t.at[i].set(v), donate_argnums=(0,),
            out_shardings=self._row_sharding)
        warm = [cid for cid in store.persisted_client_ids()
                if 0 <= cid < self.n_clients]
        for lo in range(0, len(warm), 512):
            chunk = warm[lo:lo + 512]
            rows = store.rows(np.asarray(chunk, np.int64))
            self.table = self._scatter(
                self.table, jnp.asarray(chunk, jnp.int32),
                # flint: disable=put-loop one-time table warm-up at construction
                jax.device_put(rows, self._rep))
        self._dirty = set()

        def gather_fn(table, ids):
            rows = table[jnp.clip(ids, 0, n_rows - 1)]
            valid = (ids >= 0).astype(jnp.float32)[:, None]
            return rows * valid

        self._gather = jax.jit(gather_fn, out_shardings=self._row_sharding)

        def update_fn(table, ids, new_res, ws):
            valid = (ids >= 0) & (ws > 0.0)
            return table.at[jnp.where(valid, ids, n_rows)].set(
                new_res, mode="drop")

        self._update = jax.jit(
            update_fn, donate_argnums=(0,),
            out_shardings=self._row_sharding)

    def rows(self, client_ids):
        """Client-sharded ``[K, n_params]`` residual rows (zeros for
        padding ids) — a device array, no host staging."""
        import jax.numpy as jnp
        return self._gather(self.table,
                            jnp.asarray(np.asarray(client_ids), jnp.int32))

    def update(self, client_ids, new_res, ws, ws_np) -> None:
        """Scatter the EF step's new residuals in-program.  ``new_res``
        and ``ws`` stay on device; ``ws_np`` (fetched for logging anyway)
        only marks dirty rows for ``flush()``."""
        import jax.numpy as jnp
        ids_np = np.asarray(client_ids)
        self.table = self._update(
            self.table, jnp.asarray(ids_np, jnp.int32), new_res, ws)
        for row, cid in enumerate(ids_np):
            if int(cid) >= 0 and float(ws_np[row]) > 0.0:
                self._dirty.add(int(cid))

    def flush(self) -> None:
        """Write dirty rows through to the durable ResidualStore."""
        if self._dirty:
            ids = np.asarray(sorted(self._dirty), np.int32)
            rows = np.asarray(jax.device_get(self.table[ids]))
            self.store.update(ids, rows, np.ones(len(ids), bool))
            self._dirty.clear()

    def reset(self) -> None:
        """Zero table + durable store (fallback semantics)."""
        self.table = self._zeros()
        self._dirty.clear()
        self.store.reset()


class EFQuant(FedAvg):
    """FedAvg weighting + error-feedback quantization on the
    host-orchestrated round path (``engine/server.py::_run_ef_round``).
    The strategy itself applies NO in-jit quantization — the EF step
    needs the per-client residual, which lives outside the fused round
    program."""

    supports_staleness = False
    supports_rl = False
    #: selects the host-orchestrated EF round path
    ef_rounds = True
    #: fleet paging: the residual table is the pageable state
    carry_tables = ("res",)

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        # fused carry mode (server_config.fused_carry): the [N, n_params]
        # residual table rides strategy_state as a donated device buffer;
        # the EF correct/quantize/remember cycle happens inside the vmap'd
        # client body and the round pipelines like FedAvg (PR 6).
        sc = getattr(config, "server_config", None)
        self.fused = bool(sc is not None and sc.get("fused_carry", False))
        if self.fused:
            self.ef_rounds = False
            self.device_carry = True
            if dp_config is not None and dp_config.get("adaptive_clipping"):
                raise ValueError(
                    "strategy: ef_quant with fused_carry does not compose "
                    "with dp_config.adaptive_clipping — the carry state "
                    "holds only the EF residual table, so the quantile-"
                    "tracking clip state would silently freeze at "
                    "max_grad; drop fused_carry (host EF round) or "
                    "adaptive_clipping")
        cc = config.client_config
        self.quant_bits = int(cc.get("quant_bits", 4))
        self.quant_thresh = float(cc.get("quant_thresh", 0.0))
        self.quant_anneal = float(cc.get("quant_anneal", 1.0) or 1.0)
        self.quant_approx = bool(cc.get("quant_approx", False))
        if not 1 <= self.quant_bits <= 16:
            raise ValueError(
                f"ef_quant quant_bits must be in [1, 16], "
                f"got {self.quant_bits}")
        if not 0.0 <= self.quant_thresh < 1.0:
            raise ValueError(
                f"ef_quant quant_thresh is an |.|-quantile in [0, 1), "
                f"got {self.quant_thresh}")

    # ---- fused carry mode (server_config.fused_carry) ----------------
    def init_state(self, params_like):
        if not self.fused:
            return super().init_state(params_like)
        if not self.carry_clients:
            raise ValueError(
                "fused_carry ef_quant needs carry_clients (the total "
                "client-pool size) set before init_state — the server "
                "does this from len(train_dataset)")
        n_params = sum(int(np.prod(leaf.shape))
                       for leaf in jax.tree.leaves(params_like))
        # leading dim: page-pool slots under fleet paging, else the pool
        return {"res": jnp.zeros((self._carry_table_rows(), n_params),
                                 jnp.float32)}

    def client_step_carry(self, client_update, global_params, arrays,
                          sample_mask, client_lr, rng, *, client_id,
                          live_mask, round_idx=None, leakage_threshold=None,
                          quant_threshold=None, strategy_state=None):
        from jax.flatten_util import ravel_pytree
        from ..ops.quantization import quantize_array
        # the payload post local-DP transform — exactly what the host EF
        # round compresses (DP before EF, so the residual never absorbs
        # the noise-free signal)
        parts, tl, ns, stats = super().client_step(
            client_update, global_params, arrays, sample_mask, client_lr,
            rng, round_idx=round_idx, leakage_threshold=leakage_threshold,
            quant_threshold=None, strategy_state=None)
        pg, w = parts["default"]
        pg_flat, unravel = ravel_pytree(pg)
        n_rows = strategy_state["res"].shape[0]
        valid = (client_id >= 0).astype(jnp.float32)
        res = strategy_state["res"][jnp.clip(client_id, 0, n_rows - 1)] \
            * valid
        corrected = pg_flat + res
        # per-round annealed threshold rides the quant_threshold operand
        # (the server's quant_anneal schedule, same metric log); -1 means
        # "not configured" -> the strategy's static default
        thresh = jnp.where(quant_threshold >= 0, quant_threshold,
                           self.quant_thresh) if quant_threshold is not None \
            else self.quant_thresh
        q = quantize_array(corrected, n_bins=2 ** self.quant_bits,
                           quant_threshold=thresh, approx=self.quant_approx)
        new_res = corrected - q
        parts = dict(parts)
        parts["default"] = (unravel(q), w)
        keep = valid * live_mask * (w > 0).astype(jnp.float32)
        carry = {"row": jnp.where(keep > 0, new_res, res), "keep": keep}
        return parts, tl, ns, stats, carry

    def apply_carry(self, state, client_ids, carry, rng=None):
        rows, keep = carry["row"], carry["keep"]
        n_rows = state["res"].shape[0]
        idx = jnp.where(keep > 0, client_ids, n_rows)
        return {"res": state["res"].at[idx].set(rows, mode="drop")}

    def next_threshold(self) -> float:
        """Anneal the sparsification threshold per round — the same
        ``quant_anneal`` semantics the fused DGA path applies
        (``engine/server.py`` per-round multiply + metric log)."""
        self.quant_thresh *= self.quant_anneal
        return self.quant_thresh

    # ------------------------------------------------------------------
    def ef_step(self, pgs_flat: jnp.ndarray, residuals: jnp.ndarray,
                thresh=None):
        """One jitted EF compression over the payload stack.

        ``corrected = pgs + residuals``; per-row quantization; the new
        residual is ``corrected - q`` — the EF identity
        ``q + e' == corrected`` then holds to one f32 rounding of the
        subtraction (exact when q is near corrected, Sterbenz)."""
        from ..ops.quantization import quantize_array
        thresh = self.quant_thresh if thresh is None else thresh
        corrected = pgs_flat + residuals
        q = jax.vmap(lambda row: quantize_array(
            row, n_bins=2 ** self.quant_bits,
            quant_threshold=thresh,
            approx=self.quant_approx))(corrected)
        return q, corrected - q
