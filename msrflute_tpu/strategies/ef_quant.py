"""Error-feedback quantized aggregation (EF-SGD / EF14-style memory) —
net-new vs the reference.

The reference's quantization (``extensions/quantization/quant.py:9-50``)
is memoryless: what the binning throws away each round is gone, which
biases the aggregate and stalls convergence at aggressive bit widths.
Error feedback is the standard fix (Seide et al. 2014; Karimireddy et
al. 2019 arXiv:1901.09847): each client keeps the residual of its last
compression and folds it into the next payload before compressing —

    corrected_k = pg_k + e_k
    q_k         = Q(corrected_k)          (sent; aggregated as usual)
    e_k'        = corrected_k - q_k       (kept on the client)

so quantization error is delayed, never dropped, and compressed SGD
recovers the uncompressed rate.

Cross-device FL needs the residual to SURVIVE between a client's
participations, so ``e_k`` rides the same durable per-client row store
discipline as SCAFFOLD's control variates: flat f32 rows in
ravel-pytree order, crash-safe files under the model dir, reloaded on
resume only with a matching checkpoint (``engine/server.py``).  The
round runs on the host-orchestrated path (``client_payloads`` -> one
jitted EF step over the ``[K, n_params]`` payload stack ->
``apply_custom_weights``), exactly like SCAFFOLD/RL rounds.

Config::

    strategy: ef_quant
    client_config:
      quant_bits: 4          # 2^bits levels; EF is what makes 2-4 viable
      quant_thresh: 0.0      # |.|-quantile zeroed before binning
      quant_anneal: 1.0      # per-round threshold multiplier (DGA's knob)
      quant_approx: false    # O(n) histogram quantile instead of sort

Composition: local DP runs inside ``client_payloads``'s per-client
transform BEFORE the EF step, so the noised payload is what gets
compressed — the DP guarantee is unaffected by EF (the residual never
leaves the client).  RL re-weighting and staleness use the fused path
and do not compose with EF rounds.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .fedavg import FedAvg


class ResidualStore:
    """Durable per-client EF residual rows (flat f32, ravel-pytree
    order).  Same file discipline as ``scaffold.ControlStore``: tmp+rename
    writes, unseen clients start at zero, LRU-bounded RAM when a disk
    store exists."""

    _MAX_RESIDENT = 4096

    def __init__(self, n_params: int, store_dir: Optional[str] = None,
                 resume: bool = False):
        self.n_params = int(n_params)
        self.store_dir = store_dir
        self._rows: Dict[int, np.ndarray] = {}
        if store_dir is not None:
            os.makedirs(store_dir, exist_ok=True)
            if not resume:
                for name in os.listdir(store_dir):
                    if name.startswith("residual_"):
                        os.remove(os.path.join(store_dir, name))

    def _path(self, cid: int) -> str:
        return os.path.join(self.store_dir, f"residual_{cid}.npy")

    def _evict(self) -> None:
        if self.store_dir is None:
            return
        while len(self._rows) > self._MAX_RESIDENT:
            self._rows.pop(next(iter(self._rows)))

    def _touch(self, cid: int, row: np.ndarray) -> None:
        # true LRU: re-insert at the tail on every read AND write, like
        # ControlStore — eviction pops the head (least recently used)
        self._rows.pop(cid, None)
        self._rows[cid] = row

    def rows(self, ids) -> np.ndarray:
        """[K, n_params] residual matrix; zeros for unseen/padding."""
        out = np.zeros((len(ids), self.n_params), np.float32)
        for i, cid in enumerate(np.asarray(ids)):
            cid = int(cid)
            if cid < 0:
                continue
            row = self._rows.get(cid)
            if row is None and self.store_dir is not None and \
                    os.path.exists(self._path(cid)):
                row = np.load(self._path(cid)).astype(np.float32)
            if row is not None:
                self._touch(cid, row)
                out[i] = row
        self._evict()
        return out

    def update(self, ids, new_rows: np.ndarray, keep_mask) -> None:
        for i, cid in enumerate(np.asarray(ids)):
            cid = int(cid)
            if cid < 0 or not keep_mask[i]:
                continue
            row = np.asarray(new_rows[i], np.float32)
            self._touch(cid, row)
            if self.store_dir is not None:
                path = self._path(cid)
                tmp = path + ".tmp.npy"
                np.save(tmp, row)
                os.replace(tmp, path)
        self._evict()

    # -- trajectory marker (same crash semantics as ControlStore): -1
    # sentinel while residual files mutate; the server commits the real
    # round only after the paired model checkpoint is durable
    def set_round(self, round_no: int) -> None:
        if self.store_dir is None:
            return
        path = os.path.join(self.store_dir, "residual_round.npy")
        tmp = path + ".tmp.npy"
        np.save(tmp, np.asarray([round_no], np.int64))
        os.replace(tmp, path)

    def round(self):
        if self.store_dir is None:
            return None
        path = os.path.join(self.store_dir, "residual_round.npy")
        if not os.path.exists(path):
            return None
        return int(np.load(path)[0])

    def reset(self) -> None:
        """Zero every residual and the files (fallback / trajectory
        mismatch: accumulated compression error belongs to the abandoned
        params)."""
        self._rows.clear()
        if self.store_dir is not None:
            for name in os.listdir(self.store_dir):
                if name.startswith("residual_"):
                    os.remove(os.path.join(self.store_dir, name))


class EFQuant(FedAvg):
    """FedAvg weighting + error-feedback quantization on the
    host-orchestrated round path (``engine/server.py::_run_ef_round``).
    The strategy itself applies NO in-jit quantization — the EF step
    needs the per-client residual, which lives outside the fused round
    program."""

    supports_staleness = False
    supports_rl = False
    #: selects the host-orchestrated EF round path
    ef_rounds = True

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        cc = config.client_config
        self.quant_bits = int(cc.get("quant_bits", 4))
        self.quant_thresh = float(cc.get("quant_thresh", 0.0))
        self.quant_anneal = float(cc.get("quant_anneal", 1.0) or 1.0)
        self.quant_approx = bool(cc.get("quant_approx", False))
        if not 1 <= self.quant_bits <= 16:
            raise ValueError(
                f"ef_quant quant_bits must be in [1, 16], "
                f"got {self.quant_bits}")
        if not 0.0 <= self.quant_thresh < 1.0:
            raise ValueError(
                f"ef_quant quant_thresh is an |.|-quantile in [0, 1), "
                f"got {self.quant_thresh}")

    def next_threshold(self) -> float:
        """Anneal the sparsification threshold per round — the same
        ``quant_anneal`` semantics the fused DGA path applies
        (``engine/server.py`` per-round multiply + metric log)."""
        self.quant_thresh *= self.quant_anneal
        return self.quant_thresh

    # ------------------------------------------------------------------
    def ef_step(self, pgs_flat: jnp.ndarray, residuals: jnp.ndarray,
                thresh=None):
        """One jitted EF compression over the payload stack.

        ``corrected = pgs + residuals``; per-row quantization; the new
        residual is ``corrected - q`` — the EF identity
        ``q + e' == corrected`` then holds to one f32 rounding of the
        subtraction (exact when q is near corrected, Sterbenz)."""
        from ..ops.quantization import quantize_array
        thresh = self.quant_thresh if thresh is None else thresh
        corrected = pgs_flat + residuals
        q = jax.vmap(lambda row: quantize_array(
            row, n_bins=2 ** self.quant_bits,
            quant_threshold=thresh,
            approx=self.quant_approx))(corrected)
        return q, corrected - q
