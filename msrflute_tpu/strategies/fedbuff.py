"""FedBuff — buffered asynchronous aggregation (Nguyen et al., AISTATS'22,
arXiv:2106.06639) — net-new vs the reference.

FLUTE's orchestration is synchronous (its ``stale_prob`` defers whole
AGGREGATES server-side, ``core/strategies/dga.py:260-284``); real async
FL is different: each client trains from whatever model version it was
handed, so by the time its update arrives the server has moved on.
FedBuff is the standard simulation of that regime — the server applies a
buffer of client deltas that were computed against versions up to
``max_staleness`` steps old, each discounted by a staleness weight
``(1 + s)^(-staleness_exponent)``.

TPU mapping (single jitted round, no async runtime needed):

- the strategy state carries a device-resident HISTORY of the last
  ``max_staleness`` broadcast versions — stacked leaves
  ``[S, ...param]``, index 0 = current, exactly the round-fusion-safe
  shape (the state threads through the ``lax.scan`` like every other
  strategy state);
- per client, IN-JIT: draw ``s_i ~ Uniform{0..S-1}`` from the client's
  rng fold, start local training from ``history[s_i]`` (a dynamic
  leading-axis index inside the vmapped client program — no ``[K,
  n_params]`` materialization), and scale the aggregation weight by
  ``(1 + s_i)^(-rho)``;
- the server update is owned: plain SGD on the aggregate (the paper's
  server step), then the history rolls — ``concat([new_params, ...,
  drop oldest])``.

Faithfulness notes: the pseudo-gradient a client returns is
``history[s_i] - y_T`` (its OWN version minus its trained weights) and
the server applies the discounted average to the CURRENT params — which
is precisely FedBuff's gradient-style application of stale deltas.  The
buffer size of the paper maps onto ``num_clients_per_iteration`` (K
arrivals trigger one server step).  ``max_staleness: 1`` is exactly
FedAvg (every client reads index 0) — pinned by test.

Drawn vs TRACED staleness: without the arrival plane, ``s_i`` is a
MODEL — an in-jit uniform draw from the client's rng fold, standing in
for an async timeline the simulator does not have.  With
``server_config.traffic`` in ``buffered`` mode the timeline is real:
the engine passes each update's TRUE broadcast-version gap (fires since
the client's version, ``traffic/schedule.py``) as an int32 data operand
and ``client_step`` uses it instead of drawing — the history index
clips to ``max_staleness - 1`` (the state holds that many versions; an
older client trains from the oldest retained), while the aggregation
DISCOUNT uses the unclipped true gap, so over-horizon updates are
downweighted by how stale they actually are.  The ``max_staleness: 1
== FedAvg`` pin carries over exactly when the trace's timeline is
staleness-free (every fire at version gap 0, e.g. ``mode: sync``
semantics or ``buffer_size`` small enough that no overlap occurs);
with real staleness in the trace the two differ precisely by the
discount — that difference is the measurement, not a bug.

Config::

    strategy: fedbuff
    server_config:
      fedbuff: {max_staleness: 4, staleness_exponent: 0.5}
      optimizer_config: {type: sgd, lr: ...}   # server step is owned SGD

HBM cost: ``max_staleness`` extra param copies in the strategy state.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from .fedavg import FedAvg


class FedBuff(FedAvg):

    supports_staleness = False   # DGA's aggregate deferral doesn't compose
    #: the engine compiles the traced-staleness operand in (and the
    #: server builds per-fire staleness vectors) only for strategies
    #: that declare they consume it — see the module docstring's
    #: drawn-vs-traced distinction
    supports_traced_staleness = True
    supports_rl = False
    owns_server_update = True
    stateful = True
    # the strategy state is the version history; FedAvg's adaptive-clip
    # state ("dp_clip") cannot share it — the base init then rejects
    # adaptive_clipping configs loudly (same stance as FedAC/Scaffold)
    supports_adaptive_clipping = False

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        fb = config.server_config.get("fedbuff", True)
        if not isinstance(fb, (dict, bool)):
            raise ValueError(
                f"server_config.fedbuff must be a bool or an options dict, "
                f"got {type(fb).__name__}")
        fb = fb if isinstance(fb, dict) else {}
        unknown = set(fb) - {"max_staleness", "staleness_exponent"}
        if unknown:
            raise ValueError(
                f"server_config.fedbuff has unknown keys {sorted(unknown)} "
                f"(known: max_staleness, staleness_exponent)")
        self.max_staleness = int(fb.get("max_staleness", 4))
        self.rho = float(fb.get("staleness_exponent", 0.5))
        if self.max_staleness < 1:
            raise ValueError(
                f"fedbuff.max_staleness must be >= 1 (1 == synchronous "
                f"FedAvg), got {self.max_staleness}")
        if self.rho < 0:
            raise ValueError(
                f"fedbuff.staleness_exponent must be >= 0, got {self.rho}")
        opt = config.server_config.optimizer_config
        if str(opt.get("type", "sgd")).lower() != "sgd":
            raise ValueError(
                "strategy: fedbuff owns its server update (the paper's "
                "SGD step + history roll) — server optimizer_config.type "
                f"must be sgd, got {opt.get('type')!r}")

    # ---- engine hooks -------------------------------------------------
    def init_state(self, params_like: Any) -> Any:
        # stack materializes fresh buffers, so the state never aliases the
        # params it was built from (the round step donates both — same
        # donation rule FedAC's init documents)
        s = self.max_staleness
        return {"history": jax.tree.map(
            lambda p: jnp.stack([p] * s), params_like)}

    def client_step(self, client_update, global_params, arrays, sample_mask,
                    client_lr, rng, round_idx=None, leakage_threshold=None,
                    quant_threshold=None, strategy_state=None,
                    grad_offset=None, staleness=None):
        # per-client staleness: this client trains from the version it
        # "received" s_i server-steps ago.  Early rounds have identical
        # history slots (init_state), matching a cold-start system where
        # nothing has moved yet.  ``staleness`` (traced mode, the
        # arrival plane's int32 operand) replaces the modeled draw: the
        # history index clips to the retained horizon, the discount
        # keeps the TRUE gap (module docstring).
        if staleness is not None:
            s_true = jnp.asarray(staleness, jnp.int32)
            s_i = jnp.clip(s_true, 0, self.max_staleness - 1)
        else:
            s_i = jax.random.randint(jax.random.fold_in(rng, 23), (), 0,
                                     self.max_staleness)
            s_true = s_i
        start = jax.tree.map(lambda h: h[s_i],
                             strategy_state["history"])
        parts, tl, ns, stats = super().client_step(
            client_update, start, arrays, sample_mask, client_lr, rng,
            round_idx=round_idx, leakage_threshold=leakage_threshold,
            quant_threshold=quant_threshold, strategy_state=strategy_state,
            grad_offset=grad_offset)
        pg, w = parts["default"]
        discount = (1.0 + s_true.astype(jnp.float32)) ** (-self.rho)
        parts["default"] = (pg, w * discount)
        return parts, tl, ns, stats

    def megabatch_passes(self, *, strategy_state, global_params,
                         client_ids, slots, rng):
        """ONE lane-scan pass starting each client at its stale history
        version: the per-client ``s_i`` draw replays :meth:`client_step`'s
        ``fold_in(rng_client, 23)`` stream on the TRUE client ids, so the
        lane scan trains from (and anchors against) exactly the version
        the vmap arm would have handed ``client_update``."""
        from jax.flatten_util import ravel_pytree
        hist = strategy_state["history"]

        def row(cid):
            r = jax.random.fold_in(jax.random.fold_in(rng, cid), 23)
            s_i = jax.random.randint(r, (), 0, self.max_staleness)
            return ravel_pytree(
                jax.tree.map(lambda h: h[s_i], hist))[0]

        return ({"init_rows": jax.vmap(row)(client_ids)},)

    def apply_server_update(self, params: Any, agg: Any, state: Any,
                            server_lr) -> Tuple[Any, Any]:
        lr = jnp.asarray(server_lr, jnp.float32)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, agg)
        # roll the version history: index 0 = the params clients of the
        # NEXT round may read as "current"
        new_hist = jax.tree.map(
            lambda p, h: jnp.concatenate([p[None], h[:-1]], axis=0),
            new_params, state["history"])
        return new_params, {"history": new_hist}
