"""Byzantine-robust aggregation (fluteshield's aggregator half).

Coordinate-wise trimmed mean and coordinate-wise median (Yin et al.,
arXiv:1803.01498) over the SCREENED per-client payload stack, selectable
via ``server_config.robust.aggregator``.  Unlike every other strategy in
this package, these estimators are not decomposable into the engine's
weighted ``psum`` — each coordinate needs the full sorted cohort — so
:class:`RobustFedAvg` sets ``wants_client_stack`` and the round program
``all_gather``s the sanitized per-client payloads (``[K, ...]`` per
leaf, replicated) before combining.  That is the estimator's inherent
memory cost: K x model size per device, the same order the RL/norm-dump
paths already pay; it stays inside the fused program, so the one-packed-
fetch-per-round and strict-transfer contracts hold unchanged.

Both estimators are UNWEIGHTED over the kept clients (the literature's
setting: sample-count weighting would let an adversary buy influence by
claiming samples).  FedAvg's weighted mean remains available as
``aggregator: mean`` — screening only.

All functions here are pure traced code composed into the jitted round
program; masked clients are excluded by rank against ``+inf`` sentinels
(never a ``0 * inf`` multiply, which would mint NaNs).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from .base import BaseStrategy
from .fedavg import FedAvg


def _rank_shape(g: jnp.ndarray) -> Tuple[int, ...]:
    return (g.shape[0],) + (1,) * (g.ndim - 1)


def coordinate_trimmed_mean(stack: Any, keep: jnp.ndarray,
                            trim_fraction: float) -> Any:
    """Coordinate-wise ``trim_fraction``-trimmed mean over the kept
    clients of a ``[K, ...]``-leading payload stack.

    ``keep [K]``: 1 for clients that participate (live AND unscreened).
    Per coordinate: masked AND non-finite entries sort to the top as
    ``+inf``, the finite kept entries occupy ranks ``[0, n)``, and ranks
    ``[t, n - t)`` average, with ``t = floor(trim_fraction * n)``.  The
    finite check must happen BEFORE the sort: ``jnp.sort`` ranks NaN
    above ``+inf``, so a kept NaN coordinate (screening off) would
    otherwise push a sentinel into the averaged window.  ``n`` is a
    traced per-coordinate count, so a round with a different live count
    reuses the same compiled program; an all-non-finite coordinate
    contributes zero (a no-op for that coordinate).
    """
    def leaf(g):
        live = keep.reshape(_rank_shape(g)) > 0
        part = live & jnp.isfinite(g)
        n = jnp.sum(part, axis=0, keepdims=True).astype(g.dtype)
        t = jnp.floor(trim_fraction * n)
        denom = jnp.maximum(n - 2.0 * t, 1.0)
        srt = jnp.sort(jnp.where(part, g, jnp.inf), axis=0)
        ranks = jnp.arange(g.shape[0]).reshape(_rank_shape(g))
        ind = (ranks >= t) & (ranks < n - t)
        return (jnp.sum(jnp.where(ind, srt, 0.0), axis=0)
                / jnp.squeeze(denom, axis=0))

    return jax.tree.map(leaf, stack)


def coordinate_median(stack: Any, keep: jnp.ndarray) -> Any:
    """Coordinate-wise median over the finite kept clients of a
    ``[K, ...]`` stack (even counts interpolate the two middle ranks).
    Non-finite kept coordinates are excluded per coordinate BEFORE the
    sort (NaN ranks above ``+inf``, so it cannot be excluded after); an
    empty vote yields zero for that coordinate (a no-op server step),
    matching the weighted-mean path's ``max(weight_sum, eps)``
    behavior."""
    def leaf(g):
        live = keep.reshape(_rank_shape(g)) > 0
        part = live & jnp.isfinite(g)
        n = jnp.sum(part.astype(jnp.int32), axis=0, keepdims=True)
        i_lo = jnp.maximum((n - 1) // 2, 0)
        i_hi = jnp.maximum(n // 2, 0)
        srt = jnp.sort(jnp.where(part, g, jnp.inf), axis=0)
        ranks = jnp.arange(g.shape[0]).reshape(_rank_shape(g))
        ind = 0.5 * ((ranks == i_lo).astype(g.dtype)
                     + (ranks == i_hi).astype(g.dtype))
        med = jnp.sum(jnp.where(ind > 0, srt, 0.0) * ind, axis=0)
        return jnp.where(jnp.squeeze(n, axis=0) > 0, med,
                         jnp.zeros_like(med))

    return jax.tree.map(leaf, stack)


class RobustFedAvg(FedAvg):
    """FedAvg plumbing with a Byzantine-robust combine.

    Client side is UNCHANGED (local SGD, DP transform, privacy metrics,
    strategy weights) — the robustness is entirely in how the cohort's
    payload stack reduces.  The engine detects ``wants_client_stack``
    and calls :meth:`combine_stack` on the gathered, screened stack
    instead of :meth:`combine` on the psum'd sums.
    """

    wants_client_stack = True
    # the payload stack reduces as one cohort; deferring a slice of it a
    # round (DGA staleness) or re-weighting it post hoc (RL) would
    # reintroduce exactly the single-client leverage this estimator
    # removes
    supports_staleness = False
    supports_rl = False

    def __init__(self, config, dp_config=None):
        super().__init__(config, dp_config)
        raw = dict(config.server_config.get("robust") or {})
        self.aggregator = str(raw.get("aggregator", "mean"))
        self.trim_fraction = float(raw.get("trim_fraction", 0.1))
        if self.aggregator not in ("trimmed_mean", "median"):
            raise ValueError(
                "RobustFedAvg is the stack-combining strategy — "
                f"aggregator {self.aggregator!r} does not need it "
                "(screened mean rides the plain FedAvg sum path)")
        if self.adaptive_clip is not None:
            raise ValueError(
                "dp_config.adaptive_clipping tracks its quantile through "
                "the weighted-sum combine, which a robust aggregator "
                "bypasses — disable one of them")

    def combine_stack(self, stack: Any, keep: jnp.ndarray,
                      rng: jax.Array) -> Any:
        """TRACED: reduce the gathered ``[K, ...]`` payload stack to the
        aggregate pseudo-gradient.  ``keep`` is the live-and-unscreened
        mask the round program folded (padding, chaos dropout, and
        quarantine are all already zeros)."""
        if self.aggregator == "median":
            return coordinate_median(stack, keep)
        return coordinate_trimmed_mean(stack, keep, self.trim_fraction)


def select_robust_strategy(config, dp_config, base_cls) -> BaseStrategy:
    """Server-side selection: swap FedAvg for :class:`RobustFedAvg` when
    ``server_config.robust`` asks for a stack aggregator.  Non-FedAvg
    strategies are refused loudly (schema enforces this too) — silently
    aggregating unscreened payloads under a ``robust`` block is the
    quiet failure fluteshield exists to prevent."""
    raw = dict(config.server_config.get("robust") or {})
    if not raw or not raw.get("enable", True):
        return base_cls(config, dp_config)
    from .secure_agg import SecureAgg
    aggregator = str(raw.get("aggregator", "mean"))
    if base_cls is SecureAgg:
        # secure_agg composes with the MEAN shield: screening votes on
        # per-client submitted norms (Shield.screen_masked) and a
        # quarantined client feeds the pairwise-mask cancellation path
        # as one more dropout cause (tests/test_secagg_compose.py).
        # Stack aggregators still cannot work here — coordinate-wise
        # sort estimators need plaintext per-client payloads, and a
        # secure_agg submission is a masked int32 group element whose
        # only meaningful reduction is the SUM
        if aggregator in ("trimmed_mean", "median"):
            raise ValueError(
                f"robust.aggregator={aggregator!r} sorts per-client "
                "payload coordinates, but secure_agg submissions are "
                "masked int32 group elements — use aggregator: mean "
                "(submitted-norm screening still applies)")
        return base_cls(config, dp_config)
    # exact-class check: the remaining specialised strategies (QFFL,
    # FedBuff, Scaffold, EFQuant, fedlabels, ...) SUBCLASS FedAvg but
    # aggregate through their own payload parts / multi-part reweighting
    # that quarantine zeroing would silently corrupt, and the engine's
    # RL / adaptive-clipping guards refuse screening for the same
    # reason — issubclass would wave them all through when the schema
    # layer is bypassed
    if base_cls is not FedAvg:
        raise ValueError(
            "server_config.robust requires strategy: fedavg/fedprox/"
            f"secure_agg — {base_cls.__name__} aggregates through its "
            "own parts and would ignore the screening; drop the robust "
            "block or the strategy")
    if aggregator in ("trimmed_mean", "median"):
        return RobustFedAvg(config, dp_config)
    return base_cls(config, dp_config)
