"""Auxiliary training schedulers.

Parity targets:
- :class:`ScheduledSamplingScheduler` (reference ``utils/utils.py:228-260``):
  ramps a scheduled-sampling rate from ``initial_rate`` to ``final_rate``
  between ``ramp_start`` and ``ramp_stop`` iterations.  Functional here:
  instead of mutating a model attribute, :meth:`rate` returns the value for
  an iteration and the engine passes it into the task (tasks read
  ``batch['scheduled_sampling_rate']`` or a loss kwarg).
- :class:`NBestTaskScheduler` (reference ``utils/utils.py:263-294``):
  staged multi-task schedule (ASR n-best legacy) — cycles through stages of
  ``num_tasks`` with boundaries ``iteration_per_task``.
"""

from __future__ import annotations

from typing import Dict, List, Sequence


class ScheduledSamplingScheduler:

    def __init__(self, ramp_start: int, ramp_stop: int,
                 initial_rate: float, final_rate: float):
        self.ramp_start = int(ramp_start)
        self.ramp_stop = int(ramp_stop)
        self.initial_rate = float(initial_rate)
        self.final_rate = float(final_rate)
        self.iter = 0

    def rate(self, iteration: int) -> float:
        if iteration < self.ramp_start:
            return self.initial_rate
        if iteration <= self.ramp_stop:
            frac = (iteration - self.ramp_start) / max(
                self.ramp_stop - self.ramp_start, 1)
            return self.initial_rate + (self.final_rate -
                                        self.initial_rate) * frac
        return self.final_rate

    def step(self) -> float:
        value = self.rate(self.iter)
        self.iter += 1
        return value

    def state_dict(self) -> Dict:
        return dict(self.__dict__)

    def load_state_dict(self, state: Dict) -> None:
        self.__dict__.update(state)


class NBestTaskScheduler:

    def __init__(self, num_tasks: Sequence[int],
                 iteration_per_task: Sequence[int]):
        if len(num_tasks) != len(iteration_per_task):
            raise ValueError(
                f"mismatched lengths {len(num_tasks)} != "
                f"{len(iteration_per_task)}")
        self.iter = 0
        self.stagex = 0
        self.num_tasks = list(num_tasks)
        self.iteration_per_task = list(iteration_per_task)

    def current_num_tasks(self) -> int:
        return self.num_tasks[self.stagex]

    def no_label_updates(self) -> int:
        return (self.iter // self.iteration_per_task[-1]) + 1

    def set_iteration_no(self, iter_no: int) -> None:
        self.iter = iter_no

    def step(self) -> None:
        local_iter = self.iter % self.iteration_per_task[-1]
        if local_iter == 0:
            self.stagex = 0
        elif local_iter >= self.iteration_per_task[self.stagex]:
            self.stagex += 1
        self.iter += 1

    def state_dict(self) -> Dict:
        return dict(self.__dict__)

    def load_state_dict(self, state: Dict) -> None:
        self.__dict__.update(state)
