"""Fused apply-updates — the per-step optimizer tail as few traversals.

The megakernel local-SGD work (ISSUE 12) found the inner-step tail of
``engine/client_update.py`` paying five separate pytree traversals per
local step: grad-offset add (SCAFFOLD), FedProx proximal add, global-norm
clip scale, ``optax.apply_updates``, and the all-padding-step no-op pin.
Each traversal is a Python loop over every leaf at trace time — for a
scan body that is pure program text, and for deep models it is the bulk
of the traced op count.  This module collapses them:

- :func:`combine_grad_terms` — offset + proximal + clip in ONE combining
  traversal plus the unavoidable global-norm pass (the clip scale depends
  on the combined gradient, so it cannot fold further);
- :func:`fused_apply` — optimizer transform + frozen-layer mask +
  parameter apply + no-op pinning, with the apply and the pin fused into
  a single traversal (``where(live, p + u, p)``), and the optimizer-state
  pin kept as its own traversal only because optax state trees differ in
  structure from the param tree.

Bit-identity contract: every fused expression evaluates the SAME ops in
the SAME association as the legacy spelling (``(g + o) + mu*(w - w0)``,
``g * scale``, ``(p + u)`` then select), so an f32 run is bit-identical
to the pre-fusion program — pinned by tests/test_megakernel.py.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


def combine_grad_terms(grads: Any, *, offset: Any = None,
                       prox_mu: float = 0.0, params: Any = None,
                       global_params: Any = None,
                       max_norm: Optional[float] = None) -> Any:
    """``clip((g + offset) + mu * (w - w0))`` with one combining
    traversal.  ``offset`` is the SCAFFOLD drift correction, ``prox_mu``
    the FedProx proximal weight (needs ``params``/``global_params``),
    ``max_norm`` the global-norm clip bound; any of them absent compiles
    to nothing."""
    if offset is not None and prox_mu > 0.0:
        grads = jax.tree.map(
            lambda g, o, w, w0: (g + o) + prox_mu * (w - w0),
            grads, offset, params, global_params)
    elif offset is not None:
        grads = jax.tree.map(lambda g, o: g + o, grads, offset)
    elif prox_mu > 0.0:
        grads = jax.tree.map(
            lambda g, w, w0: g + prox_mu * (w - w0),
            grads, params, global_params)
    if max_norm is not None:
        norm = optax.global_norm(grads)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
        grads = jax.tree.map(lambda g: g * scale, grads)
    return grads


def fused_apply(tx: optax.GradientTransformation, grads: Any,
                opt_state: Any, params: Any, *, update_mask: Any = None,
                has_data: Any = None) -> Tuple[Any, Any]:
    """Optimizer update + masked apply + no-op pin.

    ``update_mask`` (per-leaf static Python bools, or None) freezes
    non-updatable layers; ``has_data`` (traced scalar, or None) pins
    all-padding steps to a no-op — params AND optimizer state — exactly
    like the legacy two-pass spelling, but the apply and the param pin
    share one traversal."""
    updates, new_opt = tx.update(grads, opt_state, params)
    if update_mask is not None:
        # static mask: frozen leaves are zero constants in XLA
        updates = jax.tree.map(
            lambda u, keep: u if keep else jnp.zeros_like(u),
            updates, update_mask)
    if has_data is None:
        return optax.apply_updates(params, updates), new_opt
    live = has_data > 0
    # apply + pin in one traversal; the (p + u) cast matches
    # optax.apply_updates so the f32 trace is bit-identical
    new_params = jax.tree.map(
        lambda p, u: jnp.where(live, jnp.asarray(p + u).astype(
            jnp.asarray(p).dtype), p),
        params, updates)
    new_opt = jax.tree.map(
        lambda new, old: jnp.where(live, new, old), new_opt, opt_state)
    return new_params, new_opt


def segment_select(pred: Any, fresh: Any, carried: Any) -> Any:
    """Tree-wise ``where(pred, fresh, carried)`` — the cross-client
    megabatch lane scan's SEGMENT-RESET primitive (engine/client_update.
    build_mega_update).  At a tape slot whose segment id differs from the
    previous slot's, the lane is starting a NEW client: params, optimizer
    state, rng, and loss/stat accumulators all select the fresh client
    values in one spelling.  ``pred`` is a scalar (per lane under vmap),
    so every leaf compiles to a broadcast select — the grouped analogue
    of :func:`fused_apply`'s no-op pin, and like it the select is the
    LAST op on each leaf, keeping the f32 segment math bit-identical to
    a per-client trace that never selects."""
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), fresh, carried)


def sgd_pallas_fusable(opt_cfg: Any) -> bool:
    """True when the client optimizer is the plain-SGD shape the pallas
    fused apply kernel implements: ``type: sgd``, no nesterov, no weight
    decay (momentum is fine — the kernel carries the trace buffer)."""
    kind = str(opt_cfg.get("type", "sgd")).lower()
    return (kind == "sgd"
            and not bool(opt_cfg.get("nesterov", False))
            and not float(opt_cfg.get("weight_decay", 0.0) or 0.0))
