"""Optimizer & LR-scheduler factories.

Parity targets:
- ``make_optimizer`` (reference ``utils/utils.py:27-64``): types
  sgd / adam / adamax / lars / LarsSGD / lamb / adamW
  (``core/schema.py:90``), with the vendored LAMB/LARS variants in
  ``utils/optimizers/``.  Here every type maps onto optax transforms — the
  TPU-native replacements of the torch/apex implementations.
- ``make_lr_scheduler`` (reference ``utils/utils.py:151-224``): ``step_lr``,
  ``multi_step_lr``, ``rampup-keep-expdecay-keep`` (SpecAugment schedule),
  and ``val_loss`` (ReduceLROnPlateau) — the last is data-dependent, so it
  stays host-side as :class:`PlateauTracker` and feeds a scalar LR into the
  jitted step (the reference likewise steps it outside the train loop,
  ``core/trainer.py:139-155``).

The server optimizer consumes *pseudo-gradients* (w0 - wT aggregates), same
as the reference's ``ModelUpdater.update_model`` (``core/trainer.py:127-137``).
LR is injected as a runtime scalar via ``optax.inject_hyperparams`` so
annealing never retriggers compilation.
"""

from __future__ import annotations

from typing import Callable, Optional

import optax

from ..config import AnnealingConfig, OptimizerConfig


def make_optimizer(cfg: OptimizerConfig,
                   learning_rate: Optional[float] = None) -> optax.GradientTransformation:
    """Build an optax optimizer from a FLUTE-vocabulary optimizer config.

    The returned transformation is wrapped in ``optax.inject_hyperparams`` so
    ``opt_state.hyperparams['learning_rate']`` can be overwritten each round
    (the reference mutates ``param_group['lr']`` the same way,
    ``core/client.py:309-312``).
    """
    lr = float(cfg.lr if learning_rate is None else learning_rate)
    kind = str(cfg.get("type", "sgd"))
    kind_l = kind.lower()
    wd = float(cfg.get("weight_decay", 0.0) or 0.0)

    if kind_l == "sgd":
        def base(learning_rate):
            tx = optax.sgd(learning_rate, momentum=float(cfg.get("momentum", 0.0)) or None,
                           nesterov=bool(cfg.get("nesterov", False)))
            if wd:
                tx = optax.chain(optax.add_decayed_weights(wd), tx)
            return tx
    elif kind_l == "adam":
        betas = cfg.get("betas") or [0.9, 0.999]
        def base(learning_rate):
            return optax.adam(learning_rate, b1=float(betas[0]), b2=float(betas[1]),
                              eps=float(cfg.get("eps", 1e-8)))
    elif kind_l == "adamax":
        def base(learning_rate):
            return optax.adamax(learning_rate, eps=float(cfg.get("eps", 1e-8)))
    elif kind_l in ("adamw",):
        def base(learning_rate):
            return optax.adamw(learning_rate, eps=float(cfg.get("eps", 1e-8)),
                               weight_decay=wd)
    elif kind_l == "lamb":
        def base(learning_rate):
            return optax.lamb(learning_rate, weight_decay=wd)
    elif kind_l in ("lars", "larssgd"):
        def base(learning_rate):
            return optax.lars(learning_rate, weight_decay=wd,
                              momentum=float(cfg.get("momentum", 0.9)))
    elif kind_l == "yogi":
        # net-new vs the reference's 7 types: as the SERVER optimizer over
        # pseudo-gradients this is FedYogi (Reddi et al.,
        # arXiv:2003.00295 — adam already gives FedAdam); yogi's additive
        # second-moment update tames adam's aggressiveness under the
        # sparse/noisy aggregate gradients federated rounds produce
        betas = cfg.get("betas") or [0.9, 0.999]
        def base(learning_rate):
            tx = optax.yogi(learning_rate, b1=float(betas[0]),
                            b2=float(betas[1]),
                            eps=float(cfg.get("eps", 1e-3)))
            if wd:  # optax.yogi has no weight_decay arg; chain like sgd
                tx = optax.chain(optax.add_decayed_weights(wd), tx)
            return tx
    else:
        raise ValueError(f"unknown optimizer type {kind!r}")

    return optax.inject_hyperparams(base)(learning_rate=lr)


def make_lr_schedule(cfg: Optional[AnnealingConfig],
                     base_lr: float) -> Callable[[int], float]:
    """Host-side LR schedule: round/epoch index -> LR scalar.

    Covers the reference's scheduler zoo (``utils/utils.py:151-224``) except
    ``val_loss``, which needs validation data and lives in
    :class:`PlateauTracker`.
    """
    if cfg is None or cfg.get("type", "step_lr") == "constant":
        return lambda step: base_lr

    kind = cfg.get("type", "step_lr")
    if kind == "step_lr":
        step_size = int(cfg.get("step_size", 1))
        gamma = float(cfg.get("gamma", 1.0))
        return lambda step: base_lr * (gamma ** (step // max(step_size, 1)))
    if kind == "multi_step_lr":
        milestones = sorted(cfg.get("milestones") or [])
        gamma = float(cfg.get("gamma", 1.0))
        def sched(step: int) -> float:
            k = sum(1 for m in milestones if step >= m)
            return base_lr * (gamma ** k)
        return sched
    if kind == "rampup-keep-expdecay-keep":
        # SpecAugment schedule (reference utils/utils.py:189-224): linear
        # ramp 0->peak over rampup_steps, hold hold_steps, exponential decay
        # to floor over decay_steps, then hold floor.
        peak = float(cfg.get("peak_lr", base_lr))
        floor = float(cfg.get("floor_lr", base_lr * 0.01))
        r = int(cfg.get("rampup_steps", 0))
        h = int(cfg.get("hold_steps", 0))
        d = max(int(cfg.get("decay_steps", 1)), 1)
        import math
        def sched(step: int) -> float:
            if r and step < r:
                return peak * (step + 1) / r
            step2 = step - r
            if step2 < h:
                return peak
            step3 = step2 - h
            if step3 < d:
                frac = step3 / d
                return peak * math.exp(math.log(max(floor / peak, 1e-12)) * frac)
            return floor
        return sched
    if kind == "val_loss":
        # handled by PlateauTracker; return constant here
        return lambda step: base_lr
    raise ValueError(f"unknown annealing type {kind!r}")


class PlateauTracker:
    """ReduceLROnPlateau equivalent (reference ``val_loss`` mode,
    ``utils/utils.py:151-186`` + ``core/trainer.py:139-155``): multiply LR by
    ``factor`` after ``patience`` rounds without val-loss improvement."""

    def __init__(self, cfg: AnnealingConfig, base_lr: float):
        self.lr = float(base_lr)
        self.factor = float(cfg.get("factor", 0.1))
        self.patience = int(cfg.get("patience", 10))
        self.best: Optional[float] = None
        self.bad_rounds = 0

    def step(self, val_loss: float) -> float:
        if self.best is None or val_loss < self.best:
            self.best = val_loss
            self.bad_rounds = 0
        else:
            self.bad_rounds += 1
            if self.bad_rounds > self.patience:
                self.lr *= self.factor
                self.bad_rounds = 0
        return self.lr
