from .factory import (  # noqa: F401
    make_optimizer, make_lr_schedule, PlateauTracker,
)
from .fused import (  # noqa: F401
    combine_grad_terms, fused_apply, sgd_pallas_fusable,
)
from .schedulers import (  # noqa: F401
    NBestTaskScheduler, ScheduledSamplingScheduler,
)
