from .factory import (  # noqa: F401
    make_optimizer, make_lr_schedule, PlateauTracker,
)
from .schedulers import (  # noqa: F401
    NBestTaskScheduler, ScheduledSamplingScheduler,
)
