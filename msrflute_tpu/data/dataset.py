"""Dataset contract for task plugins.

Parity target: reference ``core/dataset.py:7-27`` (``BaseDataset`` with
``user_list``, ``user_data``, ``num_samples`` [, ``user_data_label``] attrs)
and each task's ``dataloaders/dataset.py``.

The TPU-native contract is array-first: a task dataset must expose, per
user, *numeric fixed-width arrays* (featurization — tokenization, padding to
``max_seq_length``, image normalization — happens once at load time, not per
batch).  The engine then packs users into static-shape round batches
(:mod:`msrflute_tpu.data.batching`) with sample masks; there is no per-batch
Python in the hot loop, unlike the reference's torch DataLoader iteration
(``core/trainer.py:341-414``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class BaseDataset:
    """Abstract federated dataset.

    Subclasses populate :attr:`user_list` / :attr:`num_samples` and implement
    :meth:`user_arrays` returning a dict of numpy arrays whose leading axis is
    the user's sample count — canonically ``{'x': [n, ...], 'y': [n, ...]}``,
    plus any extra per-sample arrays the model consumes (e.g.
    ``attention_mask``).
    """

    user_list: List[str]
    num_samples: List[int]

    def __len__(self) -> int:
        return len(self.user_list)

    def user_arrays(self, user_idx: int) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    @property
    def element_spec(self) -> Dict[str, tuple]:
        """Trailing (per-sample) shapes, derived from the first user."""
        arrays = self.user_arrays(0)
        return {k: tuple(v.shape[1:]) for k, v in arrays.items()}


class ArraysDataset(BaseDataset):
    """A dataset backed by per-user numpy arrays held in memory.

    The workhorse for every built-in task: plugins featurize the raw user
    blob into arrays once, then hand them here.
    """

    def __init__(self, user_list: Sequence[str],
                 per_user: Sequence[Dict[str, np.ndarray]],
                 num_samples: Optional[Sequence[int]] = None):
        if len(user_list) != len(per_user):
            raise ValueError("user_list and per_user length mismatch")
        self.user_list = list(user_list)
        self._per_user = list(per_user)
        if num_samples is None:
            num_samples = [len(next(iter(u.values()))) for u in per_user]
        self.num_samples = [int(n) for n in num_samples]
        for i, arrays in enumerate(self._per_user):
            lens = {k: len(v) for k, v in arrays.items()}
            if any(n != self.num_samples[i] for n in lens.values()):
                raise ValueError(
                    f"user {user_list[i]}: array lengths {lens} != "
                    f"num_samples {self.num_samples[i]}")

    def user_arrays(self, user_idx: int) -> Dict[str, np.ndarray]:
        return self._per_user[user_idx]

    @classmethod
    def concat_users(cls, ds: "ArraysDataset") -> Dict[str, np.ndarray]:
        """All users' samples concatenated (for server replay / central eval)."""
        keys = ds.user_arrays(0).keys()
        return {k: np.concatenate([ds.user_arrays(i)[k] for i in range(len(ds))])
                for k in keys}


def scrub_empty_clients(dataset: ArraysDataset) -> ArraysDataset:
    """Drop users with zero samples (reference ``utils/utils.py:563-582``)."""
    keep = [i for i, n in enumerate(dataset.num_samples) if n > 0]
    return ArraysDataset(
        [dataset.user_list[i] for i in keep],
        [dataset.user_arrays(i) for i in keep],
        [dataset.num_samples[i] for i in keep],
    )
