"""Dataset contract for task plugins.

Parity target: reference ``core/dataset.py:7-27`` (``BaseDataset`` with
``user_list``, ``user_data``, ``num_samples`` [, ``user_data_label``] attrs)
and each task's ``dataloaders/dataset.py``.

The TPU-native contract is array-first: a task dataset must expose, per
user, *numeric fixed-width arrays* (featurization — tokenization, padding to
``max_seq_length``, image normalization — happens once at load time, not per
batch).  The engine then packs users into static-shape round batches
(:mod:`msrflute_tpu.data.batching`) with sample masks; there is no per-batch
Python in the hot loop, unlike the reference's torch DataLoader iteration
(``core/trainer.py:341-414``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class BaseDataset:
    """Abstract federated dataset.

    Subclasses populate :attr:`user_list` / :attr:`num_samples` and implement
    :meth:`user_arrays` returning a dict of numpy arrays whose leading axis is
    the user's sample count — canonically ``{'x': [n, ...], 'y': [n, ...]}``,
    plus any extra per-sample arrays the model consumes (e.g.
    ``attention_mask``).
    """

    user_list: List[str]
    num_samples: List[int]

    def __len__(self) -> int:
        return len(self.user_list)

    def user_arrays(self, user_idx: int) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    @property
    def element_spec(self) -> Dict[str, tuple]:
        """Trailing (per-sample) shapes, derived from the first user."""
        arrays = self.user_arrays(0)
        return {k: tuple(v.shape[1:]) for k, v in arrays.items()}


class ArraysDataset(BaseDataset):
    """A dataset backed by per-user numpy arrays held in memory.

    The workhorse for every built-in task: plugins featurize the raw user
    blob into arrays once, then hand them here.
    """

    def __init__(self, user_list: Sequence[str],
                 per_user: Sequence[Dict[str, np.ndarray]],
                 num_samples: Optional[Sequence[int]] = None):
        if len(user_list) != len(per_user):
            raise ValueError("user_list and per_user length mismatch")
        self.user_list = list(user_list)
        self._per_user = list(per_user)
        if num_samples is None:
            num_samples = [len(next(iter(u.values()))) for u in per_user]
        self.num_samples = [int(n) for n in num_samples]
        for i, arrays in enumerate(self._per_user):
            lens = {k: len(v) for k, v in arrays.items()}
            if any(n != self.num_samples[i] for n in lens.values()):
                raise ValueError(
                    f"user {user_list[i]}: array lengths {lens} != "
                    f"num_samples {self.num_samples[i]}")

    def user_arrays(self, user_idx: int) -> Dict[str, np.ndarray]:
        return self._per_user[user_idx]

    @classmethod
    def concat_users(cls, ds: "ArraysDataset") -> Dict[str, np.ndarray]:
        """All users' samples concatenated (for server replay / central eval)."""
        keys = ds.user_arrays(0).keys()
        return {k: np.concatenate([ds.user_arrays(i)[k] for i in range(len(ds))])
                for k in keys}


def scrub_empty_clients(dataset: BaseDataset) -> BaseDataset:
    """Drop users with zero samples (reference ``utils/utils.py:563-582``)."""
    keep = [i for i, n in enumerate(dataset.num_samples) if n > 0]
    if len(keep) == len(dataset.num_samples):
        return dataset
    if isinstance(dataset, LazyUserDataset):
        return dataset.subset(keep)  # no sample IO
    return ArraysDataset(
        [dataset.user_list[i] for i in keep],
        [dataset.user_arrays(i) for i in keep],
        [dataset.num_samples[i] for i in keep],
    )


class LazyUserDataset(BaseDataset):
    """Featurize-on-access dataset over a :class:`~msrflute_tpu.data.
    user_blob.LazyHDF5Users` handle — the "millions of clients" path
    (reference ``README.md:9``): a round touches only its sampled users,
    so sample IO and featurization happen on demand with a bounded LRU
    cache instead of materializing the whole blob up front.

    ``featurize(data_entry, label_or_None) -> {name: np.ndarray}`` runs
    per user on first access (default: the same numeric passthrough as
    :func:`msrflute_tpu.tasks.default_featurize`, per-user).
    """

    def __init__(self, users, featurize=None, cache_users: int = 256,
                 keep: Optional[Sequence[int]] = None):
        import threading
        from collections import OrderedDict
        self._users = users
        self._featurize = featurize or _numeric_featurize_user
        self._idx = (list(range(len(users.user_list))) if keep is None
                     else list(keep))
        self.user_list = [users.user_list[i] for i in self._idx]
        self.num_samples = [users.num_samples[i] for i in self._idx]
        self._cache: "OrderedDict[int, Dict[str, np.ndarray]]" = OrderedDict()
        self._cache_users = max(int(cache_users), 1)
        # the layer below serializes hdf5 reads for off-controller-thread
        # callers (personalization/eval helpers); the cache needs the same
        # discipline or a concurrent insert's eviction can race a reader's
        # membership-check -> move_to_end sequence
        self._cache_lock = threading.Lock()
        #: monotone cache counters (fleet observability): the server
        #: publishes these through the host-side devbus per drained
        #: chunk, so a fleet run's featurize-IO behavior is a rollup
        #: column instead of a guess — see :meth:`cache_stats`
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0

    def cache_stats(self) -> Dict[str, int]:
        """Hit/miss/eviction counters + live resident size, read under
        the cache lock (the structured-telemetry surface)."""
        with self._cache_lock:
            return {"hits": self.cache_hits, "misses": self.cache_misses,
                    "evictions": self.cache_evictions,
                    "resident": len(self._cache)}

    def user_arrays(self, user_idx: int) -> Dict[str, np.ndarray]:
        with self._cache_lock:
            if user_idx in self._cache:
                self.cache_hits += 1
                self._cache.move_to_end(user_idx)
                return self._cache[user_idx]
            self.cache_misses += 1
        data, label = self._users.read(self.user_list[user_idx])
        arrays = self._featurize(data, label)
        # the eager ArraysDataset validates array lengths against
        # num_samples at construction; lazy must fail as loudly, or a
        # blob whose metadata disagrees with its rows trains silently on
        # wrong effective counts
        want = self.num_samples[user_idx]
        lens = {k: len(v) for k, v in arrays.items()}
        if any(n != want for n in lens.values()):
            raise ValueError(
                f"user {self.user_list[user_idx]}: blob num_samples says "
                f"{want} but arrays have {lens} rows")
        with self._cache_lock:
            self._cache[user_idx] = arrays
            if len(self._cache) > self._cache_users:
                self._cache.popitem(last=False)
                self.cache_evictions += 1
        return arrays

    def subset(self, keep: Sequence[int]) -> "LazyUserDataset":
        """A view over a subset of users — no sample IO."""
        return LazyUserDataset(self._users, self._featurize,
                               self._cache_users,
                               keep=[self._idx[i] for i in keep])


def _numeric_featurize_user(data, label) -> Dict[str, np.ndarray]:
    """Per-user numeric passthrough — EXACTLY ``tasks.default_featurize``
    per user (x float32, y int32), so flipping ``lazy`` never changes what
    the model sees.  Dtype-preserving tricks (raw uint8 pixels) belong to
    task featurize_user hooks like the CV family's ``to_image``."""
    return ({"x": np.asarray(data, dtype=np.float32)} if label is None else
            {"x": np.asarray(data, dtype=np.float32),
             "y": np.asarray(label).astype(np.int32)})
