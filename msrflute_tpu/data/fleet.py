"""Fleet-scale population — streaming user stores + O(cohort) sampling.

FLUTE's headline scale claim is "millions of clients, sampling tens of
thousands per round" (PAPER.md §intro).  Everything here exists so that
POPULATION SIZE is a free variable: per-round host work and memory are
O(cohort) / O(cache), never O(N).

Three pieces:

- :func:`floyd_sample` / :func:`weighted_reservoir_sample` — cohort
  draws that never materialize an O(N) array.  Floyd's algorithm is
  O(k) time AND memory for the uniform draw; the weighted draw is the
  Efraimidis–Spirakis exponential-key reservoir, one streaming pass
  over the weights in bounded chunks (O(N) time is inherent to
  arbitrary weights; O(k + chunk) memory is the point).

  RNG-trail contract: the DEFAULT server path keeps
  ``np.random.Generator.choice(N, size=k, replace=False)`` — numpy's
  Generator already implements Floyd's algorithm (measured O(k):
  a 1k draw from a 10^9 population is ~0.1 ms and allocates nothing
  O(N); ``tests/test_fleet.py`` pins this), so the historical rng
  trail is preserved at fleet scale for free.  The ``fleet`` samplers
  below draw DIFFERENT trails (documented in
  ``docs/config_extensions.md``): enabling the ``fleet`` block starts
  a new sampling trail, exactly like changing the seed.  Within one
  mode, trails stay deterministic and resume-stable (the numpy
  bit-generator state rides the status-log snapshot either way).

- :class:`SyntheticFleetDataset` — a deterministic synthetic
  population of arbitrary size whose per-user metadata (``num_samples``)
  is a single vectorized draw (int32, 4 bytes/user) and whose feature
  arrays are generated per user on demand behind a bounded LRU cache.
  ``user_list`` is a lazy name sequence — 10^6 python strings would be
  ~50 MB of host RSS for names nothing reads.  This is the fleet smoke
  population: 10^6 users cost ~4 MB of host metadata.

- :func:`steps_for_array` — the vectorized ``steps_for`` over a whole
  population's ``num_samples``: the ONE streaming metadata pass that
  ``bucket_boundaries`` / ``bucket_capacities`` need at server init
  (the per-user python loop was O(N) interpreter work).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence

import numpy as np

from .dataset import BaseDataset

__all__ = [
    "floyd_sample", "weighted_reservoir_sample", "sample_cohort",
    "steps_for_array", "lane_shard_map", "LazyNameList",
    "SyntheticFleetDataset",
]


def lane_shard_map(k: int, num_shards: int) -> np.ndarray:
    """Shard index per lane of a ``[K]`` cohort grid — THE cohort→shard
    layout contract of the sharded fleet transfer plane.

    ``shard_map``/``NamedSharding`` split the client axis into
    ``num_shards`` CONTIGUOUS blocks in device order, so lane ``j``
    executes on shard ``j // (K / num_shards)``.  The carry-page
    allocator keys slot placement off this map (a lane's carry row must
    live on the shard that computes the lane — the no-cross-shard-
    collective invariant), and the multihost row store needs only the
    rows of its own hosts' lanes because of it.  One vectorized pass,
    int32; refuses a grid the mesh cannot split."""
    k, num_shards = int(k), int(num_shards)
    if num_shards <= 0 or k % num_shards:
        raise ValueError(
            f"lane_shard_map: grid of {k} lanes does not split over "
            f"{num_shards} shards — pad the cohort to a mesh multiple "
            "(pad_to_mesh / mesh-quantized bucket capacities)")
    return (np.arange(k, dtype=np.int32) // (k // num_shards)) \
        .astype(np.int32)


# ----------------------------------------------------------------------
# O(cohort) samplers
# ----------------------------------------------------------------------
def floyd_sample(rng: np.random.Generator, population: int,
                 k: int) -> list:
    """``k`` distinct uniform indices from ``range(population)`` in
    O(k) time and memory (Robert Floyd's sampling algorithm), followed
    by an O(k) shuffle so cohort ORDER is uniform too (Floyd's raw
    output is biased toward placing large indices late, and cohort
    order feeds the packing shuffle trail).

    Deterministic in the generator state; consumes exactly ``k``
    ``integers`` draws plus one length-``k`` ``shuffle``.
    """
    population = int(population)
    k = int(min(k, population))
    chosen: set = set()
    out = []
    for j in range(population - k, population):
        t = int(rng.integers(0, j + 1))
        if t in chosen:
            t = j
        chosen.add(t)
        out.append(t)
    out = np.asarray(out, dtype=np.int64)
    rng.shuffle(out)
    return [int(i) for i in out]


def weighted_reservoir_sample(rng: np.random.Generator, weights,
                              k: int, chunk: int = 65536) -> list:
    """``k`` distinct indices drawn without replacement with
    probability proportional to ``weights`` — the Efraimidis–Spirakis
    A-Res reservoir: key ``u_i^(1/w_i)`` per item, keep the top-k.

    ``weights`` is any sequence/array-like of non-negative numbers;
    it is consumed in ``chunk``-sized slices, so peak memory is
    O(k + chunk) no matter the population size.  Zero-weight users are
    never sampled.  Returns indices in descending-key order (uniform
    given the weights), as a plain int list.
    """
    k = int(k)
    if k <= 0:
        return []
    best_keys = np.empty((0,), np.float64)
    best_idx = np.empty((0,), np.int64)
    n = len(weights)
    for lo in range(0, n, int(chunk)):
        w = np.asarray(weights[lo:lo + int(chunk)], np.float64)
        u = rng.random(w.shape[0])
        with np.errstate(divide="ignore"):
            keys = np.where(w > 0, u ** (1.0 / np.maximum(w, 1e-300)),
                            -1.0)
        keys = np.where(w > 0, keys, -1.0)
        cand_keys = np.concatenate([best_keys, keys])
        cand_idx = np.concatenate(
            [best_idx, np.arange(lo, lo + w.shape[0], dtype=np.int64)])
        live = cand_keys >= 0
        cand_keys, cand_idx = cand_keys[live], cand_idx[live]
        if cand_keys.shape[0] > k:
            top = np.argpartition(cand_keys, -k)[-k:]
            cand_keys, cand_idx = cand_keys[top], cand_idx[top]
        best_keys, best_idx = cand_keys, cand_idx
    order = np.argsort(-best_keys, kind="stable")
    return [int(i) for i in best_idx[order]]


def sample_cohort(rng: np.random.Generator, population: int, k: int,
                  mode: str = "uniform",
                  num_samples=None) -> list:
    """The ``fleet`` block's cohort draw.

    ``uniform`` (the default) is numpy ``Generator.choice`` without
    replacement — already O(cohort) (Floyd's algorithm internally) AND
    trail-identical to the non-fleet server path, so plain fleet runs
    stay bit-comparable to resident runs.  ``floyd`` is this module's
    explicit Floyd implementation (useful where numpy's algorithm is
    not contractual); ``by_samples`` is the sample-count-weighted
    reservoir.  The latter two draw NEW rng trails.
    """
    k = int(min(k, population))
    if mode == "uniform":
        return list(rng.choice(int(population), size=k, replace=False))
    if mode == "floyd":
        return floyd_sample(rng, population, k)
    if mode == "by_samples":
        if num_samples is None:
            raise ValueError(
                "fleet.sampling: by_samples needs the population's "
                "num_samples metadata")
        return weighted_reservoir_sample(rng, num_samples, k)
    raise ValueError(f"unknown fleet.sampling mode {mode!r} "
                     "(uniform | floyd | by_samples)")


# ----------------------------------------------------------------------
# vectorized step-needs metadata pass
# ----------------------------------------------------------------------
def steps_for_array(num_samples, batch_size: int,
                    desired_max_samples: Optional[int] = None
                    ) -> np.ndarray:
    """Vectorized :func:`msrflute_tpu.data.batching.steps_for` over a
    whole population's ``num_samples`` — int64 throughout (no float
    detour, so no precision loss at any realistic count), one numpy
    pass instead of an O(N) python loop."""
    ns = np.asarray(num_samples, dtype=np.int64)
    if desired_max_samples is not None:
        ns = np.minimum(ns, np.int64(desired_max_samples))
    b = np.int64(max(int(batch_size), 1))
    return np.maximum(-(-ns // b), 1)


# ----------------------------------------------------------------------
# fleet-scale synthetic population
# ----------------------------------------------------------------------
class LazyNameList(Sequence):
    """``["u0", "u1", ...]`` without materializing N strings — the
    ``user_list`` of a fleet population (names are only ever read for
    log lines and per-user blob keys)."""

    def __init__(self, n: int, prefix: str = "u"):
        self._n = int(n)
        self._prefix = prefix

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        i = int(i)
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        return f"{self._prefix}{i}"


class SyntheticFleetDataset(BaseDataset):
    """Deterministic synthetic classification population of arbitrary
    size — the 10^6-user smoke workload.

    Host cost is O(cache) + one int32 metadata array:

    - ``num_samples`` is a single vectorized seeded draw (the "one
      streaming metadata pass"): 75% tiny users of ``base_samples``
      plus a heavy tail at 2/4/8x — the skew cohort bucketing exists
      for (same shape as ``tools/endurance.py``'s hetero cohort);
    - ``user_arrays(i)`` regenerates user ``i``'s features from
      ``default_rng((seed, i))`` on demand, behind a bounded LRU cache
      with hit/miss/eviction counters (the same cache-stats contract
      as :class:`~msrflute_tpu.data.dataset.LazyUserDataset`).
    """

    def __init__(self, num_users: int, input_dim: int = 8,
                 num_classes: int = 4, base_samples: int = 8,
                 seed: int = 0, cache_users: int = 256):
        n = int(num_users)
        self.input_dim = int(input_dim)
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        self.user_list = LazyNameList(n)
        # one vectorized metadata draw: int32, 4 bytes/user
        meta_rng = np.random.default_rng([self.seed, 0x1F1EE7, n])
        counts = np.full((n,), int(base_samples), np.int32)
        tail = meta_rng.integers(1, 4, size=(n + 3) // 4).astype(np.int32)
        counts[::4] = int(base_samples) * (2 ** tail)
        self.num_samples = counts
        self._cache: "OrderedDict[int, Dict[str, np.ndarray]]" = \
            OrderedDict()
        self._cache_users = max(int(cache_users), 1)
        self._cache_lock = threading.Lock()
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0

    def __len__(self) -> int:
        return len(self.user_list)

    def cache_stats(self) -> Dict[str, int]:
        """Monotone hit/miss/eviction counters plus the live resident
        size — the structured-telemetry surface the server publishes."""
        with self._cache_lock:
            return {"hits": self.cache_hits, "misses": self.cache_misses,
                    "evictions": self.cache_evictions,
                    "resident": len(self._cache)}

    def user_arrays(self, user_idx: int) -> Dict[str, np.ndarray]:
        user_idx = int(user_idx)
        with self._cache_lock:
            if user_idx in self._cache:
                self.cache_hits += 1
                self._cache.move_to_end(user_idx)
                return self._cache[user_idx]
            self.cache_misses += 1
        n = int(self.num_samples[user_idx])
        rng = np.random.default_rng([self.seed, 0xF7EE7, user_idx])
        y = rng.integers(0, self.num_classes, n).astype(np.int32)
        # class-conditioned means so the protocol actually learns
        x = (rng.normal(size=(n, self.input_dim)).astype(np.float32)
             + (y[:, None] - (self.num_classes - 1) / 2.0)
             .astype(np.float32))
        arrays = {"x": x, "y": y}
        with self._cache_lock:
            self._cache[user_idx] = arrays
            if len(self._cache) > self._cache_users:
                self._cache.popitem(last=False)
                self.cache_evictions += 1
        return arrays
