"""Non-IID dataset partitioners.

Parity target: reference ``experiments/cv/data.py`` ``DataPartitioner`` —
the balanced Dirichlet label-skew partition (``__getDirichletData__``,
``data.py:118-149``, the standard FedML/Hsu-et-al. algorithm) plus the
per-client rotation ranges the cv personalization task uses to make client
distributions *transform*-skewed as well (``return_partition``,
``data.py:39-64``: client ``j`` of ``n`` draws rotations from the 360°/n
wedge ``[-180 + j*360/n, -180 + (j+1)*360/n)``).

TPU-native difference: partitioning happens once, host-side, at data-prep
time (``tools/create_data.py``) and lands in the standard user-blob format —
the round path then stays a fixed-shape jitted program.  The reference
re-applies torchvision transforms per __getitem__; here rotations are baked
into the blob (eval uses the wedge midpoint, the deterministic analogue of
the reference's test-time fixed rotation).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


def dirichlet_partition(labels: Sequence[int], num_clients: int,
                        alpha: float, rng: np.random.Generator,
                        num_classes: Optional[int] = None,
                        max_tries: int = 1000) -> List[np.ndarray]:
    """Split sample indices into ``num_clients`` label-skewed shards.

    For every class, client shares are drawn from ``Dirichlet(alpha)``;
    clients already holding >= N/num_clients samples are excluded from
    further draws (the "balance" rule), and the whole draw repeats until
    every client has at least ``num_classes`` samples — same acceptance
    loop as the reference (``experiments/cv/data.py:124-140``), but
    bounded: the target min size caps at N/num_clients (tiny synthetic
    sets can't satisfy the class-count bar at all) and after
    ``max_tries`` draws the best-so-far partition is accepted.

    Smaller ``alpha`` -> more skew; ``alpha -> inf`` approaches IID.
    """
    labels = np.asarray(labels)
    n_total = len(labels)
    k_classes = int(num_classes if num_classes is not None
                    else labels.max() + 1)
    if num_clients <= 0:
        raise ValueError("num_clients must be positive")

    target = min(k_classes, n_total // num_clients)
    min_size, best, best_min = -1, None, -1
    for _ in range(max_tries):
        shards: List[List[int]] = [[] for _ in range(num_clients)]
        for k in range(k_classes):
            idx_k = np.flatnonzero(labels == k)
            if idx_k.size == 0:
                continue
            rng.shuffle(idx_k)
            props = rng.dirichlet(np.full(num_clients, float(alpha)))
            # balance: stop feeding clients that already hold their quota
            open_lane = np.array([len(s) < n_total / num_clients
                                  for s in shards], dtype=np.float64)
            props = props * open_lane
            total = props.sum()
            if total <= 0:  # everyone full for this class draw
                props = np.full(num_clients, 1.0 / num_clients)
            else:
                props = props / total
            cuts = (np.cumsum(props) * idx_k.size).astype(int)[:-1]
            for shard, part in zip(shards, np.split(idx_k, cuts)):
                shard.extend(part.tolist())
        min_size = min(len(s) for s in shards)
        if min_size > best_min:
            best, best_min = shards, min_size
        if min_size >= target:
            break
    shards = best

    out = []
    for shard in shards:
        arr = np.asarray(shard, dtype=np.int64)
        rng.shuffle(arr)
        out.append(arr)
    return out


def partition_label_counts(labels: Sequence[int],
                           partitions: Sequence[np.ndarray]) -> List[Dict[int, int]]:
    """Per-client class histograms (the reference's ``net_cls_counts``
    debug statistic, ``experiments/cv/data.py:142-146``)."""
    labels = np.asarray(labels)
    stats = []
    for part in partitions:
        unq, cnt = np.unique(labels[np.asarray(part, dtype=np.int64)],
                             return_counts=True)
        stats.append({int(u): int(c) for u, c in zip(unq, cnt)})
    return stats


def client_rotation_range(client: int, num_clients: int) -> tuple:
    """The 360°/n wedge of rotation angles assigned to ``client``
    (reference ``experiments/cv/data.py:50-52``)."""
    lo = -180 + 2 * int(client * 180 / num_clients)
    hi = -180 + 2 * int((client + 1) * 180 / num_clients)
    return lo, hi


def rotate_images(x: np.ndarray, angle_deg: float) -> np.ndarray:
    """Rotate a batch of HWC (or HW) images about their center.

    scipy.ndimage backs the interpolation (order-1, like torchvision's
    bilinear default); dtype and value range are preserved.
    """
    from scipy import ndimage

    x = np.asarray(x)
    out = np.empty_like(x)
    for i in range(len(x)):
        img = x[i].astype(np.float32)
        # per-image spatial dims are leading: HW or HWC -> rotate in (0, 1)
        rot = ndimage.rotate(img, angle_deg, axes=(1, 0),
                             reshape=False, order=1, mode="nearest")
        if np.issubdtype(x.dtype, np.integer):
            info = np.iinfo(x.dtype)
            rot = np.clip(np.rint(rot), info.min, info.max)
        out[i] = rot.astype(x.dtype)
    return out


def dirichlet_blob(x: np.ndarray, y: np.ndarray, num_clients: int,
                   alpha: float, rng: np.random.Generator,
                   rotate: bool = False, is_train: bool = True) -> dict:
    """Build a user-blob dict from flat arrays via Dirichlet partitioning.

    ``rotate=True`` additionally applies each client's rotation wedge
    (random angle per train sample, wedge midpoint at eval — reference
    ``experiments/cv/data.py:50-52``), producing the transform-skew the cv
    personalization benchmark relies on.
    """
    parts = dirichlet_partition(y, num_clients, alpha, rng)
    users, data, labels, counts = [], {}, {}, []
    for j, idx in enumerate(parts):
        uid = f"{j:04d}"
        xs = np.asarray(x)[idx]
        if rotate and xs.ndim >= 3:
            lo, hi = client_rotation_range(j, num_clients)
            if is_train:
                angles = rng.uniform(lo, hi, size=len(xs))
                xs = np.stack([rotate_images(xs[i:i + 1], a)[0]
                               for i, a in enumerate(angles)])
            else:
                xs = rotate_images(xs, (lo + hi) / 2.0)
        users.append(uid)
        data[uid] = {"x": xs.tolist()}
        labels[uid] = np.asarray(y)[idx].astype(int).tolist()
        counts.append(int(len(idx)))
    return {"users": users, "num_samples": counts, "user_data": data,
            "user_data_label": labels}
