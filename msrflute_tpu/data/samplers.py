"""Host-side batch-order samplers.

Parity target: reference ``utils/data_utils.py``:

- :class:`BatchSampler` (``data_utils.py:9-39``): contiguous index batches
  (keeps neighbors together so padding stays low), shuffled at the batch
  level, optional drop-last.
- :class:`DynamicBatchSampler` (``data_utils.py:42-119``): duration-sorted,
  frames-budgeted batch packing with a padding-efficiency meter.

Status in the TPU pipeline: these are the host-side *iteration* parity API
(plugin dataloaders that want the reference's sampler semantics).  The round
engine itself does not consume them — its static ``[K, S, B, L]`` grids get
the same padding-efficiency win from per-chunk bucketing instead: step
bucketing (``engine/server.py::_chunk_steps``) sizes S to the chunk, and
length bucketing (``data.batching.seq_length_bucket``) crops token grids to
the chunk's real-length power-of-two bucket — the static-shape translation
of :class:`DynamicBatchSampler`'s frames budget (measured in ``bench.py``
``varlen_bucketing``).
"""

from __future__ import annotations

import logging
import random
from typing import Callable, List, Optional, Sequence

from ..utils.logging import print_rank


class AverageMeter:
    """Ratio meter (reference ``utils.AverageMeter`` as used for padding
    efficiency)."""

    def __init__(self, name: str):
        self.name = name
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, num: float, den: float) -> None:
        self.numerator += num
        self.denominator += den

    @property
    def value(self) -> float:
        return self.numerator / max(self.denominator, 1e-12)

    def display_results(self, loglevel: int = logging.DEBUG) -> None:
        print_rank(f"{self.name}: {self.value:.4f}", loglevel=loglevel)


class BatchSampler:
    """Contiguous batches, shuffled at batch level."""

    def __init__(self, dataset_len: int, batch_size: int,
                 randomize: bool = True, drop_last: bool = False,
                 rng: Optional[random.Random] = None):
        self.randomize = randomize
        self._rng = rng or random.Random(0)
        batches = [list(range(b, min(b + batch_size, dataset_len)))
                   for b in range(0, dataset_len, batch_size)]
        if drop_last and batches and len(batches[-1]) < batch_size:
            del batches[-1]
        self.batches = batches

    def __iter__(self):
        batches = list(self.batches)
        if self.randomize:
            self._rng.shuffle(batches)
        return iter(batches)

    def __len__(self) -> int:
        return len(self.batches)


class DynamicBatchSampler:
    """Frames-budgeted batches over variable-duration samples.

    ``durations[i]`` is each sample's duration; batches are built so
    ``sum(frames) <= frames_threshold`` (frames = duration * fps), sorted by
    duration first unless ``unsorted_batch`` — exactly the reference's
    packing rule, including the padding-efficiency meter
    (batch_frames / (max_frames_in_batch * len(batch)))."""

    def __init__(self, durations: Sequence[float], frames_threshold: float,
                 max_batch_size: int = 0, unsorted_batch: bool = False,
                 fps: float = 1000 / 30,
                 rng: Optional[random.Random] = None):
        self._rng = rng or random.Random(0)
        indices = [(i, d) for i, d in enumerate(durations)]
        if not unsorted_batch:
            indices.sort(key=lambda e: e[1])

        batches: List[List[int]] = []
        batch: List[int] = []
        batch_frames = 0.0
        batch_area = 0.0  # snapshot of this batch's max_frames * size
        max_frames_in_batch = 0.0
        meter = AverageMeter("Padding Efficiency")
        for idx, duration in indices:
            if duration <= 0:
                continue
            frames = duration * fps
            fits = ((unsorted_batch and len(batch) < max_batch_size) or
                    (not unsorted_batch and
                     batch_frames + frames <= frames_threshold and
                     (max_batch_size == 0 or len(batch) < max_batch_size)))
            if fits:
                batch.append(idx)
                batch_frames += frames
                max_frames_in_batch = max(max_frames_in_batch, frames)
                # area snapshotted inside the fits branch so a later
                # overflowing item cannot contaminate this batch's max
                # (reference data_utils.py:89-94)
                batch_area = max_frames_in_batch * len(batch)
            else:
                if batch and batch_area > 0:
                    meter.add(batch_frames, batch_area)
                    batches.append(batch)
                batch = [idx]
                batch_frames = frames
                max_frames_in_batch = frames
                batch_area = frames
        if batch and batch_area > 0:
            meter.add(batch_frames, batch_area)
            batches.append(batch)
        self.batches = batches
        self.padding_efficiency = meter.value
        meter.display_results()

    def __iter__(self):
        batches = list(self.batches)
        self._rng.shuffle(batches)
        return iter(batches)

    def __len__(self) -> int:
        return len(self.batches)
