"""Static-shape round batching — the TPU replacement for torch DataLoaders.

Parity target: reference per-task ``dataloaders/dataloader.py`` + the
samplers in ``utils/data_utils.py`` (``BatchSampler`` contiguous batches,
``DynamicBatchSampler`` padding-efficiency batching) + the
``desired_max_samples`` early stop (``core/trainer.py:363-364``).

TPU-first design: a round's sampled clients become ONE array program input of
static shape ``[K, S, B, ...]`` (K clients x S local steps x B batch) with a
``[K, S, B]`` sample mask.  Ragged client sizes are absorbed by masking, not
by Python-side dynamic batching, so the whole round jits once per (K, S, B)
and never retraces.  Sample weights count only *real* samples — the mask sums
reproduce FLUTE's ``num_samples`` aggregation weights exactly
(``core/strategies/fedavg.py:61-91``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

from .dataset import BaseDataset


@dataclass
class RoundBatch:
    """One round's client data as static-shape arrays.

    arrays:       each ``[K, S, B, *feat]``
    sample_mask:  ``[K, S, B]`` — 1.0 for real samples
    num_samples:  ``[K]`` — real (capped) per-client sample counts
    client_mask:  ``[K]`` — 1.0 for real clients, 0.0 for mesh padding
    client_ids:   ``[K]`` — dataset user indices (-1 for padding)
    """

    arrays: Dict[str, np.ndarray]
    sample_mask: np.ndarray
    num_samples: np.ndarray
    client_mask: np.ndarray
    client_ids: np.ndarray
    #: fleet paging (server_config.fleet): per-lane PAGE-POOL SLOT ids
    #: for the carry gather/scatter, parallel to ``client_ids`` (-1 for
    #: padding).  None outside paged-carry mode — the engine then uses
    #: ``client_ids`` for both, which is the resident-table program.
    carry_slots: Optional[np.ndarray] = None
    #: cross-client megabatching (server_config.megabatch): the
    #: super-batch pointer tape covering this grid, attached by the
    #: server's bucket packer when the bucket's analytic gate holds.
    #: None = per-client vmap arm only.
    mega: Optional["MegaTape"] = None

    @property
    def shape(self):
        return self.sample_mask.shape


def ceil_div(n: int, d: int) -> int:
    """Integer ceiling division — the ONE spelling of the idiom that
    :func:`steps_for` and :func:`_sample_cap` both used to hand-roll
    (``math.ceil(a / b)`` truncates for large ints via the float detour;
    ``-(-a // b)`` is exact but write-only).  Property-tested at the
    ``desired_max_samples`` mid-batch boundary in
    ``tests/test_cohort_bucketing.py``."""
    return -(-int(n) // int(d))


def steps_for(max_samples: int, batch_size: int,
              desired_max_samples: Optional[int] = None) -> int:
    """Static local-step count S for a round program.

    FLUTE stops a client's epoch once ``desired_max_samples`` is reached
    (``core/trainer.py:363-364``); the static equivalent caps every client at
    ``S*B`` samples where ``S = ceil(min(max, desired)/B)``.
    """
    cap = max_samples if desired_max_samples is None else min(
        max_samples, desired_max_samples)
    return max(1, ceil_div(cap, batch_size))


def _sample_cap(S: int, B: int, desired_max_samples: Optional[int]) -> int:
    """Per-client sample cap in the reference's BATCH-granular semantics:
    its epoch loop checks the accumulated count at the TOP of each batch
    (``core/trainer.py:363-364``), so the batch that crosses
    ``desired_max_samples`` still trains in full — the effective cap is
    ``ceil(desired/B)*B``, not ``desired`` (an exact-sample cap would
    train on fewer samples than the reference whenever the cap is not a
    batch multiple; with one batch per client a cap below the batch size
    would wrongly engage at all)."""
    if desired_max_samples is None:
        return S * B
    return min(S * B, ceil_div(desired_max_samples, B) * B)


def _pad_feat(sample_count: int, shape: tuple, dtype) -> np.ndarray:
    return np.zeros((sample_count,) + shape, dtype=dtype)


def pack_round_batches(
    dataset: BaseDataset,
    client_indices: Sequence[int],
    batch_size: int,
    max_steps: int,
    rng: Optional[np.random.Generator] = None,
    shuffle: bool = True,
    pad_clients_to: Optional[int] = None,
    desired_max_samples: Optional[int] = None,
    orders: Optional[Dict[int, np.ndarray]] = None,
) -> RoundBatch:
    """Assemble ``[K, S, B, ...]`` arrays for the sampled clients.

    Per client: optionally shuffle its samples (the reference's train
    DataLoaders shuffle), truncate to ``min(S*B, desired_max_samples)``, and
    zero-pad to the static grid.  K is padded to ``pad_clients_to`` (mesh
    divisibility) with zero-weight clients — the masked equivalent of
    FLUTE's idle-node dummy syncs (``core/federated.py:251-262``).

    ``orders`` (client id -> sample permutation) overrides the in-place
    shuffle draw: cohort bucketing pre-draws every sampled client's
    permutation in COHORT order before packing per-bucket grids, so the
    rng trail — and hence every client's sample order — is identical to
    what the monolithic pack would have drawn (the cross-mode
    bit-identity anchor, ``tests/test_cohort_bucketing.py``).

    A ``-1`` entry in ``client_indices`` is an explicit PADDING HOLE:
    the row packs as all-padding (mask 0, id -1) exactly like the tail
    padding.  Megabatch grouping uses holes to shard-align rows with
    the super-batch tape's lane blocks (``plan_megabatch``).
    """
    rng = rng or np.random.default_rng(0)
    K = len(client_indices)
    K_pad = max(pad_clients_to or K, K)
    S, B = max_steps, batch_size
    spec = dataset.element_spec

    # an EMPTY client list still packs a valid all-padding grid (a
    # bucketed round dispatches every bucket at its static capacity,
    # occupied or not) — dtypes come from the first real user (or 0)
    first_real = next((int(ci) for ci in client_indices if int(ci) >= 0), 0)
    ref = dataset.user_arrays(first_real)
    arrays = {k: np.zeros((K_pad, S, B) + shape, dtype=ref[k].dtype)
              for k, shape in spec.items()}
    sample_mask = np.zeros((K_pad, S, B), dtype=np.float32)
    num_samples = np.zeros((K_pad,), dtype=np.float32)
    client_mask = np.zeros((K_pad,), dtype=np.float32)
    client_ids = np.full((K_pad,), -1, dtype=np.int32)

    cap = _sample_cap(S, B, desired_max_samples)
    users, takes = [], []
    for j, ci in enumerate(client_indices):
        if int(ci) < 0:
            # hole row: keep users/takes aligned with grid row j so the
            # parallel gather below writes nothing into it
            users.append({k: np.zeros((0,) + shape, dtype=ref[k].dtype)
                          for k, shape in spec.items()})
            takes.append(np.zeros((0,), dtype=np.int64))
            continue
        user = dataset.user_arrays(ci)
        n = len(next(iter(user.values())))
        if orders is not None:
            order = orders[ci]
        else:
            order = rng.permutation(n) if shuffle else np.arange(n)
        take = order[:cap]
        users.append(user)
        takes.append(take)
        t = len(take)
        sample_mask[j].reshape(-1)[:t] = 1.0
        num_samples[j] = t
        client_mask[j] = 1.0
        client_ids[j] = ci

    # row gather: the native packer memcpy's all clients in parallel (the
    # runtime analogue of the reference's DataLoader worker collation);
    # numpy fallback is identical, just single-threaded
    from ..native import gather_rows
    for k, shape in spec.items():
        if not users:
            break
        dst = arrays[k].reshape((K_pad, S * B) + shape)
        srcs = [np.asarray(u[k]) for u in users]
        if not gather_rows(dst, srcs, takes):
            for j, (src, take) in enumerate(zip(srcs, takes)):
                dst[j, :len(take)] = src[take]
    return RoundBatch(arrays, sample_mask, num_samples, client_mask, client_ids)


@dataclass
class IndexRoundBatch:
    """One round's client data as POOL INDICES instead of gathered rows
    (the device-resident dataset mode).

    ``indices``: ``[K, S, B]`` int32 rows into the flat sample pool built
    by :func:`build_sample_pool` (0 for padding slots — masked anyway).
    The mask/count fields match :class:`RoundBatch`; there is deliberately
    NO ``arrays`` field — feature rows exist only on-device, and the one
    consumer is ``RoundEngine._stage_arrays`` (pool mode).
    """

    indices: np.ndarray
    sample_mask: np.ndarray
    num_samples: np.ndarray
    client_mask: np.ndarray
    client_ids: np.ndarray
    #: see :class:`RoundBatch.carry_slots`
    carry_slots: Optional[np.ndarray] = None
    #: see :class:`RoundBatch.mega`
    mega: Optional["MegaTape"] = None

    @property
    def shape(self):
        return self.sample_mask.shape


def build_sample_pool(dataset: BaseDataset):
    """Concatenate every user's samples into flat per-key arrays.

    Returns ``(pool, offsets)``: ``pool[k]`` is ``[total_samples, *feat]``
    in user order (dtype preserved — uint8 pixels stay uint8 so the
    one-time upload is as small as the dataset), ``offsets`` is ``[N+1]``
    int64 with user ``i``'s rows at ``offsets[i]:offsets[i+1]``.

    This is the TPU-native dataloader endgame: upload the pool to HBM
    ONCE, then each round ships only ``[K, S, B]`` int32 indices and the
    round program gathers on-device — no per-round host packing of
    feature bytes, no per-round host->device feature transfer (which
    rides a network tunnel on remote-attached chips).  Requires the
    dataset to fit in host memory to build and in HBM to use; the
    federated benchmarks (SURVEY §2.8) all fit with room to spare.
    """
    spec = dataset.element_spec
    n_users = len(dataset)
    counts = [int(dataset.num_samples[i]) for i in range(n_users)]
    offsets = np.zeros((n_users + 1,), np.int64)
    np.cumsum(counts, out=offsets[1:])
    total = int(offsets[-1])
    first = dataset.user_arrays(0)
    pool = {k: np.empty((total,) + shape, dtype=np.asarray(first[k]).dtype)
            for k, shape in spec.items()}
    for i in range(n_users):
        user = dataset.user_arrays(i)
        lo, hi = int(offsets[i]), int(offsets[i + 1])
        for k in pool:
            pool[k][lo:hi] = np.asarray(user[k])
    return pool, offsets


def pack_round_indices(
    dataset: BaseDataset,
    offsets: np.ndarray,
    client_indices: Sequence[int],
    batch_size: int,
    max_steps: int,
    rng: Optional[np.random.Generator] = None,
    shuffle: bool = True,
    pad_clients_to: Optional[int] = None,
    desired_max_samples: Optional[int] = None,
    orders: Optional[Dict[int, np.ndarray]] = None,
) -> IndexRoundBatch:
    """:func:`pack_round_batches` with the row gather deferred to the
    device: identical sampling/shuffle/cap/mask semantics (same rng
    consumption, so a pool-mode round is bit-comparable to a host-packed
    one), but the output is ``[K, S, B]`` int32 indices into the
    :func:`build_sample_pool` flat pool instead of gathered feature rows.
    ``orders`` and ``-1`` padding holes as in :func:`pack_round_batches`.
    """
    rng = rng or np.random.default_rng(0)
    K = len(client_indices)
    K_pad = max(pad_clients_to or K, K)
    S, B = max_steps, batch_size

    indices = np.zeros((K_pad, S, B), dtype=np.int32)
    sample_mask = np.zeros((K_pad, S, B), dtype=np.float32)
    num_samples = np.zeros((K_pad,), dtype=np.float32)
    client_mask = np.zeros((K_pad,), dtype=np.float32)
    client_ids = np.full((K_pad,), -1, dtype=np.int32)

    cap = _sample_cap(S, B, desired_max_samples)
    for j, ci in enumerate(client_indices):
        if int(ci) < 0:
            continue
        n = int(dataset.num_samples[ci])
        if orders is not None:
            order = orders[ci]
        else:
            order = rng.permutation(n) if shuffle else np.arange(n)
        take = order[:cap]
        t = len(take)
        indices[j].reshape(-1)[:t] = offsets[ci] + take
        sample_mask[j].reshape(-1)[:t] = 1.0
        num_samples[j] = t
        client_mask[j] = 1.0
        client_ids[j] = ci
    return IndexRoundBatch(indices, sample_mask, num_samples, client_mask,
                           client_ids)


def pack_eval_batches(
    dataset: BaseDataset,
    batch_size: int,
    pad_steps_to_multiple_of: int = 1,
    user_indices: Optional[Sequence[int]] = None,
) -> Dict[str, np.ndarray]:
    """Flatten eval users into ``[T, B, ...]`` batches with a mask.

    The reference chunks eval users ~evenly across workers
    (``core/evaluation.py:185-216``) and weights metrics by batch size
    (``core/evaluation.py:160-183``); here all samples go into one padded
    grid sharded over devices, and per-sample masking makes the weighted
    average exact.  Also returns ``user_idx`` ``[T, B]`` so personalization
    / per-user metrics can segment by user.
    """
    idxs = list(user_indices) if user_indices is not None else list(range(len(dataset)))
    spec = dataset.element_spec
    total = sum(int(dataset.num_samples[i]) for i in idxs)
    T = max(1, math.ceil(total / batch_size))
    if T % pad_steps_to_multiple_of:
        T += pad_steps_to_multiple_of - (T % pad_steps_to_multiple_of)
    B = batch_size

    first = dataset.user_arrays(idxs[0]) if idxs else {}
    out = {k: np.zeros((T * B,) + shape, dtype=first[k].dtype)
           for k, shape in spec.items()}
    mask = np.zeros((T * B,), dtype=np.float32)
    user_idx = np.full((T * B,), -1, dtype=np.int32)

    pos = 0
    for i in idxs:
        user = dataset.user_arrays(i)
        n = len(next(iter(user.values())))
        for k, arr in user.items():
            out[k][pos:pos + n] = arr
        mask[pos:pos + n] = 1.0
        user_idx[pos:pos + n] = i
        pos += n

    batched = {k: v.reshape((T, B) + v.shape[1:]) for k, v in out.items()}
    batched["sample_mask"] = mask.reshape(T, B)
    batched["user_idx"] = user_idx.reshape(T, B)
    return batched


# ----------------------------------------------------------------------
# cohort shape-bucketing (server_config.cohort_bucketing): the step-count
# analogue of seq_length_bucket.  One monolithic [K, S, B, ...] grid pads
# every client to the slowest one's step count; partitioning the cohort
# into a small set of power-of-two step buckets builds one COMPACT grid
# per bucket instead, so small clients stop burning masked FLOPs on a
# big client's steps.  Everything here is host-side numpy over counts —
# the device half (per-bucket collect + on-device combine) lives in
# engine/round.py.
# ----------------------------------------------------------------------
def pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (min 1) — the shape quantizer that
    keeps the compiled-variant set logarithmic, same discipline as
    :func:`seq_length_bucket`'s length buckets."""
    return 1 << max(int(n) - 1, 0).bit_length()


def bucket_boundaries(needs: Sequence[int], max_buckets: int,
                      max_steps: int) -> list:
    """Derive the step-bucket boundary set from the POPULATION's
    per-client step needs: the distinct power-of-two ceilings (capped at
    ``max_steps``), greedily merged down to ``max_buckets`` by the
    smallest added padded-step cost.

    The result is strictly increasing and always ends at
    ``pow2_ceil(max need)`` (clamped to ``max_steps``), so every client
    fits some bucket — a client's grid S must be >= its need or its
    data would silently truncate.  Deterministic in the needs multiset.
    """
    if max_buckets < 1:
        raise ValueError("cohort_bucketing.max_buckets must be >= 1")
    # vectorized pow2-ceil histogram (fleet scale: a 10^6-entry needs
    # array is one numpy pass, not 10^6 interpreter iterations) —
    # searchsorted against the exact power table, no float log2 detour
    arr = np.maximum(np.asarray(needs, dtype=np.int64), 1)
    pow_table = np.int64(1) << np.arange(63, dtype=np.int64)
    ceils = np.minimum(pow_table[np.searchsorted(pow_table, arr)],
                       np.int64(max_steps))
    uniq, counts = np.unique(ceils, return_counts=True)
    pops: dict = {int(s): int(c) for s, c in zip(uniq, counts)}
    bounds = sorted(pops)
    # greedy merge: absorbing bucket b into the next-larger one costs its
    # population x the extra padded steps; drop the cheapest until bounded
    while len(bounds) > max_buckets:
        costs = [(pops[bounds[i]] * (bounds[i + 1] - bounds[i]), i)
                 for i in range(len(bounds) - 1)]
        _, i = min(costs)
        pops[bounds[i + 1]] += pops.pop(bounds[i])
        del bounds[i]
    return bounds


def assign_step_buckets(needs: Sequence[int],
                        boundaries: Sequence[int],
                        capacities: Optional[Sequence[int]] = None
                        ) -> "Dict[int, list]":
    """Deterministic bucket assignment for one round's cohort.

    ``needs[j]``: sampled client j's step need (``steps_for``);
    ``boundaries``: strictly increasing bucket S values whose last entry
    covers every need.  Each client goes to the SMALLEST bucket whose S
    covers it — a pure function of (needs, boundaries, capacities),
    independent of rng or host loop arrangement, so serial/pipelined/
    resumed runs bucket identically.

    Without ``capacities``: returns only occupied buckets.  With
    ``capacities`` (one per boundary): every bucket appears (possibly
    empty — the STATIC-shape contract: every bucket grid dispatches
    every round at its fixed capacity, so the compiled shape set is
    closed by construction), and a bucket at capacity spills its
    overflow UP to the next larger bucket — a larger S is always
    mathematically correct (masked padding steps are no-ops), it only
    wastes steps.  The TOP bucket ignores its capacity; the caller
    enlarges its grid for the (rare, sentinel-visible) overflow round.

    Returns ``{S: [cohort positions]}``, positions in cohort order,
    keys ascending.
    """
    bounds = list(boundaries)
    if any(b <= a for a, b in zip(bounds, bounds[1:])):
        raise ValueError(
            f"bucket boundaries must be strictly increasing, got {bounds}")
    # vectorized first-fit-with-spill (fleet scale: 10^6-entry cohorts
    # must assign in one numpy pass per bucket, not a python scan per
    # client).  Semantics are EXACTLY the sequential first-fit's:
    # bucket i holds the first cap_i cohort-order clients whose need
    # fits and who weren't placed lower — proved by induction on i and
    # pinned against the brute loop in tests/test_fleet.py.
    arr = np.maximum(np.asarray(needs, dtype=np.int64), 1)
    b_arr = np.asarray(bounds, dtype=np.int64)
    if arr.size and int(arr.max()) > int(b_arr[-1]):
        bad = int(arr.max())
        raise ValueError(
            f"client step need {bad} exceeds the largest bucket "
            f"boundary {bounds[-1]} — boundaries must cover max_steps")
    first_fit = np.searchsorted(b_arr, arr)  # smallest covering bucket
    out: Dict[int, list] = ({s: [] for s in bounds}
                            if capacities is not None else {})
    placed = np.zeros(arr.shape, dtype=bool)
    for i, s in enumerate(bounds):
        elig = np.flatnonzero((first_fit <= i) & ~placed)
        if capacities is not None and i < len(bounds) - 1:
            elig = elig[:int(capacities[i])]  # overflow spills UP
        if elig.size:
            out.setdefault(s, []).extend(int(j) for j in elig)
            placed[elig] = True
    return {s: out[s] for s in sorted(out)}


def bucket_capacities(needs: Sequence[int], boundaries: Sequence[int],
                      cohort_size: int, quantum: int = 1,
                      slack: float = 1.5) -> list:
    """Static per-bucket client capacities from the POPULATION mix.

    For each boundary: the expected bucket occupancy of a
    ``cohort_size`` sample (population fraction x cohort) with
    ``slack`` headroom for sampling variance, clamped to the cohort
    size and the bucket's population (without-replacement sampling can
    never exceed either), rounded up to ``quantum`` (mesh
    divisibility).  Computed ONCE at server init — capacities are what
    make every bucket grid's ``[K_b, S_b, B]`` shape static across
    rounds, so the run compiles exactly one collect program per bucket
    and zero post-warmup recompiles (overflow spills up; top-bucket
    overflow is the one sentinel-visible exception — ITS enlarged grid
    is pow2-quantized so even pathological overflow stays logarithmic
    in compiled variants)."""
    bounds = list(boundaries)
    # vectorized smallest-covering-bucket histogram (fleet scale): one
    # searchsorted over the population instead of a per-client scan
    arr = np.maximum(np.asarray(needs, dtype=np.int64), 1)
    b_arr = np.asarray(bounds, dtype=np.int64)
    fit = np.searchsorted(b_arr, arr)
    fit = fit[fit < len(bounds)]  # needs beyond the top bucket: uncounted
    hist = np.bincount(fit, minlength=len(bounds))
    counts = {s: int(hist[i]) for i, s in enumerate(bounds)}
    total = max(sum(counts.values()), 1)
    caps = []
    for s in bounds:
        pop_b = counts[s]
        want = ceil_div(int(math.ceil(slack * cohort_size * pop_b)), total) \
            if pop_b else 1
        cap = max(min(want, int(cohort_size), max(pop_b, 1)), 1)
        caps.append(ceil_div(cap, quantum) * quantum)
    return caps


# ----------------------------------------------------------------------
# cross-client megabatching (server_config.megabatch): within one step
# bucket, most clients need far fewer than S_b steps and a capacity-
# padded grid burns whole client rows — the super-batch tape re-reads
# the SAME [K_b, S_b, B, ...] grid through a [lanes, depth] pointer
# tape instead: each lane concatenates many small clients' step
# sequences back to back (segment ids mark the boundaries), so one
# scan step trains `lanes` different clients' batches at once and idle
# tape slots — not empty client rows — are the only padding.  Host
# side: pure numpy first-fit planning over step needs; the device half
# (the segment-carrying lane scan) lives in engine/client_update.py.
# ----------------------------------------------------------------------
@dataclass
class MegaTape:
    """Super-batch pointer tape for ONE bucket grid.

    ptr: ``[lanes, depth]`` int32 — flat SHARD-LOCAL grid step index
         ``row * S + step`` each tape slot trains on (0 for idle slots);
    seg: ``[lanes, depth]`` int32 — shard-local grid row (segment id /
         output slot) owning the slot, -1 for idle padding.

    A client occupies ``num_epochs * need`` CONSECUTIVE slots of one
    lane (pointers repeat per epoch — no feature duplication), entirely
    inside its mesh shard's lane block, so the engine's lane scan can
    reset params/optimizer/rng at segment starts and harvest at ends
    with shard-local gathers only.
    """

    ptr: np.ndarray
    seg: np.ndarray
    lanes: int
    depth: int
    shards: int
    #: real (non-idle) tape slots — numerator feed for the
    #: megabatch_utilization meter
    entries: int


def megabatch_lanes(needs: Sequence[int], boundaries: Sequence[int],
                    cohort_size: int, num_epochs: int,
                    quantum: int = 1, slack: float = 1.25,
                    lanes: Optional[int] = None,
                    caps: Optional[Sequence[int]] = None) -> list:
    """Static per-bucket lane counts from the POPULATION mix (the
    megabatch analogue of :func:`bucket_capacities`): expected tape
    entries of a ``cohort_size`` draw landing in each bucket, with
    ``slack`` headroom, divided by the bucket's tape depth
    (``num_epochs * S_b``), rounded up to ``quantum`` (mesh
    divisibility).  An explicit ``lanes`` overrides every bucket.
    ``caps`` (the bucket client capacities) clamps from above —
    ``lanes == K_b`` is the break-even where the tape holds as many
    padded slots as the per-client grid it replaces."""
    bounds = list(boundaries)
    E = max(int(num_epochs), 1)
    quantum = max(int(quantum), 1)
    if lanes is not None:
        out = [ceil_div(int(lanes), quantum) * quantum for _ in bounds]
    else:
        arr = np.maximum(np.asarray(needs, dtype=np.int64), 1)
        b_arr = np.asarray(bounds, dtype=np.int64)
        fit = np.searchsorted(b_arr, arr)
        keep = fit < len(bounds)
        fit_k, arr_k = fit[keep], arr[keep]
        total = max(int(keep.sum()), 1)
        out = []
        for i, s in enumerate(bounds):
            need_sum = float(arr_k[fit_k == i].sum())
            # expected entries = pop fraction x cohort x mean need x E
            exp_entries = slack * cohort_size * need_sum * E / total
            want = max(int(math.ceil(exp_entries / float(E * int(s)))), 1)
            out.append(ceil_div(want, quantum) * quantum)
    if caps is not None:
        out = [min(l, ceil_div(int(c), quantum) * quantum)
               for l, c in zip(out, caps)]
    return [max(l, quantum) for l in out]


def plan_megabatch(needs: Sequence[int], num_epochs: int, lanes: int,
                   step_grid: int, shards: int, capacity: int) -> list:
    """First-fit super-batch planning for one bucket's cohort.

    ``needs[j]``: step need of the bucket's j-th client (cohort order);
    the tape depth is ``num_epochs * step_grid``.  Returns a list of
    ``(rows, tape)`` groups: ``rows`` is a length-``capacity`` list of
    cohort positions with ``-1`` padding holes (feed it through the
    hole-aware packers), ``tape`` the matching :class:`MegaTape`.

    Shard locality: grid row block ``[m*K/M, (m+1)*K/M)`` and lane
    block ``[m*L/M, (m+1)*L/M)`` belong to mesh shard ``m``; a client's
    slots land in the same shard as its grid row, so the engine's
    shard_map lane scan never gathers across shards.  A cohort that
    exceeds one group's rows or lane capacity spills into EXTRA GROUPS
    OF THE SAME SHAPE — the compiled-variant set stays one program per
    bucket, same discipline as top-bucket overflow.  Deterministic in
    (needs, geometry)."""
    M = max(int(shards), 1)
    L, S, E = int(lanes), int(step_grid), max(int(num_epochs), 1)
    cap = int(capacity)
    if L % M or cap % M:
        raise ValueError(
            f"megabatch geometry must be mesh-divisible: lanes={L}, "
            f"capacity={cap}, shards={M}")
    depth = E * S
    L_loc, K_loc = L // M, cap // M
    groups: list = []

    def _new_group():
        groups.append({
            "rows": [[] for _ in range(M)],          # per-shard positions
            "fill": np.zeros((L,), dtype=np.int64),  # per-lane used depth
            "ptr": np.zeros((L, depth), dtype=np.int32),
            "seg": np.full((L, depth), -1, dtype=np.int32),
            "entries": 0,
        })

    for pos, need in enumerate(needs):
        e = E * max(int(need), 1)
        if e > depth:
            raise ValueError(
                f"megabatch: client step need {need} exceeds the bucket "
                f"grid S={S} — bucket assignment must cover every need")
        placed = False
        for g in groups:
            for m in range(M):
                if len(g["rows"][m]) >= K_loc:
                    continue
                lanes_m = range(m * L_loc, (m + 1) * L_loc)
                lane = next((l for l in lanes_m
                             if int(g["fill"][l]) + e <= depth), None)
                if lane is None:
                    continue
                r = len(g["rows"][m])      # shard-local grid row
                o = int(g["fill"][lane])
                j = np.arange(e)
                g["ptr"][lane, o:o + e] = r * S + (j % max(int(need), 1))
                g["seg"][lane, o:o + e] = r
                g["fill"][lane] += e
                g["rows"][m].append(pos)
                g["entries"] += e
                placed = True
                break
            if placed:
                break
        if not placed:
            _new_group()
            g = groups[-1]
            m = 0
            lane = 0
            g["ptr"][lane, :e] = 0 * S + (np.arange(e) % max(int(need), 1))
            g["seg"][lane, :e] = 0
            g["fill"][lane] = e
            g["rows"][m].append(pos)
            g["entries"] = e

    if not groups:
        _new_group()
    out = []
    for g in groups:
        rows: list = []
        for m in range(M):
            block = list(g["rows"][m])
            rows.extend(block + [-1] * (K_loc - len(block)))
        out.append((rows, MegaTape(g["ptr"], g["seg"], L, depth, M,
                                   int(g["entries"]))))
    return out


def megabatch_slots(tapes: Sequence[MegaTape], batch_size: int) -> int:
    """Total super-batch sample slots (``lanes * depth * B`` summed) —
    the denominator of the megabatch_utilization meter."""
    return sum(int(t.lanes) * int(t.depth) * int(batch_size)
               for t in tapes)


def grid_slots(batches: Sequence) -> int:
    """Total padded sample slots of a chunk's grids (``K*S*B`` summed) —
    the denominator of the padding-efficiency meter."""
    total = 0
    for b in batches:
        k, s, bs = b.sample_mask.shape
        total += int(k) * int(s) * int(bs)
    return total


def padding_efficiency(batches: Sequence) -> float:
    """Real samples / padded grid slots of a chunk (1.0 = zero waste).
    The scorecard/bench meter the cohort-bucketing win is gated on —
    counts REAL (capped) samples from ``num_samples``, same convention
    as the aggregation weights."""
    slots = grid_slots(batches)
    real = sum(float(np.sum(b.num_samples)) for b in batches)
    return real / slots if slots else 0.0


def seq_length_bucket(batches: Sequence[RoundBatch],
                      seq_keys: Sequence[str],
                      min_len: int = 8) -> Optional[dict]:
    """Crop token-sequence grids to the power-of-two bucket of the chunk's
    real max length (the static-shape answer to the reference's
    ``DynamicBatchSampler`` padding-efficiency packing,
    ``utils/data_utils.py:42-119``).

    ``seq_keys`` name 0-padded ``[K, S, B, L]`` int arrays (the task's
    ``seq_pad_keys``).  All batches of a fused chunk are cropped to one
    common bucket so the chunk still compiles as a single program; cropping
    only removes all-zero tail columns, and the in-model position mask is
    derived from the ids themselves, so the math is identical — XLA just
    stops running matmuls over padding.  Buckets are powers of two (floored
    at ``min_len``), so the number of distinct compiled programs stays
    logarithmic in max L.

    Returns a stats dict (tokens_real / tokens_grid_before/after, bucket,
    ``cropped``) when the grids hold sequence keys, else None.
    """
    keys = [k for k in seq_keys if batches and k in batches[0].arrays]
    if not keys:
        return None
    L = max(b.arrays[k].shape[-1] for b in batches for k in keys)
    # the padding-efficiency meter counts each real token ONCE, from a
    # single canonical key — tok_mask when present (it marks real
    # positions even where x holds id 0), else the first seq key; summing
    # over all keys would triple-count and the keys legitimately disagree
    canon = "tok_mask" if "tok_mask" in keys else keys[0]
    # max real length across the chunk: position of the last nonzero
    # column over ALL keys (the crop must cover every key's extent)
    need = 1
    tokens_real = 0
    for b in batches:
        for k in keys:
            arr = b.arrays[k]
            nz = arr.reshape(-1, arr.shape[-1]) != 0
            if k == canon:
                tokens_real += int(nz.sum())
            cols = nz.any(axis=0)
            if cols.any():
                need = max(need, int(np.max(np.nonzero(cols)[0])) + 1)
    bucket = max(min_len, 1 << max(need - 1, 0).bit_length())
    stats = {
        "bucket": int(min(bucket, L)),
        "full_len": int(L),
        "tokens_real": int(tokens_real),
        "tokens_grid_before": int(sum(
            b.arrays[canon].reshape(-1, b.arrays[canon].shape[-1]).shape[0]
            * L for b in batches)),
    }
    stats["cropped"] = bucket < L
    if bucket < L:
        for b in batches:
            for k in keys:
                b.arrays[k] = np.ascontiguousarray(b.arrays[k][..., :bucket])
    stats["tokens_grid_after"] = int(sum(
        b.arrays[canon].reshape(-1, b.arrays[canon].shape[-1]).shape[0]
        * b.arrays[canon].shape[-1] for b in batches))
    return stats
