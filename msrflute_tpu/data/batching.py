"""Static-shape round batching — the TPU replacement for torch DataLoaders.

Parity target: reference per-task ``dataloaders/dataloader.py`` + the
samplers in ``utils/data_utils.py`` (``BatchSampler`` contiguous batches,
``DynamicBatchSampler`` padding-efficiency batching) + the
``desired_max_samples`` early stop (``core/trainer.py:363-364``).

TPU-first design: a round's sampled clients become ONE array program input of
static shape ``[K, S, B, ...]`` (K clients x S local steps x B batch) with a
``[K, S, B]`` sample mask.  Ragged client sizes are absorbed by masking, not
by Python-side dynamic batching, so the whole round jits once per (K, S, B)
and never retraces.  Sample weights count only *real* samples — the mask sums
reproduce FLUTE's ``num_samples`` aggregation weights exactly
(``core/strategies/fedavg.py:61-91``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

from .dataset import BaseDataset


@dataclass
class RoundBatch:
    """One round's client data as static-shape arrays.

    arrays:       each ``[K, S, B, *feat]``
    sample_mask:  ``[K, S, B]`` — 1.0 for real samples
    num_samples:  ``[K]`` — real (capped) per-client sample counts
    client_mask:  ``[K]`` — 1.0 for real clients, 0.0 for mesh padding
    client_ids:   ``[K]`` — dataset user indices (-1 for padding)
    """

    arrays: Dict[str, np.ndarray]
    sample_mask: np.ndarray
    num_samples: np.ndarray
    client_mask: np.ndarray
    client_ids: np.ndarray

    @property
    def shape(self):
        return self.sample_mask.shape


def steps_for(max_samples: int, batch_size: int,
              desired_max_samples: Optional[int] = None) -> int:
    """Static local-step count S for a round program.

    FLUTE stops a client's epoch once ``desired_max_samples`` is reached
    (``core/trainer.py:363-364``); the static equivalent caps every client at
    ``S*B`` samples where ``S = ceil(min(max, desired)/B)``.
    """
    cap = max_samples if desired_max_samples is None else min(
        max_samples, desired_max_samples)
    return max(1, math.ceil(cap / batch_size))


def _sample_cap(S: int, B: int, desired_max_samples: Optional[int]) -> int:
    """Per-client sample cap in the reference's BATCH-granular semantics:
    its epoch loop checks the accumulated count at the TOP of each batch
    (``core/trainer.py:363-364``), so the batch that crosses
    ``desired_max_samples`` still trains in full — the effective cap is
    ``ceil(desired/B)*B``, not ``desired`` (an exact-sample cap would
    train on fewer samples than the reference whenever the cap is not a
    batch multiple; with one batch per client a cap below the batch size
    would wrongly engage at all)."""
    if desired_max_samples is None:
        return S * B
    return min(S * B, -(-int(desired_max_samples) // B) * B)


def _pad_feat(sample_count: int, shape: tuple, dtype) -> np.ndarray:
    return np.zeros((sample_count,) + shape, dtype=dtype)


def pack_round_batches(
    dataset: BaseDataset,
    client_indices: Sequence[int],
    batch_size: int,
    max_steps: int,
    rng: Optional[np.random.Generator] = None,
    shuffle: bool = True,
    pad_clients_to: Optional[int] = None,
    desired_max_samples: Optional[int] = None,
) -> RoundBatch:
    """Assemble ``[K, S, B, ...]`` arrays for the sampled clients.

    Per client: optionally shuffle its samples (the reference's train
    DataLoaders shuffle), truncate to ``min(S*B, desired_max_samples)``, and
    zero-pad to the static grid.  K is padded to ``pad_clients_to`` (mesh
    divisibility) with zero-weight clients — the masked equivalent of
    FLUTE's idle-node dummy syncs (``core/federated.py:251-262``).
    """
    rng = rng or np.random.default_rng(0)
    K = len(client_indices)
    K_pad = max(pad_clients_to or K, K)
    S, B = max_steps, batch_size
    spec = dataset.element_spec

    arrays = {k: np.zeros((K_pad, S, B) + shape,
                          dtype=dataset.user_arrays(client_indices[0])[k].dtype)
              for k, shape in spec.items()}
    sample_mask = np.zeros((K_pad, S, B), dtype=np.float32)
    num_samples = np.zeros((K_pad,), dtype=np.float32)
    client_mask = np.zeros((K_pad,), dtype=np.float32)
    client_ids = np.full((K_pad,), -1, dtype=np.int32)

    cap = _sample_cap(S, B, desired_max_samples)
    users, takes = [], []
    for j, ci in enumerate(client_indices):
        user = dataset.user_arrays(ci)
        n = len(next(iter(user.values())))
        order = rng.permutation(n) if shuffle else np.arange(n)
        take = order[:cap]
        users.append(user)
        takes.append(take)
        t = len(take)
        sample_mask[j].reshape(-1)[:t] = 1.0
        num_samples[j] = t
        client_mask[j] = 1.0
        client_ids[j] = ci

    # row gather: the native packer memcpy's all clients in parallel (the
    # runtime analogue of the reference's DataLoader worker collation);
    # numpy fallback is identical, just single-threaded
    from ..native import gather_rows
    for k, shape in spec.items():
        dst = arrays[k].reshape((K_pad, S * B) + shape)
        srcs = [np.asarray(u[k]) for u in users]
        if not gather_rows(dst, srcs, takes):
            for j, (src, take) in enumerate(zip(srcs, takes)):
                dst[j, :len(take)] = src[take]
    return RoundBatch(arrays, sample_mask, num_samples, client_mask, client_ids)


@dataclass
class IndexRoundBatch:
    """One round's client data as POOL INDICES instead of gathered rows
    (the device-resident dataset mode).

    ``indices``: ``[K, S, B]`` int32 rows into the flat sample pool built
    by :func:`build_sample_pool` (0 for padding slots — masked anyway).
    The mask/count fields match :class:`RoundBatch`; there is deliberately
    NO ``arrays`` field — feature rows exist only on-device, and the one
    consumer is ``RoundEngine._stage_arrays`` (pool mode).
    """

    indices: np.ndarray
    sample_mask: np.ndarray
    num_samples: np.ndarray
    client_mask: np.ndarray
    client_ids: np.ndarray

    @property
    def shape(self):
        return self.sample_mask.shape


def build_sample_pool(dataset: BaseDataset):
    """Concatenate every user's samples into flat per-key arrays.

    Returns ``(pool, offsets)``: ``pool[k]`` is ``[total_samples, *feat]``
    in user order (dtype preserved — uint8 pixels stay uint8 so the
    one-time upload is as small as the dataset), ``offsets`` is ``[N+1]``
    int64 with user ``i``'s rows at ``offsets[i]:offsets[i+1]``.

    This is the TPU-native dataloader endgame: upload the pool to HBM
    ONCE, then each round ships only ``[K, S, B]`` int32 indices and the
    round program gathers on-device — no per-round host packing of
    feature bytes, no per-round host->device feature transfer (which
    rides a network tunnel on remote-attached chips).  Requires the
    dataset to fit in host memory to build and in HBM to use; the
    federated benchmarks (SURVEY §2.8) all fit with room to spare.
    """
    spec = dataset.element_spec
    n_users = len(dataset)
    counts = [int(dataset.num_samples[i]) for i in range(n_users)]
    offsets = np.zeros((n_users + 1,), np.int64)
    np.cumsum(counts, out=offsets[1:])
    total = int(offsets[-1])
    first = dataset.user_arrays(0)
    pool = {k: np.empty((total,) + shape, dtype=np.asarray(first[k]).dtype)
            for k, shape in spec.items()}
    for i in range(n_users):
        user = dataset.user_arrays(i)
        lo, hi = int(offsets[i]), int(offsets[i + 1])
        for k in pool:
            pool[k][lo:hi] = np.asarray(user[k])
    return pool, offsets


def pack_round_indices(
    dataset: BaseDataset,
    offsets: np.ndarray,
    client_indices: Sequence[int],
    batch_size: int,
    max_steps: int,
    rng: Optional[np.random.Generator] = None,
    shuffle: bool = True,
    pad_clients_to: Optional[int] = None,
    desired_max_samples: Optional[int] = None,
) -> IndexRoundBatch:
    """:func:`pack_round_batches` with the row gather deferred to the
    device: identical sampling/shuffle/cap/mask semantics (same rng
    consumption, so a pool-mode round is bit-comparable to a host-packed
    one), but the output is ``[K, S, B]`` int32 indices into the
    :func:`build_sample_pool` flat pool instead of gathered feature rows.
    """
    rng = rng or np.random.default_rng(0)
    K = len(client_indices)
    K_pad = max(pad_clients_to or K, K)
    S, B = max_steps, batch_size

    indices = np.zeros((K_pad, S, B), dtype=np.int32)
    sample_mask = np.zeros((K_pad, S, B), dtype=np.float32)
    num_samples = np.zeros((K_pad,), dtype=np.float32)
    client_mask = np.zeros((K_pad,), dtype=np.float32)
    client_ids = np.full((K_pad,), -1, dtype=np.int32)

    cap = _sample_cap(S, B, desired_max_samples)
    for j, ci in enumerate(client_indices):
        n = int(dataset.num_samples[ci])
        order = rng.permutation(n) if shuffle else np.arange(n)
        take = order[:cap]
        t = len(take)
        indices[j].reshape(-1)[:t] = offsets[ci] + take
        sample_mask[j].reshape(-1)[:t] = 1.0
        num_samples[j] = t
        client_mask[j] = 1.0
        client_ids[j] = ci
    return IndexRoundBatch(indices, sample_mask, num_samples, client_mask,
                           client_ids)


def pack_eval_batches(
    dataset: BaseDataset,
    batch_size: int,
    pad_steps_to_multiple_of: int = 1,
    user_indices: Optional[Sequence[int]] = None,
) -> Dict[str, np.ndarray]:
    """Flatten eval users into ``[T, B, ...]`` batches with a mask.

    The reference chunks eval users ~evenly across workers
    (``core/evaluation.py:185-216``) and weights metrics by batch size
    (``core/evaluation.py:160-183``); here all samples go into one padded
    grid sharded over devices, and per-sample masking makes the weighted
    average exact.  Also returns ``user_idx`` ``[T, B]`` so personalization
    / per-user metrics can segment by user.
    """
    idxs = list(user_indices) if user_indices is not None else list(range(len(dataset)))
    spec = dataset.element_spec
    total = sum(int(dataset.num_samples[i]) for i in idxs)
    T = max(1, math.ceil(total / batch_size))
    if T % pad_steps_to_multiple_of:
        T += pad_steps_to_multiple_of - (T % pad_steps_to_multiple_of)
    B = batch_size

    first = dataset.user_arrays(idxs[0]) if idxs else {}
    out = {k: np.zeros((T * B,) + shape, dtype=first[k].dtype)
           for k, shape in spec.items()}
    mask = np.zeros((T * B,), dtype=np.float32)
    user_idx = np.full((T * B,), -1, dtype=np.int32)

    pos = 0
    for i in idxs:
        user = dataset.user_arrays(i)
        n = len(next(iter(user.values())))
        for k, arr in user.items():
            out[k][pos:pos + n] = arr
        mask[pos:pos + n] = 1.0
        user_idx[pos:pos + n] = i
        pos += n

    batched = {k: v.reshape((T, B) + v.shape[1:]) for k, v in out.items()}
    batched["sample_mask"] = mask.reshape(T, B)
    batched["user_idx"] = user_idx.reshape(T, B)
    return batched


def seq_length_bucket(batches: Sequence[RoundBatch],
                      seq_keys: Sequence[str],
                      min_len: int = 8) -> Optional[dict]:
    """Crop token-sequence grids to the power-of-two bucket of the chunk's
    real max length (the static-shape answer to the reference's
    ``DynamicBatchSampler`` padding-efficiency packing,
    ``utils/data_utils.py:42-119``).

    ``seq_keys`` name 0-padded ``[K, S, B, L]`` int arrays (the task's
    ``seq_pad_keys``).  All batches of a fused chunk are cropped to one
    common bucket so the chunk still compiles as a single program; cropping
    only removes all-zero tail columns, and the in-model position mask is
    derived from the ids themselves, so the math is identical — XLA just
    stops running matmuls over padding.  Buckets are powers of two (floored
    at ``min_len``), so the number of distinct compiled programs stays
    logarithmic in max L.

    Returns a stats dict (tokens_real / tokens_grid_before/after, bucket,
    ``cropped``) when the grids hold sequence keys, else None.
    """
    keys = [k for k in seq_keys if batches and k in batches[0].arrays]
    if not keys:
        return None
    L = max(b.arrays[k].shape[-1] for b in batches for k in keys)
    # the padding-efficiency meter counts each real token ONCE, from a
    # single canonical key — tok_mask when present (it marks real
    # positions even where x holds id 0), else the first seq key; summing
    # over all keys would triple-count and the keys legitimately disagree
    canon = "tok_mask" if "tok_mask" in keys else keys[0]
    # max real length across the chunk: position of the last nonzero
    # column over ALL keys (the crop must cover every key's extent)
    need = 1
    tokens_real = 0
    for b in batches:
        for k in keys:
            arr = b.arrays[k]
            nz = arr.reshape(-1, arr.shape[-1]) != 0
            if k == canon:
                tokens_real += int(nz.sum())
            cols = nz.any(axis=0)
            if cols.any():
                need = max(need, int(np.max(np.nonzero(cols)[0])) + 1)
    bucket = max(min_len, 1 << max(need - 1, 0).bit_length())
    stats = {
        "bucket": int(min(bucket, L)),
        "full_len": int(L),
        "tokens_real": int(tokens_real),
        "tokens_grid_before": int(sum(
            b.arrays[canon].reshape(-1, b.arrays[canon].shape[-1]).shape[0]
            * L for b in batches)),
    }
    stats["cropped"] = bucket < L
    if bucket < L:
        for b in batches:
            for k in keys:
                b.arrays[k] = np.ascontiguousarray(b.arrays[k][..., :bucket])
    stats["tokens_grid_after"] = int(sum(
        b.arrays[canon].reshape(-1, b.arrays[canon].shape[-1]).shape[0]
        * b.arrays[canon].shape[-1] for b in batches))
    return stats
