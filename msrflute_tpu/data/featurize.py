"""Featurization helpers: raw user-blob samples -> fixed-width arrays.

Parity targets:
- image reshaping done per-task in reference ``dataloaders/dataset.py``
  files (MNIST flat vectors, FEMNIST 28x28, CIFAR HWC/CHW);
- Shakespeare char encoding (FedML-style 90-symbol table, reference
  ``experiments/nlp_rnn_fedshakespeare``);
- LEAF Reddit word encoding with case backoff: try the word, then its
  lowercase, else unk=0 (reference ``experiments/nlg_gru/dataloaders/
  dataset.py:37-47``) with the vocab loader of
  ``experiments/nlg_gru/utils/utility.py:19-33``;
- truncation to ``max_num_words``/``max_seq_length``
  (``dataset.py:75-77``, ``core/config.py:180``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

# FedML/LEAF Shakespeare symbol table: pad=0, then letters; OOV maps to the
# last id.  86 printable symbols -> vocab 90 with room for specials.
SHAKESPEARE_LETTERS = (
    "\n !\"&'(),-.0123456789:;>?ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    "[]abcdefghijklmnopqrstuvwxyz}"
)
_CHAR_TO_ID = {c: i + 1 for i, c in enumerate(SHAKESPEARE_LETTERS)}


def encode_chars(text: str, seq_len: int, oov_id: int = 87) -> np.ndarray:
    """Unpadded char ids (pad to a matrix with :func:`pad_token_matrix`)."""
    return np.asarray([_CHAR_TO_ID.get(c, oov_id) for c in text[:seq_len]],
                      np.int64)


def load_vocab(path: str) -> Dict[str, int]:
    """Word vocab from a json dict / json list / newline list (reference
    ``experiments/nlg_gru/utils/utility.py:19-33``)."""
    with open(path) as fh:
        if path.endswith(".json"):
            raw = json.load(fh)
            if isinstance(raw, dict):
                if "vocab" in raw and isinstance(raw["vocab"], dict):
                    raw = raw["vocab"]
                return {str(w): int(i) for w, i in raw.items()}
            return {str(w): i for i, w in enumerate(raw)}
        return {line.strip(): i for i, line in enumerate(fh) if line.strip()}


def encode_words(text_or_tokens, vocab: Dict[str, int], seq_len: int,
                 unk_id: int = 0) -> np.ndarray:
    """Case-backoff word encoding (reference ``dataset.py:37-47``)."""
    tokens = (text_or_tokens.split() if isinstance(text_or_tokens, str)
              else list(text_or_tokens))
    ids = []
    for tok in tokens[:seq_len]:
        tok = str(tok)
        if tok in vocab:
            ids.append(vocab[tok])
        elif tok.lower() in vocab:
            ids.append(vocab[tok.lower()])
        else:
            ids.append(unk_id)  # unk is a REAL token (id 0), not padding
    return np.asarray(ids, np.int64)


def to_image(x: np.ndarray, example_shape: Sequence[int]) -> np.ndarray:
    """Reshape flat/CHW/HW samples to the task's example shape.

    Dtype-preserving: uint8 pixels stay uint8 (models normalize on device,
    see ``models.base.to_float_image``); anything else becomes float32.
    """
    x = np.asarray(x)
    if x.dtype != np.uint8:
        if x.dtype.kind in "iu" and x.size and 0 <= x.min() and x.max() <= 255:
            # integer pixel values (json round-trips uint8 as int64):
            # keep them as bytes so device-side [0,1] normalization applies
            x = x.astype(np.uint8)
        else:
            x = x.astype(np.float32)
    target = tuple(example_shape)
    n = x.shape[0]
    if x.shape[1:] == target:
        return x
    # CHW -> HWC
    if x.ndim == 4 and x.shape[1] in (1, 3) and \
            (x.shape[2], x.shape[3], x.shape[1]) == target:
        return np.transpose(x, (0, 2, 3, 1))
    # HW -> HW1
    if x.ndim == 3 and x.shape[1:] + (1,) == target:
        return x[..., None]
    # any layout whose element count matches (flat <-> image both ways)
    if int(np.prod(x.shape[1:])) == int(np.prod(target)):
        return x.reshape((n,) + target)
    raise ValueError(f"cannot reshape samples {x.shape} to {target}")


def pad_token_matrix(seqs: List[np.ndarray], seq_len: int):
    """Returns (ids [n, L] int32, tok_mask [n, L] float32).

    The explicit mask keeps the reference's distinction between padding
    (negative ids, ``nlg_gru/model.py:88-91``) and a *real* unk token id 0
    — an unk target stays in the loss/accuracy denominator (and is always
    counted wrong by the OOV-rejecting accuracy), while padding drops out.
    """
    out = np.zeros((len(seqs), seq_len), np.int32)
    mask = np.zeros((len(seqs), seq_len), np.float32)
    for i, s in enumerate(seqs):
        s = np.asarray(s, np.int64).reshape(-1)[:seq_len]
        real = s >= 0  # negative ids mark padding in the reference pipeline
        out[i, :len(s)] = np.where(real, s, 0)
        mask[i, :len(s)] = real.astype(np.float32)
    return out, mask
