from .user_blob import load_user_blob, UserBlob  # noqa: F401
from .dataset import BaseDataset, ArraysDataset  # noqa: F401
from .batching import (  # noqa: F401
    IndexRoundBatch, RoundBatch, assign_step_buckets, bucket_boundaries,
    build_sample_pool, ceil_div, pack_eval_batches, pack_round_batches,
    pack_round_indices, padding_efficiency, pow2_ceil, steps_for,
)
from .samplers import BatchSampler, DynamicBatchSampler  # noqa: F401
from .fleet import (  # noqa: F401
    SyntheticFleetDataset, floyd_sample, sample_cohort, steps_for_array,
    weighted_reservoir_sample,
)
