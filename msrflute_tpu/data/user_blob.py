"""Readers for FLUTE's "user blob" federated dataset format.

Parity target: the dataset contract in reference
``doc/sphinx/scenarios.rst:5-33`` — a JSON or HDF5 blob with:

- ``users`` (a.k.a. ``user_list``): list of client ids
- ``num_samples``: per-user sample counts
- ``user_data``: mapping user id -> samples (either ``{'x': [...]}`` dicts or
  a raw list)
- ``user_data_label`` (optional): mapping user id -> labels

plus the json<->hdf5 converters in ``utils/preprocessing/``.  The reference
reads these blobs in each task's ``dataloaders/dataset.py``; here a single
reader feeds every task plugin.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class UserBlob:
    """In-memory federated dataset: per-user raw sample lists.

    ``user_data[i]`` is whatever the blob stored for user ``user_list[i]``
    (list of samples or ``{'x': ...}`` dict — normalized to the list), and
    ``user_labels[i]`` the matching labels when present.
    """

    user_list: List[str]
    num_samples: List[int]
    user_data: List[Any]
    user_labels: Optional[List[Any]] = None
    metadata: Dict[str, Any] = field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.user_list)


def _normalize_samples(entry: Any) -> Any:
    """Blobs store either ``{'x': [...]}`` or a bare list
    (``doc/sphinx/scenarios.rst:13-33``).  Rich dicts with extra streams
    (e.g. semisupervision's unlabeled ``ux``, fednewsrec's
    ``clicked``/``impressions``) are preserved whole for task featurizers."""
    if isinstance(entry, dict) and "x" in entry:
        if set(entry.keys()) - {"x", "y"}:
            return entry
        return entry["x"]
    return entry


def _entry_len(entry: Any) -> int:
    """Sample count of a normalized entry.  ``len(dict)`` would count
    streams, not samples — rich dicts measure their ``x`` stream (or first
    stream for x-less formats like fednewsrec, whose featurizer recounts)."""
    if isinstance(entry, dict):
        stream = entry.get("x", next(iter(entry.values()), []))
        return len(stream)
    return len(entry)


def _labels_of(entry: Any) -> Optional[Any]:
    if isinstance(entry, dict) and "y" in entry:
        return entry["y"]
    return None


def load_user_blob(path: str) -> UserBlob:
    """Load a federated user blob from ``.json`` or ``.hdf5``."""
    ext = os.path.splitext(path)[1].lower()
    if ext in (".json", ".txt"):
        return _load_json(path)
    if ext in (".hdf5", ".h5"):
        return _load_hdf5(path)
    raise ValueError(f"unsupported user-blob extension: {path}")


def _load_json(path: str) -> UserBlob:
    with open(path, "r") as fh:
        blob = json.load(fh)
    users = blob.get("users", blob.get("user_list"))
    if users is None:
        raise ValueError(f"{path}: no 'users'/'user_list' key")
    user_data_map = blob.get("user_data", {})
    labels_map = blob.get("user_data_label")
    data, labels = [], []
    for user in users:
        entry = user_data_map.get(user, [])
        data.append(_normalize_samples(entry))
        if labels_map is not None:
            labels.append(labels_map[user] if isinstance(labels_map, dict)
                          else labels_map[len(labels)])
        else:
            labels.append(_labels_of(entry))
    have_labels = any(lab is not None for lab in labels)
    num_samples = blob.get("num_samples") or [_entry_len(d) for d in data]
    return UserBlob(
        user_list=list(users),
        num_samples=[int(n) for n in num_samples],
        user_data=data,
        user_labels=labels if have_labels else None,
    )


def _hdf5_decode(value):
    arr = np.asarray(value)
    if arr.dtype.kind == "S" or (
            arr.dtype.kind == "O" and arr.size and
            isinstance(arr.reshape(-1)[0], (bytes, str))):
        # vlen strings come back as bytes
        return [v.decode() if isinstance(v, bytes) else str(v)
                for v in arr]
    if arr.dtype.kind == "O":
        # vlen numeric (ragged) datasets: keep per-sample arrays
        return [np.asarray(v) for v in arr]
    return arr


def _read_hdf5_user(fh, user: str):
    """One user's ``(data_entry, label_or_None)`` from an open blob file.

    Shared by the eager loader and :class:`LazyHDF5Users` so the two paths
    cannot drift on layout handling."""
    import h5py

    entry = fh["user_data"][user]
    labels_grp = fh.get("user_data_label")
    label = (np.asarray(labels_grp[user][()])
             if labels_grp is not None else None)
    if isinstance(entry, h5py.Group):
        keys = set(entry.keys())
        if keys - {"x", "y"}:
            # rich per-user dict (semisup ux, fednewsrec
            # clicked/impressions): every stream round-trips;
            # '<key>.json' datasets hold non-array streams
            rich: Dict[str, Any] = {}
            for key in entry.keys():
                if key.endswith(".json"):
                    rich[key[:-len(".json")]] = json.loads(
                        bytes(entry[key][()]).decode("utf-8"))
                else:
                    rich[key] = _hdf5_decode(entry[key][()])
            if label is None and "y" in entry:
                label = np.asarray(entry["y"][()])
            return rich, label
        data = _hdf5_decode(entry["x"][()])
        if label is None and "y" in entry:
            label = np.asarray(entry["y"][()])
        return data, label
    return _hdf5_decode(entry[()]), label


def _read_hdf5_header(fh):
    """``(users, num_samples)`` from an open blob file — shared by the
    eager and lazy loaders so the header decode cannot drift either."""
    users_ds = fh.get("users", fh.get("user_list"))
    users = [u.decode() if isinstance(u, bytes) else str(u)
             for u in users_ds[()]]
    return users, [int(n) for n in fh["num_samples"][()]]


def _load_hdf5(path: str) -> UserBlob:
    import h5py

    with h5py.File(path, "r") as fh:
        users, num_samples = _read_hdf5_header(fh)
        data: List[Any] = []
        labels: List[Any] = []
        for user in users:
            entry, label = _read_hdf5_user(fh, user)
            data.append(entry)
            # always append (None when absent) to keep user<->label
            # alignment with mixed layouts, like _load_json does
            labels.append(label)
    return UserBlob(
        user_list=users,
        num_samples=num_samples,
        user_data=data,
        user_labels=(labels if any(l is not None for l in labels) else None),
    )


class LazyHDF5Users:
    """Per-user on-demand reader over an hdf5 blob (the scale path).

    The eager loaders above materialize EVERY user's samples — fine for the
    benchmark blobs, impossible at the reference's stated scale ("millions
    of clients", reference ``README.md:9``) where a round only ever touches
    the sampled clients.  This handle reads ``users``/``num_samples`` (two
    small datasets) eagerly and defers all sample IO to :meth:`read`.

    The h5py file is opened lazily per process and reads are serialized
    with a lock (h5py is not thread-safe; the engine's prefetch overlap
    packs on the controller thread, but personalization/eval helpers may
    not).
    """

    def __init__(self, path: str):
        import h5py  # noqa: F401  (fail fast if unavailable)
        self.path = path
        self._fh = None
        import threading
        self._lock = threading.Lock()
        with self._open() as fh:
            self.user_list, self.num_samples = _read_hdf5_header(fh)

    def _open(self):
        import h5py
        return h5py.File(self.path, "r")

    def read(self, user: str):
        """``(data_entry, label_or_None)`` for one user, read on demand."""
        with self._lock:
            if self._fh is None:
                self._fh = self._open()
            return _read_hdf5_user(self._fh, user)


def save_user_blob_hdf5(path: str, blob: UserBlob) -> None:
    """Write the hdf5 layout produced by reference
    ``utils/preprocessing/create-hdf5.py``."""
    import h5py

    def _as_dataset_value(samples):
        try:
            arr = np.asarray(samples)
        except ValueError:  # ragged lengths -> object array
            arr = np.empty(len(samples), dtype=object)
            arr[:] = [np.asarray(s) for s in samples]
        if arr.dtype.kind == "U" or (
                arr.dtype.kind == "O" and len(samples) and
                isinstance(samples[0], (str, bytes))):
            # text samples -> vlen utf-8
            return np.asarray([str(s) for s in samples],
                              dtype=h5py.string_dtype("utf-8"))
        if arr.dtype.kind == "O":
            # ragged numeric samples -> vlen float64
            return np.asarray([np.asarray(s, np.float64).reshape(-1)
                               for s in samples],
                              dtype=h5py.vlen_dtype(np.float64))
        return arr

    with h5py.File(path, "w") as fh:
        fh.create_dataset("users", data=np.array(blob.user_list, dtype="S"))
        fh.create_dataset("num_samples", data=np.asarray(blob.num_samples))
        grp = fh.create_group("user_data")
        for user, samples in zip(blob.user_list, blob.user_data):
            sub = grp.create_group(user)
            if isinstance(samples, dict):
                # rich per-user dict: one dataset per stream; streams that
                # have no array form (nested dicts, e.g. fednewsrec
                # impressions) persist as '<key>.json'
                for key, value in samples.items():
                    try:
                        sub.create_dataset(key, data=_as_dataset_value(value))
                    except (TypeError, ValueError):
                        sub.create_dataset(
                            f"{key}.json",
                            data=np.void(json.dumps(value).encode("utf-8")))
            else:
                sub.create_dataset("x", data=_as_dataset_value(samples))
        if blob.user_labels is not None:
            lab = fh.create_group("user_data_label")
            for user, y in zip(blob.user_list, blob.user_labels):
                if y is not None:
                    lab.create_dataset(user, data=_as_dataset_value(y))
