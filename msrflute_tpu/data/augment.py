"""Host-side image augmentation — numpy RandAugment.

Parity target: reference ``experiments/semisupervision/dataloaders/
RandAugment.py`` (the public Cubuk et al. policy: pick N ops at magnitude M
from a fixed list).  That file is PIL/torchvision per-__getitem__; here the
whole augmentation is vectorized numpy/scipy over a sample batch, because in
the TPU design augmentation happens once at blob/featurize time — the jitted
round program only ever sees fixed-shape arrays (``ux_rand`` in the
FedLabels ``uda: 1`` path, ``strategies/fedlabels.py``).

Value semantics: images may arrive as uint8 [0,255] or float (any range).
Ops are defined on a normalized [0,1] view and the original scale/dtype is
restored on the way out, so the augmented view stays distribution-compatible
with the clean view the way the reference's PIL pipeline does.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

# each op: (name, fn(img01, magnitude01, rng) -> img01, uses_magnitude)
# magnitudes follow the reference ranges (RandAugment.py:167-196), mapped
# onto the normalized [0,1] pixel view.


def _affine(img: np.ndarray, matrix: np.ndarray, offset) -> np.ndarray:
    from scipy import ndimage
    if img.ndim == 2:
        return ndimage.affine_transform(img, matrix, offset=offset,
                                        order=1, mode="nearest")
    out = np.empty_like(img)
    for c in range(img.shape[-1]):
        out[..., c] = ndimage.affine_transform(img[..., c], matrix,
                                               offset=offset, order=1,
                                               mode="nearest")
    return out


def _shear_x(img, m, rng):
    v = (m * 0.6 - 0.3) * _sign(rng)
    mat = np.array([[1.0, 0.0], [v, 1.0]])
    return _affine(img, mat, offset=(0.0, -v * img.shape[0] / 2))


def _shear_y(img, m, rng):
    v = (m * 0.6 - 0.3) * _sign(rng)
    mat = np.array([[1.0, v], [0.0, 1.0]])
    return _affine(img, mat, offset=(-v * img.shape[1] / 2, 0.0))


def _translate_x(img, m, rng):
    v = m * 0.45 * _sign(rng) * img.shape[1]
    return _affine(img, np.eye(2), offset=(0.0, v))


def _translate_y(img, m, rng):
    v = m * 0.45 * _sign(rng) * img.shape[0]
    return _affine(img, np.eye(2), offset=(v, 0.0))


def _rotate(img, m, rng):
    from scipy import ndimage
    angle = m * 30.0 * _sign(rng)
    if img.ndim == 2:
        return ndimage.rotate(img, angle, reshape=False, order=1,
                              mode="nearest")
    out = np.empty_like(img)
    for c in range(img.shape[-1]):
        out[..., c] = ndimage.rotate(img[..., c], angle, reshape=False,
                                     order=1, mode="nearest")
    return out


def _auto_contrast(img, m, rng):
    lo, hi = img.min(), img.max()
    if hi - lo < 1e-6:
        return img
    return (img - lo) / (hi - lo)


def _invert(img, m, rng):
    return 1.0 - img


def _equalize(img, m, rng):
    # histogram equalization on the [0,1] view (256 bins, like PIL)
    flat = img.reshape(-1)
    hist, bins = np.histogram(flat, bins=256, range=(0.0, 1.0))
    cdf = np.cumsum(hist).astype(np.float64)
    if cdf[-1] == 0:
        return img
    cdf = cdf / cdf[-1]
    return np.interp(flat, bins[:-1], cdf).reshape(img.shape).astype(
        img.dtype)


def _solarize(img, m, rng):
    thresh = 1.0 - m  # magnitude 0 -> no-op threshold 1.0
    return np.where(img >= thresh, 1.0 - img, img)


def _posterize(img, m, rng):
    bits = max(int(round(8 - 4 * m)), 1)  # 8 -> 4 bits over the range
    levels = 2 ** bits
    return np.floor(img * (levels - 1) + 0.5) / (levels - 1)


def _contrast(img, m, rng):
    f = 0.1 + m * 1.8  # reference range [0.1, 1.9]
    mean = img.mean()
    return np.clip((img - mean) * f + mean, 0.0, 1.0)


def _brightness(img, m, rng):
    f = 0.1 + m * 1.8
    return np.clip(img * f, 0.0, 1.0)


def _cutout(img, m, rng):
    frac = m * 0.2
    h, w = img.shape[0], img.shape[1]
    ch, cw = int(h * frac), int(w * frac)
    if ch == 0 or cw == 0:
        return img
    cy = int(rng.integers(0, h))
    cx = int(rng.integers(0, w))
    y0, y1 = max(cy - ch // 2, 0), min(cy + ch // 2, h)
    x0, x1 = max(cx - cw // 2, 0), min(cx + cw // 2, w)
    out = img.copy()
    out[y0:y1, x0:x1] = 0.5  # grey fill (reference fills (125,123,114))
    return out


def _identity(img, m, rng):
    return img


def _sign(rng) -> float:
    return 1.0 if rng.random() < 0.5 else -1.0


AUGMENT_OPS: List[Tuple[str, Callable]] = [
    ("identity", _identity),
    ("shear_x", _shear_x),
    ("shear_y", _shear_y),
    ("translate_x", _translate_x),
    ("translate_y", _translate_y),
    ("rotate", _rotate),
    ("auto_contrast", _auto_contrast),
    ("invert", _invert),
    ("equalize", _equalize),
    ("solarize", _solarize),
    ("posterize", _posterize),
    ("contrast", _contrast),
    ("brightness", _brightness),
    ("cutout", _cutout),
]


def rand_augment(images: np.ndarray, num_ops: int = 2, magnitude: int = 9,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Apply RandAugment(N=num_ops, M=magnitude/30) per image.

    ``images``: [B, H, W] or [B, H, W, C]; returns same shape/dtype.
    Flat-vector inputs (e.g. 784-dim rows) pass through with additive
    jitter only — geometric ops need spatial structure.
    """
    rng = rng or np.random.default_rng(0)
    x = np.asarray(images)
    if x.ndim < 3:  # no spatial structure: noise view only
        scale = max(float(np.std(x)), 1e-6)
        return (x + 0.05 * scale * rng.standard_normal(x.shape)).astype(
            x.dtype)
    # normalize to [0,1]
    if np.issubdtype(x.dtype, np.integer):
        lo, span = 0.0, float(np.iinfo(x.dtype).max)
    else:
        lo = float(x.min())
        span = max(float(x.max()) - lo, 1e-6)
    m01 = min(max(magnitude / 30.0, 0.0), 1.0)
    out = np.empty_like(x)
    for i in range(len(x)):
        img = ((x[i].astype(np.float32)) - lo) / span
        for k in range(num_ops):
            name, fn = AUGMENT_OPS[int(rng.integers(len(AUGMENT_OPS)))]
            img = fn(img, m01, rng)
        img = np.clip(img, 0.0, 1.0) * span + lo
        if np.issubdtype(x.dtype, np.integer):
            img = np.rint(img)
        out[i] = img.astype(x.dtype)
    return out
